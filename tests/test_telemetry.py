"""Telemetry plane: metrics core, flight recorder, piggyback wire,
exporters, and the live scrape e2e (docs/observability.md).

The slow test is the CI telemetry job's teeth: a real `train.py --env fake
--telemetry_port` run must expose master+predictor+learner+fleet series on
the scrape endpoint, every /json series must appear in /metrics, and every
/metrics line must parse as Prometheus text exposition.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.telemetry import metrics as tmetrics

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_registries():
    telemetry.reset_all()
    yield
    telemetry.reset_all()


# -- metrics core -----------------------------------------------------------


def test_counter_sums_across_threads():
    c = tmetrics.Counter("x_total")

    def work():
        for _ in range(10_000):
            c.inc()

    ts = [threading.Thread(target=work, daemon=True) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    # no locks anywhere, yet the per-thread shards make the total exact
    assert c.value() == 40_000


def test_gauge_set_and_fn():
    g = tmetrics.Gauge("depth")
    g.set(3)
    assert g.value() == 3.0
    g.set_fn(lambda: 7)
    assert g.value() == 7.0
    g.set_fn(lambda: 1 / 0)  # a dead fn reads 0, never raises
    assert g.value() == 0.0


def test_histogram_log2_buckets():
    h = tmetrics.Histogram("wait_s", unit=1e-6)
    h.observe(0.0)        # below unit -> bucket 0
    h.observe(3e-6)       # ~2 us -> bucket 2 ([2us, 4us))
    h.observe(1.0)        # 1e6 us -> high bucket
    assert h.count == 3
    assert h.sum == pytest.approx(1.000003)
    b = h.buckets()
    assert b[0] == 1 and sum(b) == 3
    assert b[2] == 1  # int(3e-6/1e-6)=3 -> bit_length 2


def test_registry_get_or_create_and_scalars():
    r = telemetry.registry("master")
    assert r.counter("a_total") is r.counter("a_total")
    r.counter("a_total").inc(5)
    r.gauge("g", fn=lambda: 2)
    r.histogram("h_s").observe(0.5)
    s = r.scalars()
    assert s["a_total"] == 5 and s["g"] == 2
    assert s["h_s_count"] == 1 and s["h_s_sum"] == pytest.approx(0.5)


def test_set_enabled_gates_writes():
    r = telemetry.registry("master")
    c = r.counter("gated_total")
    try:
        telemetry.set_enabled(False)
        c.inc(10)
        r.histogram("gated_s").observe(1)
        assert c.value() == 0
    finally:
        telemetry.set_enabled(True)
    c.inc(2)
    assert c.value() == 2


# -- flight recorder --------------------------------------------------------


def test_flight_recorder_ring_and_dump(tmp_path):
    rec = telemetry.FlightRecorder(capacity=4)
    for i in range(7):
        rec.record("evt", i=i)
    snap = rec.snapshot()
    assert [e["i"] for e in snap] == [3, 4, 5, 6]  # ring keeps the newest
    path = rec.dump("test", path=str(tmp_path / "flight.json"))
    doc = json.load(open(path))
    assert doc["reason"] == "test" and len(doc["events"]) == 4
    assert {"anchor_monotonic", "anchor_wall"} <= set(doc)


def test_flight_dump_never_raises(tmp_path):
    rec = telemetry.FlightRecorder()
    rec.record("evt")
    # unwritable target: dump must swallow, not mask the original failure
    assert rec.dump("x", path="/proc/nope/flight.json") is None


# -- piggyback wire ---------------------------------------------------------


def test_delta_tracker_emits_deltas_once():
    r = telemetry.registry("simulator")
    c = r.counter("env_steps_total")
    t = telemetry.DeltaTracker(r)
    c.inc(100)
    assert t.deltas() == {"env_steps_total": 100}
    assert t.deltas() == {}  # nothing moved since
    c.inc(5)
    assert t.deltas() == {"env_steps_total": 5}


def test_apply_fleet_deltas_aggregates_and_rejects_garbage():
    telemetry.apply_fleet_deltas(b"a", {"env_steps_total": 10})
    telemetry.apply_fleet_deltas(b"b", {"env_steps_total": 7, 42: 1, "x": "no"})
    telemetry.apply_fleet_deltas(b"c", "not-a-dict")
    telemetry.apply_fleet_deltas(b"d", [1, 2])
    s = telemetry.registry("fleet").scalars()
    assert s["env_steps_total"] == 17
    assert s["reporting_clients"] >= 2


# -- exporters --------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^ba3c_[A-Za-z0-9_]+(\{[A-Za-z0-9_]+=\"[^\"]*\"(,[A-Za-z0-9_]+=\"[^\"]*\")*\})? "
    r"[-+]?[0-9.eE+naninf-]+$"  # trailing '-' admits negative exponents (5e-05)
)


def _assert_prom_parses(text: str) -> set:
    names = set()
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert line.startswith("# TYPE ba3c_"), line
            continue
        assert _PROM_LINE.match(line), f"unparseable metrics line: {line!r}"
        names.add(line.split("{")[0].split(" ")[0])
    return names


def test_prometheus_text_covers_every_registered_series():
    telemetry.registry("master").counter("a_total").inc()
    telemetry.registry("predictor").gauge("depth", fn=lambda: 1)
    telemetry.registry("learner").histogram("step_s").observe(0.01)
    names = _assert_prom_parses(telemetry.prometheus_text())
    assert {"ba3c_a_total", "ba3c_depth"} <= names
    # histograms expand to the full prometheus triplet
    assert {"ba3c_step_s_bucket", "ba3c_step_s_sum", "ba3c_step_s_count"} <= names


def test_prometheus_text_one_type_line_per_family():
    """The same metric name in two roles (episodes_total lives in learner,
    simulator AND fleet by design) must share ONE # TYPE line — the
    Prometheus text parser rejects a whole scrape with duplicate TYPEs."""
    telemetry.registry("learner").counter("episodes_total").inc(3)
    telemetry.registry("fleet").counter("episodes_total").inc(7)
    text = telemetry.prometheus_text()
    _assert_prom_parses(text)
    assert text.count("# TYPE ba3c_episodes_total ") == 1
    assert 'ba3c_episodes_total{role="learner"} 3' in text
    assert 'ba3c_episodes_total{role="fleet"} 7' in text


def test_prometheus_text_small_values_parse():
    """Negative-exponent renderings (5e-05) must pass the parse gate."""
    telemetry.registry("master").histogram("tiny_s").observe(5e-5)
    _assert_prom_parses(telemetry.prometheus_text())


def test_telemetry_server_endpoints():
    telemetry.registry("master").counter("served_total").inc(3)
    telemetry.record("evt", note="x")
    srv = telemetry.TelemetryServer(0)  # ephemeral port
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(f"{base}/metrics", timeout=10).read().decode()
        _assert_prom_parses(text)
        assert 'ba3c_served_total{role="master"} 3' in text
        snap = json.loads(
            urllib.request.urlopen(f"{base}/json", timeout=10).read()
        )
        assert snap["master"]["served_total"]["value"] == 3
        ring = json.loads(
            urllib.request.urlopen(f"{base}/flight", timeout=10).read()
        )
        assert any(e["kind"] == "evt" for e in ring)
    finally:
        srv.stop()
        srv.join(timeout=5)
        srv.close()


def test_export_scalars_prefixes_roles():
    telemetry.registry("learner").counter("train_steps_total").inc(4)
    out = telemetry.export_scalars()
    assert out["tele/learner/train_steps_total"] == 4


# -- live e2e: scrape a real training run -----------------------------------


def _get_json(url, timeout=5):
    return json.loads(urllib.request.urlopen(url, timeout=timeout).read())


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_live_e2e_scrape_endpoint(tmp_path):
    """A real `train.py --env fake --telemetry_port` run exposes
    master+predictor+learner+fleet series; /metrics covers every /json
    series and parses as Prometheus text (the CI telemetry job)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    logdir = str(tmp_path / "log")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT
    proc = subprocess.Popen(
        [
            sys.executable, os.path.join(REPO_ROOT, "train.py"),
            "--env", "fake", "--simulator_procs", "4",
            "--batch_size", "32", "--image_size", "16", "--fc_units", "16",
            "--steps_per_epoch", "80", "--max_epoch", "2", "--nr_eval", "2",
            "--telemetry_port", str(port), "--logdir", logdir,
        ],
        env=env, cwd=REPO_ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    base = f"http://127.0.0.1:{port}"
    try:
        # wait for the endpoint (it starts with the actor plane, after the
        # train-step compile), then for all four roles to report
        deadline = time.monotonic() + 420
        snap = None
        while time.monotonic() < deadline and proc.poll() is None:
            try:
                snap = _get_json(f"{base}/json")
                if all(
                    snap[role][series]["value"] > 0
                    for role, series in (
                        ("master", "per_env_msgs_total"),
                        ("predictor", "batches_total"),
                        ("learner", "train_steps_total"),
                        ("fleet", "env_steps_total"),
                    )
                ):
                    break
            except (OSError, KeyError):
                pass
            time.sleep(1.0)
        assert snap is not None, "scrape endpoint never came up"
        assert {"master", "predictor", "learner", "fleet"} <= set(snap), snap.keys()
        # the fleet aggregation actually flowed (piggybacked sim deltas)
        assert snap["fleet"]["env_steps_total"]["value"] > 0
        assert snap["master"]["per_env_msgs_total"]["value"] > 0
        assert snap["learner"]["train_steps_total"]["value"] > 0
        assert snap["predictor"]["batches_total"]["value"] > 0

        # every registered series is present in /metrics and parseable
        text = urllib.request.urlopen(f"{base}/metrics", timeout=10).read().decode()
        prom_names = _assert_prom_parses(text)
        for role, series in snap.items():
            for name, m in series.items():
                safe = "ba3c_" + re.sub(r"[^A-Za-z0-9_]", "_", name)
                want = {safe} if m["type"] != "histogram" else {
                    f"{safe}_bucket", f"{safe}_sum", f"{safe}_count"
                }
                missing = want - prom_names
                assert not missing, f"{role}/{name}: missing {missing}"
    finally:
        try:
            out, _ = proc.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
            pytest.fail("training run did not finish")
    assert proc.returncode == 0, out[-3000:]
    # the stat.json/TB bridge carried the same series
    stats = json.load(open(os.path.join(logdir, "stat.json")))
    assert any(k.startswith("tele/") for k in stats[-1]), stats[-1].keys()
