"""tools/ba3caudit: per-rule toys, the real registry end-to-end, tripwire.

Layout mirrors test_ba3clint.py: every T-rule must (a) fire on a seeded
IR-level violation and (b) stay quiet on the clean construction, so a rule
regression that would spam (or blind) the real audit fails here first. The
end-to-end test runs the registry against the COMMITTED manifest — the same
check CI's audit job gates on.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ba3c_tpu import audit as audit_mod
from distributed_ba3c_tpu.audit import AuditError, RetraceTripwire, TraceTarget
from tools import ba3caudit
from tools.ba3caudit import ir, rules

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sds = jax.ShapeDtypeStruct


def _toy_target(fn, args, donate_argnums=None, **kwargs):
    fields = dict(
        name="toy",
        jit_fn=None,
        args=args,
        grad_shapes=None,
        donated_nonscalar_indices=[],
    )
    fields.update(kwargs)
    if fn is not None:
        fields["jit_fn"] = (
            jax.jit(fn, donate_argnums=donate_argnums)
            if donate_argnums is not None else jax.jit(fn)
        )
    return TraceTarget(**fields)


def _measure(target):
    return rules.measure(target)


# --------------------------------------------------------------------------
# T1: conv dtype policy
# --------------------------------------------------------------------------


def _conv_fn(dtype):
    def f(x, w):
        return jax.lax.conv_general_dilated(
            x.astype(dtype), w.astype(dtype),
            window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
    return f


_CONV_ARGS = (sds((1, 8, 8, 4), jnp.float32), sds((3, 3, 4, 8), jnp.float32))


def test_t1_flags_f32_conv():
    t = _toy_target(_conv_fn(jnp.float32), _CONV_ARGS)
    findings = rules.check_t1(t, _measure(t))
    assert findings and findings[0].rule == "T1"


def test_t1_clean_on_bf16_conv():
    t = _toy_target(_conv_fn(jnp.bfloat16), _CONV_ARGS)
    assert rules.check_t1(t, _measure(t)) == []


# --------------------------------------------------------------------------
# T2: donation materialized
# --------------------------------------------------------------------------


def test_t2_clean_when_donation_aliases():
    t = _toy_target(
        lambda x: x + 1.0, (sds((64, 64), jnp.float32),),
        donate_argnums=(0,), donated_nonscalar_indices=[0],
    )
    assert rules.check_t2(t, _measure(t)) == []


def test_t2_flags_dropped_donation():
    # donated arg has no same-shape output -> XLA cannot alias it
    t = _toy_target(
        lambda x: jnp.sum(x), (sds((64, 64), jnp.float32),),
        donate_argnums=(0,), donated_nonscalar_indices=[0],
    )
    findings = rules.check_t2(t, _measure(t))
    assert findings and findings[0].rule == "T2"


# --------------------------------------------------------------------------
# T3: exactly one gradient all-reduce
# --------------------------------------------------------------------------

_GRAD_SHAPE = (4, 4)


def _psum_step(n_psums):
    from distributed_ba3c_tpu.parallel.mesh import DATA_AXIS, shard_map

    mesh = audit_mod.canonical_mesh()

    def body(params, x):
        g = jax.grad(lambda p: jnp.sum((x @ p) ** 2))(params)
        for _ in range(n_psums):
            g = jax.lax.psum(g, DATA_AXIS)
        return params - g

    from jax.sharding import PartitionSpec as P

    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(), P("data")), out_specs=P()
    ))


_T3_ARGS = (sds(_GRAD_SHAPE, jnp.float32), sds((8, 4), jnp.float32))


def test_t3_clean_on_single_grad_psum():
    t = _toy_target(None, _T3_ARGS, grad_shapes=[_GRAD_SHAPE])
    t.jit_fn = _psum_step(1)
    assert rules.check_t3(t, _measure(t)) == []


def test_t3_flags_double_psum():
    t = _toy_target(None, _T3_ARGS, grad_shapes=[_GRAD_SHAPE])
    t.jit_fn = _psum_step(2)
    findings = rules.check_t3(t, _measure(t))
    assert findings and "extra" in findings[0].message


def test_t3_flags_missing_psum():
    t = _toy_target(None, _T3_ARGS, grad_shapes=[_GRAD_SHAPE])
    t.jit_fn = _psum_step(0)
    findings = rules.check_t3(t, _measure(t))
    assert findings and "NEVER all-reduced" in findings[0].message


def test_t3_flags_collectives_in_collective_free_entry():
    t = _toy_target(None, _T3_ARGS, allow_collectives=False)
    t.jit_fn = _psum_step(1)
    findings = rules.check_t3(t, _measure(t))
    assert findings and "single-device" in findings[0].message


# --------------------------------------------------------------------------
# T4: host callbacks
# --------------------------------------------------------------------------


def test_t4_flags_debug_print():
    def f(x):
        jax.debug.print("x={x}", x=x)
        return x * 2

    t = _toy_target(f, (sds((4,), jnp.float32),))
    findings = rules.check_t4(t, _measure(t))
    assert findings and findings[0].rule == "T4"


def test_t4_flags_pure_callback():
    def f(x):
        y = jax.pure_callback(
            lambda v: np.asarray(v) * 2, jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )
        return y + 1

    t = _toy_target(f, (sds((4,), jnp.float32),))
    assert rules.check_t4(t, _measure(t))


def test_t4_clean_without_callbacks():
    t = _toy_target(lambda x: x * 2, (sds((4,), jnp.float32),))
    assert rules.check_t4(t, _measure(t)) == []


# --------------------------------------------------------------------------
# T5: manifest drift (pure logic — no tracing)
# --------------------------------------------------------------------------


def _fake_measurement(**overrides):
    base = dict(
        entry="toy", collectives={"psum": 3}, host_callbacks={},
        conv_dtypes=[], dot_dtypes={"bfloat16": 2},
        nonscalar_psum_shapes=[(4, 4)], aliased_inputs=[0, 1],
        flops=1000.0, bytes_accessed=2000.0,
    )
    base.update(overrides)
    return rules.Measurement(**base)


def test_t5_missing_manifest_entry_is_a_finding():
    findings = rules.check_t5(_fake_measurement(), None, tolerance=0.25)
    assert findings and "missing from audit_manifest" in findings[0].message


def test_t5_within_tolerance_is_clean():
    m = _fake_measurement()
    entry = m.manifest_entry()
    entry["flops"] *= 1.2  # 20% < 25%
    assert rules.check_t5(m, entry, tolerance=0.25) == []


def test_t5_flags_cost_drift_beyond_tolerance():
    m = _fake_measurement()
    entry = m.manifest_entry()
    entry["bytes_accessed"] *= 2.0
    findings = rules.check_t5(m, entry, tolerance=0.25)
    assert findings and "bytes_accessed drifted" in findings[0].message


def test_t5_flags_exact_structure_drift():
    m = _fake_measurement()
    entry = m.manifest_entry()
    entry["collectives"] = {"psum": 4}
    findings = rules.check_t5(m, entry, tolerance=0.25)
    assert findings and "collectives drifted" in findings[0].message


# --------------------------------------------------------------------------
# IR plumbing
# --------------------------------------------------------------------------


def test_input_aliases_parses_tuple_and_bare_forms():
    s = ("HloModule jit_f, is_scheduled=true, input_output_alias="
         "{ {0}: (0, {}, may-alias), {12}: (7, {}, may-alias) }, entry=x")
    assert ir.input_aliases(s) == [0, 7]
    s2 = "HloModule j, input_output_alias={ {}: (3, {}, may-alias) }, e={y}"
    assert ir.input_aliases(s2) == [3]
    assert ir.input_aliases("HloModule j, no aliases here") == []


def test_iter_eqns_descends_into_scan():
    def f(x):
        def body(c, _):
            return jax.lax.psum(c, "i") if False else c * 2, None
        y, _ = jax.lax.scan(body, x, None, length=3)
        return y

    jaxpr = jax.make_jaxpr(f)(jnp.ones((4,)))
    names = [e.primitive.name for e in ir.iter_eqns(jaxpr)]
    assert "scan" in names and "mul" in names  # mul only inside the body


# --------------------------------------------------------------------------
# the real registry, end to end
# --------------------------------------------------------------------------


def test_registry_names():
    assert audit_mod.entry_names() == [
        "fused.actor",
        "fused.actor_bf16",
        "fused.actor_int8",
        "fused.greedy_eval",
        "fused.learner",
        "fused.macro_learner",
        "fused.step",
        "parallel.train_macro_step",
        "parallel.train_step",
        "parallel.vtrace_macro_step",
        "parallel.vtrace_step",
        "pod.learner",
        "predict.server",
        "predict.server_bf16",
        "predict.server_greedy",
        "predict.server_int8",
    ]


def test_real_entry_points_pass_against_committed_manifest():
    """The acceptance check: every registered hot-path program satisfies
    T1–T4 and matches the committed audit_manifest.json (T5)."""
    measurements, findings = ba3caudit.run_audit()
    assert sorted(measurements) == audit_mod.entry_names()
    assert findings == [], [f"{f.entry} [{f.rule}] {f.message}" for f in findings]


@pytest.mark.slow
def test_cli_end_to_end_json():
    out = subprocess.run(
        [sys.executable, "-m", "tools.ba3caudit", "--json"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=560,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["findings"] == []
    assert sorted(payload["entries"]) == audit_mod.entry_names()


def test_cli_rejects_unknown_entry():
    out = subprocess.run(
        [sys.executable, "-m", "tools.ba3caudit", "--entries", "nope"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 2
    assert "unknown entry point" in out.stderr


def test_stale_manifest_entry_is_a_finding(tmp_path):
    """A manifest key with no registered entry point (rename/delete) must
    surface instead of silently pinning nothing."""
    from tools.ba3caudit import manifest as manifest_mod

    stored = dict(manifest_mod.load() or {})
    stored["fused.step_OLD_NAME"] = stored["fused.step"]
    path = str(tmp_path / "m.json")
    manifest_mod.save(stored, path)
    _, findings = ba3caudit.run_audit(
        entries=["predict.server"], manifest_path=path
    )
    assert [f.entry for f in findings] == ["fused.step_OLD_NAME"]
    assert "no registered entry point" in findings[0].message


def test_update_manifest_prunes_stale_and_records_toolchain(tmp_path):
    from tools.ba3caudit import manifest as manifest_mod

    stored = dict(manifest_mod.load() or {})
    stored["fused.step_OLD_NAME"] = stored["fused.step"]
    path = str(tmp_path / "m.json")
    manifest_mod.save(stored, path)
    _, findings = ba3caudit.run_audit(
        entries=["predict.server"], manifest_path=path, update_manifest=True
    )
    assert findings == []
    rewritten = manifest_mod.load(path)
    assert "fused.step_OLD_NAME" not in rewritten
    # pins for entries NOT re-measured in this subset run are preserved
    assert "fused.step" in rewritten and "parallel.train_step" in rewritten


def test_subset_update_preserves_old_toolchain_stamp(tmp_path):
    """A subset --update-manifest must NOT re-stamp _meta: the preserved
    entries still hold the old toolchain's numbers, and re-stamping would
    suppress the CLI's toolchain-mismatch hint."""
    from tools.ba3caudit import manifest as manifest_mod

    stored = dict(manifest_mod.load() or {})
    stored[manifest_mod.META_KEY] = {"jax": "0.0.0-test"}
    path = str(tmp_path / "m.json")
    manifest_mod.save(stored, path)
    ba3caudit.run_audit(
        entries=["predict.server"], manifest_path=path, update_manifest=True
    )
    assert manifest_mod.load(path)[manifest_mod.META_KEY] == {
        "jax": "0.0.0-test"
    }
    # a FULL update re-stamps to the running toolchain
    ba3caudit.run_audit(manifest_path=path, update_manifest=True)
    assert manifest_mod.load(path)[manifest_mod.META_KEY]["jax"] == jax.__version__


# --------------------------------------------------------------------------
# the BA3C_AUDIT=1 runtime tripwire
# --------------------------------------------------------------------------


def test_tripwire_off_by_default(monkeypatch):
    monkeypatch.delenv("BA3C_AUDIT", raising=False)
    fn = audit_mod.tripwire_jit("test.off", lambda x: x * 2)
    assert not isinstance(fn, RetraceTripwire)
    assert float(fn(jnp.float32(2.0))) == 4.0


def test_tripwire_fires_on_injected_recompile(monkeypatch):
    monkeypatch.setenv("BA3C_AUDIT", "1")
    tw = audit_mod.tripwire_jit("test.unstable", lambda x: x * 2)
    assert isinstance(tw, RetraceTripwire)
    tw(jnp.zeros((4,)))   # warmup compile; auto-arms
    tw(jnp.zeros((4,)))   # cache hit: fine
    assert tw.traces == 1
    with pytest.raises(AuditError, match="re-traced after warmup"):
        tw(jnp.zeros((8,)))  # deliberately shape-unstable


def test_tripwire_manual_arm_allows_bucketed_warmup(monkeypatch):
    monkeypatch.setenv("BA3C_AUDIT", "1")
    tw = audit_mod.tripwire_jit("test.buckets", lambda x: x + 1, auto_arm=False)
    for b in (1, 2, 4):  # the predictor's pow-2 warmup sequence
        tw(jnp.zeros((b,)))
    tw.arm()
    tw(jnp.zeros((2,)))  # warm bucket: fine
    with pytest.raises(AuditError):
        tw(jnp.zeros((8,)))  # a NEW bucket mid-serving


def test_predictor_chunks_oversized_eval_batch_after_arm(monkeypatch):
    """An Evaluator batch larger than the serving bucket must be chunked to
    warmed buckets, not compile a new one — with BA3C_AUDIT=1 armed, a new
    bucket mid-serving would raise AuditError and kill the run."""
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.predict.server import BatchedPredictor

    monkeypatch.setenv("BA3C_AUDIT", "1")
    state_shape = (8, 8, 2)
    model = BA3CNet(num_actions=3, fc_units=8)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, *state_shape), jnp.uint8)
    )["params"]
    pred = BatchedPredictor(model, params, batch_size=2)
    assert isinstance(pred._fwd, RetraceTripwire)
    pred.warmup(state_shape)
    assert pred._fwd.armed
    # 5 states > the pow-2 serving cap of 2: three chunks (2, 2, 1), zero
    # new compiles
    actions, values, greedy = pred.predict_batch(
        np.zeros((5, *state_shape), np.uint8)
    )
    assert actions.shape == values.shape == greedy.shape == (5,)


def test_tripwire_fires_on_real_train_step(monkeypatch):
    """Integration: the registered sync-step site detects a batch-shape
    change after warmup (the silent-recompile regression, as a machine
    check)."""
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer
    from distributed_ba3c_tpu.parallel.mesh import make_mesh
    from distributed_ba3c_tpu.parallel.train_step import (
        create_train_state,
        make_train_step,
    )

    monkeypatch.setenv("BA3C_AUDIT", "1")
    cfg = BA3CConfig(num_actions=4, fc_units=16)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    mesh = make_mesh()
    step = make_train_step(model, opt, cfg, mesh)
    state = create_train_state(jax.random.PRNGKey(0), model, cfg, opt)

    def batch(n):
        return {
            "state": np.zeros((n, *cfg.state_shape), np.uint8),
            "action": np.zeros((n,), np.int32),
            "return": np.zeros((n,), np.float32),
        }

    n = 2 * mesh.shape["data"]
    state, _ = step(state, batch(n), cfg.entropy_beta)   # warmup
    state, _ = step(state, batch(n), cfg.entropy_beta)   # steady state
    with pytest.raises(AuditError, match="parallel.train_step"):
        step(state, batch(2 * n), cfg.entropy_beta)      # injected recompile
