"""C++ env core: binding, batched stepping, semantic parity with jaxenv."""

import numpy as np
import pytest

from distributed_ba3c_tpu.envs import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="cpp/libba3c_env.so not built (make -C cpp)"
)


def test_create_and_metadata():
    env = native.CppBatchedEnv("pong", 4, seed=1)
    assert env.num_actions == 6 and env.n == 4
    assert env.h == 84 and env.w == 84
    b = native.CppBatchedEnv("breakout", 2)
    assert b.num_actions == 4
    s = native.CppBatchedEnv("seaquest", 2)
    assert s.num_actions == 6
    q = native.CppBatchedEnv("qbert", 2)
    assert q.num_actions == 5
    with pytest.raises(ValueError):
        native.CppBatchedEnv("doom", 1)


def test_action_space_parity_with_jaxenv():
    """Full-gameset parity: the C++ core and the on-device JAX envs must
    agree on the action maps so policies transfer between planes."""
    jaxenv = pytest.importorskip("distributed_ba3c_tpu.envs.jaxenv")
    for name in (
        "pong", "breakout", "seaquest", "qbert",
        "space_invaders", "boxing", "assault",
    ):
        assert (
            native.CppBatchedEnv(name, 1).num_actions
            == jaxenv.get_env(name).num_actions
        ), name


def test_gameset_cpp_semantics():
    """Space Invaders / Boxing / Assault C++ mirrors: reward structure
    invariants matching their jaxenv counterparts."""
    rng = np.random.default_rng(0)
    # space invaders: fire-heavy play scores in row-point quanta (5..30)
    env = native.CppBatchedEnv("space_invaders", 4, seed=7)
    env.reset()
    total = 0.0
    for _ in range(300):
        a = rng.choice([1, 1, 2, 3, 4, 5], size=4).astype(np.int32)
        _, rew, _ = env.step(a)
        total += float(rew.sum())
    assert total > 0.0 and total % 5.0 == 0.0

    # assault: 21-point quanta
    env = native.CppBatchedEnv("assault", 4, seed=8)
    env.reset()
    total = 0.0
    for _ in range(400):
        a = rng.choice([1, 1, 3, 4, 5, 6, 2], size=4).astype(np.int32)
        _, rew, _ = env.step(a)
        total += float(rew.sum())
    assert total > 0.0 and total % 21.0 == 0.0

    # boxing: rewards are per-punch units in [-4, 4] per agent step, and the
    # tuned opponent keeps aggressive random play near break-even
    env = native.CppBatchedEnv("boxing", 4, seed=9)
    env.reset()
    total = 0.0
    for _ in range(500):
        a = rng.integers(0, 18, size=4).astype(np.int32)
        _, rew, _ = env.step(a)
        assert (np.abs(rew) <= 4.0).all()
        total += float(rew.sum())
    assert abs(total) / (4 * 500) < 0.5  # near break-even per step


def test_seaquest_oxygen_and_lives():
    """No-op agent never surfaces or shoots: oxygen runs out every 50 agent
    steps (200 substeps / frameskip 4), 3 lives -> episode ends, zero reward
    (mirrors jaxenv/seaquest.py oxygen/lives semantics)."""
    env = native.CppBatchedEnv("seaquest", 1, seed=11)
    obs = env.reset()
    assert obs.max() == 255  # submarine drawn
    total, done_at = 0.0, None
    for t in range(400):
        _, rew, done = env.step(np.zeros(1, np.int32))
        total += float(rew[0])
        if done[0]:
            done_at = t + 1
            break
    # 3 suffocations x ~50 steps each (collisions can only end it sooner)
    assert done_at is not None and done_at <= 160
    assert total == 0.0


def test_seaquest_torpedo_scores():
    """Fire torpedoes while sitting on a lane: fish kills must score +20
    multiples; surfacing by holding 'up' must outlive the no-op baseline."""
    env = native.CppBatchedEnv("seaquest", 1, seed=5)
    env.reset()
    total = 0.0
    for t in range(300):
        act = 1 if t % 3 == 0 else (2 if t % 50 > 44 else 0)  # fire + surface
        _, rew, done = env.step(np.array([act], np.int32))
        assert float(rew[0]) % 20.0 == 0.0
        total += float(rew[0])
        if done[0]:
            break
    assert total >= 20.0, "firing torpedoes into lanes never hit a fish"


def test_qbert_diagonal_descent_scores_then_falls():
    """Deterministic parity walk (mirrors jaxenv/qbert.py): hopping
    down-right flips (1,1)..(5,5) for 5x25 points, the 6th hop leaves the
    pyramid and costs a life; 3 lives of the same path end the episode with
    no new flips after the first pass."""
    env = native.CppBatchedEnv("qbert", 1, seed=3)
    env.reset()
    total, steps, done_seen = 0.0, 0, False
    for t in range(40):
        _, rew, done = env.step(np.array([2], np.int32))  # down-right
        total += float(rew[0])
        steps += 1
        if done[0]:
            done_seen = True
            break
    assert done_seen and steps == 18  # 3 lives x 6 hops
    assert total == pytest.approx(125.0)  # 5 new cubes x 25, once


def test_qbert_render_shows_pyramid():
    env = native.CppBatchedEnv("qbert", 1, seed=0)
    obs = env.reset()
    frame = obs[0]
    # unflipped cubes (100), agent (255) present; no flipped cubes yet
    assert (frame == 100).sum() > 200
    assert (frame == 255).sum() > 0
    assert (frame == 200).sum() == 0
    env.step(np.array([2], np.int32))  # flip (1,1)
    frame = env._obs[0]
    assert (frame == 200).sum() > 0


def test_reset_renders_scene():
    env = native.CppBatchedEnv("pong", 2)
    obs = env.reset()
    assert obs.shape == (2, 84, 84) and obs.dtype == np.uint8
    assert obs.max() == 255  # ball/paddles
    # paddles at fixed columns: agent at x=0.95 -> col ~79, opp at ~4
    assert obs[0][:, 78:82].max() == 255
    assert obs[0][:, 2:6].max() == 255


def test_batched_step_shapes_and_bounds():
    env = native.CppBatchedEnv("pong", 8, seed=3)
    env.reset()
    rng = np.random.default_rng(0)
    total_done = 0
    for _ in range(200):
        acts = rng.integers(0, env.num_actions, 8).astype(np.int32)
        obs, rew, done = env.step(acts)
        assert obs.shape == (8, 84, 84)
        assert np.isin(rew, [-1.0, 0.0, 1.0]).all() or np.abs(rew).max() <= 2
        total_done += int(done.sum())
    assert total_done >= 0  # matches are long; dones rare in 200 steps


def test_pong_still_agent_loses_match():
    """Semantic parity with jaxenv pong: a still agent loses to the tracking
    opponent and the match terminates at 21."""
    env = native.CppBatchedEnv("pong", 1, seed=7)
    env.reset()
    total, done_seen = 0.0, False
    for i in range(6000):
        _, rew, done = env.step(np.zeros(1, np.int32))
        total += float(rew[0])
        if done[0]:
            done_seen = True
            break
    assert done_seen and total <= -1


def test_breakout_semantics():
    """Fire + track the rendered ball with the paddle: bricks MUST break."""
    env = native.CppBatchedEnv("breakout", 1, seed=2)
    obs = env.reset()
    total = 0.0
    for i in range(1500):
        frame = obs[0]
        # ball = 255 pixels in the free-play band (below bricks ~row 45,
        # above the paddle ~row 77); paddle = 255 pixels near row 77
        ball_px = np.argwhere(frame[4:70] == 255)
        paddle_px = np.argwhere(frame[75:80] == 255)
        if len(ball_px) and len(paddle_px):
            ball_col = ball_px[:, 1].mean()
            paddle_col = paddle_px[:, 1].mean()
            act = 2 if ball_col > paddle_col + 1 else 3 if ball_col < paddle_col - 1 else 0
        else:
            act = 1  # serve
        obs, rew, done = env.step(np.array([act], np.int32))
        total += float(rew[0])
        if done[0]:
            break
    assert total > 0.0, "tracking paddle never broke a brick"


def test_cpp_player_protocol():
    p = native.build_cpp_player(0, "pong", frame_history=4)
    s = p.current_state()
    assert s.shape == (84, 84, 4) and s.dtype == np.uint8
    r, over = p.action(2)
    assert isinstance(r, float) and isinstance(over, bool)
    assert p.get_action_space_size() == 6


@pytest.mark.timeout(600)
def test_cpp_env_server_speaks_wire_protocol(tmp_path):
    """The server process is indistinguishable from B SimulatorProcesses.

    Generous timeouts: under a fully loaded suite the spawned server can
    take minutes to start (process spawn + import contention)."""
    import zmq

    from distributed_ba3c_tpu.utils.serialize import dumps, loads

    import time

    c2s = f"ipc://{tmp_path}/c2s"
    s2c = f"ipc://{tmp_path}/s2c"
    ctx = zmq.Context()
    pull = ctx.socket(zmq.PULL)
    pull.setsockopt(zmq.RCVTIMEO, 10_000)
    pull.bind(c2s)
    router = ctx.socket(zmq.ROUTER)
    router.bind(s2c)

    # this test pins the PER-ENV reference protocol (SimulatorProcess
    # compatibility); the block wires have their own live e2e coverage in
    # test_block_wire.py
    proc = native.CppEnvServerProcess(
        0, c2s, s2c, game="pong", n_envs=3, wire="per-env"
    )
    proc.start()

    def recv_with_liveness(deadline):
        """Poll-recv so a dead/stuck server fails with a DIAGNOSIS, not a
        bare timeout (this test has flaked under full-suite load)."""
        while True:
            try:
                return loads(pull.recv())
            except zmq.Again:
                assert proc.is_alive(), (
                    f"env server died, exitcode={proc.exitcode}"
                )
                assert time.time() < deadline, (
                    "env server alive but silent past the deadline"
                )

    try:
        deadline = time.time() + 550  # startup under load can take minutes
        seen = {}
        for round_ in range(3):
            for _ in range(3):
                ident, state, reward, is_over = recv_with_liveness(deadline)
                assert state.shape == (84, 84, 4) and state.dtype == np.uint8
                seen[ident] = seen.get(ident, 0) + 1
                router.send_multipart([ident, dumps(0)])
        assert len(seen) == 3  # three distinct env idents
        assert all(v == 3 for v in seen.values())
    finally:
        proc.terminate()
        proc.join(timeout=5)
        ctx.destroy(0)
