"""Golden-value tests for the BA3C loss (SURVEY.md §7 step 1)."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ba3c_tpu.ops import a3c_loss


def test_loss_components_match_numpy():
    rng = np.random.default_rng(0)
    B, A = 16, 4
    logits = rng.normal(size=(B, A)).astype(np.float32)
    values = rng.normal(size=(B,)).astype(np.float32)
    actions = rng.integers(0, A, size=(B,)).astype(np.int32)
    returns = rng.normal(size=(B,)).astype(np.float32)
    beta, vc = 0.01, 0.5

    out = a3c_loss(jnp.array(logits), jnp.array(values), jnp.array(actions),
                   jnp.array(returns), beta, vc)

    # numpy reference
    z = logits - logits.max(axis=1, keepdims=True)
    logp = z - np.log(np.exp(z).sum(axis=1, keepdims=True))
    p = np.exp(logp)
    alp = logp[np.arange(B), actions]
    adv = returns - values
    pl = -(alp * adv).mean()
    vl = 0.5 * ((values - returns) ** 2).mean()
    ent = -(p * logp).sum(axis=1).mean()

    np.testing.assert_allclose(out.policy_loss, pl, rtol=1e-5)
    np.testing.assert_allclose(out.value_loss, vl, rtol=1e-5)
    np.testing.assert_allclose(out.entropy, ent, rtol=1e-5)
    np.testing.assert_allclose(out.total, pl + vc * vl - beta * ent, rtol=1e-5)


def test_policy_gradient_ignores_value_through_advantage():
    """Advantage uses stop_grad(V): d(policy_loss)/d(values) must be zero."""
    B, A = 4, 3
    logits = jnp.ones((B, A))
    actions = jnp.zeros((B,), jnp.int32)
    returns = jnp.ones((B,))

    def pol_loss(values):
        return a3c_loss(logits, values, actions, returns, 0.0, 0.0).policy_loss

    g = jax.grad(pol_loss)(jnp.zeros((B,)))
    np.testing.assert_allclose(np.asarray(g), 0.0)


def test_entropy_of_uniform_policy():
    B, A = 2, 4
    out = a3c_loss(jnp.zeros((B, A)), jnp.zeros((B,)), jnp.zeros((B,), jnp.int32),
                   jnp.zeros((B,)))
    np.testing.assert_allclose(out.entropy, np.log(A), rtol=1e-6)
