"""Gym adapter, symbolic ops, experiment channels, launch script sanity."""

import json
import os
import subprocess

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ba3c_tpu.ops.symbolic import huber_loss


def test_huber_loss_regions():
    x = jnp.array([-3.0, -0.5, 0.0, 0.5, 3.0])
    out = huber_loss(x, delta=1.0)
    np.testing.assert_allclose(
        np.asarray(out), [2.5, 0.125, 0.0, 0.125, 2.5], rtol=1e-6
    )


def test_huber_value_loss_in_a3c_loss():
    """huber_delta routes the value loss through Huber (wired, not filler)."""
    from distributed_ba3c_tpu.ops.loss import a3c_loss

    logits = jnp.zeros((4, 3))
    values = jnp.array([0.0, 0.0, 0.0, 0.0])
    actions = jnp.zeros(4, jnp.int32)
    returns = jnp.array([10.0, 10.0, 10.0, 10.0])  # large residual -> linear
    l2 = a3c_loss(logits, values, actions, returns)
    hub = a3c_loss(logits, values, actions, returns, huber_delta=1.0)
    assert float(hub.value_loss) == pytest.approx(9.5)  # delta*(|x|-delta/2)
    assert float(l2.value_loss) == pytest.approx(50.0)


def test_gym_player_factory_imageizes():
    """--env gym:<name> route: vector obs become stacked uint8 frames."""
    pytest.importorskip("gymnasium")
    from distributed_ba3c_tpu.envs.gym_adapter import build_gym_player

    p = build_gym_player(0, "CartPole-v1", frame_history=4, image_size=(84, 84))
    s = p.current_state()
    assert s.shape == (84, 84, 4) and s.dtype == np.uint8
    r, over = p.action(0)
    assert isinstance(r, float) and isinstance(over, bool)


def test_gym_adapter_cartpole():
    gym = pytest.importorskip("gymnasium")
    from distributed_ba3c_tpu.envs.gym_adapter import GymEnv

    env = GymEnv("CartPole-v1", seed=0)
    assert env.get_action_space_size() == 2
    s = env.current_state()
    assert s.shape == (4,)
    total_eps = 0
    for _ in range(300):
        r, over = env.action(np.random.default_rng(0).integers(0, 2))
        if over:
            total_eps += 1
    assert total_eps >= 1
    assert len(env.stats["score"]) == total_eps


def test_channel_writer_and_logger(tmp_path):
    from distributed_ba3c_tpu.train.experiment import ChannelWriter, ExperimentLogger
    from distributed_ba3c_tpu.utils.stats import StatHolder

    path = str(tmp_path / "channels.jsonl")
    w = ChannelWriter(path)
    w.send("score", 1, 2.5)
    w.send("fps", 1, 1000.0)
    w.close()
    lines = [json.loads(l) for l in open(path)]
    assert lines[0] == pytest.approx(
        {"channel": "score", "x": 1, "y": 2.5, "ts": lines[0]["ts"]}
    )

    class _T:
        pass

    tr = _T()
    tr.global_step = 7

    class C:
        log_dir = str(tmp_path)

    tr.config = C()
    tr.stat_holder = StatHolder(str(tmp_path))
    tr.stat_holder.add_stat("mean_score", 3.0)
    tr.stat_holder.add_stat("global_step", 7)
    tr.stat_holder.finalize()

    cb = ExperimentLogger()
    cb.setup(tr)
    cb.before_train()
    cb.trigger_epoch()
    cb.after_train()
    recs = [json.loads(l) for l in open(tmp_path / "channels.jsonl")]
    assert any(r["channel"] == "mean_score" and r["y"] == 3.0 for r in recs)


def test_launch_script_rank_computation():
    out = subprocess.run(
        ["bash", "-c", 'python3 - "h1:1,h2:1,h3:1" h2 <<\'EOF\'\nimport sys\nhosts=[h.split(":")[0].split(".")[0] for h in sys.argv[1].split(",")]\nprint(hosts.index(sys.argv[2]))\nEOF'],
        capture_output=True,
        text=True,
    )
    assert out.stdout.strip() == "1"
    script = os.path.join(
        os.path.dirname(__file__), "..", "scripts", "launch_multihost.sh"
    )
    assert os.access(script, os.R_OK)
