"""Tests for V-trace: on-policy reduction + golden recursion check."""

import jax.numpy as jnp
import numpy as np

from distributed_ba3c_tpu.ops import vtrace_returns
from distributed_ba3c_tpu.ops import n_step_returns


def _rand(rng, *shape):
    return rng.normal(size=shape).astype(np.float32)


def test_on_policy_vtrace_equals_n_step_returns():
    """With pi == mu and no clipping active, vs_t equals n-step returns."""
    rng = np.random.default_rng(2)
    T, B = 6, 4
    logp = np.log(np.full((T, B), 0.25, np.float32))
    rewards = _rand(rng, T, B)
    values = _rand(rng, T, B)
    bootstrap = _rand(rng, B)
    dones = np.zeros((T, B), np.float32)
    gamma = 0.95

    out = vtrace_returns(
        jnp.array(logp), jnp.array(logp), jnp.array(rewards), jnp.array(dones),
        jnp.array(values), jnp.array(bootstrap), gamma,
    )
    want = n_step_returns(jnp.array(rewards), jnp.array(dones), jnp.array(bootstrap), gamma)
    np.testing.assert_allclose(np.asarray(out.vs), np.asarray(want), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out.clipped_rhos), 1.0)


def test_vtrace_matches_sequential_recursion():
    rng = np.random.default_rng(3)
    T, B = 5, 2
    b_logp = _rand(rng, T, B)
    t_logp = _rand(rng, T, B)
    rewards = _rand(rng, T, B)
    values = _rand(rng, T, B)
    bootstrap = _rand(rng, B)
    dones = (rng.random((T, B)) < 0.2).astype(np.float32)
    gamma, rho_bar, c_bar = 0.9, 1.0, 1.0

    out = vtrace_returns(
        jnp.array(b_logp), jnp.array(t_logp), jnp.array(rewards), jnp.array(dones),
        jnp.array(values), jnp.array(bootstrap), gamma, rho_bar, c_bar,
    )

    # sequential reference implementation straight from the paper
    rhos = np.exp(t_logp - b_logp)
    crho = np.minimum(rho_bar, rhos)
    cs = np.minimum(c_bar, rhos)
    disc = gamma * (1.0 - dones)
    vtp1 = np.concatenate([values[1:], bootstrap[None]], axis=0)
    deltas = crho * (rewards + disc * vtp1 - values)
    vs_minus_v = np.zeros((T + 1, B), np.float32)
    for t in range(T - 1, -1, -1):
        vs_minus_v[t] = deltas[t] + disc[t] * cs[t] * vs_minus_v[t + 1]
    vs = vs_minus_v[:T] + values
    vs_tp1 = np.concatenate([vs[1:], bootstrap[None]], axis=0)
    pg_adv = crho * (rewards + disc * vs_tp1 - values)

    np.testing.assert_allclose(np.asarray(out.vs), vs, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out.pg_advantages), pg_adv, rtol=1e-4, atol=1e-5)
