"""Player protocol, wrappers, FakeEnv (envs/)."""

import numpy as np

from distributed_ba3c_tpu.envs import (
    FakeEnv,
    HistoryFramePlayer,
    LimitLengthPlayer,
    PreventStuckPlayer,
)


def test_fake_env_optimal_policy():
    env = FakeEnv(chain_len=4, max_steps=16, image_size=(16, 16), noise=0)
    total, steps = 0.0, 0
    for _ in range(3):  # three episodes of always-right
        while True:
            r, over = env.action(1)
            total += r
            steps += 1
            if over:
                break
    assert total == 3.0
    assert steps == 3 * 3  # chain_len-1 per episode


def test_fake_env_timeout_and_autorestart():
    env = FakeEnv(chain_len=4, max_steps=5, image_size=(16, 16), noise=0)
    rewards = [env.action(0) for _ in range(5)]  # always-left never scores
    assert rewards[-1] == (0.0, True)
    assert env.pos == 0 and env.steps == 0  # auto-restarted


def test_fake_env_observation_encodes_position():
    env = FakeEnv(chain_len=4, image_size=(16, 16), noise=0)
    s0 = env.current_state()
    env.action(1)
    s1 = env.current_state()
    assert s0.shape == (16, 16) and s0.dtype == np.uint8
    assert not np.array_equal(s0, s1)
    # bright band moved right
    assert s0[:, 0:4].min() == 230 and s1[:, 4:8].min() == 230


def test_history_player_stacks_and_clears():
    env = FakeEnv(chain_len=3, max_steps=8, image_size=(8, 8), noise=0)
    p = HistoryFramePlayer(env, 4)
    s = p.current_state()
    assert s.shape == (8, 8, 4)
    # first state: 3 zero frames + 1 real frame
    assert s[..., :3].max() == 0 and s[..., 3].max() == 230
    p.action(1)
    assert p.current_state()[..., 2:].max() == 230
    # finish the episode; history must reset to fresh-episode padding
    _, over = p.action(1)
    assert over
    s = p.current_state()
    assert s[..., :3].max() == 0


def test_limit_length_player():
    env = FakeEnv(chain_len=10, max_steps=1000, image_size=(8, 8), noise=0)
    p = LimitLengthPlayer(env, limit=7)
    n = 0
    while True:
        _, over = p.action(3)  # no-op action never ends naturally
        n += 1
        if over:
            break
    assert n == 7


def test_prevent_stuck_player():
    env = FakeEnv(chain_len=4, max_steps=100, image_size=(8, 8), noise=0)
    p = PreventStuckPlayer(env, limit=3, action_on_stuck=1)
    # feed no-ops; after 3 identical observations the wrapper forces action 1
    for _ in range(30):
        _, over = p.action(3)
        if over:
            break
    # the forced right-moves must eventually reach the goal (reward episode end)
    assert env.stats["score"] and env.stats["score"][0] == 1.0
