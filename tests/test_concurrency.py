"""Direct unit tests for utils/concurrency.py (previously only exercised
indirectly through the actor plane): StoppableThread stop semantics, the
stoppable queue helpers' return contracts, LoopThread shutdown, and the
module-level helpers the masters/predictor use."""

import queue
import threading
import time

from distributed_ba3c_tpu.utils.concurrency import (
    LoopThread,
    StoppableThread,
    queue_get_stoppable,
    queue_put_stoppable,
)


def test_stoppable_thread_stop_flag():
    t = StoppableThread()
    assert not t.stopped()
    t.stop()
    assert t.stopped()
    # stop() before start() is legal and idempotent
    t.stop()
    assert t.stopped()


def test_stoppable_thread_run_until_stopped():
    ticks = []

    class T(StoppableThread):
        def run(self):
            while not self.stopped():
                ticks.append(1)
                time.sleep(0.001)

    t = T(daemon=True)
    t.start()
    time.sleep(0.05)
    t.stop()
    t.join(timeout=5)
    assert not t.is_alive()
    assert ticks, "thread never entered its loop"


def test_queue_put_stoppable_success_and_stop():
    q = queue.Queue(maxsize=1)
    evt = threading.Event()
    assert queue_put_stoppable(q, "a", evt, timeout=0.01) is True
    assert q.get_nowait() == "a"
    # full queue + stop mid-wait -> False, item NOT enqueued
    q.put("blocker")
    stopper = threading.Timer(0.05, evt.set)
    stopper.start()
    try:
        assert queue_put_stoppable(q, "b", evt, timeout=0.01) is False
    finally:
        stopper.cancel()
    assert q.get_nowait() == "blocker"
    assert q.empty()
    # already-stopped -> immediate False without touching the queue
    assert queue_put_stoppable(q, "c", evt, timeout=0.01) is False
    assert q.empty()


def test_queue_get_stoppable_success_and_stop():
    q = queue.Queue()
    evt = threading.Event()
    q.put("x")
    assert queue_get_stoppable(q, evt, timeout=0.01) == "x"
    # empty queue + stop mid-wait -> None
    stopper = threading.Timer(0.05, evt.set)
    stopper.start()
    try:
        assert queue_get_stoppable(q, evt, timeout=0.01) is None
    finally:
        stopper.cancel()
    # already-stopped -> None even though an item is available (contract:
    # stop wins; the caller is shutting down and must not consume)
    q.put("y")
    assert queue_get_stoppable(q, evt, timeout=0.01) is None
    assert q.get_nowait() == "y"


def test_thread_queue_helpers_use_own_stop_flag():
    t = StoppableThread()
    q = queue.Queue(maxsize=1)
    assert t.queue_put_stoppable(q, 1, timeout=0.01) is True
    assert t.queue_get_stoppable(q, timeout=0.01) == 1
    t.stop()
    assert t.queue_put_stoppable(q, 2, timeout=0.01) is False
    assert t.queue_get_stoppable(q, timeout=0.01) is None


def test_loop_thread_runs_func_and_stops():
    calls = []
    lt = LoopThread(lambda: (calls.append(1), time.sleep(0.001)))
    lt.start()
    time.sleep(0.05)
    lt.stop()
    lt.join(timeout=5)
    assert not lt.is_alive()
    assert len(calls) >= 2, "LoopThread should call func repeatedly"
    n = len(calls)
    time.sleep(0.02)
    assert len(calls) == n, "LoopThread kept running after stop()+join()"
