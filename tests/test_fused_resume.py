"""Fused-trainer resume continues the run (epoch counter + schedule).

A stall-kill + ``--load`` (scripts/run_with_resume.sh) must CONTINUE the
single-command run: the epoch counter derives from the restored global step,
so ``--max_epoch`` is a total budget and the LR/β anneal picks up where it
left off instead of restarting from the top (the failure mode that made the
round-2 north-star a hand-driven multi-phase recipe).
"""

import json
import os

import pytest

from distributed_ba3c_tpu.cli import main


def _run(logdir, max_epoch, load=False):
    args = [
        "--trainer", "tpu_fused_ba3c",
        "--env", "jax:pong",
        "--batch_size", "8",
        "--rollout_len", "2",
        "--fc_units", "16",
        "--steps_per_epoch", "2",
        "--max_epoch", str(max_epoch),
        "--nr_eval", "1",
        "--eval_max_steps", "8",
        "--learning_rate_final", "1e-5",
        "--anneal", "exp",
        "--logdir", logdir,
    ]
    if load:
        args += ["--load", os.path.join(logdir, "checkpoints")]
    return main(args)


@pytest.mark.slow
def test_fused_resume_continues_epochs(tmp_path):
    logdir = str(tmp_path / "run")
    assert _run(logdir, max_epoch=2) == 0
    stats = json.load(open(os.path.join(logdir, "stat.json")))
    assert [s["epoch"] for s in stats] == [1, 2]
    assert [s["global_step"] for s in stats] == [2, 4]

    # resume with a LARGER total budget: continues at epoch 3, not epoch 1
    assert _run(logdir, max_epoch=4, load=True) == 0
    stats = json.load(open(os.path.join(logdir, "stat.json")))
    assert [s["epoch"] for s in stats] == [1, 2, 3, 4]
    assert [s["global_step"] for s in stats] == [2, 4, 6, 8]

    # resume with the budget already spent: a no-op clean exit (this is what
    # lets run_with_resume.sh terminate after the final restart)
    assert _run(logdir, max_epoch=4, load=True) == 0
    stats = json.load(open(os.path.join(logdir, "stat.json")))
    assert len(stats) == 4
