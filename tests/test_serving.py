"""SLO-aware serving plane (predict/server.py, docs/serving.md).

Deterministic fake-clock tests of the continuous-batching scheduler's
deadline semantics — a saturated predictor SHEDS late tasks with a typed
reject and never executes a task past its deadline, admitted-task p99 stays
bounded under sustained overload — plus canary/shadow multi-policy
contracts and the BA3C_AUDIT=1 trace-stability of continuous batching.

The fake clock drives every scheduler decision (admission stamps,
viability, latency accounting); the null device advances it by a fixed
service time per fetched call, so the whole overload scenario plays out in
deterministic virtual time while threads synchronize on real events.
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.predict.server import (
    BatchedPredictor,
    ShedReject,
    make_fwd_sample,
)

N_ACTIONS = 4
STATE = (4, 4, 2)


class _FakeClock:
    def __init__(self):
        self.t = 0.0
        self._lock = threading.Lock()

    def __call__(self):
        return self.t

    def advance(self, dt):
        with self._lock:
            self.t += dt


class _NullPred(BatchedPredictor):
    """Null device in VIRTUAL time: each fetched call advances the fake
    clock by ``service_s`` — the deterministic analogue of a serialized
    device queue."""

    service_s = 0.0
    vclock = None

    def _dispatch(self, params, batch):
        b = np.asarray(batch)
        k = b.shape[0]
        acts = (np.arange(k) % N_ACTIONS).astype(np.int32)
        return k, (
            acts,
            np.zeros(k, np.float32),
            np.full(k, -1.0, np.float32),
            acts,
        )

    def _collect(self, handle):
        if self.vclock is not None and self.service_s:
            self.vclock.advance(self.service_s)
        return handle[1]


def _null_pred(service_s=0.0, **kw):
    telemetry.reset_all()
    clock = _FakeClock()
    model = SimpleNamespace(num_actions=N_ACTIONS, apply=None)
    kw.setdefault("coalesce_ms", 0.0)
    pred = _NullPred(model, {}, clock=clock, **kw)
    pred.service_s = service_s
    pred.vclock = clock
    return pred, clock


def _drain(pred, resolved, total, timeout=20.0):
    """Wait (real time) until ``total`` tasks resolved in virtual time."""
    deadline = time.monotonic() + timeout
    while resolved() < total and time.monotonic() < deadline:
        time.sleep(0.005)
    assert resolved() == total, f"only {resolved()}/{total} tasks resolved"


def _pred_scalar(name):
    return telemetry.registry("predictor").scalars().get(name, 0.0)


# -- deadline semantics ------------------------------------------------------


def test_expired_task_is_shed_with_typed_reject():
    """A task whose deadline passed while queued is never served."""
    pred, clock = _null_pred(batch_size=8, queue_depth=16)
    served, sheds = [], []
    evt = threading.Event()
    pred.put_block_task(
        np.zeros((4, *STATE), np.uint8),
        lambda a, v, lp: served.append(a),
        deadline=clock() + 0.05,
        shed_callback=lambda r: (sheds.append(r), evt.set()),
    )
    clock.advance(0.1)  # the deadline passes while the task sits queued
    pred.start()
    try:
        assert evt.wait(10)
        assert served == []
        assert isinstance(sheds[0], ShedReject)
        assert sheds[0].reason == "deadline"
        assert _pred_scalar("sheds_deadline_total") == 4  # rows, not tasks
    finally:
        pred.stop()
        pred.join(timeout=5)


def test_full_admission_queue_rejects_fast():
    """Overload past the bounded queue is an immediate typed reject, not a
    blocking wait — the scheduler is deliberately not running."""
    pred, _ = _null_pred(batch_size=8, queue_depth=4, slo_ms=1000.0)
    rejects = []
    admitted = 0
    try:
        for _ in range(10):
            admitted += pred.put_block_task(
                np.zeros((2, *STATE), np.uint8),
                lambda a, v, lp: None,
                shed_callback=lambda r: rejects.append(r),
            )
        assert admitted == 4
        assert len(rejects) == 6
        assert all(r.reason == "queue_full" for r in rejects)
        assert _pred_scalar("sheds_queue_full_total") == 12  # 6 tasks x 2 rows
    finally:
        pred.stop()


def test_overload_sheds_but_admitted_p99_stays_bounded():
    """2x sustained overload: shed rate rises, NO task executes past its
    deadline, and the latency of everything actually served stays <= SLO
    (load shedding, not latency collapse)."""
    slo_s = 0.05
    service_s = 0.01
    pred, clock = _null_pred(
        service_s=service_s, batch_size=8, queue_depth=64, slo_ms=1000 * slo_s
    )
    # capacity: one 8-row call per 10 ms of virtual time = 800 rows/s;
    # each round bursts 2x the rows a full SLO window can serve
    per_round = 2 * int(slo_s / service_s)
    lats, sheds = [], []
    pred.start()
    try:
        for _ in range(3):  # sustained: pressure re-applied every round
            t0 = clock()

            def cb(a, v, lp, t0=t0):
                lats.append(clock() - t0)

            before = len(lats) + len(sheds)
            for _ in range(per_round):
                pred.put_block_task(
                    np.zeros((8, *STATE), np.uint8), cb,
                    shed_callback=lambda r: sheds.append(r),
                )
            _drain(
                pred, lambda: len(lats) + len(sheds), before + per_round
            )
    finally:
        pred.stop()
        pred.join(timeout=5)
    assert sheds, "2x overload produced no sheds"
    assert all(r.reason in ("deadline", "queue_full") for r in sheds)
    # the SLO claim, in virtual time: nothing served ran past its budget
    assert max(lats) <= slo_s + 1e-9, f"served latency {max(lats)} > SLO"
    # and the scheduler PROVED it: zero rows served past their deadline
    assert _pred_scalar("deadline_misses_total") == 0
    assert len(lats) >= 3  # the plane kept serving while shedding


def test_no_deadline_means_backpressure_and_full_service():
    """Without deadlines (the training plane's contract) nothing is ever
    shed — every task is served, in FIFO order."""
    pred, _ = _null_pred(batch_size=4, queue_depth=256)
    got = []
    done = threading.Event()
    n = 50

    def cb(i):
        def _cb(a, v, lp):
            got.append(i)
            if len(got) == n:
                done.set()

        return _cb

    for i in range(n):
        pred.put_task(np.zeros(STATE, np.uint8), cb(i))
    pred.start()
    try:
        assert done.wait(20)
        assert got == list(range(n))
        assert _pred_scalar("sheds_total") == 0
    finally:
        pred.stop()
        pred.join(timeout=5)


def test_estimator_recovers_after_transient_stall():
    """A one-off stall that inflates the serve-time estimate past the
    whole SLO budget must NOT shed forever: fresh-task sheds decay the
    estimate until a probe gets through and re-measures the truth (found
    live — a 446 ms scheduler stall on a busy host otherwise turned a
    healthy plane into a permanent 100%-shed outage)."""
    slo_s = 0.05
    pred, clock = _null_pred(
        service_s=0.2, batch_size=8, queue_depth=64, slo_ms=1000 * slo_s
    )
    served, sheds = [], []
    pred.start()
    try:
        # the stall: one 200 ms call inflates the estimate to 4x the SLO
        pred.put_block_task(
            np.zeros((8, *STATE), np.uint8),
            lambda a, v, lp: served.append(1),
            shed_callback=lambda r: sheds.append(r),
        )
        _drain(pred, lambda: len(served) + len(sheds), 1)
        assert served == [1]  # est was still 0 — the stall call serves
        # back to a healthy device
        pred.service_s = 0.01
        # fresh tasks trickle in; each full-budget shed decays the
        # estimate 10%, so service MUST resume within a bounded number
        for i in range(2, 42):
            pred.put_block_task(
                np.zeros((8, *STATE), np.uint8),
                lambda a, v, lp: served.append(1),
                shed_callback=lambda r: sheds.append(r),
            )
            _drain(pred, lambda: len(served) + len(sheds), i)
            if len(served) >= 3:
                break
    finally:
        pred.stop()
        pred.join(timeout=5)
    assert sheds, "the inflated estimate should shed the first probes"
    assert len(served) >= 3, (
        "the plane never recovered from the transient stall — the "
        "estimator death-spiraled"
    )


# -- multi-policy serving ----------------------------------------------------


def test_canary_routing_is_deterministic_fraction():
    pred, _ = _null_pred(batch_size=4, queue_depth=256)
    pred.add_policy("canary", {})
    pred.set_canary("canary", 0.25)
    n = 16
    done = threading.Event()
    served = []

    def cb(a, v, lp):
        served.append(a)
        if len(served) == n:
            done.set()

    for _ in range(n):
        pred.put_task(np.zeros(STATE, np.uint8), cb)
    pred.start()
    try:
        assert done.wait(20)
        # deficit-accumulator split at group granularity: 4 groups of 4
        # rows, the 4th's debt covers it — exactly fraction*n rows, no
        # RNG, and no group ever fragmented at a policy boundary
        assert _pred_scalar("policy_canary_rows_total") == 4
        assert _pred_scalar("policy_default_rows_total") == 12
    finally:
        pred.stop()
        pred.join(timeout=5)


def test_policy_table_validation():
    pred, _ = _null_pred(batch_size=4)
    try:
        with pytest.raises(ValueError, match="policy id"):
            pred.add_policy("Not-Valid!", {})
        with pytest.raises(KeyError, match="unknown policy"):
            pred.set_canary("ghost", 0.5)
        with pytest.raises(KeyError, match="unknown policy"):
            pred.set_shadow("ghost")
        with pytest.raises(KeyError, match="unknown policy"):
            # a typo'd republish must fail loudly, never mint a dead entry
            # while the real policy keeps serving stale weights
            pred.update_params({}, policy="ghost")
        with pytest.raises(KeyError, match="unknown policy"):
            # validated in the CALLER's thread — an unknown id reaching the
            # scheduler would kill the one thread the plane runs on
            pred.put_task(
                np.zeros(STATE, np.uint8), lambda *a: None, policy="ghost"
            )
        pred.add_policy("ok_2", {})
        with pytest.raises(ValueError, match="fraction"):
            pred.set_canary("ok_2", 1.5)
        pred.set_canary("ok_2", 0.5)
        pred.set_canary("ok_2", 0)  # 0 clears
        assert pred._canary is None
    finally:
        pred.stop()


def test_raising_callback_does_not_kill_the_scheduler():
    """One bad caller's exception must not take down the one thread the
    whole serving plane runs on — it is counted, and service continues."""
    pred, _ = _null_pred(batch_size=4, queue_depth=64)
    served = []
    done = threading.Event()
    pred.start()
    try:
        pred.put_task(
            np.zeros(STATE, np.uint8),
            lambda a, v, lp: (_ for _ in ()).throw(RuntimeError("bad cb")),
        )
        pred.put_task(
            np.zeros(STATE, np.uint8),
            lambda a, v, lp: (served.append(a), done.set()),
        )
        assert done.wait(20), "scheduler died on the raising callback"
        assert served and pred.threads[0].is_alive()
        assert _pred_scalar("callback_errors_total") == 1
    finally:
        pred.stop()
        pred.join(timeout=5)


def test_stop_delivers_shutdown_reject_to_queued_tasks():
    """A task queued when stop() wins the race gets the promised typed
    "shutdown" reject — a caller waiting on either callback must not
    hang."""
    pred, _ = _null_pred(batch_size=4, queue_depth=16, slo_ms=1000.0)
    sheds = []
    served = []
    for _ in range(3):
        pred.put_block_task(
            np.zeros((2, *STATE), np.uint8),
            lambda a, v, lp: served.append(a),
            shed_callback=lambda r: sheds.append(r),
        )
    # scheduler was never started: stop() must still resolve the queue
    pred.stop()
    pred.threads[0].start()  # runs straight into teardown drain
    pred.join(timeout=10)
    assert served == []
    assert len(sheds) == 3
    assert all(r.reason == "shutdown" for r in sheds)


def _real_model_and_params(seed):
    import jax

    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.models.a3c import BA3CNet

    cfg = BA3CConfig(image_size=(16, 16), fc_units=16, num_actions=N_ACTIONS)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    params = model.init(
        jax.random.PRNGKey(seed), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    return cfg, model, params


def test_shadow_sees_identical_states_and_never_reaches_callers():
    """Canary/shadow parity (ISSUE 9): the shadow policy is dispatched the
    IDENTICAL batch, and the caller's actions come from the primary policy
    only — greedy mode makes both sides deterministic."""
    import jax

    telemetry.reset_all()
    cfg, model, params0 = _real_model_and_params(0)
    _, _, params1 = _real_model_and_params(7)
    pred = BatchedPredictor(model, params0, batch_size=8, greedy=True)
    pred.add_policy("shadow_p", params1)
    pred.set_shadow("shadow_p")
    taps = []
    pred.shadow_tap = lambda states, actions, pid: taps.append(
        (states, actions, pid)
    )
    rng = np.random.default_rng(3)
    states = rng.integers(0, 255, (5, *cfg.state_shape)).astype(np.uint8)
    got = []
    evt = threading.Event()
    pred.put_block_task(states, lambda a, v, lp: (got.append(a), evt.set()))
    pred.start()
    try:
        assert evt.wait(60)
        deadline = time.monotonic() + 30
        while not taps and time.monotonic() < deadline:
            time.sleep(0.01)
        assert taps, "shadow mirror never fetched through the tap"

        def greedy_actions(params):
            out = model.apply({"params": jax.device_get(params)}, states)
            return np.argmax(np.asarray(out.logits), axis=-1)

        # callers got the PRIMARY policy's deterministic actions
        np.testing.assert_array_equal(got[0], greedy_actions(params0))
        tap_states, tap_actions, pid = taps[0]
        assert pid == "shadow_p"
        # the shadow saw the identical states...
        np.testing.assert_array_equal(tap_states, states)
        # ...and produced the SHADOW policy's actions, which went nowhere
        np.testing.assert_array_equal(tap_actions, greedy_actions(params1))
        assert _pred_scalar("shadow_rows_total") == 5
    finally:
        pred.stop()
        pred.join(timeout=5)


# -- packed-fetch shapes (make_fwd_sample satellite) ------------------------


def test_fwd_sample_packed_shapes():
    """greedy=True drops the duplicated argmax row: [3, B] vs [4, B] —
    both shapes are pinned by their own audit entries (T5)."""
    import jax

    cfg, model, params = _real_model_and_params(0)
    B = 4
    states = jax.ShapeDtypeStruct((B, *cfg.state_shape), np.uint8)
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    p_aval = jax.eval_shape(lambda: params)
    sampling = jax.eval_shape(make_fwd_sample(model, False), p_aval, states, key)
    greedy = jax.eval_shape(make_fwd_sample(model, True), p_aval, states, key)
    assert sampling.shape == (4, B)
    assert greedy.shape == (3, B)


def test_greedy_predict_batch_actions_are_argmax():
    cfg, model, params = _real_model_and_params(0)
    pred = BatchedPredictor(model, params, batch_size=8, greedy=True)
    rng = np.random.default_rng(0)
    states = rng.integers(0, 255, (5, *cfg.state_shape)).astype(np.uint8)
    actions, values, greedy = pred.predict_batch(states)
    np.testing.assert_array_equal(actions, greedy)
    assert values.shape == (5,)
    pred.stop()


# -- audit trace stability of continuous batching ---------------------------


def test_audit_tripwire_clean_through_serving_run(monkeypatch):
    """BA3C_AUDIT=1: a serving run through the continuous-batching
    scheduler — mixed singles, blocks of several sizes, an oversize
    chunked sync call — must introduce NO trace shape beyond the warmed
    pow-2 buckets (ISSUE 9 acceptance)."""
    monkeypatch.setenv("BA3C_AUDIT", "1")
    from distributed_ba3c_tpu import audit

    cfg, model, params = _real_model_and_params(0)
    pred = BatchedPredictor(model, params, batch_size=8)
    pred.warmup(cfg.state_shape)  # compiles buckets 1..8, arms the tripwire
    tw = audit.live_tripwires()["predict.server"]
    assert tw.armed
    served = []
    done = threading.Event()
    n_expected = 2 + 3  # 2 blocks + 3 singles
    rng = np.random.default_rng(1)

    def block_cb(a, v, lp):
        served.append(len(a))
        if len(served) == n_expected:
            done.set()

    def row_cb(a, v, lp):
        served.append(1)
        if len(served) == n_expected:
            done.set()

    pred.start()
    try:
        for k in (3, 8):
            pred.put_block_task(
                rng.integers(0, 255, (k, *cfg.state_shape)).astype(np.uint8),
                block_cb,
            )
        for _ in range(3):
            pred.put_task(
                rng.integers(0, 255, cfg.state_shape).astype(np.uint8), row_cb
            )
        assert done.wait(60), (
            "serving callbacks missing — the scheduler likely died on an "
            "AuditError retrace"
        )
        # oversize sync call: chunked to the warmed bucket, never retraced
        pred.predict_batch(
            rng.integers(0, 255, (20, *cfg.state_shape)).astype(np.uint8)
        )
        assert pred.threads[0].is_alive()
        assert tw.armed
    finally:
        pred.stop()
        pred.join(timeout=5)


# -- the masters' shed fallback (reply path) --------------------------------


class _SheddingPredictor:
    """Predictor stub that sheds EVERYTHING with a typed reject."""

    num_actions = N_ACTIONS

    def put_block_task(self, states, cb, shed_callback=None, **kw):
        shed_callback(ShedReject("deadline"))
        return False

    def put_task(self, state, cb, shed_callback=None, **kw):
        shed_callback(ShedReject("queue_full"))
        return False


def test_master_shed_fallback_keeps_lockstep_alive(tmp_path):
    """A shed block reply falls back to uniform-random actions with the
    TRUE fallback behavior logp (-log A) so the lockstep server keeps
    stepping and V-trace stays exact."""
    from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
    from distributed_ba3c_tpu.actors.simulator import BlockClientState

    telemetry.reset_all()
    master = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c",
        _SheddingPredictor(),
    )
    try:
        ident = b"srv-0"
        master.clients[ident] = BlockClientState(ident, 4)
        states = np.zeros((4, *STATE), np.uint8)
        master._on_block_state(states, ident)
        blk = master.clients[ident]
        assert len(blk.steps) == 1, "shed fallback did not record the step"
        step = blk.steps[0]
        assert ((step.actions >= 0) & (step.actions < N_ACTIONS)).all()
        np.testing.assert_allclose(step.values, 0.0)
        np.testing.assert_allclose(step.logps, -np.log(N_ACTIONS), rtol=1e-6)
        assert master.send_queue.qsize() == 1  # the action reply went out
        # per-env path too
        e_ident = b"env-1"
        master._on_state(np.zeros(STATE, np.uint8), e_ident)
        assert len(master.clients[e_ident].memory) == 1
        assert master.send_queue.qsize() == 2
        scal = telemetry.registry("master").scalars()
        assert scal["predictor_shed_fallbacks_total"] == 5  # 4 rows + 1
    finally:
        master.close()
