"""run_with_resume.sh resume-gate contract, with a stubbed train.py.

Same gate as launch_multihost.sh (tests/test_launch_script.py): resume
only from a FINALIZED checkpoint (checkpoint.json "latest" non-null).
CheckpointManager creates the checkpoints dir at startup, so a stall-kill
before the first save must NOT make subsequent attempts --load an empty
dir (exit-1 crash burning MAX_RESTARTS on a run that never trained).
jax-free: the script resolves train.py relative to its own location, so
the stub lives in a copied tree.
"""

import json
import os
import shutil
import stat
import subprocess

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(__file__)), "scripts", "run_with_resume.sh"
)

_STUB = r"""#!/usr/bin/env python3
import json, os, sys
calls_path = os.environ["STUB_CALLS"]
calls = json.load(open(calls_path)) if os.path.exists(calls_path) else []
calls.append(sys.argv[1:])
json.dump(calls, open(calls_path, "w"))
sys.exit(0)
"""


def _run(tmp_path, meta):
    """Copy the script into a stub tree; return the first attempt's argv."""
    tree = tmp_path / "tree"
    (tree / "scripts").mkdir(parents=True)
    shutil.copy(_SCRIPT, tree / "scripts" / "run_with_resume.sh")
    stub = tree / "train.py"
    stub.write_text(_STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    logdir = tree / "runs" / "x"
    (logdir / "checkpoints").mkdir(parents=True)
    if meta is not None:
        (logdir / "checkpoints" / "checkpoint.json").write_text(
            json.dumps(meta)
        )
    calls = tree / "calls.json"
    env = dict(os.environ)
    env["STUB_CALLS"] = str(calls)
    p = subprocess.run(
        ["bash", str(tree / "scripts" / "run_with_resume.sh"),
         str(logdir), "2", "60", "--", "--logdir", str(logdir)],
        cwd=tree, env=env, capture_output=True, text=True, timeout=60,
    )
    assert p.returncode == 0, p.stderr
    return json.load(open(calls))[0]


def test_finalized_checkpoint_resumes(tmp_path):
    argv = _run(tmp_path, {"all": [80], "latest": 80})
    assert "--load" in argv
    assert argv[argv.index("--load") + 1].endswith("checkpoints")


def test_unfinalized_meta_starts_fresh(tmp_path):
    argv = _run(tmp_path, {"all": [], "latest": None})
    assert "--load" not in argv


def test_startup_created_dir_without_meta_starts_fresh(tmp_path):
    argv = _run(tmp_path, None)
    assert "--load" not in argv
