"""Fused on-device actor+learner: sharded step runs, learns, tracks episodes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.envs.jaxenv import pong
from distributed_ba3c_tpu.fused.loop import create_fused_state, make_fused_step
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import make_optimizer
from distributed_ba3c_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def fused_setup():
    cfg = BA3CConfig(num_actions=pong.num_actions, fc_units=16)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    mesh = make_mesh()
    n_data = mesh.shape["data"]
    n_envs = 2 * n_data
    step = make_fused_step(model, opt, cfg, mesh, pong, rollout_len=3)

    def make_state():
        return step.put(
            create_fused_state(
                jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
                n_shards=n_data,
            )
        )

    return cfg, step, make_state, n_envs


@pytest.fixture
def fused(fused_setup):
    # fresh state per test: the step DONATES its input state, so a shared
    # module-scoped state would be deleted after the first test touches it
    cfg, step, make_state, n_envs = fused_setup
    return cfg, step, make_state(), n_envs


def test_fused_step_advances_and_is_finite(fused):
    cfg, step, state, n_envs = fused
    state, metrics = step(state, cfg.entropy_beta)
    state, metrics = step(state, cfg.entropy_beta)
    assert int(state.train.step) == 2
    for k, v in metrics.items():
        assert np.isfinite(float(v)), k
    assert state.obs_stack.shape == (n_envs, 84, 84, cfg.frame_history)


def test_fused_params_update_and_lr_zero_freezes(fused):
    cfg, step, state, _ = fused
    p0 = np.asarray(jax.tree_util.tree_leaves(state.train.params)[0]).copy()
    state, _ = step(state, cfg.entropy_beta, learning_rate=0.0)
    p1 = np.asarray(jax.tree_util.tree_leaves(state.train.params)[0])
    np.testing.assert_array_equal(p0, p1)
    state, _ = step(state, cfg.entropy_beta, learning_rate=1e-3)
    p2 = np.asarray(jax.tree_util.tree_leaves(state.train.params)[0])
    assert not np.allclose(p1, p2)


def test_fused_rng_differs_across_shards(fused):
    """Each mesh shard must consume its own RNG stream — identical streams
    would roll identical envs and silently divide the effective batch."""
    cfg, step, state, n_envs = fused
    for _ in range(5):
        state, _ = step(state, cfg.entropy_beta)
    # after a few steps, per-shard env states must have diverged
    ball = np.asarray(state.env_state.ball_xy)  # [n_envs, 2]
    n_data = step.mesh.shape["data"]
    per_shard = ball.reshape(n_data, n_envs // n_data, 2)
    # shard 0's envs should not all equal shard 1's envs
    assert not np.allclose(per_shard[0], per_shard[1])


def test_greedy_eval_runs_and_bounds(fused_setup):
    """On-device greedy Evaluator: completes episodes, returns Pong-bounded
    means, and is deterministic given the same params+key."""
    from distributed_ba3c_tpu.fused.loop import make_greedy_eval
    from distributed_ba3c_tpu.parallel.mesh import make_mesh

    cfg, step, make_state, n_envs = fused_setup
    state = make_state()
    mesh = make_mesh()
    n_data = mesh.shape["data"]
    evaluate = make_greedy_eval(
        BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units),
        cfg,
        mesh,
        pong,
        n_envs=2 * n_data,
        max_steps=900,
    )
    params = jax.device_get(state.train.params)
    mean, mx, n = evaluate(params, jax.random.PRNGKey(7))
    assert n >= 1, "greedy eval completed no episodes in 900 steps"
    assert -21.0 <= mean <= 21.0 and -21.0 <= mx <= 21.0
    mean2, mx2, n2 = evaluate(params, jax.random.PRNGKey(7))
    assert (mean2, mx2, n2) == (mean, mx, n)


def test_fused_episode_accounting(fused):
    """Run enough steps that the still-ish random policy finishes matches;
    episode counters must rise and mean return must be within Pong bounds."""
    cfg, step, state, _ = fused
    for _ in range(10):
        state, metrics = step(state, cfg.entropy_beta)
    eps = float(metrics["episodes"])
    if eps > 0:
        mean_ret = float(metrics["episode_return_sum"]) / eps
        assert -21.0 <= mean_ret <= 21.0
    # ep_return accumulators stay bounded
    assert np.all(np.abs(np.asarray(state.ep_return)) <= 21.0 + 1e-6)


def test_scanned_dispatch_matches_sequential_steps(fused_setup):
    """steps_per_dispatch=K parity against K sequential dispatches.

    With learning_rate=0 the params are frozen, so both variants consume the
    IDENTICAL key sequence and must produce bit-identical env trajectories,
    frame stacks, and episode counters — exercising the whole scan plumbing.
    (With a live lr, bit-equality across differently-compiled programs is
    not a sound contract: XLA fuses the scan body differently, a 1-ulp logit
    change flips a sampled action, and the RL trajectory is chaotic.)"""
    cfg, step, make_state, n_envs = fused_setup
    mesh = make_mesh()
    n_data = mesh.shape["data"]
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    K = 4
    step_k = make_fused_step(
        model, opt, cfg, mesh, pong, rollout_len=3, steps_per_dispatch=K
    )

    def fresh(putter):
        return putter(
            create_fused_state(
                jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
                n_shards=n_data,
            )
        )

    # --- lr=0: params frozen => trajectories must be bit-identical ---
    state_seq = fresh(step.put)
    for _ in range(K):
        state_seq, m_seq = step(state_seq, cfg.entropy_beta, learning_rate=0.0)
    state_scan = fresh(step_k.put)
    state_scan, m_scan = step_k(state_scan, cfg.entropy_beta, learning_rate=0.0)

    assert int(state_scan.train.step) == int(state_seq.train.step) == K
    np.testing.assert_array_equal(
        np.asarray(state_seq.obs_stack), np.asarray(state_scan.obs_stack)
    )
    np.testing.assert_array_equal(
        np.asarray(state_seq.ep_count), np.asarray(state_scan.ep_count)
    )
    np.testing.assert_array_equal(
        np.asarray(state_seq.ep_return), np.asarray(state_scan.ep_return)
    )
    # cumulative counters: scan's LAST-step metric == sequential's last
    assert float(m_scan["episodes"]) == float(m_seq["episodes"])
    assert float(m_scan["episode_return_sum"]) == float(
        m_seq["episode_return_sum"]
    )

    # --- live lr: the scanned program must actually train ---
    state_live = fresh(step_k.put)
    p0 = np.asarray(jax.tree_util.tree_leaves(state_live.train.params)[0]).copy()
    state_live, m_live = step_k(state_live, cfg.entropy_beta)
    assert int(state_live.train.step) == K
    p1 = np.asarray(jax.tree_util.tree_leaves(state_live.train.params)[0])
    assert not np.array_equal(p0, p1), "scanned dispatch did not update params"
    for k, v in m_live.items():
        assert np.isfinite(float(v)), k
