"""The SURVEY.md §7 minimum end-to-end slice, as a learning assertion.

Full CLI path: FakeEnv simulator processes → ZMQ → master → batched
predictor → TrainFeed → mesh-sharded sync learner → callbacks/eval — and the
policy must actually LEARN the scripted MDP (greedy optimum = 1.0/episode).
The reference could only validate this shape on a live cluster with an
overnight Atari curve (SURVEY.md §4); here it is a 2-minute CPU test.
"""

import json
import os

import pytest

from distributed_ba3c_tpu.cli import main
from distributed_ba3c_tpu.utils import sanitizer


@pytest.mark.slow
def test_cli_fake_env_learns(tmp_path):
    logdir = str(tmp_path / "log")
    sanitizer.reset()  # fresh registry in case earlier tests recorded
    rc = main(
        [
            "--env",
            "fake",
            "--simulator_procs",
            "4",
            "--batch_size",
            "32",
            "--image_size",
            "16",
            "--fc_units",
            "16",
            "--steps_per_epoch",
            "80",
            "--max_epoch",
            "2",
            "--nr_eval",
            "4",
            "--logdir",
            logdir,
        ]
    )
    assert rc == 0
    stats = json.load(open(os.path.join(logdir, "stat.json")))
    assert len(stats) == 2
    final = stats[-1]
    # greedy eval must have solved the MDP (optimal score 1.0)
    assert final["eval_mean_score"] >= 0.75, final
    # sampled rollouts should be clearly above the random-policy level too
    assert final["mean_score"] >= 0.4, final
    # checkpoints written
    assert os.path.isdir(os.path.join(logdir, "checkpoints"))
    # under BA3C_SANITIZE=1 (the CI sanitize job) the client table and the
    # plane queues were wrapped for the whole run: no cross-thread
    # structural writes, no second queue consumers (vacuous when disabled)
    assert sanitizer.findings() == [], sanitizer.findings()
