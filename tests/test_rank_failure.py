"""Rank-failure semantics for the multi-host gradient plane.

The reference had NO failure detection on its parameter-server plane
(SURVEY.md §5: a dead worker just stalled the queue). This framework defines
the semantics: when a rank dies, every survivor — wedged in the next
psum/save barrier — exits nonzero within a bounded time (LockstepWatchdog,
parallel/watchdog.py), and relaunching all ranks with ``--load`` on the
shared checkpoint dir resumes the run's schedule to completion.

Two layers:
- a fast unit test that the watchdog thread itself fires (and that beats
  defer it) — in a subprocess, since firing is ``os._exit(75)``;
- a slow end-to-end test that SIGKILLs one of two real jax.distributed
  ranks mid-soak, asserts the survivor's bounded-time nonzero exit, then
  completes the run by resuming both ranks from the shared checkpoints.
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
_REPO = os.path.dirname(os.path.dirname(_WORKER))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["BA3C_PARAM_DIGEST"] = "1"
    return env


def _digests(out: str) -> list:
    return [
        l.split("param_digest ", 1)[1]
        for l in out.splitlines()
        if "param_digest " in l
    ]


def test_watchdog_fires_exit75_and_beats_defer():
    """Unit semantics in a subprocess: beats keep it alive past several
    timeouts; stopping the beats makes it exit EXIT_CODE promptly."""
    code = r"""
import sys, time
sys.path.insert(0, %r)
from distributed_ba3c_tpu.parallel.watchdog import LockstepWatchdog, EXIT_CODE
with LockstepWatchdog(1.0, what="unit") as wd:
    for _ in range(8):          # 2s of life > 2 timeouts, held by beats
        time.sleep(0.25)
        wd.beat()
    print("BEATS_HELD", flush=True)
    time.sleep(30)              # no more beats: watchdog must fire
print("UNREACHABLE", flush=True)
""" % (_REPO,)
    t0 = time.monotonic()
    p = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=60, env=_env(),
    )
    dt = time.monotonic() - t0
    assert "BEATS_HELD" in p.stdout, p.stdout + p.stderr
    assert "UNREACHABLE" not in p.stdout
    assert p.returncode == 75, (p.returncode, p.stdout, p.stderr)
    assert dt < 20, f"watchdog took {dt:.1f}s to fire a 1s timeout"


def test_slow_epoch_with_subepoch_beats_does_not_fire():
    """VERDICT r4 weak #4: an epoch whose TOTAL time is 2x the timeout must
    not fire as long as each proven-progress window (compute / eval / save)
    stays under the limit — the loop beats at each of those points."""
    code = r"""
import sys, time
sys.path.insert(0, %r)
from distributed_ba3c_tpu.parallel.watchdog import LockstepWatchdog
with LockstepWatchdog(0.6, what="unit") as wd:
    for _ in range(3):          # 3 "epochs" of 1.2s each (2x the timeout)
        time.sleep(0.4); wd.beat()   # compute window -> metrics fetch
        time.sleep(0.4); wd.beat()   # slow eval window
        time.sleep(0.4); wd.beat()   # collective save window
print("SURVIVED", flush=True)
""" % (_REPO,)
    p = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=60, env=_env(),
    )
    assert p.returncode == 0, (p.returncode, p.stdout, p.stderr)
    assert "SURVIVED" in p.stdout


def test_gradual_window_creep_raises_limit():
    """Healthy windows that creep past the configured timeout raise the
    effective limit to MARGIN x the slowest observed window instead of
    killing a correctly operating run; a real stall still fires (bounded
    by the raised limit)."""
    code = r"""
import sys, time
sys.path.insert(0, %r)
from distributed_ba3c_tpu.parallel.watchdog import LockstepWatchdog
with LockstepWatchdog(0.8, what="unit") as wd:
    # each window fits the CURRENT limit with real headroom (the first
    # beat doesn't ratchet — pre-first-beat runs on the 3x grace), and
    # they grow past the configured 0.8s: 0.6 -> derived 1.2; 0.9 -> 1.8
    # (still under the 2.4s first-timeout cap)
    for w in (0.4, 0.6, 0.9):
        time.sleep(w)
        wd.beat()
    assert wd._derived_limit <= wd.first_timeout_s  # ratchet is capped
    print("CREPT", flush=True)
    time.sleep(30)              # stall: must fire at the raised limit
print("UNREACHABLE", flush=True)
""" % (_REPO,)
    t0 = time.monotonic()
    p = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=60, env=_env(),
    )
    dt = time.monotonic() - t0
    assert "CREPT" in p.stdout, p.stdout + p.stderr
    assert p.returncode == 75, (p.returncode, p.stdout, p.stderr)
    assert dt < 25, f"raised-limit fire took {dt:.1f}s"


def test_graced_window_survives_and_does_not_ratchet():
    """grace() before a compile-heavy window (the first eval jit) arms the
    generous first-beat deadline for that window only — and the graced
    window is excluded from the derived-limit ratchet, so a long compile
    doesn't weaken later detection."""
    code = r"""
import sys, time
sys.path.insert(0, %r)
from distributed_ba3c_tpu.parallel.watchdog import LockstepWatchdog
with LockstepWatchdog(0.5, what="unit") as wd:
    time.sleep(0.2); wd.beat()       # first (compute) window
    wd.grace()
    time.sleep(1.0); wd.beat()       # compile-heavy eval window, 2x timeout
    assert wd._derived_limit == 0.5, wd._derived_limit   # no ratchet
    print("GRACED", flush=True)
    time.sleep(30)                   # real stall: fires at the 0.5s limit
print("UNREACHABLE", flush=True)
""" % (_REPO,)
    t0 = time.monotonic()
    p = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=60, env=_env(),
    )
    dt = time.monotonic() - t0
    assert "GRACED" in p.stdout, p.stdout + p.stderr
    assert p.returncode == 75, (p.returncode, p.stdout, p.stderr)
    assert dt < 15, f"post-grace fire took {dt:.1f}s"


def test_resolve_timeout_sentinel_disables(monkeypatch):
    """--rank_stall_timeout -1 disables the watchdog even multi-host
    (ADVICE r4 #2); 0 still means 'default when multi-host'."""
    import jax

    from distributed_ba3c_tpu.parallel import watchdog

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    assert watchdog.resolve_timeout(-1) == 0.0
    assert watchdog.resolve_timeout(0) == watchdog.DEFAULT_TIMEOUT_S
    assert watchdog.resolve_timeout(250.0) == 250.0
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    assert watchdog.resolve_timeout(250.0) == 0.0


def _spawn_soak(rank, coord, logdir, max_epoch, load, stall_timeout):
    return subprocess.Popen(
        [
            sys.executable, _WORKER, str(rank), "2", coord, "soak",
            logdir, str(max_epoch), "load" if load else "fresh",
            str(stall_timeout),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=_env(),
        cwd=_REPO,
    )


@pytest.mark.slow
def test_rank_death_bounded_exit_then_resume_completes(tmp_path):
    logdir = str(tmp_path / "soak")
    coord = f"127.0.0.1:{_free_port()}"
    stall_timeout = 40.0
    max_epoch = 8

    p0 = _spawn_soak(0, coord, logdir, max_epoch, False, stall_timeout)
    p1 = _spawn_soak(1, coord, logdir, max_epoch, False, stall_timeout)

    # stream rank 0's output so we can kill rank 1 only after real progress
    # (first epochs done => compile finished, checkpoints exist)
    lines0: list = []

    def _reader():
        for line in p0.stdout:
            lines0.append(line)

    t = threading.Thread(target=_reader, daemon=True)
    t.start()

    try:
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if len(_digests("".join(lines0))) >= 2:
                break
            if p0.poll() is not None:
                pytest.fail("rank 0 exited before the kill: " + "".join(lines0))
            time.sleep(0.5)
        else:
            pytest.fail("no progress within 300s: " + "".join(lines0))

        t_kill = time.monotonic()
        os.kill(p1.pid, signal.SIGKILL)

        # bounded-time failure: watchdog timeout + poll granularity + exit,
        # with CI margin — the point is MINUTES, not forever
        try:
            p0.wait(timeout=stall_timeout + 120)
        except subprocess.TimeoutExpired:
            pytest.fail(
                "survivor still alive %.0fs after peer death: undefined-hang "
                "semantics are back" % (time.monotonic() - t_kill)
            )
        detect_s = time.monotonic() - t_kill
        out0 = "".join(lines0)
        assert p0.returncode != 0, (
            "survivor exited 0 despite losing its peer:\n" + out0
        )
        # either our watchdog fired (75) or the runtime surfaced the dead
        # peer as an error — both are defined, bounded-time failures; the
        # watchdog is the guaranteed backstop
        assert "CLI_RC 0" not in out0
    finally:
        for p in (p0, p1):
            if p.poll() is None:
                p.kill()
        t.join(timeout=10)

    phase_a = _digests("".join(lines0))
    assert phase_a, "no digests recorded before the failure"

    # --- resume: relaunch BOTH ranks with --load on the shared ckpts ---
    coord2 = f"127.0.0.1:{_free_port()}"
    q0 = _spawn_soak(0, coord2, logdir, max_epoch, True, stall_timeout)
    q1 = _spawn_soak(1, coord2, logdir, max_epoch, True, stall_timeout)
    outs = []
    for q in (q0, q1):
        try:
            out, _ = q.communicate(timeout=600)
        finally:
            if q.poll() is None:
                q.kill()
        outs.append(out)
        assert q.returncode == 0, out
        assert "CLI_RC 0" in out, out
    d0, d1 = _digests(outs[0]), _digests(outs[1])
    assert d0 and d0 == d1, (
        "resumed ranks diverged:\nrank0 %s\nrank1 %s" % (d0, d1)
    )
    # schedule continued, not restarted: resumed leg trains only the
    # remaining epochs (the soak is 8 epochs total; >=2 ran before the kill)
    assert len(d0) < max_epoch, (len(d0), d0)

    print(
        "rank-failure e2e: detect+exit %.1fs after SIGKILL; resume ran %d "
        "epochs to completion in lockstep" % (detect_s, len(d0))
    )
