"""The north-star verification tool, tested before it is trusted (VERDICT
r4 weak #2): `scripts/eval_sweep.py` + `train/eval_tools.py` are what the
headline "independently verified >= threshold at step N" claim rests on.

Coverage:
- make_checkpoint_evaluator's n_eval rounding (load-bearing: envs shard
  over the mesh data axis; a non-multiple silently drops envs and makes
  completion gates unsatisfiable);
- a REAL sweep over a real tiny fused run's kept checkpoints, where no
  episode can finish inside the horizon — the 0.95-completion gate must
  refuse to certify a crossing (incomplete evals cannot make claims);
- earliest-crossing selection + JSON contract over real checkpoint
  enumeration with a scripted evaluator (step-indexed means);
- --steps subset narrowing.
"""

import importlib.util
import json
import os

import pytest

_SWEEP_PATH = os.path.join(
    os.path.dirname(os.path.dirname(__file__)), "scripts", "eval_sweep.py"
)


def _load_sweep_module():
    spec = importlib.util.spec_from_file_location("eval_sweep", _SWEEP_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def tiny_run(tmp_path_factory):
    """A real fused run with 3 kept checkpoints (steps 2, 4, 6)."""
    from distributed_ba3c_tpu.cli import main

    logdir = str(tmp_path_factory.mktemp("sweep") / "run")
    rc = main([
        "--trainer", "tpu_fused_ba3c",
        "--env", "jax:pong",
        "--batch_size", "8",
        "--rollout_len", "2",
        "--fc_units", "16",
        "--steps_per_epoch", "2",
        "--max_epoch", "3",
        "--nr_eval", "1",
        "--eval_max_steps", "8",
        "--max_to_keep", "64",
        "--logdir", logdir,
    ])
    assert not rc
    return logdir


def test_n_eval_rounds_up_to_data_axis_multiple(tmp_path):
    from distributed_ba3c_tpu.parallel.mesh import DATA_AXIS, make_mesh
    from distributed_ba3c_tpu.train.eval_tools import make_checkpoint_evaluator

    n_data = make_mesh().shape[DATA_AXIS]
    assert n_data == 8  # the conftest's forced 8-device CPU mesh
    for requested, expected in [
        (1, 8), (7, 8), (8, 8), (9, 16), (128, 128), (0, 8),
    ]:
        _, _, _, n_eval = make_checkpoint_evaluator(
            "jax:pong", str(tmp_path / "ckpts"), requested, 16, fc_units=16
        )
        assert n_eval == expected, (requested, n_eval)
        assert n_eval % n_data == 0


def _run_sweep(monkeypatch, tmp_path, argv_tail, mod=None):
    """Drive the sweep script's main() with argv; return the written JSON.

    Pass ``mod`` to run a module whose evaluator factory was patched
    beforehand (the scripted-evaluator tests)."""
    mod = mod or _load_sweep_module()
    out = str(tmp_path / "sweep.json")
    monkeypatch.setattr(
        "sys.argv", ["eval_sweep.py", "--out", out] + argv_tail
    )
    mod.main()
    return json.load(open(out)), mod


def test_incomplete_evals_cannot_certify_crossing(
    monkeypatch, tiny_run, tmp_path
):
    """Real checkpoints, real restores, real on-device eval — but no Pong
    episode can finish in an 8-step horizon, so n==0 for every step and
    the completion gate must report earliest_at_threshold=None even with a
    trivially low threshold."""
    summary, _ = _run_sweep(monkeypatch, tmp_path, [
        "--env", "jax:pong",
        "--load", os.path.join(tiny_run, "checkpoints"),
        "--nr_eval", "8", "--max_steps", "8",
        "--threshold", "-1000", "--fc_units", "16",
    ])
    assert [r["step"] for r in summary["results"]] == [2, 4, 6]
    for r in summary["results"]:
        assert r["episodes"] == 0
        assert r["eval_mean"] is None
    assert summary["earliest_at_threshold"] is None


def _scripted_evaluator(mod, means_by_step):
    """Patch the sweep's evaluator factory: real CheckpointManager + real
    restore target, scripted eval results keyed by the restored step."""
    import distributed_ba3c_tpu.train.eval_tools as et

    real = et.make_checkpoint_evaluator

    def fake(env_spec, load, nr_eval, max_steps, fc_units=512):
        mgr, target, _evaluate, n_eval = real(
            env_spec, load, nr_eval, max_steps, fc_units
        )
        calls = {"step": None}

        real_restore = mgr.restore

        def restore(t, step=None):
            state = real_restore(t, step)
            calls["step"] = int(state.step)
            return state

        mgr.restore = restore

        def evaluate(_params, _seed):
            mean = means_by_step[calls["step"]]
            return mean, mean + 1.0, n_eval  # full completion

        return mgr, target, evaluate, n_eval

    mod.make_checkpoint_evaluator = fake


def test_earliest_crossing_selected(monkeypatch, tiny_run, tmp_path):
    mod = _load_sweep_module()
    _scripted_evaluator(mod, {2: 10.0, 4: 19.0, 6: 20.0})
    summary, _ = _run_sweep(monkeypatch, tmp_path, [
        "--env", "jax:pong",
        "--load", os.path.join(tiny_run, "checkpoints"),
        "--nr_eval", "8", "--max_steps", "8",
        "--threshold", "18", "--fc_units", "16",
    ], mod=mod)
    # earliest step clearing 18 is 4 — NOT the higher-scoring 6
    assert summary["earliest_at_threshold"]["step"] == 4
    assert summary["earliest_at_threshold"]["eval_mean"] == 19.0
    assert [r["step"] for r in summary["results"]] == [2, 4, 6]
    assert summary["threshold"] == 18


def test_steps_subset_narrows_sweep(monkeypatch, tiny_run, tmp_path):
    mod = _load_sweep_module()
    _scripted_evaluator(mod, {2: 10.0, 4: 19.0, 6: 20.0})
    summary, _ = _run_sweep(monkeypatch, tmp_path, [
        "--env", "jax:pong",
        "--load", os.path.join(tiny_run, "checkpoints"),
        "--steps", "6",
        "--nr_eval", "8", "--max_steps", "8",
        "--threshold", "18", "--fc_units", "16",
    ], mod=mod)
    assert [r["step"] for r in summary["results"]] == [6]
    assert summary["earliest_at_threshold"]["step"] == 6


def test_midsweep_failure_keeps_prior_results_and_continues(
    monkeypatch, tiny_run, tmp_path
):
    """One bad checkpoint (corrupt save, tunnel wedge surfacing as a
    device error) must not discard the evals already done — the sweep IS
    the verification artifact. The failed step gets an error record, the
    sweep continues, and the summary marks itself incomplete."""
    mod = _load_sweep_module()
    import distributed_ba3c_tpu.train.eval_tools as et

    real = et.make_checkpoint_evaluator

    def fake(env_spec, load, nr_eval, max_steps, fc_units=512):
        mgr, target, _e, n_eval = real(
            env_spec, load, nr_eval, max_steps, fc_units
        )
        calls = {"step": None}
        real_restore = mgr.restore

        def restore(t, step=None):
            if step == 4:
                raise RuntimeError("corrupt checkpoint")
            state = real_restore(t, step)
            calls["step"] = int(state.step)
            return state

        mgr.restore = restore
        means = {2: 10.0, 6: 20.0}
        return (
            mgr, target,
            (lambda p, s: (means[calls["step"]], 21.0, n_eval)), n_eval,
        )

    mod.make_checkpoint_evaluator = fake
    summary, _ = _run_sweep(monkeypatch, tmp_path, [
        "--env", "jax:pong",
        "--load", os.path.join(tiny_run, "checkpoints"),
        "--nr_eval", "8", "--max_steps", "8",
        "--threshold", "18", "--fc_units", "16",
    ], mod=mod)
    assert [r["step"] for r in summary["results"]] == [2, 4, 6]
    assert "corrupt checkpoint" in summary["results"][1]["error"]
    assert summary["results"][2]["eval_mean"] == 20.0  # continued past it
    assert summary["earliest_at_threshold"]["step"] == 6
    assert summary["sweep_complete"] is False


def test_partial_completion_below_gate_is_not_certified(
    monkeypatch, tiny_run, tmp_path
):
    """n under the 0.95 gate: a high mean over too few episodes must not
    certify (the round-3 lesson: long rallies leave envs unfinished —
    int(0.95*8)=7, so 7/8 still passes but 6/8 must not)."""
    mod = _load_sweep_module()
    import distributed_ba3c_tpu.train.eval_tools as et

    real = et.make_checkpoint_evaluator

    def fake(env_spec, load, nr_eval, max_steps, fc_units=512):
        mgr, target, _e, n_eval = real(
            env_spec, load, nr_eval, max_steps, fc_units
        )
        return mgr, target, (lambda p, s: (99.0, 99.0, int(0.75 * n_eval))), n_eval

    mod.make_checkpoint_evaluator = fake
    summary, _ = _run_sweep(monkeypatch, tmp_path, [
        "--env", "jax:pong",
        "--load", os.path.join(tiny_run, "checkpoints"),
        "--nr_eval", "8", "--max_steps", "8",
        "--threshold", "18", "--fc_units", "16",
    ], mod=mod)
    assert summary["earliest_at_threshold"] is None
    assert all(r["eval_mean"] == 99.0 for r in summary["results"])
