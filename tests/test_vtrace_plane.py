"""V-trace rollout plane: segment assembly, RolloutFeed, vtrace train step."""

import queue

import jax
import numpy as np
import pytest

from distributed_ba3c_tpu.actors.vtrace_master import VTraceSimulatorMaster, _Step
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.data.dataflow import RolloutFeed
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import make_optimizer
from distributed_ba3c_tpu.parallel.mesh import make_mesh
from distributed_ba3c_tpu.parallel.train_step import create_train_state
from distributed_ba3c_tpu.parallel.vtrace_step import make_vtrace_train_step


class _NullPredictor:
    def put_task(self, state, cb, **kw):
        raise AssertionError("unused")


def _segment(T=4, shape=(6, 6, 2), seed=0):
    rng = np.random.default_rng(seed)
    return {
        "state": rng.integers(0, 255, (T, *shape), np.uint8),
        "action": rng.integers(0, 4, (T,), np.int32),
        "reward": rng.normal(size=(T,)).astype(np.float32),
        "done": np.zeros((T,), np.float32),
        "behavior_log_probs": -np.abs(rng.normal(size=(T,))).astype(np.float32),
        "bootstrap_state": rng.integers(0, 255, shape, np.uint8),
    }


def test_master_emits_fixed_length_segments(tmp_path):
    m = VTraceSimulatorMaster(
        f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c", _NullPredictor(),
        unroll_len=3,
    )
    ident = b"sim-0"
    client = m.clients[ident]
    # simulate 7 completed transitions (one per _on_state + attach)
    for t in range(7):
        client.memory.append(_Step(np.full((4, 4), t, np.uint8), t % 4, -0.5))
        client.memory[-1].reward = float(t)
        client.memory[-1].done = t == 4  # an episode boundary mid-stream
        m._maybe_emit(ident)
    segs = []
    while True:
        try:
            segs.append(m.queue.get_nowait())
        except queue.Empty:
            break
    assert len(segs) == 2  # 7 transitions -> two full 3-unrolls + 1 leftover
    s0 = segs[0]
    assert s0["state"].shape == (3, 4, 4)
    np.testing.assert_array_equal(s0["reward"], [0.0, 1.0, 2.0])
    # bootstrap of segment 0 is the state of transition 3
    assert s0["bootstrap_state"][0, 0] == 3
    # segment 1 covers t=3..5 and carries the episode boundary at t=4
    np.testing.assert_array_equal(segs[1]["done"], [0.0, 1.0, 0.0])
    assert len(client.memory) == 1  # leftover t=6


def test_rollout_feed_time_major():
    q = queue.Queue()
    for i in range(4):
        q.put(_segment(T=4, seed=i))
    feed = RolloutFeed(q, batch_size=4)
    feed.start()
    batch = feed.next_batch(timeout=10)
    feed.stop()
    assert batch["state"].shape == (4, 4, 6, 6, 2)  # [T, B, ...]
    assert batch["bootstrap_state"].shape == (4, 6, 6, 2)
    # check time-major transpose is correct for one known element
    seg0 = _segment(T=4, seed=0)
    np.testing.assert_array_equal(batch["action"][:, 0], seg0["action"])


@pytest.fixture(scope="module")
def vtrace_setup():
    cfg = BA3CConfig(
        image_size=(16, 16), fc_units=16, num_actions=4, local_time_max=4
    )
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    optimizer = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    mesh = make_mesh()
    step = make_vtrace_train_step(model, optimizer, cfg, mesh)
    state = create_train_state(jax.random.PRNGKey(0), model, cfg, optimizer)
    return cfg, step, state


def test_vtrace_step_runs_and_updates(vtrace_setup):
    cfg, step, state = vtrace_setup
    T, B = cfg.local_time_max, 16
    rng = np.random.default_rng(0)
    batch = {
        "state": rng.integers(0, 255, (T, B, *cfg.state_shape), np.uint8),
        "action": rng.integers(0, cfg.num_actions, (T, B), np.int32),
        "reward": rng.normal(size=(T, B)).astype(np.float32),
        "done": (rng.random((T, B)) < 0.1).astype(np.float32),
        "behavior_log_probs": -np.abs(rng.normal(size=(T, B))).astype(np.float32),
        "bootstrap_state": rng.integers(0, 255, (B, *cfg.state_shape), np.uint8),
    }
    batch = {
        k: jax.device_put(v, step.batch_sharding[k]) for k, v in batch.items()
    }
    state = jax.device_put(state, step.state_sharding)
    p0 = np.asarray(jax.tree_util.tree_leaves(state.params)[0]).copy()
    state, metrics = step(state, batch, cfg.entropy_beta)
    assert int(state.step) == 1
    for k, v in metrics.items():
        assert np.isfinite(float(v)), k
    assert 0.0 < float(metrics["mean_rho"]) <= 1.0
    p1 = np.asarray(jax.tree_util.tree_leaves(state.params)[0])
    assert not np.allclose(p0, p1)
