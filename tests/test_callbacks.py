"""Callback system, schedules, stats, checkpoint manager."""

import json
import os

import numpy as np
import pytest

from distributed_ba3c_tpu.train.callbacks import (
    Callback,
    Callbacks,
    HumanHyperParamSetter,
    HyperParamSetterWithFunc,
    PeriodicTrigger,
    ScheduledHyperParamSetter,
)
from distributed_ba3c_tpu.train.checkpoint import CheckpointManager
from distributed_ba3c_tpu.utils.stats import StatCounter, StatHolder


class _FakeTrainer:
    def __init__(self, log_dir=None):
        self.hyperparams = {}
        self.epoch_num = 0
        self.global_step = 0

        class C:
            pass

        self.config = C()
        self.config.log_dir = log_dir


def test_scheduled_setter_step_interp():
    tr = _FakeTrainer()
    cb = ScheduledHyperParamSetter("lr", [(1, 1.0), (5, 0.5), (10, 0.1)])
    cb.setup(tr)
    expect = {1: 1.0, 3: 1.0, 5: 0.5, 7: 0.5, 10: 0.1, 20: 0.1}
    for e, v in expect.items():
        tr.epoch_num = e
        cb.trigger_epoch()
        assert tr.hyperparams["lr"] == pytest.approx(v), f"epoch {e}"


def test_scheduled_setter_linear_interp():
    tr = _FakeTrainer()
    cb = ScheduledHyperParamSetter("beta", [(0, 0.0), (10, 1.0)], interp="linear")
    cb.setup(tr)
    tr.epoch_num = 5
    cb.trigger_epoch()
    assert tr.hyperparams["beta"] == pytest.approx(0.5)


def test_scheduled_setter_exp_interp():
    tr = _FakeTrainer()
    cb = ScheduledHyperParamSetter(
        "beta", [(1, 1e-2), (41, 1e-4)], interp="exp"
    )
    cb.setup(tr)
    tr.epoch_num = 21  # geometric midpoint of a 2-decade anneal
    cb.trigger_epoch()
    assert tr.hyperparams["beta"] == pytest.approx(1e-3)
    tr.epoch_num = 41
    cb.trigger_epoch()
    assert tr.hyperparams["beta"] == pytest.approx(1e-4)


def test_func_setter():
    tr = _FakeTrainer()
    cb = HyperParamSetterWithFunc("lr", lambda e, cur: 0.1 / (e + 1))
    cb.setup(tr)
    tr.epoch_num = 4
    cb.trigger_epoch()
    assert tr.hyperparams["lr"] == pytest.approx(0.02)


def test_human_setter(tmp_path):
    tr = _FakeTrainer(log_dir=str(tmp_path))
    cb = HumanHyperParamSetter("learning_rate")
    cb.setup(tr)
    (tmp_path / "hyper.txt").write_text("learning_rate: 0.042\nother: 1\n")
    cb.trigger_epoch()
    assert tr.hyperparams["learning_rate"] == pytest.approx(0.042)


def test_human_setter_moves_both_knobs(tmp_path):
    """ONE hyper.txt drives learning_rate AND entropy_beta (SURVEY §2.7 #21)."""
    tr = _FakeTrainer(log_dir=str(tmp_path))
    cbs = [
        HumanHyperParamSetter("learning_rate"),
        HumanHyperParamSetter("entropy_beta"),
    ]
    for cb in cbs:
        cb.setup(tr)
    (tmp_path / "hyper.txt").write_text(
        "learning_rate: 0.0003\nentropy_beta: 0.001\n"
    )
    for cb in cbs:
        cb.trigger_epoch()
    assert tr.hyperparams["learning_rate"] == pytest.approx(3e-4)
    assert tr.hyperparams["entropy_beta"] == pytest.approx(1e-3)


def test_max_saver_follows_monitor_stat(tmp_path):
    """MaxSaver reads the stat it names from the epoch record: the best
    pointer must follow greedy eval, not the sampling mean (VERDICT r2 #4)."""
    from distributed_ba3c_tpu.train.callbacks import MaxSaver

    tr = _FakeTrainer(log_dir=str(tmp_path))
    tr.stat_holder = StatHolder(str(tmp_path), tensorboard=False)
    tr.ckpt_manager = CheckpointManager(str(tmp_path / "ck"))
    cb = MaxSaver(monitor="eval_mean_score")
    cb.setup(tr)

    def epoch(step, sampling_mean, eval_mean):
        tr.global_step = step
        tr.last_mean_score = sampling_mean
        tr.stat_holder.add_stat("mean_score", sampling_mean)
        if eval_mean is not None:
            tr.stat_holder.add_stat("eval_mean_score", eval_mean)
        tr.stat_holder.finalize()
        cb.trigger_epoch()

    epoch(100, 5.0, 10.0)
    assert tr.ckpt_manager.best_step == 100
    # sampling mean jumps but eval is absent this epoch -> best unchanged
    epoch(200, 50.0, None)
    assert tr.ckpt_manager.best_step == 100
    # sampling mean FALLS while eval improves -> best follows eval
    epoch(300, 1.0, 12.0)
    assert tr.ckpt_manager.best_step == 300
    # eval regresses -> best stays
    epoch(400, 99.0, 8.0)
    assert tr.ckpt_manager.best_step == 300


def test_periodic_trigger_epochs():
    tr = _FakeTrainer()
    fired = []

    class Probe(Callback):
        def trigger_epoch(self):
            fired.append(self.trainer.epoch_num)

    cb = PeriodicTrigger(Probe(), every_k_epochs=3)
    cb.setup(tr)
    for e in range(1, 10):
        tr.epoch_num = e
        cb.trigger_epoch()
    assert fired == [3, 6, 9]


def test_callbacks_after_train_survives_errors():
    ran = []

    class Bad(Callback):
        def after_train(self):
            raise RuntimeError("boom")

    class Good(Callback):
        def after_train(self):
            ran.append(1)

    group = Callbacks([Bad(), Good()])
    group.after_train()
    assert ran == [1]


def test_stat_counter():
    c = StatCounter()
    for v in [1.0, 2.0, 6.0]:
        c.feed(v)
    assert c.count == 3 and c.average == 3.0 and c.max == 6.0 and c.sum == 9.0
    c.reset()
    assert c.count == 0


def test_stat_holder_writes_stat_json(tmp_path):
    h = StatHolder(str(tmp_path))
    h.add_stat("mean_score", 1.5)
    h.add_stat("epoch", 1)
    h.finalize()
    h.add_stat("mean_score", 2.5)
    h.finalize()
    data = json.load(open(tmp_path / "stat.json"))
    assert [d["mean_score"] for d in data] == [1.5, 2.5]
    # resume appends
    h2 = StatHolder(str(tmp_path))
    h2.add_stat("mean_score", 3.5)
    h2.finalize()
    data = json.load(open(tmp_path / "stat.json"))
    assert len(data) == 3


def test_checkpoint_manager_roundtrip_and_best(tmp_path):
    state = {"w": np.arange(4.0), "step": np.array(7, np.int32)}
    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
    mgr.save(state, 1)
    assert mgr.mark_best(1, 10.0)
    state["w"] = state["w"] + 1
    mgr.save(state, 2)
    assert not mgr.mark_best(2, 5.0)  # worse score
    mgr.save({"w": state["w"] + 1, "step": np.array(9, np.int32)}, 3)
    assert mgr.latest_step == 3 and mgr.best_step == 1

    mgr2 = CheckpointManager(str(tmp_path / "ck"))
    restored = mgr2.restore({"w": np.zeros(4), "step": np.array(0, np.int32)})
    np.testing.assert_array_equal(restored["w"], np.arange(4.0) + 2)
    assert restored["step"] == 9


def test_checkpoint_max_to_keep_one_never_deletes_latest(tmp_path):
    """Regression: with max_to_keep=1 + a protected best, the just-saved
    checkpoint must survive (latest must always be restorable)."""
    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=1)
    state = {"w": np.arange(3.0)}
    mgr.save(state, 1)
    mgr.mark_best(1, 10.0)
    mgr.save({"w": np.arange(3.0) + 1}, 2)
    assert mgr.latest_step == 2
    restored = CheckpointManager(str(tmp_path / "ck")).restore(
        {"w": np.zeros(3)}
    )
    np.testing.assert_array_equal(restored["w"], np.arange(3.0) + 1)
    # best is protected too
    import os

    assert os.path.isdir(tmp_path / "ck" / "ckpt-1")


def test_checkpoint_run_meta_roundtrip(tmp_path):
    """run_meta.json persists the run shape next to the checkpoints so a
    resume can warn on a schedule-stretching mismatch (ADVICE r3 #2)."""
    mgr = CheckpointManager(str(tmp_path / "ck"))
    assert mgr.read_run_meta() == {}
    mgr.write_run_meta(steps_per_epoch=3200, batch_size=640, rollout_len=20)
    meta = CheckpointManager(str(tmp_path / "ck")).read_run_meta()
    assert meta == {"steps_per_epoch": 3200, "batch_size": 640,
                    "rollout_len": 20}


def test_checkpoint_keep_all_for_sweeps(tmp_path):
    """--max_to_keep large retains EVERY saved step (the post-hoc crossing
    verification protocol, scripts/eval_sweep.py, needs all of them)."""
    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=64)
    for s in range(1, 11):
        mgr.save({"w": np.full(2, float(s))}, s)
    assert sorted(mgr._meta["all"]) == list(range(1, 11))
    import os

    for s in range(1, 11):
        assert os.path.isdir(tmp_path / "ck" / f"ckpt-{s}")


def test_read_hyper_file_keeps_valid_lines_on_typo(tmp_path):
    """A malformed line mid-live-edit must not discard the other overrides
    (ADVICE r3 #3: the old whole-file parse reverted lr AND beta on one
    typo)."""
    from distributed_ba3c_tpu.train.callbacks import read_hyper_file

    p = tmp_path / "hyper.txt"
    p.write_text("learning_rate: 0.001\nentropy_beta: oops\ngamma: 0.99\n")
    out = read_hyper_file(str(p))
    assert out == {"learning_rate": 0.001, "gamma": 0.99}
