"""tools/ba3cwire: per-rule fixtures, historical replays, CLI contract.

Mirrors the ba3clint/ba3cflow test structure: every wire rule must (a)
fire on its ``w*_flagged.py`` fixture and (b) stay quiet on its
``w*_clean.py`` fixture — the clean fixtures encode the wire idioms the
real codebase uses (paired codecs with agreeing frame counts,
length-guarded optional header reads, wrapped receive-loop decodes with
counted rejects, sign-split counters), so a rule regression that would
spam the repo fails here first. The replay fixtures pin the analyzer to
two bugs that actually shipped in this repo: PR 14's receive-loop kill
(one corrupt frame starved every peer) and PR 5's sign-mixed reward
counter (decreasing counters read as Prometheus resets). The CLI tests
pin the exit-status contract CI gates on, and the SARIF test pins the
schema the upload step consumes.
"""

import json
import os
import subprocess
import sys

import pytest

from tools.analyzer_core import stale_suppressions, suppressions
from tools.ba3cwire import all_rules
from tools.ba3cwire.engine import build_context, filter_suppressed, run_rules

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures", "wire")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RULE_IDS = ["W1", "W2", "W3", "W4", "W5", "W6"]


def _analyze(*names, suppress=True):
    paths = [os.path.join(FIXTURES, n) for n in names]
    ctx = build_context(paths, root=REPO_ROOT)
    raw = run_rules(ctx, all_rules())
    return (filter_suppressed(ctx, raw) if suppress else raw), ctx


def _findings(name, rule_id=None, suppress=True):
    out, _ = _analyze(name, suppress=suppress)
    if rule_id is not None:
        out = [f for f in out if f.rule == rule_id]
    return out


def _cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.ba3cwire", *args],
        cwd=cwd, capture_output=True, text=True,
    )


def _fx(name):
    return os.path.join("tests", "lint_fixtures", "wire", name)


# -- rule registry ----------------------------------------------------------


def test_rule_registry_complete():
    assert [r.id for r in all_rules()] == RULE_IDS
    for r in all_rules():
        assert r.id and r.name and r.summary and r.__doc__


# -- fixture pairs ----------------------------------------------------------


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_flagged_fixture_fires(rule_id):
    name = f"{rule_id.lower()}_flagged.py"
    hits = _findings(name, rule_id)
    assert hits, f"{rule_id} produced no findings on {name}"


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_flagged_fixture_fires_only_its_own_rule(rule_id):
    """Cross-rule noise on a flagged fixture means a rule is over-broad."""
    name = f"{rule_id.lower()}_flagged.py"
    other = [f for f in _findings(name) if f.rule != rule_id]
    assert not other, other


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_clean_fixture_clean_under_every_rule(rule_id):
    hits = _findings(f"{rule_id.lower()}_clean.py")
    assert not hits, hits


def test_expected_flag_counts():
    """Pin exact counts so rules don't silently widen or narrow: W1 sees
    the orphan packer and the frame-count drift; W3 sees the bare decode
    and the interprocedural chain; W5 sees the *_total gauge (twice:
    naming + undocumented), the set(), and both bad inc() forms."""
    assert len(_findings("w1_flagged.py", "W1")) == 2
    assert len(_findings("w2_flagged.py", "W2")) == 1
    assert len(_findings("w3_flagged.py", "W3")) == 2
    assert len(_findings("w4_flagged.py", "W4")) == 1
    assert len(_findings("w5_flagged.py", "W5")) == 5
    assert len(_findings("w6_flagged.py", "W6")) == 2


def test_w3_interprocedural_witness_names_the_chain():
    hits = _findings("w3_flagged.py", "W3")
    chained = [f for f in hits if "witness" in f.message]
    assert len(chained) == 1
    assert "_decode" in chained[0].message


def test_w4_witness_names_recv_and_decode_lines():
    (hit,) = _findings("w4_flagged.py", "W4")
    assert "recv at line" in hit.message
    assert "loads at line" in hit.message


# -- historical replays -----------------------------------------------------


def test_replay_recv_loop_kill_is_a_w3():
    """PR 14's bug class: the master pump decoded straight off the socket
    inside its poller loop — one corrupt frame killed every peer."""
    hits = _findings("replay_w3_recv_kill.py", "W3")
    assert len(hits) == 1
    assert "PR 14" in hits[0].message
    assert "master_pump" in hits[0].message
    assert [f.rule for f in _findings("replay_w3_recv_kill.py")] == ["W3"]


def test_replay_sign_mixed_counter_is_a_w5():
    """PR 5's bug class: raw (sign-mixed) rewards accumulated into one
    counter-typed series — rate() reads the dips as counter resets."""
    hits = _findings("replay_w5_counter.py", "W5")
    assert len(hits) == 1
    assert "PR 5" in hits[0].message
    assert "inc(-reward)" in hits[0].message
    assert [f.rule for f in _findings("replay_w5_counter.py")] == ["W5"]


# -- suppressions -----------------------------------------------------------


def test_suppressions_silence_real_findings_both_forms():
    raw = _findings("suppressed.py", "W6", suppress=False)
    assert len(raw) == 2, raw  # trailing AND standalone form both land
    assert _findings("suppressed.py") == []


def test_docstring_mention_of_disable_is_not_a_suppression():
    """Only real comment tokens suppress — documentation text that quotes
    the syntax must neither mask findings nor read as stale."""
    src = '"""uses # ba3cwire: disable=W3 like this"""\nx = 1\n'
    assert suppressions(src, tool="ba3cwire") == {}
    assert stale_suppressions(src, "d.py", [], "ba3cwire") == []


def test_check_suppressions_flags_stale_comment():
    _, ctx = _analyze("stale_suppressed.py", suppress=False)
    (path, mod), = ctx.project.by_path.items()
    out = stale_suppressions(mod.source, path, [], "ba3cwire")
    assert [f.rule for f in out] == ["S001"]
    assert "W2" in out[0].message


# -- whole-repo gate --------------------------------------------------------


def test_repo_is_wire_clean():
    """The acceptance bar: the analyzer runs over the real codebase and
    exits clean (true positives fixed, false positives suppressed with
    justifications)."""
    ctx = build_context(
        [os.path.join(REPO_ROOT, "distributed_ba3c_tpu"),
         os.path.join(REPO_ROOT, "tools")],
        root=REPO_ROOT,
    )
    assert not ctx.project.broken
    findings = filter_suppressed(ctx, run_rules(ctx, all_rules()))
    assert findings == [], findings


def test_repo_catalog_and_code_series_agree_both_ways():
    """W5's cross-check is two-directional: every code series documented,
    every documented series created — the repo must satisfy both."""
    ctx = build_context(
        [os.path.join(REPO_ROOT, "distributed_ba3c_tpu")], root=REPO_ROOT)
    assert ctx.catalog is not None and ctx.has_metrics_module
    declared = {d.name for d in ctx.series}
    undocumented = {n for n in declared if not ctx.catalog.documents(n)}
    assert undocumented == set(), undocumented
    absent = {n for n in ctx.catalog.names if n not in declared}
    assert absent == set(), absent


# -- engine behavior --------------------------------------------------------


def test_syntax_error_becomes_e001_not_a_crash(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    ctx = build_context([str(bad)], root=str(tmp_path))
    out = run_rules(ctx, all_rules())
    assert [f.rule for f in out] == ["E001"]


def test_missing_catalog_disables_docs_checks(tmp_path):
    """A sliced analysis with no docs/observability.md must not spam
    undocumented-series findings — the docs contract only binds when the
    catalog (and the metrics core) are in view."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "from distributed_ba3c_tpu import telemetry\n"
        "c = telemetry.registry('x').counter('nowhere_documented_total')\n")
    ctx = build_context([str(mod)], root=str(tmp_path))
    assert ctx.catalog is None
    out = run_rules(ctx, all_rules())
    assert out == [], out


# -- CLI contract -----------------------------------------------------------


def test_cli_exit_one_on_findings_and_zero_on_clean():
    assert _cli(_fx("w6_flagged.py")).returncode == 1
    assert _cli(_fx("w6_clean.py")).returncode == 0


def test_cli_select_unknown_rule_is_usage_error():
    r = _cli("--select", "W99", _fx("w6_clean.py"))
    assert r.returncode == 2
    assert "W99" in r.stderr


def test_cli_select_narrows_rules():
    r = _cli("--select", "W2", _fx("w6_flagged.py"))
    assert r.returncode == 0, r.stdout


def test_cli_json_output_parses():
    r = _cli("--json", _fx("w3_flagged.py"))
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    assert payload and payload[0]["rule"] == "W3"
    assert payload[0]["line"] > 0


def test_cli_list_rules():
    r = _cli("--list-rules")
    assert r.returncode == 0
    for rid in RULE_IDS:
        assert rid in r.stdout


def test_cli_check_suppressions_exits_one_on_stale():
    r = _cli("--check-suppressions", _fx("stale_suppressed.py"))
    assert r.returncode == 1
    assert "S001" in r.stdout
    r = _cli("--check-suppressions", _fx("suppressed.py"))
    assert r.returncode == 0, r.stdout


def test_cli_sarif_output(tmp_path):
    sarif_path = tmp_path / "wire.sarif"
    r = _cli("--sarif", str(sarif_path), _fx("w1_flagged.py"))
    assert r.returncode == 1
    doc = json.loads(sarif_path.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "ba3cwire"
    rule_ids = {rd["id"] for rd in run["tool"]["driver"]["rules"]}
    assert set(RULE_IDS) <= rule_ids
    results = run["results"]
    assert results and all(res["ruleId"] == "W1" for res in results)
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("w1_flagged.py")
    assert loc["region"]["startLine"] > 0
