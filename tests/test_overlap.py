"""Overlapped rollout/learner programs (fused/overlap.py): parity + units.

The contracts this suite pins (ISSUE 8 acceptance):

- lag-0 bit-exactness: the overlap split run sequentially (lag=0) with
  frozen params consumes the identical key sequence as the fused step and
  must produce bit-identical trajectories, frame stacks and episode
  counters over a K-window — the shared rollout body
  (fused/loop.py make_rollout_body) is what makes this a real contract.
- lag-0 learning math: V-trace with behavior == target reduces to the
  n-step-return A3C objective, so ONE live update from identical state
  must land on the same params as the fused step up to fp reassociation.
- lag-1 mode actually trains, donates safely across facade calls, and the
  bf16 rollout snapshot runs.
- the BA3C_AUDIT=1 retrace tripwire covers both new entry points (the CI
  audit job runs this file's smoke with the env var set).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.envs.jaxenv import pong
from distributed_ba3c_tpu.fused.loop import create_fused_state, make_fused_step
from distributed_ba3c_tpu.fused.overlap import make_overlap_step
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import make_optimizer
from distributed_ba3c_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def parts():
    cfg = BA3CConfig(num_actions=pong.num_actions, fc_units=16)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    mesh = make_mesh()
    return cfg, model, opt, mesh


@pytest.fixture(scope="module")
def overlap_setup(parts):
    cfg, model, opt, mesh = parts
    n_data = mesh.shape["data"]
    n_envs = 2 * n_data
    step = make_overlap_step(model, opt, cfg, mesh, pong, rollout_len=3)

    def make_state(s=step):
        return s.put(
            create_fused_state(
                jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
                n_shards=n_data,
            )
        )

    return cfg, step, make_state, n_envs


def test_overlap_step_advances_and_is_finite(overlap_setup):
    cfg, step, make_state, n_envs = overlap_setup
    state = make_state()
    state, metrics = step(state, cfg.entropy_beta)
    state, metrics = step(state, cfg.entropy_beta)
    assert int(state.train.step) == 2
    for k, v in metrics.items():
        assert np.isfinite(float(v)), k
    # the overlap-specific series exist
    assert "mean_rho" in metrics and "value_lag_mae" in metrics
    # lag-1: a block is in flight between facade calls
    assert state.block is not None
    assert state.actor.obs_stack.shape == (n_envs, 84, 84, cfg.frame_history)


def test_overlap_lag1_trains(overlap_setup):
    cfg, step, make_state, _ = overlap_setup
    state = make_state()
    p0 = np.asarray(jax.tree_util.tree_leaves(state.train.params)[0]).copy()
    state, _ = step(state, cfg.entropy_beta, learning_rate=0.0)
    p1 = np.asarray(jax.tree_util.tree_leaves(state.train.params)[0])
    np.testing.assert_array_equal(p0, p1)
    state, _ = step(state, cfg.entropy_beta, learning_rate=1e-3)
    p2 = np.asarray(jax.tree_util.tree_leaves(state.train.params)[0])
    assert not np.allclose(p1, p2)


def test_lag0_bitexact_with_fused_one_window(parts, overlap_setup):
    """The acceptance parity: a lag-0 overlap run with frozen params is
    BIT-EXACT with the fused step over one K-window (K sequential
    iterations here) — same trajectories, frame stacks, env states and
    episode counters. (With a live lr, bit-equality across
    differently-compiled programs is not a sound contract — the fused
    scanned-dispatch parity test documents why; the learning-math
    equivalence at live lr is pinned separately below.)"""
    cfg, model, opt, mesh = parts
    _, _, _, n_envs = overlap_setup
    n_data = mesh.shape["data"]
    K = 4
    fstep = make_fused_step(model, opt, cfg, mesh, pong, rollout_len=3)
    ostep = make_overlap_step(model, opt, cfg, mesh, pong, rollout_len=3,
                              lag=0)

    def fresh(putter):
        return putter(
            create_fused_state(
                jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
                n_shards=n_data,
            )
        )

    f = fresh(fstep.put)
    o = fresh(ostep.put)
    for _ in range(K):
        f, mf = fstep(f, cfg.entropy_beta, learning_rate=0.0)
        o, mo = ostep(o, cfg.entropy_beta, learning_rate=0.0)
    assert int(f.train.step) == int(o.train.step) == K
    np.testing.assert_array_equal(
        np.asarray(f.obs_stack), np.asarray(o.actor.obs_stack)
    )
    for fl, ol in zip(
        jax.tree_util.tree_leaves(f.env_state),
        jax.tree_util.tree_leaves(o.actor.env_state),
    ):
        np.testing.assert_array_equal(np.asarray(fl), np.asarray(ol))
    np.testing.assert_array_equal(
        np.asarray(f.ep_count), np.asarray(o.actor.ep_count)
    )
    np.testing.assert_array_equal(
        np.asarray(f.ep_return), np.asarray(o.actor.ep_return)
    )
    assert float(mf["episodes"]) == float(mo["episodes"])
    assert float(mf["episode_return_sum"]) == float(mo["episode_return_sum"])


def test_lag0_learner_update_matches_fused_math(parts, overlap_setup):
    """The learning-math half of the parity gate: at lag 0 the V-trace
    correction is the identity (rho == c == 1 up to fp noise), its value
    targets reduce to the n-step returns, and the overlap learner's loss
    mirrors ops/loss.py — so ONE live update from identical state must
    produce the same params as the fused step up to float reassociation
    (different program structure ⇒ different fusion ⇒ small ulp drift,
    hence allclose, not array_equal)."""
    cfg, model, opt, mesh = parts
    _, _, _, n_envs = overlap_setup
    n_data = mesh.shape["data"]
    fstep = make_fused_step(model, opt, cfg, mesh, pong, rollout_len=3)
    ostep = make_overlap_step(model, opt, cfg, mesh, pong, rollout_len=3,
                              lag=0)

    def fresh(putter):
        return putter(
            create_fused_state(
                jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
                n_shards=n_data,
            )
        )

    f, mf = fstep(fresh(fstep.put), cfg.entropy_beta)
    o, mo = ostep(fresh(ostep.put), cfg.entropy_beta)
    # identical trajectory (params were identical for the one rollout) —
    # so the updates optimized the same batch
    assert abs(float(mo["mean_rho"]) - 1.0) < 1e-5
    for fl, ol in zip(
        jax.tree_util.tree_leaves(f.train.params),
        jax.tree_util.tree_leaves(o.train.params),
    ):
        np.testing.assert_allclose(
            np.asarray(fl), np.asarray(ol), rtol=2e-4, atol=2e-5
        )
    for k in ("loss", "policy_loss", "value_loss", "entropy"):
        assert abs(float(mf[k]) - float(mo[k])) < 5e-4, k


def test_overlap_steps_per_dispatch_pairs(parts):
    cfg, model, opt, mesh = parts
    n_data = mesh.shape["data"]
    n_envs = 2 * n_data
    step = make_overlap_step(model, opt, cfg, mesh, pong, rollout_len=3,
                             steps_per_dispatch=3)
    state = step.put(
        create_fused_state(
            jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
            n_shards=n_data,
        )
    )
    state, metrics = step(state, cfg.entropy_beta)
    assert int(state.train.step) == 3
    for k, v in metrics.items():
        assert np.isfinite(float(v)), k


def test_overlap_learner_env_column_chunking(parts):
    """Chunked gradient accumulation over env columns: (a) a
    grad_chunk_samples smaller than one env column's T samples must CLAMP
    to per-column chunks instead of spinning forever hunting a divisor of
    B above B (the rounding loop walks upward — regression for the
    unbounded-loop bug), and (b) mean-of-column-chunk grads equals the
    full-batch gradient, so one update lands on the same params."""
    cfg, model, opt, mesh = parts
    n_data = mesh.shape["data"]
    n_envs = 2 * n_data

    def one_update(gcs):
        step = make_overlap_step(model, opt, cfg, mesh, pong, rollout_len=3,
                                 lag=0, grad_chunk_samples=gcs)
        state = step.put(
            create_fused_state(
                jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
                n_shards=n_data,
            )
        )
        state, m = step(state, cfg.entropy_beta)
        return state.train.params, m

    # per-shard T*B = 6, B = 2: gcs=2 makes ceil(6/2)=3 > B — the clamp
    # case; gcs large = the single-chunk reference
    p_ref, m_ref = one_update(4096)
    p_chunk, m_chunk = one_update(2)
    for a, b in zip(
        jax.tree_util.tree_leaves(p_ref), jax.tree_util.tree_leaves(p_chunk)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )
    assert abs(float(m_ref["loss"]) - float(m_chunk["loss"])) < 5e-4


def test_overlap_bf16_rollout_runs(parts):
    """The bf16 params-snapshot actor: runs, stays finite, and the learner
    (whose target forward stays f32-param) still trains on its blocks."""
    cfg, model, opt, mesh = parts
    n_data = mesh.shape["data"]
    n_envs = 2 * n_data
    step = make_overlap_step(model, opt, cfg, mesh, pong, rollout_len=3,
                             rollout_dtype="bfloat16")
    state = step.put(
        create_fused_state(
            jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
            n_shards=n_data,
        )
    )
    p0 = np.asarray(jax.tree_util.tree_leaves(state.train.params)[0]).copy()
    state, metrics = step(state, cfg.entropy_beta)
    for k, v in metrics.items():
        assert np.isfinite(float(v)), k
    assert np.all(np.isfinite(np.asarray(state.block.behavior_log_probs)))
    p1 = np.asarray(jax.tree_util.tree_leaves(state.train.params)[0])
    assert not np.array_equal(p0, p1)


def test_overlap_reset_episode_stats_hook(overlap_setup):
    cfg, step, make_state, n_envs = overlap_setup
    state = make_state()
    for _ in range(6):
        state, metrics = step(state, cfg.entropy_beta)
    state = step.reset_episode_stats(state, n_envs)
    assert int(np.sum(np.asarray(state.actor.ep_count))) == 0
    assert float(np.sum(np.asarray(state.actor.ep_return_sum))) == 0.0
    # the running (uncompleted) episode return is NOT reset — same
    # contract as the fused epoch loop
    state, metrics = step(state, cfg.entropy_beta)
    for k, v in metrics.items():
        assert np.isfinite(float(v)), k


def test_overlap_probe_reports_and_advances(overlap_setup):
    """probe_overlap: the sanctioned measurement site returns the solo and
    pair wall times, publishes the telemetry gauges, and ADVANCES the
    state (no experience replay)."""
    from distributed_ba3c_tpu import telemetry

    cfg, step, make_state, _ = overlap_setup
    state = make_state()
    state, _ = step(state, cfg.entropy_beta)
    step0 = int(state.train.step)
    state, probe = step.probe_overlap(state, cfg.entropy_beta, reps=2)
    assert int(state.train.step) > step0
    for k in ("actor_ms", "learner_ms", "pair_ms", "overlap_efficiency"):
        assert k in probe
    assert probe["actor_ms"] > 0 and probe["learner_ms"] > 0
    scalars = telemetry.registry("learner").scalars()
    for series in ("actor_program_ms", "learner_program_ms",
                   "overlap_pair_ms", "overlap_efficiency"):
        assert series in scalars, series


def test_audit_tripwire_covers_both_programs(parts, monkeypatch):
    """BA3C_AUDIT=1 smoke of the two new entry points (the CI audit job
    runs exactly this test): both programs get a RetraceTripwire, warm up
    in one trace each, arm, and a steady-state run raises nothing."""
    monkeypatch.setenv("BA3C_AUDIT", "1")
    from distributed_ba3c_tpu import audit

    cfg, model, opt, mesh = parts
    n_data = mesh.shape["data"]
    n_envs = 2 * n_data
    step = make_overlap_step(model, opt, cfg, mesh, pong, rollout_len=2)
    state = step.put(
        create_fused_state(
            jax.random.PRNGKey(1), model, cfg, opt, pong, n_envs,
            n_shards=n_data,
        )
    )
    for _ in range(3):
        state, metrics = step(state, cfg.entropy_beta)
    float(metrics["loss"])
    live = audit.live_tripwires()
    for name in ("fused.actor", "fused.learner"):
        assert name in live, name
        assert live[name].armed
        assert live[name].traces == 1, (name, live[name].traces)


def test_overlap_registry_entries_trace_clean():
    """The registry builders for the two new entries produce programs the
    T1-T4 rules accept (T5 manifest comparison is owned by
    test_ba3caudit's registry e2e)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs the 2-device canonical mesh")
    from distributed_ba3c_tpu import audit
    from tools.ba3caudit import rules

    for name in ("fused.actor", "fused.learner"):
        target = audit.build_entry(name)
        m = rules.measure(target)
        findings = (
            rules.check_t1(target, m) + rules.check_t2(target, m)
            + rules.check_t3(target, m) + rules.check_t4(target, m)
        )
        assert findings == [], findings
    # the actor really is collective-free — the schedule premise
    m = rules.measure(audit.build_entry("fused.actor"))
    assert m.collectives == {}


def test_overlap_cli_e2e_trains_and_resumes(tmp_path):
    """The whole driver path under --overlap: epoch loop (metrics fetch,
    reset hook, checkpoint save) runs, and a second invocation resumes
    from the finalized checkpoint — the overlap facade is state-compatible
    with the fused trainer's checkpoints."""
    import json

    from distributed_ba3c_tpu.cli import main

    args = [
        "--trainer", "tpu_fused_ba3c", "--env", "jax:pong", "--overlap",
        "--fc_units", "16", "--batch_size", "8", "--rollout_len", "4",
        "--steps_per_epoch", "4", "--eval_every", "5",
    ]
    rc = main(args + ["--max_epoch", "1", "--logdir", str(tmp_path / "a")])
    assert rc == 0
    stats = json.load(open(tmp_path / "a" / "stat.json"))
    assert stats[-1]["global_step"] == 4
    assert np.isfinite(stats[-1]["loss"])
    rc = main(args + [
        "--max_epoch", "2", "--logdir", str(tmp_path / "b"),
        "--load", str(tmp_path / "a" / "checkpoints"),
    ])
    assert rc == 0
    stats = json.load(open(tmp_path / "b" / "stat.json"))
    assert stats[-1]["global_step"] == 8


def test_overlap_cli_flag_validation():
    """--overlap outside the fused trainer is a usage error, not a
    mystery crash later."""
    from distributed_ba3c_tpu.cli import main

    with pytest.raises(SystemExit):
        main(["--overlap", "--trainer", "tpu_sync_ba3c", "--env", "fake"])
