"""The reconcile loop (orchestrate/reconcile.py, docs/topology.md).

Three layers, matching the module's own split:

- the PURE diff functions — a deterministic unit suite: snapshot in,
  exact action list out, no fakes needed;
- the resource-kind matrix driven through ``Reconciler.tick_once()``
  with fake controllers: every kind × {kill, wedge, scale up, scale
  down, failed act retried next tick};
- the loop's own machinery: per-resource exponential backoff parks, the
  topology-wide restart-budget circuit breaker (open → half-open drain →
  closed), flight-recorded decisions carrying their input snapshot.
"""

import time

import pytest

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.orchestrate.reconcile import (
    Action,
    FleetResource,
    LearnerResource,
    PolicyResource,
    Reconcilable,
    Reconciler,
    ServingResource,
    diff_fleet,
    diff_learner,
    diff_serving,
)
from distributed_ba3c_tpu.orchestrate.topology import ReconcilePolicy


def verbs(actions):
    return [a.verb for a in actions]


# --------------------------------------------------------------------------
# the deterministic diff unit suite
# --------------------------------------------------------------------------


class TestDiffFleet:
    def test_steady_state_is_empty(self):
        assert diff_fleet("f", {"target": 4, "live": 4}) == []

    def test_wedged_slots_die_first(self):
        acts = diff_fleet("f", {
            "wedged": ("env-srv-1", "env-srv-3"),
            "vacant_due": (2,),
            "ever_started": True,
        })
        assert verbs(acts) == ["kill", "kill", "respawn"]
        assert acts[0].detail_dict()["ident"] == "env-srv-1"

    def test_vacancy_respawns_after_first_start(self):
        acts = diff_fleet("f", {"vacant_due": (0, 1), "ever_started": True})
        assert verbs(acts) == ["respawn", "respawn"]
        assert [a.detail_dict()["slot"] for a in acts] == [0, 1]

    def test_never_started_fleet_spawns(self):
        acts = diff_fleet("f", {"vacant_due": (0,), "ever_started": False})
        assert verbs(acts) == ["spawn"]

    def test_supervisor_circuit_open_parks_all_but_kills(self):
        acts = diff_fleet("f", {
            "wedged": ("w",), "vacant_due": (0,), "circuit_open": True,
            "scale_delta": 2,
        })
        assert verbs(acts) == ["kill"]

    def test_scale_intent_becomes_scale_action(self):
        acts = diff_fleet("f", {
            "scale_delta": -2, "scale_reason": "queue drained",
        })
        assert verbs(acts) == ["scale"]
        assert acts[0].detail_dict()["delta"] == -2
        assert acts[0].reason == "queue drained"

    def test_backoff_parked_vacancy_is_drift_not_action(self):
        # vacant slots still inside their spawn backoff are NOT due
        assert diff_fleet("f", {"vacant_backoff": (1,)}) == []


class TestDiffLearner:
    def test_terminal_states_want_nothing(self):
        assert diff_learner("l", {"done": True}) == []
        assert diff_learner("l", {"given_up": True, "running": False}) == []

    def test_healthy_run_wants_nothing(self):
        assert diff_learner("l", {"running": True, "stalled": False}) == []

    def test_stall_kills(self):
        acts = diff_learner("l", {
            "running": True, "stalled": True, "attempt": 2,
        })
        assert verbs(acts) == ["kill"]
        assert acts[0].detail_dict()["attempt"] == 2

    def test_dead_learner_rearms_through_resume_gate(self):
        acts = diff_learner("l", {"running": False, "finalized_step": 600})
        assert verbs(acts) == ["re-arm"]
        assert "finalized checkpoint" in acts[0].reason
        assert acts[0].detail_dict()["resume_step"] == 600

    def test_no_checkpoint_rearms_from_scratch(self):
        acts = diff_learner("l", {"running": False, "finalized_step": None})
        assert verbs(acts) == ["re-arm"]
        assert "scratch" in acts[0].reason


class TestDiffServing:
    def test_steady_state_is_empty(self):
        assert diff_serving("s", {"target": 2, "min_replicas": 2}) == []

    def test_dead_replicas_replaced_one_to_one(self):
        acts = diff_serving("s", {
            "target": 2, "min_replicas": 2, "dead": ("r0", "r1"),
        })
        assert verbs(acts) == ["replace", "replace"]

    def test_shortfall_grows_back_to_floor(self):
        acts = diff_serving("s", {"target": 1, "min_replicas": 3})
        assert verbs(acts) == ["spawn"]
        assert acts[0].detail_dict()["n"] == 2

    def test_dead_suppresses_the_spawn_path(self):
        # the replace act heals-to-count already; a second grow action
        # the same round would double-spawn
        acts = diff_serving("s", {
            "target": 1, "min_replicas": 2, "dead": ("r0",),
        })
        assert verbs(acts) == ["replace"]


def test_action_detail_round_trip_and_hashable():
    a = Action.make("scale", "fleet0", reason="why", delta=2, slot=1)
    assert a.detail_dict() == {"delta": 2, "slot": 1}
    assert hash(a) == hash(Action.make("scale", "fleet0", reason="why",
                                       slot=1, delta=2))


# --------------------------------------------------------------------------
# fakes: scripted controllers under the real adapters / loop
# --------------------------------------------------------------------------


class FakeFleetSup:
    """Scripted FleetSupervisor surface: observe() returns whatever the
    test staged, act calls are counted."""

    def __init__(self, obs=None):
        self.obs = dict(obs or {})
        self.spawned = False
        self.ticks = 0
        self.scales = []
        self.closed = False

    def spawn_initial(self):
        self.spawned = True

    def observe(self):
        return dict(self.obs)

    def tick(self):
        self.ticks += 1

    def scale_by(self, delta, reason=""):
        self.scales.append((delta, reason))

    def close(self):
        self.closed = True


class FakeLearnerSup:
    def __init__(self):
        self.attempt = 0
        self.running = False
        self.stalled = False
        self.ckpt_dir = "/nonexistent-ckpt-dir"
        self.pending_rc = None
        self.verdict = "retry"
        self.starts = 0
        self.kills = 0
        self.terminated = False

    def attempt_running(self):
        return self.running

    def attempt_stalled(self):
        return self.stalled

    def kill_attempt(self, reason=""):
        self.kills += 1
        self.running = False

    def reap_attempt(self):
        rc, self.pending_rc = self.pending_rc, None
        return rc

    def note_exit(self, rc):
        return self.verdict

    def start_attempt(self):
        self.starts += 1
        self.running = True

    def terminate_attempt(self):
        self.terminated = True


class FakeRouter:
    def __init__(self):
        self.states = {}

    def replica_states(self):
        return dict(self.states)


class FakeReplicaSet:
    def __init__(self, live, min_replicas=1, max_replicas=4):
        self.router = FakeRouter()
        self.live = list(live)
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.reconciles = 0
        self.scale_calls = []

    def replica_ids(self):
        return list(self.live)

    def reconcile(self):
        self.reconciles += 1
        # heal-to-count: dead incarnations replaced
        self.router.states = {r: "ready" for r in self.live}

    def scale_to(self, n, reason=""):
        self.scale_calls.append((n, reason))
        self.live = [f"r{i}" for i in range(n)]


class FakeController:
    def __init__(self):
        self.ticks = 0
        self.stopped = False

    def tick(self):
        self.ticks += 1

    def stop(self):
        self.stopped = True


class FlakyResource(Reconcilable):
    """Always wants one heal; act fails the first ``fail_n`` times."""

    kind = "fleet"

    def __init__(self, name, fail_n=0):
        self.name = name
        self.fail_n = fail_n
        self.acts = 0

    def observe(self):
        return {"kind": "fleet"}

    def diff(self, observed):
        return [Action.make("respawn", self.name, reason="always vacant")]

    def act(self, action):
        self.acts += 1
        if self.acts <= self.fail_n:
            raise RuntimeError(f"respawn attempt {self.acts} failed")


def quiet_policy(**kw):
    kw.setdefault("poll_interval_s", 0.01)
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("backoff_max_s", max(30.0, kw["backoff_base_s"]))
    return ReconcilePolicy(**kw)


# --------------------------------------------------------------------------
# the resource-kind matrix, through the real loop
# --------------------------------------------------------------------------


class TestFleetMatrix:
    def test_kill_and_respawn_ride_one_supervisor_tick(self):
        sup = FakeFleetSup({
            "wedged": ("w-1",), "vacant_due": (0, 1), "ever_started": True,
        })
        rec = Reconciler(policy=quiet_policy())
        rec.add(FleetResource("fleet0", sup))
        executed = rec.tick_once()
        assert verbs(executed) == ["kill", "respawn", "respawn"]
        # one underlying slot pass heals the whole round
        assert sup.ticks == 1

    def test_scale_up_and_down_through_scale_intent(self):
        sup = FakeFleetSup()
        intents = [(2, "queue deep"), (0, ""), (-1, "queue drained")]
        rec = Reconciler(policy=quiet_policy())
        rec.add(FleetResource("fleet0", sup, scale_intent=intents.pop))
        # intents pop from the tail: -1 first, then 0 (no action), then +2
        assert verbs(rec.tick_once()) == ["scale"]
        assert verbs(rec.tick_once()) == []
        assert verbs(rec.tick_once()) == ["scale"]
        assert sup.scales == [(-1, "queue drained"), (2, "queue deep")]

    def test_failed_respawn_retried_next_tick(self):
        res = FlakyResource("fleet0", fail_n=1)
        rec = Reconciler(policy=quiet_policy())  # backoff base 0: due at once
        rec.add(res)
        assert rec.tick_once() == []  # act raised: nothing executed
        assert verbs(rec.tick_once()) == ["respawn"]  # retried and healed
        assert res.acts == 2

    def test_backoff_parks_a_failing_resource(self):
        res = FlakyResource("fleet0", fail_n=100)
        rec = Reconciler(policy=quiet_policy(backoff_base_s=60.0))
        rec.add(res)
        rec.tick_once()
        assert res.acts == 1
        rec.tick_once()  # parked: 60s backoff has not elapsed
        assert res.acts == 1
        skipped = telemetry.registry("reconciler").counter(
            "reconcile_skipped_total"
        )
        assert skipped.value() >= 1

    def test_retire_closes_the_supervisor(self):
        sup = FakeFleetSup()
        rec = Reconciler(policy=quiet_policy())
        rec.add(FleetResource("fleet0", sup))
        rec.close()  # never started: close still retires
        assert sup.closed

    def test_pod_kind_buckets_the_pod_heal_counter(self):
        sup = FakeFleetSup({"vacant_due": (0,), "ever_started": True})
        rec = Reconciler(policy=quiet_policy())
        rec.add(FleetResource("pod-hosts", sup, kind="pod"))
        before = telemetry.registry("reconciler").counter(
            "reconcile_heal_pod_total"
        ).value()
        rec.tick_once()
        after = telemetry.registry("reconciler").counter(
            "reconcile_heal_pod_total"
        ).value()
        assert after == before + 1


class TestLearnerMatrix:
    def test_dead_learner_rearmed_and_accounted(self):
        sup = FakeLearnerSup()
        sup.pending_rc = 1  # previous attempt died
        res = LearnerResource("learner", sup)
        rec = Reconciler(policy=quiet_policy())
        rec.add(res)
        assert verbs(rec.tick_once()) == ["re-arm"]
        assert sup.starts == 1 and sup.running
        assert res.final_rc is None

    def test_stalled_learner_killed_then_rearmed(self):
        sup = FakeLearnerSup()
        sup.running = True
        sup.stalled = True
        res = LearnerResource("learner", sup)
        rec = Reconciler(policy=quiet_policy())
        rec.add(res)
        assert verbs(rec.tick_once()) == ["kill"]
        assert sup.kills == 1 and not sup.running
        sup.pending_rc = 1
        assert verbs(rec.tick_once()) == ["re-arm"]
        assert sup.starts == 1

    def test_clean_exit_finishes_supervision(self):
        sup = FakeLearnerSup()
        sup.pending_rc = 0
        sup.verdict = "done"
        res = LearnerResource("learner", sup)
        rec = Reconciler(policy=quiet_policy())
        rec.add(res)
        rec.tick_once()
        assert res.final_rc == 0
        assert sup.starts == 0  # done: no relaunch
        assert rec.tick_once() == []  # terminal state wants nothing

    def test_budget_exhaustion_gives_up_with_the_fatal_rc(self):
        sup = FakeLearnerSup()
        sup.pending_rc = 9
        sup.verdict = "giveup"
        res = LearnerResource("learner", sup)
        rec = Reconciler(policy=quiet_policy())
        rec.add(res)
        rec.tick_once()
        assert res.final_rc == 9
        assert sup.starts == 0
        assert rec.tick_once() == []


class TestServingMatrix:
    def test_dead_replica_heals_through_reconcile(self):
        rs = FakeReplicaSet(["r0", "r1"], min_replicas=2)
        rs.router.states = {"r0": "ready", "r1": "dead"}
        rec = Reconciler(policy=quiet_policy())
        rec.add(ServingResource("serving", rs))
        assert verbs(rec.tick_once()) == ["replace"]
        assert rs.reconciles == 1
        assert rec.tick_once() == []  # healed: steady state

    def test_scale_up_to_floor(self):
        rs = FakeReplicaSet(["r0"], min_replicas=3)
        rec = Reconciler(policy=quiet_policy())
        rec.add(ServingResource("serving", rs))
        acts = rec.tick_once()
        assert verbs(acts) == ["spawn"]
        assert rs.scale_calls == [(3, "replica set below floor")]

    def test_two_dead_replicas_one_underlying_heal(self):
        rs = FakeReplicaSet(["r0", "r1", "r2"], min_replicas=3)
        rs.router.states = {"r0": "dead", "r1": "dead", "r2": "ready"}
        rec = Reconciler(policy=quiet_policy())
        rec.add(ServingResource("serving", rs))
        assert verbs(rec.tick_once()) == ["replace", "replace"]
        assert rs.reconciles == 1  # heal-to-count is atomic per round


class TestPolicyResource:
    def test_interval_gates_the_tick(self):
        ctrl = FakeController()
        rec = Reconciler(policy=quiet_policy())
        rec.add(PolicyResource("autoscaler", ctrl, interval_s=3600))
        rec.tick_once()
        assert ctrl.ticks == 1  # first tick is due immediately
        rec.tick_once()
        assert ctrl.ticks == 1  # interval has not elapsed

    def test_zero_interval_ticks_every_round(self):
        ctrl = FakeController()
        rec = Reconciler(policy=quiet_policy())
        rec.add(PolicyResource("autoscaler", ctrl, interval_s=0))
        rec.tick_once()
        rec.tick_once()
        assert ctrl.ticks == 2

    def test_policy_ticks_do_not_burn_restart_budget(self):
        ctrl = FakeController()
        rec = Reconciler(policy=quiet_policy(restart_budget=1))
        rec.add(PolicyResource("autoscaler", ctrl, interval_s=0))
        for _ in range(5):
            rec.tick_once()
        assert ctrl.ticks == 5
        assert not rec.circuit_open

    def test_retire_stops_the_controller(self):
        ctrl = FakeController()
        rec = Reconciler(policy=quiet_policy())
        rec.add(PolicyResource("autoscaler", ctrl))
        rec.close()
        assert ctrl.stopped


# --------------------------------------------------------------------------
# loop machinery: assembly, circuit breaker, flight trail
# --------------------------------------------------------------------------


class TestAssembly:
    def test_duplicate_names_rejected(self):
        rec = Reconciler(policy=quiet_policy())
        rec.add(FleetResource("fleet0", FakeFleetSup()))
        with pytest.raises(ValueError, match="duplicate"):
            rec.add(FleetResource("fleet0", FakeFleetSup()))

    def test_nameless_resource_rejected(self):
        with pytest.raises(ValueError, match="name"):
            Reconciler(policy=quiet_policy()).add(
                FleetResource("", FakeFleetSup())
            )

    def test_observe_diff_error_skips_resource_not_tick(self):
        class Broken(Reconcilable):
            kind, name = "fleet", "broken"

            def observe(self):
                raise RuntimeError("observation source gone")

        sup = FakeFleetSup({"vacant_due": (0,), "ever_started": True})
        rec = Reconciler(policy=quiet_policy())
        rec.add(Broken())
        rec.add(FleetResource("fleet0", sup))
        # the healthy resource still heals in the same tick
        assert verbs(rec.tick_once()) == ["respawn"]


class TestCircuitBreaker:
    def test_opens_past_budget_and_halts_healing(self):
        res = FlakyResource("fleet0")
        rec = Reconciler(policy=quiet_policy(
            restart_budget=2, budget_window_s=300,
        ))
        rec.add(res)
        for _ in range(3):
            rec.tick_once()
        assert rec.circuit_open  # 3 heals > budget 2
        assert rec.tick_once() == []  # healing paused
        assert res.acts == 3

    def test_half_open_drain_closes(self):
        res = FlakyResource("fleet0")
        rec = Reconciler(policy=quiet_policy(
            restart_budget=2, budget_window_s=300,
        ))
        rec.add(res)
        for _ in range(3):
            rec.tick_once()
        assert rec.circuit_open
        # drain the window below half the budget (as time passing would)
        while len(rec._heal_times) > 1:
            rec._heal_times.popleft()
        rec.tick_once()  # this tick still skips, then re-evaluates
        assert not rec.circuit_open
        assert verbs(rec.tick_once()) == ["respawn"]

    def test_window_expiry_drains_naturally(self):
        res = FlakyResource("fleet0")
        rec = Reconciler(policy=quiet_policy(
            restart_budget=2, budget_window_s=0.05,
        ))
        rec.add(res)
        for _ in range(3):
            rec.tick_once()
        assert rec.circuit_open
        time.sleep(0.06)
        rec.tick_once()
        assert not rec.circuit_open

    def test_zero_budget_is_permanently_open(self):
        res = FlakyResource("fleet0")
        rec = Reconciler(policy=quiet_policy(restart_budget=0))
        rec.add(res)
        assert rec.circuit_open
        for _ in range(3):
            assert rec.tick_once() == []
        assert res.acts == 0
        assert rec.circuit_open

    def test_trip_is_flight_recorded(self):
        t0 = time.monotonic()
        res = FlakyResource("fleet0")
        rec = Reconciler(policy=quiet_policy(restart_budget=1))
        rec.add(res)
        for _ in range(2):
            rec.tick_once()
        events = telemetry.flight_recorder().events_since(
            t0, kind="reconcile_circuit_open"
        )
        assert events and events[-1][2]["budget"] == 1


class TestFlightTrail:
    def test_decision_carries_its_input_snapshot(self):
        t0 = time.monotonic()
        sup = FakeFleetSup({"vacant_due": (3,), "ever_started": True})
        rec = Reconciler(policy=quiet_policy())
        rec.add(FleetResource("fleet0", sup))
        rec.tick_once()
        events = telemetry.flight_recorder().events_since(
            t0, kind="reconcile_action"
        )
        assert events
        fields = events[-1][2]
        assert fields["resource"] == "fleet0"
        assert fields["verb"] == "respawn"
        assert tuple(fields["snapshot"]["vacant_due"]) == (3,)

    def test_act_failure_is_flight_recorded(self):
        t0 = time.monotonic()
        rec = Reconciler(policy=quiet_policy())
        rec.add(FlakyResource("fleet0", fail_n=1))
        rec.tick_once()
        events = telemetry.flight_recorder().events_since(
            t0, kind="reconcile_act_error"
        )
        assert events and events[-1][2]["failures"] == 1

    def test_drift_gauge_tracks_pending_heals(self):
        sup = FakeFleetSup({
            "vacant_due": (0, 1), "ever_started": True,
        })
        rec = Reconciler(policy=quiet_policy())
        rec.add(FleetResource("fleet0", sup))
        rec.tick_once()
        g = telemetry.registry("reconciler").gauge("reconcile_drift_gauge")
        assert g.collect()["value"] == 2
        sup.obs = {}
        rec.tick_once()
        assert g.collect()["value"] == 0


def test_reconciler_thread_lifecycle_heals_live():
    """start/stop/close as cli.py's StartProcOrThread drives it: the
    thread heals without manual ticking."""
    sup = FakeFleetSup({"vacant_due": (0,), "ever_started": True})
    rec = Reconciler(policy=quiet_policy())
    rec.add(FleetResource("fleet0", sup))
    rec.start()
    assert sup.spawned  # prepare ran before the loop
    deadline = time.monotonic() + 5
    while sup.ticks == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    rec.close()
    assert sup.ticks >= 1
    assert sup.closed
