"""DP train-step tests on the fake 8-device CPU mesh (SURVEY.md §7 step 2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.models import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import make_optimizer
from distributed_ba3c_tpu.parallel import create_train_state, make_mesh, make_train_step

CFG = BA3CConfig(num_actions=4, image_size=(32, 32), frame_history=4, batch_size=16)


def _make_batch(rng, cfg, batch):
    return {
        "state": jnp.asarray(
            rng.integers(0, 256, size=(batch, *cfg.state_shape)), jnp.uint8
        ),
        "action": jnp.asarray(rng.integers(0, cfg.num_actions, size=(batch,)), jnp.int32),
        "return": jnp.asarray(rng.normal(size=(batch,)), jnp.float32),
    }


def _setup(cfg):
    model = BA3CNet(num_actions=cfg.num_actions)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    state = create_train_state(jax.random.key(0), model, cfg, opt)
    mesh = make_mesh()
    step = make_train_step(model, opt, cfg, mesh)
    return model, opt, state, step


def test_mesh_has_8_fake_devices():
    assert len(jax.devices()) == 8


def test_train_step_runs_and_advances(rng):
    _, _, state, step = _setup(CFG)
    batch = _make_batch(rng, CFG, CFG.batch_size)
    state2, metrics = step(state, batch, CFG.entropy_beta)
    assert int(state2.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["grad_norm"]) > 0


def test_sharded_step_matches_single_device(rng):
    """The psum-averaged update must equal the same update on one device.

    Uses a float32 model + SGD: Adam's first step is ~lr*sign(g), which
    amplifies bf16 reduction-order noise into spurious mismatches; SGD makes
    the comparison directly about the psum'd gradient.
    """
    import optax

    cfg = CFG
    model = BA3CNet(num_actions=cfg.num_actions, compute_dtype=jnp.float32)
    opt = optax.sgd(0.1)
    state0 = create_train_state(jax.random.key(0), model, cfg, opt)
    batch = _make_batch(rng, cfg, 16)

    mesh8 = make_mesh()
    step8 = make_train_step(model, opt, cfg, mesh8)
    mesh1 = make_mesh(num_data=1, devices=jax.devices()[:1])
    step1 = make_train_step(model, opt, cfg, mesh1)

    s8, m8 = step8(state0, batch, cfg.entropy_beta)
    state0b = create_train_state(jax.random.key(0), model, cfg, opt)
    s1, m1 = step1(state0b, batch, cfg.entropy_beta)

    np.testing.assert_allclose(float(m8["loss"]), float(m1["loss"]), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(s8.params), jax.tree_util.tree_leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_learning_rate_is_injectable(rng):
    """inject_hyperparams exposes LR in opt_state (ScheduledHyperParamSetter hook)."""
    _, _, state, step = _setup(CFG)
    hp = state.opt_state[1].hyperparams
    assert "learning_rate" in hp


def test_injected_learning_rate_scales_update(rng):
    """Regression: inject_learning_rate must actually change the applied LR
    (the installed optax state class is NOT optax.InjectHyperparamsState)."""
    import jax.numpy as jnp
    import optax

    from distributed_ba3c_tpu.ops.gradproc import (
        inject_learning_rate,
        make_optimizer,
    )

    opt = make_optimizer(1e-3)
    params = {"w": jnp.ones(3)}
    grads = {"w": jnp.full(3, 0.1)}
    st = opt.init(params)
    upd_default, _ = opt.update(grads, st, params)
    upd_injected, _ = opt.update(
        grads, inject_learning_rate(opt.init(params), 1e-4), params
    )
    ratio = float(upd_injected["w"][0] / upd_default["w"][0])
    assert ratio == pytest.approx(0.1, rel=1e-3)


def test_train_step_lr_zero_freezes_params(rng):
    """End-to-end: passing learning_rate=0 through the jitted step is a no-op
    update, proving the runtime LR plumbing reaches the optimizer."""
    _, _, state, step = _setup(CFG)
    batch = _make_batch(rng, CFG, CFG.batch_size)
    p0 = [np.asarray(x).copy() for x in jax.tree_util.tree_leaves(state.params)]
    state, _ = step(state, batch, CFG.entropy_beta, learning_rate=0.0)
    p1 = jax.tree_util.tree_leaves(state.params)
    for a, b in zip(p0, p1):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_value_loss_decreases_on_repeated_batch(rng):
    """Optimizer path sanity: value regression improves on a fixed batch.

    Small LR + entropy bonus: repeatedly maximising -logp*adv on one batch is
    divergent by construction (the A3C objective is on-policy), so this checks
    the first few steps only.
    """
    cfg = CFG.replace(learning_rate=1e-4)
    _, _, state, step = _setup(cfg)
    batch = _make_batch(rng, cfg, cfg.batch_size)
    losses = []
    for _ in range(10):
        state, metrics = step(state, batch, cfg.entropy_beta)
        losses.append(float(metrics["value_loss"]))
    assert losses[-1] < losses[0]
