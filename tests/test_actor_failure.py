"""Failure injection: an actor dies mid-stream; the plane keeps producing.

SURVEY.md §5 (failure detection): the reference tolerated NO actor loss —
a dead SimulatorProcess silently starved its client slot forever. Here the
master prunes silent clients after ``actor_timeout`` (actors/simulator.py
``_prune_dead_actors``) and the surviving actors keep the train queue fed.
This test SIGKILLs one of three simulator processes mid-run and asserts
both behaviors — the chaos case the unit tests of the pruning logic don't
cover.
"""

from __future__ import annotations

import functools
import json
import os
import queue
import signal
import time

import jax
import numpy as np
import pytest

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
from distributed_ba3c_tpu.actors.simulator import SimulatorProcess
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.envs.fake import build_fake_player
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.predict.server import BatchedPredictor
from distributed_ba3c_tpu.utils.concurrency import ensure_proc_terminate


def _drain(master, n, deadline_s):
    got = []
    deadline = time.time() + deadline_s
    while len(got) < n and time.time() < deadline:
        try:
            got.append(master.queue.get(timeout=2))
        except queue.Empty:
            pass
    return got


@pytest.mark.slow
def test_actor_killed_mid_run_is_pruned_and_plane_survives(tmp_path):
    telemetry.configure(str(tmp_path))  # flight dumps land here
    cfg = BA3CConfig(image_size=(16, 16), fc_units=16, num_actions=4)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    predictor = BatchedPredictor(model, params, batch_size=4, num_threads=1)

    c2s, s2c = f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c"
    master = BA3CSimulatorMaster(
        c2s,
        s2c,
        predictor,
        gamma=cfg.gamma,
        local_time_max=cfg.local_time_max,
        score_queue=queue.Queue(maxsize=100),
        actor_timeout=3.0,
    )
    build = functools.partial(
        build_fake_player,
        image_size=cfg.image_size,
        frame_history=cfg.frame_history,
        num_actions=cfg.num_actions,
    )
    procs = [SimulatorProcess(i, c2s, s2c, build) for i in range(3)]
    ensure_proc_terminate(procs)

    pruned0 = telemetry.registry("master").counter(
        "clients_pruned_total"
    ).value()
    predictor.start()
    master.start()
    for p in procs:
        p.start()

    try:
        # healthy phase: all three actors register and produce
        assert len(_drain(master, 32, 120)) >= 32
        n_clients_before = len(master.clients)
        assert n_clients_before >= 3

        # chaos: SIGKILL one actor (no goodbye on the wire)
        os.kill(procs[0].pid, signal.SIGKILL)
        procs[0].join(timeout=10)

        # survivors keep the queue fed...
        assert len(_drain(master, 32, 120)) >= 32
        # ...and the dead client's state is eventually pruned
        deadline = time.time() + 30
        while len(master.clients) >= n_clients_before and time.time() < deadline:
            time.sleep(0.5)
        assert len(master.clients) < n_clients_before, (
            "dead actor never pruned",
            len(master.clients),
        )
        # the SIGKILL left ACCOUNTED evidence: a ticked prune counter plus
        # a flight-recorder postmortem dump containing the prune event
        # (ISSUE-5 acceptance; counters are asserted as deltas because the
        # registry is process-global across tests)
        pruned = telemetry.registry("master").counter(
            "clients_pruned_total"
        ).value()
        assert pruned >= pruned0 + 1
        dump_path = str(tmp_path / f"flight-{os.getpid()}.json")
        assert os.path.isfile(dump_path), "prune left no flight dump"
        doc = json.load(open(dump_path))
        assert any(e["kind"] == "prune" for e in doc["events"])
    finally:
        telemetry.configure(None)
        for p in procs:
            if p.is_alive():
                p.terminate()
        master.close()
        predictor.stop()
        predictor.join(timeout=5)
        for p in procs:
            p.join(timeout=5)
