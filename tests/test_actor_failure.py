"""Failure injection: an actor dies mid-stream; the plane keeps producing.

SURVEY.md §5 (failure detection): the reference tolerated NO actor loss —
a dead SimulatorProcess silently starved its client slot forever. Here the
master prunes silent clients after ``actor_timeout`` (actors/simulator.py
``_prune_dead_actors``) and the surviving actors keep the train queue fed.
The first test SIGKILLs one of three simulator processes mid-run and
asserts both behaviors — the chaos case the unit tests of the pruning
logic don't cover.

The supervised-chain tests close the loop the orchestration subsystem
added (docs/orchestration.md): SIGKILL → the master's account ticks
(prune or incarnation reset) → the FleetSupervisor respawns the slot with
backoff → the experience stream resumes — no operator in the loop.
"""

from __future__ import annotations

import functools
import json
import os
import queue
import signal
import time

import jax
import numpy as np
import pytest

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
from distributed_ba3c_tpu.actors.simulator import SimulatorProcess
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.envs.fake import build_fake_player
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.predict.server import BatchedPredictor
from distributed_ba3c_tpu.utils.concurrency import ensure_proc_terminate


def _drain(master, n, deadline_s):
    got = []
    deadline = time.time() + deadline_s
    while len(got) < n and time.time() < deadline:
        try:
            got.append(master.queue.get(timeout=2))
        except queue.Empty:
            pass
    return got


@pytest.mark.slow
def test_actor_killed_mid_run_is_pruned_and_plane_survives(tmp_path):
    telemetry.configure(str(tmp_path))  # flight dumps land here
    cfg = BA3CConfig(image_size=(16, 16), fc_units=16, num_actions=4)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    predictor = BatchedPredictor(model, params, batch_size=4, num_threads=1)

    c2s, s2c = f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c"
    master = BA3CSimulatorMaster(
        c2s,
        s2c,
        predictor,
        gamma=cfg.gamma,
        local_time_max=cfg.local_time_max,
        score_queue=queue.Queue(maxsize=100),
        actor_timeout=3.0,
    )
    build = functools.partial(
        build_fake_player,
        image_size=cfg.image_size,
        frame_history=cfg.frame_history,
        num_actions=cfg.num_actions,
    )
    procs = [SimulatorProcess(i, c2s, s2c, build) for i in range(3)]
    ensure_proc_terminate(procs)

    pruned0 = telemetry.registry("master").counter(
        "clients_pruned_total"
    ).value()
    predictor.start()
    master.start()
    for p in procs:
        p.start()

    try:
        # healthy phase: all three actors register and produce
        assert len(_drain(master, 32, 120)) >= 32
        n_clients_before = len(master.clients)
        assert n_clients_before >= 3

        # chaos: SIGKILL one actor (no goodbye on the wire)
        os.kill(procs[0].pid, signal.SIGKILL)
        procs[0].join(timeout=10)

        # survivors keep the queue fed...
        assert len(_drain(master, 32, 120)) >= 32
        # ...and the dead client's state is eventually pruned
        deadline = time.time() + 30
        while len(master.clients) >= n_clients_before and time.time() < deadline:
            time.sleep(0.5)
        assert len(master.clients) < n_clients_before, (
            "dead actor never pruned",
            len(master.clients),
        )
        # the SIGKILL left ACCOUNTED evidence: a ticked prune counter plus
        # a flight-recorder postmortem dump containing the prune event
        # (ISSUE-5 acceptance; counters are asserted as deltas because the
        # registry is process-global across tests)
        pruned = telemetry.registry("master").counter(
            "clients_pruned_total"
        ).value()
        assert pruned >= pruned0 + 1
        dump_path = str(tmp_path / f"flight-{os.getpid()}.json")
        assert os.path.isfile(dump_path), "prune left no flight dump"
        doc = json.load(open(dump_path))
        assert any(e["kind"] == "prune" for e in doc["events"])
    finally:
        telemetry.configure(None)
        for p in procs:
            if p.is_alive():
                p.terminate()
        master.close()
        predictor.stop()
        predictor.join(timeout=5)
        for p in procs:
            p.join(timeout=5)


# ---------------------------------------------------------------------------
# supervised chain: SIGKILL -> master account -> respawn -> stream resumes
# ---------------------------------------------------------------------------


def _block_plane(tmp_path, actor_timeout, backoff_base_s):
    """A supervised 2-server block-wire C++ fleet feeding a live master."""
    from distributed_ba3c_tpu.envs import native
    from distributed_ba3c_tpu.orchestrate import FleetSpec, FleetSupervisor

    n_actions = native.CppBatchedEnv("pong", 1).num_actions
    cfg = BA3CConfig(num_actions=n_actions)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=16)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    predictor = BatchedPredictor(model, params, batch_size=8, num_threads=1)
    predictor.warmup(cfg.state_shape)
    c2s, s2c = f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c"
    master = BA3CSimulatorMaster(
        c2s, s2c, predictor,
        gamma=cfg.gamma, local_time_max=cfg.local_time_max,
        score_queue=queue.Queue(maxsize=1000),
        actor_timeout=actor_timeout,
    )
    spec = FleetSpec(
        pipe_c2s=c2s, pipe_s2c=s2c, game="pong", envs_per_server=4,
        wire="block", fleet_size=2, fleet_min=2, fleet_max=2,
        backoff_base_s=backoff_base_s, backoff_max_s=backoff_base_s,
        stable_after_s=1.0, restart_budget=16, budget_window_s=60.0,
    )
    supervisor = FleetSupervisor(spec, poll_interval_s=0.1)
    predictor.start()
    master.start()
    supervisor.start()
    return predictor, master, supervisor


def _close_plane(predictor, master, supervisor):
    supervisor.stop()
    supervisor.join(timeout=5)
    supervisor.close()
    master.close()
    predictor.stop()
    predictor.join(timeout=5)


def _native_or_skip():
    from distributed_ba3c_tpu.envs import native

    if not native.available():
        pytest.skip("cpp core not built")


@pytest.mark.slow
def test_sigkill_fast_respawn_lands_as_incarnation_reset(tmp_path):
    """Respawn INSIDE the master's patience: the replacement server reuses
    the slot's wire ident, its step counter restarts at 0, and the master
    resets the incarnation instead of growing a second client — then the
    stream resumes."""
    _native_or_skip()
    telemetry.configure(str(tmp_path))
    predictor, master, supervisor = _block_plane(
        tmp_path, actor_timeout=None, backoff_base_s=0.25
    )
    m = telemetry.registry("master")
    o = telemetry.registry("orchestrator")
    inc0 = m.counter("incarnation_resets_total").value()
    respawn0 = o.counter("server_respawns_total").value()
    try:
        assert len(_drain(master, 32, 120)) >= 32
        assert supervisor.sigkill_slot(0)
        deadline = time.time() + 60
        while (
            o.counter("server_respawns_total").value() < respawn0 + 1
            and time.time() < deadline
        ):
            time.sleep(0.2)
        assert o.counter("server_respawns_total").value() >= respawn0 + 1
        deadline = time.time() + 60
        while (
            m.counter("incarnation_resets_total").value() < inc0 + 1
            and time.time() < deadline
        ):
            time.sleep(0.2)
        assert m.counter("incarnation_resets_total").value() >= inc0 + 1
        # the full loop closed: fresh experience flows from both slots
        assert len(_drain(master, 32, 120)) >= 32
        kinds = [e[1] for e in telemetry.flight_recorder().events_since(0)]
        assert "server_death" in kinds
        assert "server_respawn" in kinds
        assert "incarnation_reset" in kinds
    finally:
        telemetry.configure(None)
        _close_plane(predictor, master, supervisor)


@pytest.mark.slow
def test_sigkill_slow_respawn_chains_prune_then_respawn(tmp_path):
    """Respawn SLOWER than the master's patience: the master prunes the
    dead client first (counter + postmortem dump), then the supervisor's
    backoff expires, the slot respawns as a brand-new client, and the
    stream resumes."""
    _native_or_skip()
    telemetry.configure(str(tmp_path))
    predictor, master, supervisor = _block_plane(
        tmp_path, actor_timeout=2.0, backoff_base_s=6.0
    )
    m = telemetry.registry("master")
    o = telemetry.registry("orchestrator")
    pruned0 = m.counter("clients_pruned_total").value()
    respawn0 = o.counter("server_respawns_total").value()
    try:
        assert len(_drain(master, 32, 120)) >= 32
        assert supervisor.sigkill_slot(1)
        # the master's account moves FIRST (prune at ~2s beats the 6s
        # backoff) — the ordering IS the scenario under test
        deadline = time.time() + 60
        while (
            m.counter("clients_pruned_total").value() < pruned0 + 1
            and time.time() < deadline
        ):
            time.sleep(0.2)
        assert m.counter("clients_pruned_total").value() >= pruned0 + 1
        assert o.counter("server_respawns_total").value() == respawn0, (
            "respawn beat the prune — backoff did not hold"
        )
        deadline = time.time() + 120
        while (
            o.counter("server_respawns_total").value() < respawn0 + 1
            and time.time() < deadline
        ):
            time.sleep(0.2)
        assert o.counter("server_respawns_total").value() >= respawn0 + 1
        assert len(_drain(master, 32, 120)) >= 32
        # the prune left its dump on disk before the respawn (postmortem
        # evidence ordering, same contract as the unsupervised test above)
        dump_path = str(tmp_path / f"flight-{os.getpid()}.json")
        assert os.path.isfile(dump_path)
        doc = json.load(open(dump_path))
        assert any(e["kind"] == "prune" for e in doc["events"])
    finally:
        telemetry.configure(None)
        _close_plane(predictor, master, supervisor)
