"""launch_multihost.sh relaunch contract, tested with a stubbed train.py.

The script's exit-75 loop is the recovery half of the rank-failure
semantics (parallel/watchdog.py): a rank that loses lockstep exits 75 and
must be relaunched WITH --load on the run's checkpoint dir — while a fresh
first launch over a reused logdir must NOT silently resume. jax-free:
the stub train.py records its argv and scripts its own exit codes.
"""

import json
import os
import stat
import subprocess

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(__file__)), "scripts", "launch_multihost.sh"
)

_STUB = r"""#!/usr/bin/env python3
import json, os, sys
calls_path = os.environ["STUB_CALLS"]
calls = json.load(open(calls_path)) if os.path.exists(calls_path) else []
calls.append(sys.argv[1:])
json.dump(calls, open(calls_path, "w"))
codes = json.loads(os.environ["STUB_EXIT_CODES"])
sys.exit(codes[len(calls) - 1])
"""


def _run(tmp_path, exit_codes, extra_args, with_ckpt_dir, ckpt_saved=True):
    """Run the launcher with a stub train.py; return (rc, recorded argvs).

    ``with_ckpt_dir`` creates $LOGDIR/checkpoints; ``ckpt_saved`` puts an
    actual ckpt-* entry inside it (CheckpointManager creates the DIR at
    startup before any save, so dir-exists alone must not trigger resume).
    """
    workdir = tmp_path / "wd"
    workdir.mkdir(exist_ok=True)
    stub = workdir / "train.py"
    stub.write_text(_STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    logdir = workdir / "logs"
    logdir.mkdir(exist_ok=True)
    if with_ckpt_dir:
        (logdir / "checkpoints").mkdir(exist_ok=True)
        if ckpt_saved:
            (logdir / "checkpoints" / "ckpt-80").mkdir(exist_ok=True)
            (logdir / "checkpoints" / "checkpoint.json").write_text(
                json.dumps({"all": [80], "latest": 80})
            )
    calls = workdir / "calls.json"
    env = dict(os.environ)
    env["STUB_CALLS"] = str(calls)
    env["STUB_EXIT_CODES"] = json.dumps(exit_codes)
    env["SLURM_PROCID"] = "0"  # skip the hostname->rank lookup
    p = subprocess.run(
        ["bash", _SCRIPT, "h1:9900,h2:9900", "--logdir", str(logdir)]
        + extra_args,
        cwd=workdir,
        env=env,
        capture_output=True,
        text=True,
        timeout=60,
    )
    recorded = json.load(open(calls)) if calls.exists() else []
    return p.returncode, recorded, p.stderr


def test_exit75_relaunches_with_load(tmp_path):
    rc, calls, err = _run(
        tmp_path, [75, 0], extra_args=[], with_ckpt_dir=True
    )
    assert rc == 0, err
    assert len(calls) == 2
    # first launch: NO --load even though a checkpoint dir exists (fresh
    # first launches stay fresh — silent auto-resume could 'complete' a
    # finished run with zero training)
    assert "--load" not in calls[0]
    # relaunch after exit 75: resumes from the logdir's checkpoints
    assert "--load" in calls[1]
    load_path = calls[1][calls[1].index("--load") + 1]
    assert load_path.endswith("checkpoints")
    # worker identity args survive both launches
    for c in calls:
        assert "--worker_hosts" in c and "--task_index" in c


def test_equals_form_logdir_is_parsed(tmp_path):
    """--logdir=PATH (argparse's '=' form) must be recognized too — a missed
    parse relaunches WITHOUT --load and restarts training from step 0."""
    workdir = tmp_path / "wd"
    workdir.mkdir()
    logdir = workdir / "logs"
    (logdir / "checkpoints" / "ckpt-80").mkdir(parents=True)
    (logdir / "checkpoints" / "checkpoint.json").write_text(
        json.dumps({"all": [80], "latest": 80})
    )
    stub = workdir / "train.py"
    stub.write_text(_STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    calls = workdir / "calls.json"
    env = dict(os.environ)
    env["STUB_CALLS"] = str(calls)
    env["STUB_EXIT_CODES"] = json.dumps([75, 0])
    env["SLURM_PROCID"] = "0"
    p = subprocess.run(
        ["bash", _SCRIPT, "h1:9900,h2:9900", f"--logdir={logdir}"],
        cwd=workdir, env=env, capture_output=True, text=True, timeout=60,
    )
    assert p.returncode == 0, p.stderr
    recorded = json.load(open(calls))
    assert "--load" in recorded[1]


def test_caller_load_replaced_by_run_checkpoints_on_relaunch(tmp_path):
    """A caller --load is a warm-START source. On an exit-75 relaunch the
    run's own $LOGDIR/checkpoints must take precedence — resuming from the
    stale warm-start dir would discard all progress since launch (ADVICE
    r4 #1: recurring rank failures would replay the same span forever)."""
    rc, calls, err = _run(
        tmp_path, [75, 0], extra_args=["--load", "/some/ckpts"],
        with_ckpt_dir=True,
    )
    assert rc == 0, err
    # first launch: the caller's warm start, untouched
    assert calls[0].count("--load") == 1
    assert calls[0][calls[0].index("--load") + 1] == "/some/ckpts"
    # relaunch: exactly ONE --load, pointing at the run's own checkpoints
    assert calls[1].count("--load") == 1
    assert calls[1][calls[1].index("--load") + 1].endswith("checkpoints")


def test_caller_load_equals_form_replaced_on_relaunch(tmp_path):
    rc, calls, err = _run(
        tmp_path, [75, 0], extra_args=["--load=/some/ckpts"],
        with_ckpt_dir=True,
    )
    assert rc == 0, err
    assert "--load=/some/ckpts" in calls[0]
    assert "--load=/some/ckpts" not in calls[1]
    assert calls[1].count("--load") == 1
    assert calls[1][calls[1].index("--load") + 1].endswith("checkpoints")


def test_caller_load_kept_when_no_run_checkpoints_yet(tmp_path):
    """Exit 75 before the first collective save: the run-local checkpoint
    dir EXISTS (CheckpointManager creates it at startup) but holds no
    saved checkpoint — the warm start is still the right resume point;
    resuming from the empty dir would crash and strand the allocation."""
    rc, calls, err = _run(
        tmp_path, [75, 0], extra_args=["--load", "/some/ckpts"],
        with_ckpt_dir=True, ckpt_saved=False,
    )
    assert rc == 0, err
    for c in calls:
        assert c.count("--load") == 1
        assert c[c.index("--load") + 1] == "/some/ckpts"


def test_unfinalized_meta_is_not_resumable(tmp_path):
    """A rank killed mid-FIRST-save leaves ckpt-* entries (or orbax temp
    dirs) with checkpoint.json's 'latest' still null — resuming from that
    would exit 1 and permanently kill the retry loop. The caller's warm
    start must be kept."""
    workdir = tmp_path / "wd"
    workdir.mkdir()
    logdir = workdir / "logs"
    ck = logdir / "checkpoints"
    (ck / "ckpt-80.orbax-checkpoint-tmp-123").mkdir(parents=True)
    (ck / "checkpoint.json").write_text(
        json.dumps({"all": [], "latest": None})
    )
    stub = workdir / "train.py"
    stub.write_text(_STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    calls = workdir / "calls.json"
    env = dict(os.environ)
    env["STUB_CALLS"] = str(calls)
    env["STUB_EXIT_CODES"] = json.dumps([75, 0])
    env["SLURM_PROCID"] = "0"
    p = subprocess.run(
        ["bash", _SCRIPT, "h1:9900,h2:9900", "--logdir", str(logdir),
         "--load", "/warm/ckpts"],
        cwd=workdir, env=env, capture_output=True, text=True, timeout=60,
    )
    assert p.returncode == 0, p.stderr
    recorded = json.load(open(calls))
    for c in recorded:
        assert c.count("--load") == 1
        assert c[c.index("--load") + 1] == "/warm/ckpts"


def test_fresh_run_empty_ckpt_dir_relaunches_fresh(tmp_path):
    """No caller --load and no saved checkpoint: relaunch must stay fresh
    (no --load pointing at the empty startup-created dir)."""
    rc, calls, err = _run(
        tmp_path, [75, 0], extra_args=[], with_ckpt_dir=True,
        ckpt_saved=False,
    )
    assert rc == 0, err
    for c in calls:
        assert "--load" not in c


def test_nonzero_non75_exit_propagates(tmp_path):
    rc, calls, err = _run(tmp_path, [1], extra_args=[], with_ckpt_dir=True)
    assert rc == 1
    assert len(calls) == 1
