"""Multi-host integration: 2 real processes over localhost TCP (gloo CPU).

The reference validated its ClusterSpec/PS wiring only on a live cluster
(SURVEY.md §4); here two subprocesses run `jax.distributed.initialize`,
feed DIFFERENT local batch shards into the sharded train step, and must
produce the IDENTICAL post-update params — equal to a single-process run
over the concatenated batch (the psum makes the update global).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _clean_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(_WORKER))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    # each process gets exactly one CPU device: drop any forced device count
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    return env


def _launch(rank: int, nprocs: int, coord: str, *extra: str) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, _WORKER, str(rank), str(nprocs), coord, *extra],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=_clean_env(),
        cwd=os.path.dirname(os.path.dirname(_WORKER)),
    )


def _run_pair(*extra: str, timeout: int = 240) -> list:
    coord = f"127.0.0.1:{_free_port()}"
    procs = [_launch(r, 2, coord, *extra) for r in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
            assert p.returncode == 0, out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def _grep(out: str, tag: str) -> str:
    lines = [l for l in out.splitlines() if l.startswith(tag + " ")]
    assert lines, f"no {tag!r} line in:\n{out}"
    return lines[-1][len(tag) + 1 :]


@pytest.mark.slow
def test_two_process_psum_update_identical_and_matches_single():
    outs = _run_pair()
    d0, d1 = (_grep(o, "DIGEST") for o in outs)
    assert d0 == d1, "workers diverged after one psum'd update"
    l0, l1 = (_grep(o, "LOSS") for o in outs)
    assert l0 == l1

    # single-process ground truth over the same (concatenated) global batch
    coord = f"127.0.0.1:{_free_port()}"
    p = _launch(0, 1, coord)
    out, _ = p.communicate(timeout=240)
    assert p.returncode == 0, out
    d_single = _grep(out, "DIGEST")
    l_single = _grep(out, "LOSS")
    import numpy as np

    # loss is computed BEFORE the update on the identical global batch: must
    # agree to bf16-reduction tolerance between 1-proc and 2-proc runs
    np.testing.assert_allclose(float(l0), float(l_single), rtol=1e-3)
    # params after one ADAM step: first-step updates are ±lr·m̂/(√v̂+ε) ≈ ±lr,
    # so a bf16 ULP difference in a near-zero gradient element flips a whole
    # ±1e-4 update. Require agreement at Adam-step scale, not float ULPs.
    a = np.array([float(x) for x in d0.split()])
    b = np.array([float(x) for x in d_single.split()])
    np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-3)


@pytest.mark.slow
def test_two_process_fused_trainer(tmp_path):
    """Fused on-device trainer across 2 processes: global mesh, per-host env
    shards, psum'd update, collective checkpoint saves — one epoch runs and
    both ranks exit 0 with the shared checkpoint written."""
    logdir = str(tmp_path / "flog")
    outs = _run_pair("fused", logdir, timeout=420)
    for out in outs:
        assert _grep(out, "CLI_RC") == "0"
    assert os.path.isdir(os.path.join(logdir, "checkpoints")), outs[0]
    assert os.path.isfile(os.path.join(logdir, "stat.json")), outs[0]


@pytest.mark.slow
def test_two_process_vtrace_trainer(tmp_path):
    """The third --trainer value (tpu_vtrace_ba3c) across 2 real processes:
    rollout-batch sharding over the global mesh + psum'd off-policy update
    (VERDICT r2 #5 — the gate and suite must exercise all three trainers)."""
    logdir = str(tmp_path / "vlog")
    outs = _run_pair("vtrace", logdir, timeout=420)
    for out in outs:
        assert _grep(out, "CLI_RC") == "0"
    assert os.path.isfile(os.path.join(logdir, "stat.json")), outs[0]


@pytest.mark.slow
def test_two_process_cli_fake_env_trains(tmp_path):
    logdir = str(tmp_path / "log")
    outs = _run_pair("cli", logdir, timeout=420)
    for out in outs:
        assert _grep(out, "CLI_RC") == "0"
    # chief owns stat.json + checkpoints; worker logs to its own dir
    assert os.path.isfile(os.path.join(logdir, "stat.json")), outs[0]
    assert os.path.isdir(os.path.join(logdir, "checkpoints")), outs[0]
    assert not os.path.isdir(os.path.join(logdir + "-worker1", "checkpoints"))
