"""Block wire protocol: parity with the per-env wire, zero-copy codecs,
shm ring semantics, block prune/heartbeat, FastQueue, predictor blocks.

The parity tests drive BOTH masters OFFLINE with identical deterministic
trajectories (same FakeEnv seeds, same deterministic policy) and assert the
emitted experience streams are identical as multisets — the block wire is
a transport optimization and must be invisible to the learner.
"""

from __future__ import annotations

import json
import queue
import tempfile
import threading
import time

import numpy as np
import pytest

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
from distributed_ba3c_tpu.actors.simulator import (
    BlockClientState,
    BlockStatesView,
)
from distributed_ba3c_tpu.actors.vtrace_master import VTraceSimulatorMaster
from distributed_ba3c_tpu.envs.fake import build_fake_player
from distributed_ba3c_tpu.utils.concurrency import FastQueue
from distributed_ba3c_tpu.utils.serialize import pack_block, unpack_block

N_ACTIONS = 4


def _counter(name: str) -> float:
    """Current value of a master-registry counter (registries are
    process-global, so tests assert DELTAS around the scenario)."""
    return telemetry.registry("master").counter(name).value()


def _policy(state: np.ndarray):
    """Deterministic (action, value, logp) from pixels — both wire drivers
    compute the same actions, so trajectories match exactly."""
    h = int(np.asarray(state, np.uint64).sum())
    return h % N_ACTIONS, (h % 8) / 8.0, -1.25


class _DetPredictor:
    """Synchronous deterministic predictor stub speaking BOTH task APIs."""

    def put_task(self, state, cb, **kw):
        a, v, lp = _policy(state)
        cb(a, v, lp)

    def put_block_task(self, states, cb, **kw):
        outs = [_policy(states[j]) for j in range(states.shape[0])]
        cb(
            np.asarray([o[0] for o in outs], np.int32),
            np.asarray([o[1] for o in outs], np.float32),
            np.asarray([o[2] for o in outs], np.float32),
        )


def _players(n, seed_base=0):
    return [
        build_fake_player(
            seed_base + i, image_size=(16, 16), frame_history=2,
            num_actions=N_ACTIONS,
        )
        for i in range(n)
    ]


def _drive_per_env(master, players, n_steps):
    b = len(players)
    idents = [f"sim-{i}".encode() for i in range(b)]
    states = [p.current_state() for p in players]
    rewards, overs = [0.0] * b, [False] * b
    for _ in range(n_steps):
        for j in range(b):
            master._on_message(idents[j], states[j], rewards[j], overs[j])
            a, _, _ = _policy(states[j])
            rewards[j], overs[j] = players[j].action(a)
            states[j] = players[j].current_state()


def _drive_block(master, players, n_steps):
    b = len(players)
    ident = b"blk-0*block"
    master.clients[ident] = BlockClientState(ident, b)
    rewards = np.zeros(b, np.float32)
    overs = np.zeros(b, bool)
    for _ in range(n_steps):
        states = np.stack([p.current_state() for p in players])
        master._on_block_message(ident, states, rewards.copy(), overs.copy())
        for j in range(b):
            a, _, _ = _policy(states[j])
            r, o = players[j].action(a)
            rewards[j], overs[j] = r, o


def _drain(q):
    out = []
    while True:
        try:
            out.append(q.get_nowait())
        except queue.Empty:
            return out


def _dp_key(dp):
    state, action, ret = dp
    return (np.asarray(state).tobytes(), int(action), float(ret))


def test_ba3c_wire_parity(tmp_path):
    """Block and per-env wires emit IDENTICAL n-step experience streams
    (as multisets — inter-env interleaving is unspecified on both wires)."""
    kw = dict(gamma=0.5, local_time_max=3)
    m1 = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/a1", f"ipc://{tmp_path}/b1", _DetPredictor(),
        score_queue=queue.Queue(), **kw,
    )
    m2 = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/a2", f"ipc://{tmp_path}/b2", _DetPredictor(),
        score_queue=queue.Queue(), **kw,
    )
    try:
        _drive_per_env(m1, _players(4), 50)
        _drive_block(m2, _players(4), 50)
        dp1 = sorted(_dp_key(d) for d in _drain(m1.queue))
        dp2 = sorted(_dp_key(d) for d in _drain(m2.queue))
        assert len(dp1) > 40  # episodes ended AND windows truncated
        assert dp1 == dp2
        s1 = sorted(_drain(m1.score_queue))
        s2 = sorted(_drain(m2.score_queue))
        assert s1 == s2 and len(s1) > 0
    finally:
        m1.close()
        m2.close()


def test_ba3c_wire_parity_with_reward_clip(tmp_path):
    """The vectorized clip matches the scalar clip through the block path."""
    kw = dict(gamma=0.5, local_time_max=2, reward_clip=1.0)
    m1 = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/a1", f"ipc://{tmp_path}/b1", _DetPredictor(),
        score_queue=queue.Queue(), **kw,
    )
    m2 = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/a2", f"ipc://{tmp_path}/b2", _DetPredictor(),
        score_queue=queue.Queue(), **kw,
    )
    try:
        _drive_per_env(m1, _players(2, seed_base=7), 30)
        _drive_block(m2, _players(2, seed_base=7), 30)
        assert sorted(_dp_key(d) for d in _drain(m1.queue)) == sorted(
            _dp_key(d) for d in _drain(m2.queue)
        )
    finally:
        m1.close()
        m2.close()


def _seg_key(seg):
    return tuple(
        np.asarray(seg[k]).tobytes()
        for k in (
            "state", "action", "reward", "done", "behavior_log_probs",
            "bootstrap_state",
        )
    )


def test_vtrace_wire_parity(tmp_path):
    """V-trace unroll segments are identical across wires (same seeds)."""
    m1 = VTraceSimulatorMaster(
        f"ipc://{tmp_path}/a1", f"ipc://{tmp_path}/b1", _DetPredictor(),
        unroll_len=3, score_queue=queue.Queue(),
    )
    m2 = VTraceSimulatorMaster(
        f"ipc://{tmp_path}/a2", f"ipc://{tmp_path}/b2", _DetPredictor(),
        unroll_len=3, score_queue=queue.Queue(),
    )
    try:
        _drive_per_env(m1, _players(3), 40)
        _drive_block(m2, _players(3), 40)
        seg1 = sorted(_seg_key(s) for s in _drain(m1.queue))
        seg2 = sorted(_seg_key(s) for s in _drain(m2.queue))
        assert len(seg1) >= 3 * (40 // 4)  # unrolls tile time with no gaps
        assert seg1 == seg2
    finally:
        m1.close()
        m2.close()


# -- zero-copy multipart codec ---------------------------------------------


def test_pack_block_roundtrip_zero_copy():
    meta = [b"ident*block", 17, 8]
    obs = np.arange(4 * 8 * 6 * 5, dtype=np.uint8).reshape(4, 8, 6, 5)
    rew = np.linspace(-2, 2, 8).astype(np.float32)
    done = np.zeros(8, np.uint8)
    frames = pack_block(meta, [obs, rew, done])
    # simulate the wire: frames arrive as bytes
    wire = [bytes(f) for f in frames]
    meta2, (o2, r2, d2) = unpack_block(wire)
    assert list(meta2) == meta
    np.testing.assert_array_equal(o2, obs)
    np.testing.assert_array_equal(r2, rew)
    np.testing.assert_array_equal(d2, done)
    # unpack is ZERO-COPY: arrays are views over the received frames
    for arr in (o2, r2, d2):
        assert arr.base is not None


def test_pack_block_noncontiguous_and_strided():
    """Strided/transposed inputs round-trip (pack pays the one copy)."""
    base = np.arange(240, dtype=np.float32).reshape(10, 24)
    strided = base[::2, ::3]              # non-contiguous view
    transposed = base.T                   # reversed strides
    frames = pack_block(None, [strided, transposed])
    _, (s2, t2) = unpack_block([bytes(f) for f in frames])
    np.testing.assert_array_equal(s2, strided)
    np.testing.assert_array_equal(t2, transposed)


def test_pack_block_send_side_is_zero_copy_for_contiguous():
    arr = np.zeros((64, 64), np.uint8)
    frames = pack_block(None, [arr])
    # the payload frame IS the array's buffer, not a tobytes() copy
    assert np.shares_memory(np.frombuffer(frames[1], np.uint8), arr)


def test_unpack_block_frame_count_mismatch():
    frames = pack_block(None, [np.zeros(3, np.uint8)])
    with pytest.raises(ValueError):
        unpack_block([bytes(frames[0])])  # header says 1 array, 0 frames


# -- BlockStatesView (block-shm states) ------------------------------------


def test_block_states_view_mature_rows_are_views():
    win = np.random.default_rng(0).integers(0, 255, (4, 3, 8, 8)).astype(np.uint8)
    v = BlockStatesView(win, np.array([5, 5, 5]))
    assert v.shape == (3, 8, 8, 4) and len(v) == 3
    row = v[1]
    assert row.shape == (8, 8, 4)
    assert np.shares_memory(row, win)  # zero-copy
    np.testing.assert_array_equal(row, win[:, 1].transpose(1, 2, 0))


def test_block_states_view_young_rows_zero_history():
    win = np.full((4, 2, 4, 4), 9, np.uint8)
    v = BlockStatesView(win, np.array([0, 2]))
    r0 = v[0]  # age 0: only the newest plane is real history
    assert (r0[..., :3] == 0).all() and (r0[..., 3] == 9).all()
    r1 = v[1]  # age 2: one missing plane
    assert (r1[..., :1] == 0).all() and (r1[..., 1:] == 9).all()
    # materialization applies the same zeroing row-wise
    full = np.asarray(v)
    np.testing.assert_array_equal(full[0], r0)
    np.testing.assert_array_equal(full[1], r1)


# -- FastQueue --------------------------------------------------------------


def test_fast_queue_fifo_and_nowait():
    q = FastQueue(maxsize=3)
    for i in range(3):
        q.put(i)
    assert q.full() and q.qsize() == 3
    with pytest.raises(queue.Full):
        q.put_nowait(99)
    assert [q.get_nowait() for _ in range(3)] == [0, 1, 2]
    assert q.empty()
    with pytest.raises(queue.Empty):
        q.get_nowait()


def test_fast_queue_timeouts():
    q = FastQueue(maxsize=1)
    t0 = time.monotonic()
    with pytest.raises(queue.Empty):
        q.get(timeout=0.05)
    assert time.monotonic() - t0 >= 0.04
    q.put(1)
    with pytest.raises(queue.Full):
        q.put(2, timeout=0.05)


def test_fast_queue_cross_thread():
    q = FastQueue(maxsize=128)
    got = []

    def consumer():
        for _ in range(1000):
            got.append(q.get(timeout=5))

    t = threading.Thread(target=consumer, daemon=True)
    t.start()
    for i in range(1000):
        q.put(i, timeout=5)
    t.join(timeout=10)
    assert got == list(range(1000))


# -- shm ring safety contract ----------------------------------------------


def test_shm_ring_capacity_check_refuses_unbounded_queue(tmp_path):
    m = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/a", f"ipc://{tmp_path}/b", _DetPredictor(),
        train_queue=queue.Queue(),  # UNBOUNDED: no backpressure
    )
    try:
        blk = BlockClientState(b"x*block", 4)
        m.clients[b"x*block"] = blk
        meta = [b"x*block", 0, 4, "ba3c-ring-test-none", 64, 8, 8, 4]
        with pytest.raises(ValueError, match="BOUNDED"):
            m._shm_states(blk, meta, 0, np.zeros(4, bool))
    finally:
        m.close()


def test_shm_ring_capacity_check_refuses_small_ring(tmp_path):
    m = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/a", f"ipc://{tmp_path}/b", _DetPredictor(),
        train_queue=queue.Queue(maxsize=4096),
    )
    try:
        blk = BlockClientState(b"x*block", 4)
        m.clients[b"x*block"] = blk
        # cap 64 << 4096/4: a backed-up queue could outlive the ring
        meta = [b"x*block", 0, 4, "ba3c-ring-test-none", 64, 8, 8, 4]
        with pytest.raises(ValueError, match="too small"):
            m._shm_states(blk, meta, 0, np.zeros(4, bool))
    finally:
        m.close()


def test_shm_ring_capacity_counts_vtrace_segment_span(tmp_path):
    # each queued V-trace segment pins a bootstrap_state ring view a whole
    # unroll behind its head: the check must count T steps per queued item.
    # This config passed the pre-fix check (64/4 + 20 + 8 = 44 < 64).
    from distributed_ba3c_tpu.actors.vtrace_master import VTraceSimulatorMaster

    m = VTraceSimulatorMaster(
        f"ipc://{tmp_path}/a", f"ipc://{tmp_path}/b", _DetPredictor(),
        unroll_len=20, train_queue=queue.Queue(maxsize=64),
    )
    try:
        blk = BlockClientState(b"x*block", 4)
        m.clients[b"x*block"] = blk
        meta = [b"x*block", 0, 4, "ba3c-ring-test-none", 64, 8, 8, 4]
        with pytest.raises(ValueError, match="too small"):
            m._shm_states(blk, meta, 0, np.zeros(4, bool))
    finally:
        m.close()


def test_shm_ring_capacity_counts_feed_holder(tmp_path):
    # items the feed's collate holder pulled OUT of the queue still pin
    # ring views; feed_batch declares that capacity to the check. Queue
    # alone is fine here (32/4 + 5 + 4 + 8 = 25 < 64), holder is not.
    m = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/a", f"ipc://{tmp_path}/b", _DetPredictor(),
        train_queue=queue.Queue(maxsize=32),
    )
    m.feed_batch = 2560
    try:
        blk = BlockClientState(b"x*block", 4)
        m.clients[b"x*block"] = blk
        meta = [b"x*block", 0, 4, "ba3c-ring-test-none", 64, 8, 8, 4]
        with pytest.raises(ValueError, match="too small"):
            m._shm_states(blk, meta, 0, np.zeros(4, bool))
    finally:
        m.close()


def test_shm_ring_create_attach_roundtrip():
    from distributed_ba3c_tpu.utils import shm

    if not shm.available():
        pytest.skip("/dev/shm not available")
    name = f"ba3c-ring-test-{time.monotonic_ns()}"
    ring = shm.ShmRing.create(name, 4, 2, 8, 8)
    try:
        ring.arr[1] = 7
        peer = shm.ShmRing.attach(name, 4, 2, 8, 8)
        assert (peer.arr[1] == 7).all() and (peer.arr[0] == 0).all()
        with pytest.raises(ValueError):
            shm.ShmRing.attach(name, 8, 2, 8, 8)  # wrong shape
        peer.close()
    finally:
        ring.close(unlink=True)
    with pytest.raises(OSError):
        shm.ShmRing.attach(name, 4, 2, 8, 8)  # unlinked


class _WireFrame:
    """Stand-in for zmq.Frame: just the .buffer the master reads."""

    def __init__(self, buf):
        self.buffer = bytes(buf)


def _wire_frames(meta, arrays):
    return [_WireFrame(f) for f in pack_block(meta, arrays)]


def test_block_restart_resets_client_state(tmp_path):
    # a crashed server restarted under the SAME ident starts over at step 0;
    # the master must reset the incarnation (pending steps, scores, ages)
    # instead of attaching post-restart rewards to pre-crash states
    m = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/a", f"ipc://{tmp_path}/b", _DetPredictor(),
        train_queue=queue.Queue(maxsize=64),
    )
    try:
        ident = b"x*block"
        b, h, w, hist = 2, 8, 8, 2
        obs = np.zeros((hist, b, h, w), np.uint8)
        rew, dn = np.zeros(b, np.float32), np.zeros(b, np.uint8)
        resets0 = _counter("incarnation_resets_total")
        for step in (0, 1, 2):
            m._on_block_frames(_wire_frames([ident, step, b], [obs, rew, dn]))
        blk = m.clients[ident]
        assert blk.last_step == 2 and len(blk.steps) == 3
        blk.scores[:] = 7.0
        m._on_block_frames(_wire_frames([ident, 0, b], [obs, rew, dn]))
        blk2 = m.clients[ident]
        assert blk2 is not blk, "restart must create a fresh incarnation"
        assert blk2.last_step == 0 and len(blk2.steps) == 1
        assert (blk2.scores == 0).all()
        # the failure is ACCOUNTED, not just handled (docs/observability.md)
        assert _counter("incarnation_resets_total") == resets0 + 1
    finally:
        m.close()


def test_block_shm_misconfig_drops_client_not_master(tmp_path):
    # a ring the safety check refuses must drop THAT client, not kill the
    # receive loop for every other client (the remote-fleet path cannot be
    # sized by cli.py, so the refusal is an expected operational error)
    m = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/a", f"ipc://{tmp_path}/b", _DetPredictor(),
        train_queue=queue.Queue(),  # UNBOUNDED: the check refuses
    )
    try:
        ident = b"x*block"
        meta = [ident, 0, 4, "ba3c-ring-test-none", 64, 8, 8, 4]
        frames = _wire_frames(
            meta, [np.zeros(4, np.float32), np.zeros(4, np.uint8)]
        )
        dropped0 = _counter("clients_dropped_total")
        m._on_block_frames(frames)  # must swallow the ValueError
        assert ident not in m.clients
        # the refusal ticked the drop counter (docs/observability.md)
        assert _counter("clients_dropped_total") == dropped0 + 1
    finally:
        m.close()


def test_malformed_block_message_skipped_not_fatal(tmp_path):
    # wire input is untrusted (a version-mismatched remote fleet, or any
    # stray sender on the bound port): an undecodable message must be
    # SKIPPED — not raise out of the receive loop, not create a client
    m = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/a", f"ipc://{tmp_path}/b", _DetPredictor(),
        train_queue=queue.Queue(maxsize=64),
    )
    try:
        b, h, w, hist = 2, 8, 8, 2
        obs = np.zeros((hist, b, h, w), np.uint8)
        rew, dn = np.zeros(b, np.float32), np.zeros(b, np.uint8)
        good = _wire_frames([b"x*block", 0, b], [obs, rew, dn])
        rejected0 = _counter("blocks_rejected_total")
        # header is not valid msgpack at all
        m._on_block_frames([_WireFrame(b"\xc1garbage"), _WireFrame(b"")])
        # header declares more arrays than the message carries
        m._on_block_frames(_wire_frames([b"y*block", 0, b], [obs, rew, dn])[:-1])
        # header meta is not (ident, step, n_envs)-shaped
        m._on_block_frames(_wire_frames([42], [rew, dn]))
        # payload shapes contradict the declared n_envs
        m._on_block_frames(
            _wire_frames([b"z*block", 0, b + 1], [obs, rew, dn])
        )
        assert not m.clients, "malformed messages must not create clients"
        # every rejection was ACCOUNTED (docs/observability.md)
        assert _counter("blocks_rejected_total") == rejected0 + 4
        m._on_block_frames(good)  # the loop is still alive and serving
        assert b"x*block" in m.clients
    finally:
        m.close()


def test_shm_ring_recreate_keeps_old_mapping_valid():
    # restart-over-stale-ring: create() renames a fresh inode over the path,
    # so a master still mapping the OLD inode reads stale-but-valid data
    # (no SIGBUS from an in-place truncate) until it re-attaches
    from distributed_ba3c_tpu.utils import shm

    if not shm.available():
        pytest.skip("/dev/shm not available")
    name = f"ba3c-ring-test-{time.monotonic_ns()}"
    ring1 = shm.ShmRing.create(name, 4, 2, 8, 8)
    ring2 = None
    peer = peer2 = None
    try:
        ring1.arr[0] = 3
        peer = shm.ShmRing.attach(name, 4, 2, 8, 8)
        ring2 = shm.ShmRing.create(name, 4, 2, 8, 8)  # the restart
        assert (peer.arr[0] == 3).all()  # old mapping intact
        ring2.arr[0] = 9
        peer2 = shm.ShmRing.attach(name, 4, 2, 8, 8)
        assert (peer2.arr[0] == 9).all() and (peer.arr[0] == 3).all()
    finally:
        for r in (peer, peer2, ring1):
            if r is not None:
                r.close()
        if ring2 is not None:
            ring2.close(unlink=True)


# -- block client prune / heartbeat under a killed server ------------------


def _block_sender_thread(c2s, s2c, ident, n_steps, stop_evt):
    """A minimal block-wire speaker: send, await actions, repeat — then go
    SILENT (the killed-server scenario; no goodbye on the wire)."""
    import zmq

    ctx = zmq.Context()
    push = ctx.socket(zmq.PUSH)
    push.connect(c2s)
    dealer = ctx.socket(zmq.DEALER)
    dealer.setsockopt(zmq.IDENTITY, ident)
    dealer.setsockopt(zmq.RCVTIMEO, 10_000)
    dealer.connect(s2c)
    b, h, w, hist = 2, 8, 8, 2
    obs = np.zeros((hist, b, h, w), np.uint8)
    rewards = np.zeros(b, np.float32)
    dones = np.zeros(b, np.uint8)
    try:
        for step in range(n_steps):
            push.send_multipart(
                pack_block([ident, step, b], [obs, rewards, dones])
            )
            acts = np.frombuffer(dealer.recv(), np.int32)
            assert acts.shape == (b,)
    finally:
        stop_evt.set()
        dealer.close(0)
        push.close(0)
        ctx.term()


def test_block_client_pruned_after_server_death(tmp_path):
    import os

    telemetry.configure(str(tmp_path))  # flight dumps land here
    c2s, s2c = f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c"
    m = BA3CSimulatorMaster(
        c2s, s2c, _DetPredictor(), gamma=0.5, local_time_max=3,
        actor_timeout=2.0, score_queue=queue.Queue(),
    )
    ident = b"mortal-0*block"
    done_evt = threading.Event()
    t = threading.Thread(
        target=_block_sender_thread, args=(c2s, s2c, ident, 5, done_evt),
        daemon=True,
    )
    pruned0 = _counter("clients_pruned_total")
    m.start()
    t.start()
    try:
        # the block registers and heartbeats while alive
        deadline = time.monotonic() + 30
        while ident not in m.clients and time.monotonic() < deadline:
            time.sleep(0.05)
        assert ident in m.clients, "block client never registered"
        assert done_evt.wait(timeout=30), "sender never finished its steps"
        # ...and is pruned once silent for > actor_timeout
        deadline = time.monotonic() + 30
        while ident in m.clients and time.monotonic() < deadline:
            time.sleep(0.2)
        assert ident not in m.clients, "dead block client never pruned"
        # the prune TICKED its counter and left a postmortem flight dump
        # containing the prune event (the ISSUE-5 acceptance scenario)
        assert _counter("clients_pruned_total") == pruned0 + 1
        dump_path = str(tmp_path / f"flight-{os.getpid()}.json")
        assert os.path.isfile(dump_path), "prune left no flight dump"
        doc = json.load(open(dump_path))
        assert doc["reason"] == "actor prune"
        prunes = [e for e in doc["events"] if e["kind"] == "prune"]
        assert prunes and repr(ident) in prunes[-1]["ident"]
    finally:
        telemetry.configure(None)
        m.close()
        t.join(timeout=5)


# -- predictor block serving -----------------------------------------------


def _tiny_predictor(batch_size=8, **kw):
    import jax

    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.predict.server import BatchedPredictor

    cfg = BA3CConfig(image_size=(16, 16), fc_units=16, num_actions=N_ACTIONS)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    return BatchedPredictor(model, params, batch_size=batch_size, **kw), cfg


def test_put_block_task_serves_whole_block():
    pred, cfg = _tiny_predictor(batch_size=8, num_threads=1, coalesce_ms=0.0)
    pred.start()
    try:
        got = []
        evt = threading.Event()

        def cb(actions, values, logps):
            got.append((actions, values, logps))
            evt.set()

        states = np.random.default_rng(0).integers(
            0, 255, (5, *cfg.state_shape)
        ).astype(np.uint8)
        pred.put_block_task(states, cb)
        assert evt.wait(timeout=60)
        actions, values, logps = got[0]
        assert actions.shape == values.shape == logps.shape == (5,)
        assert actions.dtype == np.int32
        assert ((actions >= 0) & (actions < N_ACTIONS)).all()
        assert np.isfinite(values).all() and (logps <= 0).all()
    finally:
        pred.stop()
        pred.join(timeout=5)


def test_put_block_task_rejects_oversized_block():
    pred, cfg = _tiny_predictor(batch_size=8)
    with pytest.raises(ValueError, match="exceeds the serving bucket"):
        pred.put_block_task(
            np.zeros((9, *cfg.state_shape), np.uint8), lambda *a: None
        )
    pred.stop()


@pytest.mark.timeout(600)
@pytest.mark.parametrize("wire", ["block", "block-shm"])
def test_live_block_plane_end_to_end(tmp_path, wire):
    """Real CppEnvServerProcess fleets on both block wires stream through a
    real predictor into well-formed n-step datapoints + episode scores."""
    from distributed_ba3c_tpu.envs import native

    if not native.available():
        pytest.skip("cpp/libba3c_env.so not built (make -C cpp)")
    if wire == "block-shm":
        from distributed_ba3c_tpu.utils import shm

        if not shm.available():
            pytest.skip("/dev/shm not available")
    import jax

    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.predict.server import BatchedPredictor
    from distributed_ba3c_tpu.utils.concurrency import ensure_proc_terminate

    cfg = BA3CConfig(num_actions=6, fc_units=16)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    predictor = BatchedPredictor(model, params, batch_size=8, num_threads=1)
    c2s, s2c = f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c"
    master = BA3CSimulatorMaster(
        c2s, s2c, predictor, gamma=cfg.gamma,
        local_time_max=cfg.local_time_max,
        score_queue=queue.Queue(maxsize=1000), actor_timeout=300.0,
    )
    procs = [
        native.CppEnvServerProcess(
            i, c2s, s2c, game="pong", n_envs=4, wire=wire
        )
        for i in range(2)
    ]
    ensure_proc_terminate(procs)
    predictor.start()
    master.start()
    for p in procs:
        p.start()
    try:
        datapoints = []
        deadline = time.time() + 550
        while len(datapoints) < 64 and time.time() < deadline:
            try:
                datapoints.append(master.queue.get(timeout=5))
            except queue.Empty:
                for p in procs:
                    assert p.is_alive(), f"server died, exitcode={p.exitcode}"
        assert len(datapoints) >= 64, "block plane produced too few datapoints"
        for state, action, ret in datapoints:
            s = np.asarray(state)
            assert s.shape == cfg.state_shape and s.dtype == np.uint8
            assert 0 <= action < cfg.num_actions
            assert np.isfinite(ret)
        # both servers registered as BLOCK clients
        assert sum(
            isinstance(c, BlockClientState) for c in master.clients.values()
        ) == 2
    finally:
        for p in procs:
            p.terminate()
        master.close()
        predictor.stop()
        predictor.join(timeout=5)
        for p in procs:
            p.join(timeout=5)


def test_mixed_singles_and_blocks_coalesce():
    pred, cfg = _tiny_predictor(batch_size=8, num_threads=1, coalesce_ms=20.0)
    try:
        single_got, block_got = [], []
        n_singles, block_b = 3, 4
        all_done = threading.Barrier(2, timeout=120)

        def maybe_done():
            if len(single_got) == n_singles and len(block_got) == 1:
                all_done.wait()

        for i in range(n_singles):
            pred.put_task(
                np.full(cfg.state_shape, i, np.uint8),
                lambda a, v, lp: (single_got.append((a, v, lp)), maybe_done()),
            )
        pred.put_block_task(
            np.zeros((block_b, *cfg.state_shape), np.uint8),
            lambda a, v, lp: (block_got.append((a, v, lp)), maybe_done()),
        )
        pred.start()
        all_done.wait()
        assert len(single_got) == n_singles
        assert block_got[0][0].shape == (block_b,)
        for a, v, lp in single_got:
            assert isinstance(a, int) and isinstance(v, float)
    finally:
        pred.stop()
        pred.join(timeout=5)
