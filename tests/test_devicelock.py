"""TPU-claim mutex contract (utils/devicelock.py) — jax-free.

The guard exists because two local device claimants wedge the exclusive
pool rather than erroring (OPERATIONS.md; the round-4 outage). Contract:
exclusion across processes, fail mode reports the holder, wait mode queues,
and a SIGKILLed holder releases the lock via the kernel (no stale-lock
protocol to get wrong).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from distributed_ba3c_tpu.utils.devicelock import (
    TpuLock,
    TpuLockHeld,
    guard_tpu,
    tpu_lock_needed,
)

_HOLDER = r"""
import sys, time
from distributed_ba3c_tpu.utils.devicelock import TpuLock
lock = TpuLock("holder-run", path=sys.argv[1])
lock.acquire(mode="fail")
print("HELD", flush=True)
time.sleep(120)
"""


def _spawn_holder(path):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    p = subprocess.Popen(
        [sys.executable, "-c", _HOLDER, str(path)],
        stdout=subprocess.PIPE, env=env, text=True,
    )
    assert p.stdout.readline().strip() == "HELD"
    return p


def test_needed_skips_cpu_platform(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert not tpu_lock_needed()
    assert guard_tpu("x") is None
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    assert tpu_lock_needed()
    monkeypatch.delenv("JAX_PLATFORMS")
    # unset lets the sitecustomize pick the TPU -> must lock
    assert tpu_lock_needed()


def test_fail_mode_reports_holder(tmp_path):
    path = tmp_path / "tpu.lock"
    holder = _spawn_holder(path)
    try:
        with pytest.raises(TpuLockHeld) as exc:
            TpuLock("second", path=str(path)).acquire(mode="fail")
        msg = str(exc.value)
        assert str(holder.pid) in msg
        assert "holder-run" in msg
    finally:
        holder.kill()
        holder.wait()


def test_wait_mode_queues_until_release(tmp_path):
    path = tmp_path / "tpu.lock"
    first = TpuLock("first", path=str(path)).acquire(mode="fail")
    threading.Timer(0.5, first.release).start()
    t0 = time.monotonic()
    second = TpuLock("second", path=str(path)).acquire(
        mode="wait", poll_s=0.05, log=lambda _m: None
    )
    assert second.held
    assert time.monotonic() - t0 >= 0.4
    second.release()


def test_wait_mode_timeout(tmp_path):
    path = tmp_path / "tpu.lock"
    with TpuLock("first", path=str(path)).acquire(mode="fail"):
        with pytest.raises(TpuLockHeld, match="gave up"):
            TpuLock("second", path=str(path)).acquire(
                mode="wait", poll_s=0.05, timeout_s=0.3, log=lambda _m: None
            )


def test_sigkilled_holder_releases(tmp_path):
    """The whole point of flock over a pidfile: ANY death path frees the
    chip claim — no stale lock after a SIGKILLed training run."""
    path = tmp_path / "tpu.lock"
    holder = _spawn_holder(path)
    os.kill(holder.pid, signal.SIGKILL)
    holder.wait()
    lock = TpuLock("after", path=str(path)).acquire(
        mode="wait", poll_s=0.05, timeout_s=5.0, log=lambda _m: None
    )
    assert lock.held
    lock.release()


def test_holder_info_written_and_cleared(tmp_path):
    path = tmp_path / "tpu.lock"
    lock = TpuLock("myrun", path=str(path)).acquire(mode="fail")
    info = json.load(open(path))
    assert info["pid"] == os.getpid()
    assert info["run"] == "myrun"
    lock.release()
    assert open(path).read() == ""


_CHURN_WORKER = r"""
import os, sys, time
from distributed_ba3c_tpu.utils.devicelock import TpuLock
path, log_path, iters = sys.argv[1], sys.argv[2], int(sys.argv[3])
pid = os.getpid()
for seq in range(iters):
    lock = TpuLock(f"churn-{pid}", path=path).acquire(
        mode="wait", poll_s=0.01, log=lambda _m: None
    )
    with open(log_path, "a") as f:         # O_APPEND: atomic small writes
        f.write(f"S {pid} {seq}\n"); f.flush()
    time.sleep(0.05)
    with open(log_path, "a") as f:
        f.write(f"E {pid} {seq}\n"); f.flush()
    lock.release()
print("DONE", flush=True)
"""


def test_churn_many_claimants_one_holder(tmp_path):
    """6 processes fight over the lock; 2 get SIGKILLed mid-run. Invariants:
    the hold log shows NO overlapping holds (every S is closed by its E
    before the next S, except a killed holder's final S), and the lock is
    immediately acquirable after the dust settles."""
    path = str(tmp_path / "tpu.lock")
    log_path = str(tmp_path / "holds.log")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHURN_WORKER, path, log_path, "5"],
            stdout=subprocess.PIPE, env=env, text=True,
        )
        for _ in range(6)
    ]
    time.sleep(0.4)
    os.kill(procs[0].pid, signal.SIGKILL)
    os.kill(procs[1].pid, signal.SIGKILL)
    for p in procs:
        p.wait(timeout=60)
    # a "killed" target may already have finished its 5 holds before the
    # 0.4s mark on a fast machine (the SIGKILL then hits a zombie and its
    # rc stays 0) — so derive the actually-killed set from the outcomes
    # rather than asserting an exact survivor count
    killed = {p.pid for p in procs if p.returncode != 0}
    assert len(killed) <= 2
    assert sum(p.returncode == 0 for p in procs) >= 4
    lines = [l.split() for l in open(log_path).read().splitlines()]
    open_holder = None
    for kind, pid_s, _seq in lines:
        pid = int(pid_s)
        if kind == "S":
            # a prior unclosed hold is legal ONLY if that holder was killed
            # mid-hold (the kernel released its flock with no E line)
            assert open_holder is None or open_holder in killed, lines
            open_holder = pid
        else:
            assert open_holder == pid, lines
            open_holder = None
    # and the lock is free now
    final = TpuLock("after-churn", path=path).acquire(
        mode="wait", poll_s=0.05, timeout_s=5.0, log=lambda _m: None
    )
    assert final.held
    final.release()


def test_off_mode_never_locks(tmp_path):
    path = tmp_path / "tpu.lock"
    with TpuLock("a", path=str(path)).acquire(mode="fail"):
        # off mode must not block even while another process holds it
        assert not TpuLock("b", path=str(path)).acquire(mode="off").held
