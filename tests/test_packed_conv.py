"""Lane-packed conv: exact equivalence with the plain stride-1 SAME conv."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ba3c_tpu.models.packed_conv import (
    PackedConv,
    packed_conv_same,
)


@pytest.mark.parametrize("pack,W", [(4, 84), (3, 42), (2, 16), (1, 84)])
def test_packed_conv_matches_plain(rng, pack, W):
    k, ci, co = 5, 4, 32
    x = jnp.asarray(rng.normal(size=(2, 12, W, ci)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(k, k, ci, co)).astype(np.float32) * 0.1)
    ref = jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )
    got = packed_conv_same(x, w, pack) if pack > 1 else ref
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_packed_conv_gradients_match(rng):
    """Autodiff through the packing must equal the plain conv's gradients."""
    k, ci, co, W = 3, 2, 8, 12
    x = jnp.asarray(rng.normal(size=(1, 6, W, ci)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(k, k, ci, co)).astype(np.float32) * 0.1)

    def loss_packed(w, x):
        return jnp.sum(packed_conv_same(x, w, 4) ** 2)

    def loss_plain(w, x):
        y = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        return jnp.sum(y**2)

    gw_p, gx_p = jax.grad(loss_packed, argnums=(0, 1))(w, x)
    gw_r, gx_r = jax.grad(loss_plain, argnums=(0, 1))(w, x)
    np.testing.assert_allclose(np.asarray(gw_p), np.asarray(gw_r), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gx_p), np.asarray(gx_r), atol=1e-4)


def test_packed_conv_module_param_compat(rng):
    """PackedConv owns nn.Conv-shaped params and falls back when W % pack."""
    m = PackedConv(features=32, kernel_size=5, pack=4, dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(1, 8, 84, 4)).astype(np.float32))
    params = m.init(jax.random.PRNGKey(0), x)["params"]
    assert params["kernel"].shape == (5, 5, 4, 32)
    assert params["bias"].shape == (32,)
    y = m.apply({"params": params}, x)
    assert y.shape == (1, 8, 84, 32)
    # odd width -> fallback path, still correct shape
    x2 = jnp.asarray(rng.normal(size=(1, 8, 83, 4)).astype(np.float32))
    y2 = m.apply({"params": params}, x2)
    assert y2.shape == (1, 8, 83, 32)
