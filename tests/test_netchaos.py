"""The netchaos fault-injection plane + the transport hardening it forced.

Contracts pinned here (ISSUE 13):

- schedule: pure per-sequence decisions (same seed -> same faults,
  forever), lossless JSON round-trip, loud rejection of junk, fault
  precedence exclusivity, timed/asymmetric partition windows.
- proxies: drop and latency actually injected on a live push/pull link;
  partitions HOLD the link so the sender's own bounds engage; the
  identity-preserving router proxy carries fetch round-trips; the whole
  pod wrap (pub + router + push/pull) serves a real publisher/cache pair
  with heartbeats flowing.
- replay: a finished run's event log re-derives exactly from the seed
  (the determinism gate every bench artifact embeds).
- link-state machines: up -> degraded -> partitioned on silence,
  beat-recovery, gauge export, flight-recorded transitions.
- degraded-mode: the experience shipper against a dead ingest spills to
  its bounded drop-oldest buffer with ``ship_backpressure_total``
  ticking and re-drains on heal; a params-partitioned host sheds through
  the VersionGatedPredictor's typed path.
"""

import queue
import time
import types

import numpy as np
import pytest
import zmq

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.netchaos import (
    FaultSchedule,
    LinkFaults,
    NetChaosPlane,
    Partition,
)
from distributed_ba3c_tpu.pod import (
    DEGRADED,
    PARTITIONED,
    UP,
    LinkHealth,
    ParamsPublisher,
    StaleParamsCache,
    VersionGatedPredictor,
)
from distributed_ba3c_tpu.pod.host import ExperienceShipper
from distributed_ba3c_tpu.pod.wire import pod_endpoints


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset_all()
    yield
    telemetry.reset_all()


def _free_base():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"tcp://127.0.0.1:{port}", f"tcp://127.0.0.1:{port + 1}"


# ---------------------------------------------------------------------------
# schedule
# ---------------------------------------------------------------------------

def test_decisions_are_pure_functions_of_seed_link_dir_seq():
    s1 = FaultSchedule(
        {"x": LinkFaults(drop=0.3, corrupt=0.2, truncate=0.1, jitter_ms=4)},
        seed=11,
    )
    s2 = FaultSchedule.from_json(s1.to_json())
    for seq in range(200):
        a, b = s1.decide("x", "fwd", seq), s2.decide("x", "fwd", seq)
        assert a == b
    # different seed, link or direction -> a different stream
    s3 = FaultSchedule({"x": LinkFaults(drop=0.3, corrupt=0.2)}, seed=12)
    kinds = [s1.decide("x", "fwd", i).kind for i in range(64)]
    assert kinds != [s3.decide("x", "fwd", i).kind for i in range(64)]
    assert kinds != [s1.decide("y", "fwd", i).kind for i in range(64)]
    assert kinds != [s1.decide("x", "rev", i).kind for i in range(64)]


def test_faults_are_mutually_exclusive_per_message():
    s = FaultSchedule(
        {"x": LinkFaults(drop=0.5, corrupt=0.5, truncate=0.5, reorder=0.5)},
        seed=3,
    )
    for seq in range(300):
        d = s.decide("x", "fwd", seq)
        assert sum([d.drop, d.corrupt, d.truncate, d.reorder]) <= 1


def test_schedule_json_round_trip_with_partitions():
    s = FaultSchedule(
        {
            "params_pub": LinkFaults(
                latency_ms=25, jitter_ms=5, drop=0.01,
                partitions=(Partition(2.0, 6.0, "rev"),),
            ),
            "*": LinkFaults(bandwidth_kbps=512),
        },
        seed=42,
    )
    s2 = FaultSchedule.from_json(s.to_json())
    assert s2 == s
    assert s2.partitioned("params_pub", "rev", 3.0)
    assert not s2.partitioned("params_pub", "fwd", 3.0)  # asymmetric
    assert not s2.partitioned("params_pub", "rev", 6.0)  # half-open window
    # "*" default applies to unnamed links
    assert s2.faults_for("anything").bandwidth_kbps == 512


def test_schedule_rejects_junk_loudly():
    with pytest.raises(ValueError):
        LinkFaults(drop=1.5)
    with pytest.raises(ValueError):
        LinkFaults(latency_ms=-1)
    with pytest.raises(ValueError):
        Partition(5.0, 2.0)
    with pytest.raises(ValueError):
        Partition(0.0, 1.0, "sideways")
    with pytest.raises(ValueError):
        FaultSchedule.from_json('{"links": {}, "sede": 1}')  # typoed field
    with pytest.raises(ValueError):
        FaultSchedule.from_json("[1, 2]")


def test_quiet_schedule_decides_nothing():
    s = FaultSchedule({}, seed=0)
    assert s.faults_for("any").quiet()
    assert s.decide("any", "fwd", 7).kind is None


# ---------------------------------------------------------------------------
# proxies
# ---------------------------------------------------------------------------

def _pull_all(sock, timeout_ms=500):
    got = []
    poller = zmq.Poller()
    poller.register(sock, zmq.POLLIN)
    while poller.poll(timeout_ms):
        got.append(sock.recv_multipart())
    return got


def test_push_pull_proxy_injects_drop_and_latency():
    plane = NetChaosPlane(
        FaultSchedule({"l": LinkFaults(latency_ms=40, drop=0.25)}, seed=5)
    )
    ctx = zmq.Context()
    server = ctx.socket(zmq.PULL)
    port = server.bind_to_random_port("tcp://127.0.0.1")
    front = plane.add_push_pull("l", f"tcp://127.0.0.1:{port}")
    plane.start()
    client = ctx.socket(zmq.PUSH)
    client.connect(front)
    time.sleep(0.3)
    t0 = time.monotonic()
    for i in range(60):
        client.send_multipart([b"m", b"%d" % i])
    got = _pull_all(server)
    first_latency = None
    if got:
        first_latency = time.monotonic() - t0  # upper bound incl. drain
    drops = plane.summary().get("drop", 0)
    assert drops > 0 and len(got) == 60 - drops
    assert first_latency is None or first_latency >= 0.04
    # FIFO preserved under pure latency (no reorder configured)
    seqs = [int(m[1]) for m in got]
    assert seqs == sorted(seqs)
    rc = plane.replay_check()
    assert rc["match"], rc
    plane.close()
    client.close(0)
    server.close(0)
    ctx.term()


def test_partition_holds_link_then_heals():
    """During the window the link moves NOTHING (the sender's bounds are
    what engages); after it, delivery resumes — and the transitions are
    flight-recorded."""
    sched = FaultSchedule(
        {"l": LinkFaults(partitions=(Partition(0.0, 1.0),))}, seed=1
    )
    plane = NetChaosPlane(sched)
    ctx = zmq.Context()
    server = ctx.socket(zmq.PULL)
    port = server.bind_to_random_port("tcp://127.0.0.1")
    front = plane.add_push_pull("l", f"tcp://127.0.0.1:{port}")
    plane.start()
    client = ctx.socket(zmq.PUSH)
    client.set_hwm(1000)
    client.connect(front)
    time.sleep(0.2)
    plane.rebase_clock()  # window [0, 1) starts NOW
    for i in range(10):
        client.send_multipart([b"%d" % i])
    time.sleep(0.3)
    assert _pull_all(server, timeout_ms=100) == []  # held, not delivered
    got = _pull_all(server, timeout_ms=1500)  # heal at t=1 releases them
    assert len(got) == 10
    kinds = {e["kind"] for e in plane.events()}
    assert "partition_start" in kinds and "partition_heal" in kinds
    assert plane.replay_check()["match"]
    plane.close()
    client.close(0)
    server.close(0)
    ctx.term()


def test_corruption_through_proxy_is_caught_by_crc():
    from distributed_ba3c_tpu.utils.serialize import (
        CorruptFrameError,
        pack_block,
        unpack_block,
    )

    plane = NetChaosPlane(
        FaultSchedule({"l": LinkFaults(corrupt=1.0)}, seed=2)
    )
    ctx = zmq.Context()
    server = ctx.socket(zmq.PULL)
    port = server.bind_to_random_port("tcp://127.0.0.1")
    front = plane.add_push_pull("l", f"tcp://127.0.0.1:{port}")
    plane.start()
    client = ctx.socket(zmq.PUSH)
    client.connect(front)
    time.sleep(0.3)
    obs = np.arange(4096, dtype=np.uint8).reshape(64, 64)
    client.send_multipart(pack_block([b"id", 0, 1], [obs], crc=True))
    (frames,) = _pull_all(server)
    with pytest.raises(CorruptFrameError):
        unpack_block(frames)
    assert plane.summary().get("corrupt", 0) == 1
    plane.close()
    client.close(0)
    server.close(0)
    ctx.term()


def test_pod_wrap_serves_publisher_and_cache_through_all_three_proxies():
    c2s, s2c = _free_base()
    real = pod_endpoints(c2s, s2c)
    plane = NetChaosPlane(
        FaultSchedule({"params_pub": LinkFaults(latency_ms=10)}, seed=4)
    )
    front = plane.wrap_pod(c2s, s2c)
    plane.start()
    pub = ParamsPublisher(real)
    pub.start()
    cache = StaleParamsCache(
        pod_endpoints(*front), host=0, fetch_backoff_s=0.1, heartbeat_s=0.2
    )
    cache.start()
    try:
        params = {"w": np.arange(4, dtype=np.float32)}
        pub.publish(1, params, step=10)  # before any broadcast reaches SUB,
        assert cache.wait_first(15)      # the cache FETCHES through the proxy
        for v in range(2, 5):
            pub.publish(v, params, step=v)
        deadline = time.monotonic() + 10
        while cache.version < 4 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert cache.version == 4 and cache.epoch == pub.epoch
        # heartbeats flowed: the publisher tracks this host's link as up
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if pub.link_states().get("pod_host_0") == UP:
                break
            time.sleep(0.05)
        assert pub.link_states().get("pod_host_0") == UP
        assert cache.fetch_link.poll() == UP
        # the SUB channel beats only on broadcasts — publish once more and
        # it must come back up within the proxy latency
        pub.publish(5, params, step=5)
        deadline = time.monotonic() + 5
        while cache.sub_link.poll() != UP and time.monotonic() < deadline:
            time.sleep(0.05)
        assert cache.sub_link.poll() == UP
        assert plane.replay_check()["match"]
    finally:
        cache.close()
        pub.close()
        plane.close()


# ---------------------------------------------------------------------------
# link-state machine
# ---------------------------------------------------------------------------

def test_link_health_transitions_and_gauge():
    link = LinkHealth(
        "t", "learner", degraded_after_s=0.1, partitioned_after_s=0.25
    )
    g = telemetry.registry("learner").gauge("link_state_t")
    assert link.poll() == UP and g.value() == 0.0
    time.sleep(0.12)
    assert link.poll() == DEGRADED and g.value() == 1.0
    time.sleep(0.18)
    assert link.poll() == PARTITIONED and g.value() == 2.0
    assert link.partitioned()
    link.beat()
    assert link.poll() == UP and g.value() == 0.0
    # transitions were flight-recorded
    evs = [
        f for _, k, f in telemetry.flight_recorder().events_since(0)
        if k == "link_state" and f.get("link") == "t"
    ]
    states = [(e["frm"], e["to"]) for e in evs]
    assert (UP, DEGRADED) in states and (DEGRADED, PARTITIONED) in states
    assert (PARTITIONED, UP) in states


def test_link_health_rejects_inverted_thresholds():
    with pytest.raises(ValueError):
        LinkHealth("t", "learner", degraded_after_s=5, partitioned_after_s=1)


# ---------------------------------------------------------------------------
# degraded-mode semantics
# ---------------------------------------------------------------------------

def _segment(T=3, H=8):
    return {
        "state": np.zeros((T, H, H, 4), np.uint8),
        "action": np.zeros(T, np.int32),
        "reward": np.zeros(T, np.float32),
        "done": np.zeros(T, np.float32),
        "behavior_log_probs": np.zeros(T, np.float32),
        "behavior_values": np.zeros(T, np.float32),
        "bootstrap_state": np.zeros((H, H, 4), np.uint8),
    }


def _make_shipper(addr, snd_hwm=2, spill_depth=4):
    master = types.SimpleNamespace(
        queue=queue.Queue(maxsize=1024), tele_role="master"
    )
    cache = types.SimpleNamespace(epoch=1, version=3)
    return ExperienceShipper(
        master, cache, addr, host=0, segments_per_block=1,
        snd_hwm=snd_hwm, spill_depth=spill_depth,
        degraded_after_s=0.3, partitioned_after_s=0.8,
    )


def test_shipper_spills_bounded_drop_oldest_and_redrains_on_heal():
    """A partitioned ingest: the SNDHWM bites, blocks spill (counted),
    the spill stays bounded by evicting the OLDEST, rollout's queue keeps
    draining — and a healed ingest receives the bounded freshest window,
    oldest-first."""
    ctx = zmq.Context()
    port = ctx.socket(zmq.PULL)  # reserve a port, then DON'T listen yet
    p = port.bind_to_random_port("tcp://127.0.0.1")
    port.close(0)
    addr = f"tcp://127.0.0.1:{p}"
    shipper = _make_shipper(addr, snd_hwm=2, spill_depth=4)
    tele = telemetry.registry(shipper.tele_role)
    shipper.start()
    try:
        for _ in range(16):
            shipper.master.queue.put(_segment(), timeout=1)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if (
                shipper.master.queue.qsize() == 0
                and tele.scalars().get("ship_backpressure_total", 0) > 0
                and len(shipper._spill) == 4
            ):
                break
            time.sleep(0.05)
        s = tele.scalars()
        assert shipper.master.queue.qsize() == 0  # rollout never blocked
        assert s["ship_backpressure_total"] > 0  # the bound bit, counted
        assert len(shipper._spill) == 4  # bounded
        assert s["shipped_dropped_total"] > 0  # drop-oldest, counted
        time.sleep(0.4)  # past degraded_after_s with sends still refused
        shipper.master.queue.put(_segment(), timeout=1)  # one more attempt
        deadline = time.monotonic() + 5
        while shipper.link.state == UP and time.monotonic() < deadline:
            time.sleep(0.05)
        assert shipper.link.state != UP  # refusal observed, state moved
        # heal: bind the ingest; the spill must drain without new input
        server = ctx.socket(zmq.PULL)
        try:
            server.bind(addr)
            got = _pull_all(server, timeout_ms=2000)
            assert len(got) >= 4  # spill + whatever libzmq held at the HWM
            deadline = time.monotonic() + 5
            while len(shipper._spill) and time.monotonic() < deadline:
                time.sleep(0.05)
            assert len(shipper._spill) == 0
            assert shipper.link.state == UP  # sends land again (beat)
        finally:
            server.close(0)
    finally:
        shipper.close()
        ctx.term()


def test_version_gate_sheds_on_partition_signal():
    from distributed_ba3c_tpu.predict.server import ShedReject

    sheds = []

    class _NeverCalled:
        num_actions = 4

        def put_task(self, *a, **k):  # pragma: no cover
            raise AssertionError("partitioned host must not serve")

        def put_block_task(self, *a, **k):  # pragma: no cover
            raise AssertionError("partitioned host must not serve")

    partitioned = {"v": True}
    gate = VersionGatedPredictor(
        _NeverCalled(), behind_fn=lambda: 0, max_staleness=4,
        tele_role="pod.host0", partitioned_fn=lambda: partitioned["v"],
    )
    ok = gate.put_task(
        np.zeros((8, 8, 4), np.uint8), lambda *a: None,
        shed_callback=lambda r: sheds.append(r),
    )
    assert ok is False and isinstance(sheds[0], ShedReject)
    assert sheds[0].reason == "stale_params"
    assert (
        telemetry.registry("pod.host0").scalars()["stale_params_sheds_total"]
        == 1
    )
    # heal: behind()==0 and no partition -> serve again (reaches the
    # wrapped predictor, which raises — proving the gate opened)
    partitioned["v"] = False
    with pytest.raises(AssertionError):
        gate.put_task(np.zeros((8, 8, 4), np.uint8), lambda *a: None)


# ---------------------------------------------------------------------------
# bench plumbing (fast pieces only; the live rig is the slow CI phase)
# ---------------------------------------------------------------------------

def test_dcn_schedule_shapes():
    from distributed_ba3c_tpu.netchaos.bench import (
        POD_LINKS,
        corrupt_schedule,
        dcn_schedule,
        partition_schedule,
        quiet_schedule,
    )

    s = dcn_schedule(rtt_ms=50, loss=0.01, seed=9)
    for link in POD_LINKS:
        f = s.faults_for(link)
        assert f.latency_ms == 25.0 and f.drop == 0.01
    assert quiet_schedule().faults_for("experience").quiet()
    p = partition_schedule(2.0, 4.0, seed=1)
    assert p.partitioned("experience", "fwd", 3.0)
    assert not p.partitioned("experience", "fwd", 6.5)
    c = corrupt_schedule(seed=1)
    assert c.faults_for("experience").corrupt > 0
