"""Trainer main loop: callbacks fire, stats written, checkpoint saved/resumed."""

import json
import os
import queue

import jax
import numpy as np
import optax
import pytest

from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import make_optimizer
from distributed_ba3c_tpu.parallel.mesh import make_mesh
from distributed_ba3c_tpu.parallel.train_step import (
    create_train_state,
    make_train_step,
)
from distributed_ba3c_tpu.train.callbacks import (
    Callback,
    MaxSaver,
    ModelSaver,
    ScheduledHyperParamSetter,
    StatPrinter,
)
from distributed_ba3c_tpu.train.trainer import Trainer, TrainLoopConfig


class _SyntheticFeed:
    """Random on-the-fly batches (stands in for TrainFeed)."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.rng = np.random.default_rng(0)

    def next_batch(self, timeout=None):
        c = self.cfg
        return {
            "state": self.rng.integers(
                0, 255, (c.batch_size, *c.state_shape), np.uint8
            ),
            "action": self.rng.integers(
                0, c.num_actions, (c.batch_size,), np.int32
            ),
            "return": self.rng.normal(size=(c.batch_size,)).astype(np.float32),
        }


@pytest.fixture(scope="module")
def setup():
    cfg = BA3CConfig(
        image_size=(16, 16), fc_units=16, num_actions=4, batch_size=16
    )
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    optimizer = make_optimizer(
        cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm
    )
    mesh = make_mesh()
    step = make_train_step(model, optimizer, cfg, mesh)
    state = create_train_state(jax.random.PRNGKey(0), model, cfg, optimizer)
    return cfg, step, state


def test_trainer_loop_and_checkpoint(tmp_path, setup):
    cfg, step, state = setup
    log_dir = str(tmp_path / "log")
    fired = {"step": 0, "epoch": 0}

    class Probe(Callback):
        def trigger_step(self, metrics):
            fired["step"] += 1

        def trigger_epoch(self):
            fired["epoch"] += 1

    sq = queue.Queue()
    for s in [1.0, 2.0, 3.0]:
        sq.put(s)

    tr = Trainer(
        TrainLoopConfig(steps_per_epoch=4, max_epoch=2, log_dir=log_dir),
        cfg,
        step,
        state,
        _SyntheticFeed(cfg),
        callbacks=[
            Probe(),
            ScheduledHyperParamSetter("learning_rate", [(1, 1e-3), (2, 1e-4)]),
            StatPrinter(sample_every=1),
            ModelSaver(),
            MaxSaver(),
        ],
        score_queue=sq,
    )
    tr.train()

    assert fired["step"] == 8 and fired["epoch"] == 2
    assert int(tr.state.step) == 8
    assert tr.hyperparams["learning_rate"] == pytest.approx(1e-4)

    stats = json.load(open(os.path.join(log_dir, "stat.json")))
    assert len(stats) == 2
    assert stats[0]["mean_score"] == pytest.approx(2.0)
    assert "loss" in stats[0] and "fps" in stats[0]
    assert tr.ckpt_manager.latest_step == 8
    assert tr.ckpt_manager.best_step is not None

    # -- resume (--load path) ---------------------------------------------
    tr2 = Trainer(
        TrainLoopConfig(steps_per_epoch=4, max_epoch=2, log_dir=log_dir),
        cfg,
        step,
        jax.device_get(tr.state),  # structure donor; values overwritten
        _SyntheticFeed(cfg),
        callbacks=[],
    )
    tr2.restore(os.path.join(log_dir, "checkpoints"))
    assert tr2.global_step == 8
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(tr2.state.params)[0]),
        np.asarray(jax.tree_util.tree_leaves(tr.state.params)[0]),
    )
