"""PodIngest: the learner's experience intake from N actor hosts.

One PULL socket, one receive thread, one bounded drop-oldest buffer. The
drop-oldest policy IS the pod's backpressure story (docs/pod.md): actor
hosts never slow down because the learner fell behind — a backed-up
learner consumes the NEWEST experience and sheds the oldest (counted, so
the series shows it), which in bounded-staleness terms converts learner
lag into measured params lag instead of wedging the whole pod on a full
queue. The reference's PS cluster had the same property by accident
(silently dropped async updates); here it is a typed counter.

Each received block also piggybacks the sending host's progress scalars
(the cross-host analogue of telemetry/wire.py's fleet deltas): the ingest
folds them into the learner-process ``pod.host<k>`` registries as gauges,
so per-host progress and failure attribution survive on the LEARNER'S
scrape endpoint — the satellite fix in telemetry/exporters.py makes
export_scalars carry those roles into stat.json/TB.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Dict, Optional

import numpy as np
import zmq

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.telemetry import tracing
from distributed_ba3c_tpu.pod.wire import (
    PodEndpoints,
    pod_role,
    unpack_experience_full,
)
from distributed_ba3c_tpu.utils.concurrency import StoppableThread
from distributed_ba3c_tpu.utils.serialize import CorruptFrameError


@dataclasses.dataclass
class StampedBatch:
    """One host-shipped rollout batch with its staleness provenance."""

    host: int
    version: int  # params version the block was COLLECTED under
    #: zero-copy wire views, or a data/staging.py StagedBlock when the
    #: ingest pre-stages on its receive thread (PodLearner handles both)
    batch: Dict[str, np.ndarray]
    #: publisher lifetime the version counts within (0 = unknown/legacy);
    #: the learner rejects blocks from a lineage it does not own
    epoch: int = 0
    #: tracing.TraceRef when the shipping host sampled this block — the
    #: cross-process continuation the learner's gate/step hops extend
    trace: object = None


class PodIngest:
    """Bind the experience channel and buffer stamped batches.

    ``next_batch(timeout)`` returns the OLDEST buffered
    :class:`StampedBatch` (FIFO within the bound); when the buffer is full
    the receive thread drops the oldest instead of stalling the socket —
    ``pod_ingest_dropped_total`` counts what the learner never saw.
    """

    def __init__(
        self,
        endpoints: PodEndpoints,
        depth: int = 16,
        tele_role: str = "learner",
        stager=None,
    ):
        self.endpoints = endpoints
        #: data/staging.py BlockStager (pass the consuming PodLearner's
        #: own ``stager``): when set, the wire→staging copy happens HERE,
        #: on the receive thread, so it overlaps the learner's step — the
        #: learner only pays the (async) device_put. When None the
        #: StampedBatch carries the zero-copy wire views and the learner
        #: stages on its own thread. Either way: one host copy per block.
        self.stager = stager
        self.context = zmq.Context()
        self._pull = self.context.socket(zmq.PULL)
        self._pull.setsockopt(zmq.LINGER, 0)
        self._pull.set_hwm(max(4, depth))
        self._pull.bind(endpoints.experience)
        self._buf: collections.deque = collections.deque()
        self._depth = max(1, int(depth))
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)

        tele = telemetry.registry(tele_role)
        self._tele_role = tele_role
        self._c_blocks = tele.counter("pod_ingest_blocks_total")
        self._c_steps = tele.counter("pod_ingest_env_steps_total")
        self._c_dropped = tele.counter("pod_ingest_dropped_total")
        # typed wire rejects: corrupt = CRC failed in flight (netchaos /
        # flaky DCN), rejected = structurally undecodable (version skew,
        # stray sender) — the runbook branches on the distinction
        self._c_corrupt = tele.counter("pod_corrupt_frames_total")
        self._c_rejected = tele.counter("pod_ingest_rejected_total")
        self._g_depth = tele.gauge(
            "pod_ingest_depth", fn=lambda: len(self._buf)
        )
        self._host_gauges: Dict[int, Dict[str, object]] = {}

        self._thread = StoppableThread(
            target=self._recv_loop, daemon=True, name="pod-ingest"
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._thread.stop()
        with self._ready:
            self._ready.notify_all()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread.is_alive():
            self._thread.join(timeout)

    def close(self) -> None:
        self.stop()
        self.join(timeout=2)
        try:
            self._pull.close(0)
        except zmq.ZMQError:
            pass
        self.context.term()

    # -- consumption -------------------------------------------------------
    def next_batch(self, timeout: Optional[float] = None) -> Optional[StampedBatch]:
        """Oldest buffered batch, or None on timeout/stop (the caller's
        feed-timeout turns a silent pod into a loud failure, same contract
        as the dataflow feeds)."""
        with self._ready:
            if not self._buf:
                self._ready.wait(timeout)
            if not self._buf:
                return None
            return self._buf.popleft()

    def qsize(self) -> int:
        return len(self._buf)

    # -- receive internals ---------------------------------------------------
    def _fold_host_scalars(self, host: int, scalars: Dict[str, float]) -> None:
        """Mirror the host's shipped progress counters as learner-process
        gauges under its ``pod.host<k>`` role (absolute values — the host
        owns the counting; the learner just re-exports the latest)."""
        gauges = self._host_gauges.setdefault(host, {})
        reg = telemetry.registry(pod_role(host))
        for name, v in scalars.items():
            g = gauges.get(name)
            if g is None:
                gauges[name] = g = reg.gauge(name)
            try:
                g.set(float(v))
            except (TypeError, ValueError):
                pass

    def _recv_loop(self) -> None:
        t = threading.current_thread()
        assert isinstance(t, StoppableThread)
        poller = zmq.Poller()
        poller.register(self._pull, zmq.POLLIN)
        while not t.stopped():
            try:
                if not poller.poll(100):
                    continue
                frames = self._pull.recv_multipart(copy=False)
            except (zmq.ContextTerminated, zmq.ZMQError):
                return
            try:
                host, epoch, version, scalars, batch, tr = (
                    unpack_experience_full([f.buffer for f in frames])
                )
            except CorruptFrameError as e:
                from distributed_ba3c_tpu.utils import logger

                # typed integrity reject: the CRC caught in-flight
                # corruption/truncation BEFORE any frombuffer view was
                # built — count it and keep the one receive thread alive
                self._c_corrupt.inc()
                telemetry.record(
                    "corrupt_frame", wire="pod-experience",
                    error=str(e)[:200],
                )
                logger.error("pod ingest dropped a corrupt block: %r", e)
                continue
            except Exception as e:  # msgpack raises its own hierarchy too
                from distributed_ba3c_tpu.utils import logger

                self._c_rejected.inc()
                logger.error("pod ingest dropped a malformed block: %r", e)
                continue
            T, B = batch["action"].shape
            self._c_blocks.inc()
            self._c_steps.inc(T * B)
            self._fold_host_scalars(host, scalars)
            # sampled cross-host trace: handshake the host's clock,
            # record the pod_wire transit span, carry the ref to the
            # learner loop (StalenessGate / pod_learner_step hops)
            trace = None
            out = tracing.receive_context(
                tracing.decode_context(tr), peer=pod_role(host),
                role=self._tele_role, wire_name="pod_wire",
            )
            if out is not None:
                trace = tracing.TraceRef(*out)
            if self.stager is not None:
                # the ONE host copy, paid on THIS thread: wire views →
                # reused staging buffers while the learner's step runs
                # (the zmq frames are released here instead of pinned in
                # the buffer until consumption)
                batch = self.stager.copy_in(batch)
            with self._ready:
                if len(self._buf) >= self._depth:
                    dropped = self._buf.popleft()
                    self._c_dropped.inc()
                    if self.stager is not None:
                        # a shed block's staging slot goes straight back
                        # in rotation — a busy slot held by a dropped
                        # batch would starve the ring
                        self.stager.cancel(dropped.batch)
                self._buf.append(
                    StampedBatch(host, version, batch, epoch, trace)
                )
                self._ready.notify()
