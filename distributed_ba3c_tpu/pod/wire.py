"""Pod wire: endpoint derivation + the version-stamp message formats.

Three channels per pod, all derived from the learner's base pipe pair the
same way ``actors/fleet.py fleet_pipes`` derives per-fleet experience
pipes — addressing, not new machinery (docs/pod.md):

- **params PUB** (learner binds, hosts SUB): every publish broadcasts the
  full versioned snapshot; a slow or partitioned host simply misses
  broadcasts and stays on its last version (bounded staleness is the
  learner's job, not the transport's).
- **params fetch** (learner ROUTER, hosts DEALER): the late-joiner path —
  a freshly (re)spawned host asks for the CURRENT snapshot instead of
  waiting out a publish interval; retried with backoff by the cache.
- **experience PUSH/PULL** (hosts PUSH, learner PULL): collated [T, B]
  rollout batches stamped with the params version they were collected
  under, plus a piggybacked host-telemetry snapshot (the cross-host
  analogue of telemetry/wire.py's fleet deltas).

tcp:// base pipes step the port by ``POD_PORT_OFFSET + i`` — far above
the ``2 * fleet`` stride the fleet map uses, so the two derivations can
never collide for any sane fleet count (validated at derivation); every
other transport gets a path suffix, exactly the fleet_pipes idiom.

Version-stamp format: the version is the learner's update counter at
publish time — a single monotonically increasing int — and the **epoch**
is a random token minted once per ParamsPublisher lifetime. The epoch is
what makes a learner RESTART detectable: a relaunched learner's versions
restart at 0, and without the epoch every surviving cache would silently
drop the "older" broadcasts forever while the clamped lag read 0 — the
exact silent staleness this plane exists to prevent. A params message is
``dumps({"e": epoch, "v": version, "step": learner_step, "params":
<nested dict of ndarrays>})``; an experience message is a ``pack_block``
multipart whose header meta is ``{"host": k, "e": epoch, "v": stamp,
"scalars": {...}}`` and whose array frames are :data:`EXPERIENCE_KEYS`
in order (zero-copy both ways, the block wire's codec).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from distributed_ba3c_tpu.utils.serialize import (
    dumps,
    loads,
    pack_block,
    unpack_block,
)

_TCP_RE = re.compile(r"^(tcp://[^:]+:)(\d+)$")

#: tcp port offset of the first pod channel relative to the base c2s port.
#: Far above the fleet map's ``2 * fleet`` stride (fleet_pipes) — a 50-fleet
#: learner would be needed to collide, and :func:`pod_endpoints` validates.
POD_PORT_OFFSET = 100

#: the experience frames' array order (the header carries no per-array
#: names — order IS the schema, docs/pod.md)
EXPERIENCE_KEYS = (
    "state",
    "action",
    "reward",
    "done",
    "behavior_log_probs",
    "behavior_values",
    "bootstrap_state",
)


def pod_role(host: int) -> str:
    """The canonical telemetry role for one actor host's plane: THE single
    formula (like ``telemetry.fleet_role``) both the host process and the
    learner-side ingest fold use — deriving it twice would let the host's
    own registries and the learner's per-host mirror drift apart."""
    return f"pod.host{int(host)}"


@dataclasses.dataclass(frozen=True)
class PodEndpoints:
    """The learner's three pod channel addresses (hosts connect to all)."""

    params_pub: str
    params_fetch: str
    experience: str


def pod_endpoints(
    pipe_c2s: str, pipe_s2c: str, n_fleets: int = 1
) -> PodEndpoints:
    """Derive the pod side-channel addresses from the base pipe pair.

    ``n_fleets`` is the learner's fleet count: the fleet map occupies tcp
    ports ``base .. base + 2 * n_fleets`` (fleet_pipes), and the pod
    channels must land strictly above it — an overlap would double-bind a
    fleet's experience pipe as a params channel and fail only at runtime.
    """
    if n_fleets >= 1 and 2 * n_fleets >= POD_PORT_OFFSET:
        raise ValueError(
            f"{n_fleets} fleets span {2 * n_fleets} ports from the base "
            f"pipe — the pod channels start at +{POD_PORT_OFFSET} and "
            "would collide; rebase the pod learner's pipe pair"
        )
    m = _TCP_RE.match(pipe_c2s)
    if m:
        host, port = m.group(1), int(m.group(2))
        return PodEndpoints(
            params_pub=f"{host}{port + POD_PORT_OFFSET}",
            params_fetch=f"{host}{port + POD_PORT_OFFSET + 1}",
            experience=f"{host}{port + POD_PORT_OFFSET + 2}",
        )
    # ipc:///inproc:// — suffix the c2s path (the s2c pair member exists
    # only so callers can hand the whole pipe pair through unchanged)
    return PodEndpoints(
        params_pub=f"{pipe_c2s}-pod-pub",
        params_fetch=f"{pipe_c2s}-pod-fetch",
        experience=f"{pipe_c2s}-pod-exp",
    )


def _plain(tree: Any) -> Any:
    """Param pytree → msgpack-serializable nested dict of ndarrays (flax
    FrozenDict included — it is a Mapping)."""
    if isinstance(tree, Mapping):
        return {k: _plain(v) for k, v in tree.items()}
    return np.asarray(tree)


def pack_params(
    version: int,
    params: Any,
    step: Optional[int] = None,
    epoch: int = 0,
    trace: Optional[list] = None,
    crc: Optional[bool] = None,
) -> bytes:
    """One params snapshot message (PUB broadcast == fetch reply).

    ``trace`` is a sampled trace-context element
    (telemetry/tracing.py ``encode_context``) riding as an optional
    ``"tr"`` key — dict-keyed messages version by key presence the way
    the block headers version by length; old receivers ignore it.
    ``crc`` (None = the BA3C_WIRE_CRC process default) adds the
    single-frame CRC32 prefix so a corrupted snapshot becomes a typed
    ``CorruptFrameError`` at the cache instead of torn weights."""
    doc = {
        "e": int(epoch),
        "v": int(version),
        "step": int(step or 0),
        "params": _plain(params),
    }
    if trace is not None:
        doc["tr"] = trace
    return dumps(doc, crc=crc)


def unpack_params(payload) -> Tuple[int, int, int, Dict[str, Any]]:
    """Inverse of :func:`pack_params`: ``(epoch, version, step, params)``.
    The arrays are COPIES (not buffer views): the cache hands them to a
    predictor that outlives the zmq frame."""
    return unpack_params_full(payload)[:4]


def unpack_params_full(
    payload,
) -> Tuple[int, int, int, Dict[str, Any], Any]:
    """:func:`unpack_params` plus the raw ``"tr"`` trace element (None
    when absent) — the cache's decode path; the 4-tuple wrapper stays for
    every pre-tracing caller."""
    doc = loads(payload)
    params = _copy_tree(doc["params"])
    return (
        int(doc.get("e", 0)),
        int(doc["v"]),
        int(doc.get("step", 0)),
        params,
        doc.get("tr"),
    )


def _copy_tree(tree: Any) -> Any:
    if isinstance(tree, Mapping):
        return {k: _copy_tree(v) for k, v in tree.items()}
    return np.array(tree)  # own the memory past the zmq frame's life


def pack_experience(
    host: int,
    version: int,
    batch: Dict[str, np.ndarray],
    scalars: Optional[Dict[str, float]] = None,
    epoch: int = 0,
    trace: Optional[list] = None,
    crc: Optional[bool] = None,
) -> List[Any]:
    """One stamped experience block as a zero-copy multipart message.

    ``batch`` is the collated [T, B] rollout batch (collate_rollout layout
    plus ``behavior_values``); ``version`` is the OLDEST params version
    any of the block's transitions could have been served under (the
    cache's version when the block's FIRST segment was banked — the
    conservative stamp the bounded-staleness gate measures lag from);
    ``epoch`` is the publisher lifetime the version counts within;
    ``scalars`` piggybacks the host's progress counters for the
    learner-side ``pod.host<k>`` mirror; ``trace`` is a sampled
    trace-context element (tracing.py) riding as an optional ``"tr"``
    key — the cross-process continuation of the block's rollout trace.
    """
    missing = [k for k in EXPERIENCE_KEYS if k not in batch]
    if missing:
        raise ValueError(f"experience batch missing keys {missing}")
    meta = {
        "host": int(host),
        "e": int(epoch),
        "v": int(version),
        "scalars": scalars or {},
    }
    if trace is not None:
        meta["tr"] = trace
    return pack_block(meta, [batch[k] for k in EXPERIENCE_KEYS], crc=crc)


def unpack_experience(
    frames: Sequence[Any],
) -> Tuple[int, int, int, Dict[str, float], Dict[str, np.ndarray]]:
    """Inverse of :func:`pack_experience`:
    ``(host, epoch, version, scalars, batch)`` — arrays are zero-copy
    views over the frames (they keep the frames alive,
    serialize.unpack_block)."""
    return unpack_experience_full(frames)[:5]


def unpack_experience_full(
    frames: Sequence[Any],
) -> Tuple[int, int, int, Dict[str, float], Dict[str, np.ndarray], Any]:
    """:func:`unpack_experience` plus the raw ``"tr"`` trace element
    (None when absent) — the ingest's decode path."""
    meta, arrays = unpack_block(frames)
    if len(arrays) != len(EXPERIENCE_KEYS):
        raise ValueError(
            f"experience message carries {len(arrays)} arrays, expected "
            f"{len(EXPERIENCE_KEYS)} ({EXPERIENCE_KEYS})"
        )
    batch = dict(zip(EXPERIENCE_KEYS, arrays))
    return (
        int(meta["host"]),
        int(meta.get("e", 0)),
        int(meta["v"]),
        dict(meta["scalars"]),
        batch,
        meta.get("tr") if isinstance(meta, dict) else None,
    )
