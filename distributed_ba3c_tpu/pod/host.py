"""The pod actor host: one process, one complete actor plane, zero learner.

    python -m distributed_ba3c_tpu.pod.host \\
        --host_id 0 --learner_c2s tcp://10.0.0.1:5555 \\
        --learner_s2c tcp://10.0.0.1:5556 --env fake --n_sims 4

What runs inside (docs/pod.md): a :class:`StaleParamsCache` subscribed to
the learner's params plane, a warmed :class:`BatchedPredictor` served from
that cache, a :class:`PodSimulatorMaster` binding HOST-LOCAL pipes for a
supervised env fleet, and an :class:`ExperienceShipper` collating unroll
segments into stamped [T, B] blocks pushed to the learner. The host's
policy is always *some* version behind — that is the design, not a bug:
every shipped block carries the version it was collected under, and the
learner's V-trace corrects the measured lag exactly (the behavior
log-probs AND values ride in the block).

The reference ran this role as ~50 bare simulator processes per worker
with the policy forward on the learner's parameter-server round-trip
(SURVEY.md §3.2); here the forward is host-local against the stale cache,
so actor throughput is completely decoupled from both the learner's step
time and the params RTT — the IMPALA shape (Espeholt et al. 2018).

This process never touches the TPU: it runs jax on CPU for the predictor
forward only. Supervision comes from orchestrate/pod.py (respawn with
backoff; the chaos host-loss scenario SIGKILLs exactly this process and
the respawned cache rejoins at the current version via the fetch channel).
"""

from __future__ import annotations

import argparse
import collections
import functools
import os
import signal
import sys
import threading
from typing import List, Optional

# the host is an actor-plane process: CPU jax only, decided before the
# first jax import (same guard as the test harness / launch_env_fleet)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.telemetry import tracing
from distributed_ba3c_tpu.actors.vtrace_master import VTraceSimulatorMaster
from distributed_ba3c_tpu.data.dataflow import claim_trace, collate_rollout
from distributed_ba3c_tpu.pod.cache import StaleParamsCache, VersionGatedPredictor
from distributed_ba3c_tpu.pod.linkstate import LinkHealth
from distributed_ba3c_tpu.pod.wire import pack_experience, pod_endpoints, pod_role
from distributed_ba3c_tpu.utils import logger
from distributed_ba3c_tpu.utils.concurrency import StoppableThread


class PodSimulatorMaster(VTraceSimulatorMaster):
    """VTraceSimulatorMaster whose segments carry ``behavior_values``.

    The V-trace plane deliberately drops the behavior value (its learner
    never reads it); the pod learner's staleness accounting
    (``value_lag_mae``) is built on it. ONE flag, not copied emission
    paths: the base class records the value per transition already and
    emits the key only when asked — so a flush/ring fix lands on both
    planes at once (the make_finish_update lesson)."""

    record_values = True


class ExperienceShipper(StoppableThread):
    """Collate unroll segments into stamped blocks; push them upstream.

    The stamp is ``cache.version`` read when the block's FIRST segment is
    banked — the OLDEST version any of its transitions could have been
    served under (the cache can refresh several times while the holder
    fills, and measured lag = learner − stamp, so stamping any newer
    would make the ``--max_staleness`` bound looser than the data; the
    conservative stamp can only over-measure, never under-measure, and
    the correction itself reads recorded log-probs, not the stamp).

    Partition tolerance (docs/netchaos.md): the PUSH socket carries an
    explicit SNDHWM so a partitioned ingest can buffer at most
    ``snd_hwm`` blocks inside libzmq — never unbounded learner-side RAM
    growth on the host. When that bound bites (``zmq.Again``) the block
    spills into a bounded DROP-OLDEST buffer (``ship_backpressure_total``
    counts every refusal, ``shipped_dropped_total`` counts blocks the
    spill evicted), the ``experience`` LinkHealth machine tracks the
    silence, and the spill re-drains oldest-first the moment a send lands
    again — a heal ships the freshest bounded window of history, rollout
    never blocked for a microsecond of it.
    """

    def __init__(
        self,
        master: PodSimulatorMaster,
        cache: StaleParamsCache,
        experience_addr: str,
        host: int,
        segments_per_block: int,
        tele_role: Optional[str] = None,
        snd_hwm: int = 8,
        spill_depth: int = 64,
        degraded_after_s: float = 3.0,
        partitioned_after_s: float = 10.0,
    ):
        super().__init__(daemon=True, name=f"pod-shipper-h{host}")
        import zmq

        self.master = master
        self.cache = cache
        self.host = int(host)
        self.segments_per_block = max(1, int(segments_per_block))
        self.context = zmq.Context()
        self._push = self.context.socket(zmq.PUSH)
        self._push.setsockopt(zmq.LINGER, 0)
        # the explicit BOUND on learner-ward buffering: libzmq holds at
        # most this many blocks for a slow/partitioned ingest; everything
        # past it is this class's accounted spill, not silent RAM
        self._push.setsockopt(zmq.SNDHWM, max(1, int(snd_hwm)))
        self._push.connect(experience_addr)
        self._spill: collections.deque = collections.deque()
        self._spill_depth = max(1, int(spill_depth))
        role = tele_role or pod_role(host)
        self.tele_role = role
        tele = telemetry.registry(role)
        self._c_shipped = tele.counter("shipped_blocks_total")
        self._c_dropped = tele.counter("shipped_dropped_total")
        self._c_backpressure = tele.counter("ship_backpressure_total")
        tele.gauge("ship_spill_depth", fn=lambda: len(self._spill))
        self.link = LinkHealth(
            "experience", role,
            degraded_after_s=degraded_after_s,
            partitioned_after_s=partitioned_after_s,
        )

    def _scalars(self) -> dict:
        """The piggybacked host-progress snapshot (folded into the
        learner-side ``pod.host<k>`` mirror by pod/ingest.py)."""
        m = telemetry.registry(self.master.tele_role).scalars()
        p = telemetry.registry(self.tele_role).scalars()
        return {
            "env_steps_total": m.get("datapoints_total", 0.0),
            "train_queue_depth": m.get("train_queue_depth", 0.0),
            "params_version": float(self.cache.version),
            "params_refreshes_total": p.get("params_refreshes_total", 0.0),
            "stale_params_sheds_total": p.get("stale_params_sheds_total", 0.0),
            "shipped_blocks_total": p.get("shipped_blocks_total", 0.0),
            "shipped_dropped_total": p.get("shipped_dropped_total", 0.0),
            "ship_backpressure_total": p.get("ship_backpressure_total", 0.0),
            "params_fetch_retries_total": p.get(
                "params_fetch_retries_total", 0.0
            ),
            "params_corrupt_total": p.get("params_corrupt_total", 0.0),
            "params_malformed_total": p.get("params_malformed_total", 0.0),
        }

    def _try_send(self, frames) -> bool:
        """One non-blocking send attempt; True when libzmq accepted the
        message. Acceptance beats the link (a partitioned peer stops
        accepting within SNDHWM messages); refusal is the typed
        backpressure account."""
        import zmq

        try:
            self._push.send_multipart(frames, zmq.NOBLOCK, copy=False)
        except zmq.Again:
            self._c_backpressure.inc()
            self.link.poll()
            return False
        self._c_shipped.inc()
        self.link.beat()
        return True

    def _ship(self, frames) -> None:
        """Ship oldest-first through the bounded drop-oldest spill."""
        self._spill.append(frames)
        while len(self._spill) > self._spill_depth:
            # the bound bites: shed the OLDEST block — under staleness
            # semantics old experience is the cheapest to lose (its lag
            # would be measured and possibly gate-rejected anyway)
            self._spill.popleft()
            self._c_dropped.inc()
        while self._spill and self._try_send(self._spill[0]):
            self._spill.popleft()

    def run(self) -> None:
        import queue as _queue

        import zmq

        holder: List[dict] = []
        stamp = (0, 0)  # (epoch, version) at the block's first segment
        trace = None  # sampled trace riding the block being collated
        while not self.stopped():
            try:
                # bounded single-attempt get (NOT queue_get_stoppable,
                # which only returns on item-or-stop): idle ticks must
                # still drain the spill and poll the link so a heal is
                # taken within one timeout even when rollout is quiet
                seg = self.master.queue.get(timeout=0.2)
            except _queue.Empty:
                if self._spill:
                    try:
                        while self._spill and self._try_send(self._spill[0]):
                            self._spill.popleft()
                    except zmq.ZMQError:
                        return  # socket torn down (close raced run)
                # no spill and nothing to ship = no attempts = no evidence:
                # the link state FREEZES at its last observed value (an
                # idle host must not drift to "partitioned" on silence it
                # caused itself — only refused sends are evidence here)
                continue
            ref = claim_trace(seg)
            if ref is not None:
                # emit -> shipper drain: the host-side ship wait (one
                # trace per shipped block, claimed once)
                trace = trace or ref.hop("ship_wait", self.tele_role)
            if not holder:
                stamp = (self.cache.epoch or 0, self.cache.version)
            holder.append(seg)
            if len(holder) < self.segments_per_block:
                continue
            batch = collate_rollout(holder)
            holder = []
            ctx = None
            if trace is not None:
                # collate on the host, then hand the trace across the
                # process boundary: the context carries this host's
                # monotonic stamp (clock handshake) so the learner's
                # pod_wire span lands on one aligned timeline
                trace = trace.hop("host_collate", self.tele_role)
                ctx = tracing.encode_context(trace.trace_id, trace.parent_id)
                trace = None
            frames = pack_experience(
                self.host, stamp[1], batch, self._scalars(), epoch=stamp[0],
                trace=ctx,
            )
            try:
                self._ship(frames)
            except zmq.ZMQError:
                return  # socket torn down mid-send (close raced run)

    def close(self) -> None:
        self.stop()
        if self.is_alive():
            self.join(timeout=2)
        try:
            self._push.close(0)
        except Exception:
            pass
        self.context.term()


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m distributed_ba3c_tpu.pod.host",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--host_id", type=int, required=True)
    p.add_argument("--learner_c2s", required=True, help="the learner's BASE c2s pipe (pod channels derive from it, pod/wire.py)")
    p.add_argument("--learner_s2c", required=True)
    p.add_argument("--env", default="fake", help="fake | cpp:<game> (the host-local fleet)")
    p.add_argument("--n_sims", type=int, default=4, help="fake: simulator processes; cpp: total envs on this host")
    p.add_argument("--unroll_len", type=int, default=5)
    p.add_argument("--segments_per_block", type=int, default=16, help="unroll segments collated per shipped block (the block's B)")
    p.add_argument("--max_staleness", type=int, default=0, help="host-side shed bound in params versions (0 = no host gate; the learner's gate still bounds)")
    p.add_argument("--first_params_timeout", type=float, default=120.0)
    p.add_argument("--image_size", type=int, default=84)
    p.add_argument("--frame_history", type=int, default=4)
    p.add_argument("--num_actions", type=int, default=4)
    p.add_argument("--fc_units", type=int, default=512)
    p.add_argument("--predict_batch_size", type=int, default=16)
    p.add_argument("--reward_clip", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--rollout_dtype", default="float32",
        choices=["float32", "bfloat16", "int8"],
        help="the host predictor's param-storage precision (the cached "
        "params arrive f32 from the learner and are cast at publish; "
        "audit entries predict.server_bf16 / predict.server_int8) — the "
        "actor-host half of the quantized rollout forward. int8 requires "
        "--quant_spec (pod hosts calibrate nothing: the spec is frozen "
        "once, centrally, and shipped to every host so the fleet serves "
        "ONE quantization)",
    )
    p.add_argument(
        "--quant_spec", default=None,
        help="frozen QuantSpec JSON for --rollout_dtype int8 "
        "(distributed_ba3c_tpu/quantize/; calibrate centrally via the "
        "serving tier's CalibrationTap or quantize.calibrate_offline)",
    )
    return p


def main(argv: Optional[list] = None) -> int:
    parser = make_parser()
    args = parser.parse_args(argv)
    # exit-2 usage errors, not tracebacks: the int8 rung needs its frozen
    # calibration, and a spec on a non-int8 host is a confused launch
    if args.rollout_dtype == "int8" and not args.quant_spec:
        parser.error(
            "--rollout_dtype int8 requires --quant_spec FILE (pod hosts "
            "serve a centrally frozen calibration — see docs/ingest.md)"
        )
    if args.quant_spec and args.rollout_dtype != "int8":
        parser.error(
            "--quant_spec only applies to --rollout_dtype int8"
        )
    role = pod_role(args.host_id)

    # the host is CPU-only BY CONTRACT (it must never contend for the
    # learner's chip): force the platform even when the operator's shell
    # exports something else, and override any sitecustomize that
    # re-registers a TPU plugin after the env var (the conftest/cli idiom)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from distributed_ba3c_tpu.actors.simulator import SimulatorProcess, default_pipes
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.orchestrate import FleetSpec, FleetSupervisor
    from distributed_ba3c_tpu.predict.server import BatchedPredictor

    cfg = BA3CConfig(
        image_size=(args.image_size, args.image_size),
        frame_history=args.frame_history,
        num_actions=args.num_actions,
        fc_units=args.fc_units,
        predict_batch_size=args.predict_batch_size,
        reward_clip=args.reward_clip,
        local_time_max=args.unroll_len,
    )
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    quant_spec = None
    if args.quant_spec:
        from distributed_ba3c_tpu.quantize import QuantSpec

        quant_spec = QuantSpec.load(args.quant_spec)
        logger.info(
            "[pod host %d] int8 serving from frozen spec %s (%s, %d batches)",
            args.host_id, quant_spec.sha256()[:12], quant_spec.method,
            quant_spec.calibration_batches,
        )
    endpoints = pod_endpoints(args.learner_c2s, args.learner_s2c)

    # 1. params plane first: there is nothing to roll out before a policy
    cache = StaleParamsCache(endpoints, host=args.host_id)
    cache.start()
    logger.info(
        "[pod host %d] waiting for first params (pub %s, fetch %s)",
        args.host_id, endpoints.params_pub, endpoints.params_fetch,
    )
    if not cache.wait_first(args.first_params_timeout):
        logger.error(
            "[pod host %d] no params within %.0fs — is the learner up?",
            args.host_id, args.first_params_timeout,
        )
        cache.close()
        return 3

    # 2. the serving plane, fed from the cache (the ONE sanctioned
    # update_params path — versioned by construction)
    predictor = BatchedPredictor(  # ba3clint: disable=A14 — the pod host's cache-fed plane: the VersionGatedPredictor wrap is its router-equivalent front
        model,
        cache.params,
        batch_size=cfg.predict_batch_size,
        seed=args.seed + 1000 * args.host_id,
        tele_role="predictor",
        rollout_dtype=args.rollout_dtype,
        quant_spec=quant_spec,
    )
    predictor.warmup(cfg.state_shape)
    cache.on_update(lambda params, version: predictor.update_params(params))
    serving = predictor
    if args.max_staleness > 0:
        serving = VersionGatedPredictor(
            predictor, cache.behind, args.max_staleness, tele_role=role,
            # a params-partitioned host sheds through the SAME typed gate:
            # behind() cannot grow while no broadcast arrives, so the
            # link-state machine is the staleness signal that survives a
            # partition (docs/netchaos.md)
            partitioned_fn=cache.params_partitioned,
        )

    # 3. the host-local actor plane
    c2s, s2c = default_pipes(name=f"ba3c-pod-h{args.host_id}")
    master = PodSimulatorMaster(
        c2s, s2c, serving,
        unroll_len=args.unroll_len,
        reward_clip=cfg.reward_clip,
        tele_role="master",
    )
    master.feed_batch = args.segments_per_block

    if args.env == "fake":
        from distributed_ba3c_tpu.envs.fake import build_fake_player
        from distributed_ba3c_tpu.envs.wrappers import guarded_player

        build_player = functools.partial(
            build_fake_player,
            image_size=cfg.image_size,
            frame_history=cfg.frame_history,
            num_actions=cfg.num_actions,
        )
        sim_build_player = functools.partial(
            guarded_player,
            base_build=build_player,
            episode_length_cap=cfg.episode_length_cap,
            stuck_limit=30,
            stuck_action=1,
        )
        spec = FleetSpec(
            pipe_c2s=c2s, pipe_s2c=s2c, envs_per_server=1, wire="per-env",
            frame_history=cfg.frame_history, fleet_size=args.n_sims,
            fleet_min=args.n_sims, fleet_max=args.n_sims,
        )
        base = args.host_id * 10000  # distinct sim idents across hosts
        supervisor = FleetSupervisor(
            spec,
            # parameterize-only factory: the supervisor owns the spawn
            factory=lambda i: SimulatorProcess(  # ba3clint: disable=A8
                base + i, c2s, s2c, sim_build_player
            ),
            ident_prefix=lambda i: f"simulator-{base + i}",
        )
    elif args.env.startswith("cpp:"):
        from distributed_ba3c_tpu.envs import native

        if not native.available():
            logger.error("native env core not built: run `make -C cpp`")
            return 2
        game = args.env.split(":", 1)[1]
        per = min(16, args.n_sims)
        n_servers = (args.n_sims + per - 1) // per
        spec = FleetSpec(
            pipe_c2s=c2s, pipe_s2c=s2c, game=game, envs_per_server=per,
            frame_history=cfg.frame_history, wire="block",
            fleet_size=n_servers, fleet_min=n_servers, fleet_max=n_servers,
            base_idx=args.host_id * 10000,
        )
        from distributed_ba3c_tpu.orchestrate import default_factory

        supervisor = FleetSupervisor(
            spec, factory=default_factory(spec, total_envs=args.n_sims)
        )
    else:
        logger.error("unknown --env %r (fake | cpp:<game>)", args.env)
        return 2

    # 4. the upstream shipper
    shipper = ExperienceShipper(
        master, cache, endpoints.experience, args.host_id,
        args.segments_per_block,
    )

    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        stop_evt.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    # start order: serving + master before the fleet (servers spawned
    # before the receive loop is live would park in their first recv)
    predictor.start()
    master.start()
    shipper.start()
    supervisor.start()
    logger.info(
        "[pod host %d] actor plane up: %s sims of %s, shipping %d-segment "
        "blocks to %s", args.host_id, args.n_sims, args.env,
        args.segments_per_block, endpoints.experience,
    )
    try:
        while not stop_evt.is_set():
            stop_evt.wait(0.5)
    finally:
        supervisor.stop()
        supervisor.join(timeout=5)
        supervisor.close()
        shipper.close()
        master.close()
        predictor.stop()
        predictor.join(timeout=5)
        cache.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
