"""The bounded-staleness learner: measured params lag, gated and exported.

The overlap split (fused/overlap.py) proved the decisive property: the
V-trace gradient body reads the block's RECORDED behavior log-probs, so
the off-policy correction is exact at any params lag — lag never enters
the compiled program, only the data. This module turns that property into
the pod's learner plane:

- :func:`make_pod_learner_step` builds the ``pod.learner`` program — the
  SAME gradient body and update tail as ``fused.learner``
  (make_block_grads / make_finish_update), compiled standalone so
  host-fed blocks of any [T, B] shape drive it without an actor program
  attached. ``tests/test_pod.py`` pins lag-0 bit-exactness against the
  fused step (the overlap parity contract, extended).
- :class:`StalenessGate` measures each block's lag (learner version minus
  the block's collection stamp), exports it as the ``params_lag``
  histogram, and REJECTS blocks beyond ``max_staleness`` with a typed
  counter — the reference cluster's silent staleness made measurable and
  bounded (SURVEY.md §3.4).
- :class:`PodLearner` ties gate + step + versioning + publish cadence
  together: every accepted block is one update, every update bumps the
  version, every ``publish_every``-th version goes out over the
  :class:`~distributed_ba3c_tpu.pod.publisher.ParamsPublisher`, and
  ``value_lag_mae`` is maintained as a first-class SLO gauge.
- :class:`LaggedBlockDriver` generalizes the overlap schedule's fixed
  lag-1 to ANY measured lag k, device-free: a ring of params snapshots
  (taken through the overlap step's own ``prep`` program, so nothing ever
  aliases learner-donated buffers) feeds the actor program the policy of
  k versions ago. It exists for the staleness-vs-learning-quality curve
  (scripts/pod_bench.py) and the lag-k oracle tests — the measurement the
  reference paper never published.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.audit import tripwire_jit
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.fused.overlap import (
    TrajBlock,
    make_block_grads,
    make_finish_update,
)
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.parallel.mesh import DATA_AXIS, shard_map
from distributed_ba3c_tpu.parallel.train_step import TrainState

import optax


def make_pod_learner_step(
    model: BA3CNet,
    optimizer: optax.GradientTransformation,
    cfg: BA3CConfig,
    mesh: Mesh,
    grad_chunk_samples: int = 4096,
) -> Callable:
    """The pod's compiled learner: fn(train, block, beta, lr) -> (train, m).

    Identical math to ``fused.learner`` (shared factories), registered as
    its own audit entry point ``pod.learner`` — host-fed blocks arrive at
    whatever [T, B] the actor hosts collate, which must stay ONE warmed
    shape per run (the BA3C_AUDIT=1 tripwire raises on a mid-run reshape,
    exactly the predictor-bucket contract).
    """
    block_grads = make_block_grads(model, cfg, grad_chunk_samples)
    finish_update = make_finish_update(optimizer)

    def local_learner(train: TrainState, block: TrajBlock, entropy_beta,
                      learning_rate):
        grads, aux = block_grads(train.params, block, entropy_beta)
        return finish_update(train, grads, aux, block.rewards, learning_rate)

    batch_spec = P(DATA_AXIS)
    tb_spec = P(None, DATA_AXIS)  # time-major leaves
    block_specs = TrajBlock(
        states=tb_spec,
        actions=tb_spec,
        rewards=tb_spec,
        dones=tb_spec,
        behavior_log_probs=tb_spec,
        behavior_values=tb_spec,
        bootstrap_state=batch_spec,
    )
    sharded = shard_map(
        local_learner,
        mesh=mesh,
        in_specs=(P(), block_specs, P(), P()),
        out_specs=(P(), P()),
    )
    # registered audit entry point (distributed_ba3c_tpu/audit.py): donated
    # train state, exactly-once grad psum; the block stays undonated (a
    # host-fed block is consumed once, but the LaggedBlockDriver's blocks
    # are the actor program's double-buffer slots — same contract as
    # fused.learner keeps both callers correct)
    jitted = tripwire_jit("pod.learner", sharded, donate_argnums=(0,))

    def step(train: TrainState, block: TrajBlock, entropy_beta,
             learning_rate=None):
        if learning_rate is None:
            learning_rate = cfg.learning_rate
        return jitted(
            train,
            block,
            jnp.asarray(entropy_beta, jnp.float32),
            jnp.asarray(learning_rate, jnp.float32),
        )

    step.state_sharding = NamedSharding(mesh, P())
    step.block_sharding = TrajBlock(
        states=NamedSharding(mesh, tb_spec),
        actions=NamedSharding(mesh, tb_spec),
        rewards=NamedSharding(mesh, tb_spec),
        dones=NamedSharding(mesh, tb_spec),
        behavior_log_probs=NamedSharding(mesh, tb_spec),
        behavior_values=NamedSharding(mesh, tb_spec),
        bootstrap_state=NamedSharding(mesh, batch_spec),
    )
    step.mesh = mesh
    step.audit_jit = jitted  # tools/ba3caudit traces THIS program
    return step


def batch_to_block(
    batch: Dict[str, np.ndarray], block_sharding: Optional[TrajBlock] = None
) -> TrajBlock:
    """Host [T, B] experience batch (pod/wire.py EXPERIENCE_KEYS layout) →
    a device TrajBlock. Dtypes are coerced here, in one place: the wire
    ships whatever the collate produced, the program's input contract
    lives with the program.

    COMPAT path: seven fresh allocations per block. The consuming loop
    (:meth:`PodLearner.consume`) stages through a
    :class:`~distributed_ba3c_tpu.data.staging.BlockStager` instead —
    one copy into a REUSED per-shape buffer, ready-fenced against the
    in-flight H2D — so this stays for one-shot callers only."""
    from distributed_ba3c_tpu.data.staging import count_legacy_copies

    count_legacy_copies(1.0)
    leaves = TrajBlock(
        # sanctioned compat copies — PodLearner's BlockStager is the
        # budget path (reused buffers, same dtype coercion)
        states=np.ascontiguousarray(batch["state"], np.uint8),  # ba3clint: disable=A13
        actions=np.ascontiguousarray(batch["action"], np.int32),  # ba3clint: disable=A13
        rewards=np.ascontiguousarray(batch["reward"], np.float32),  # ba3clint: disable=A13
        dones=np.ascontiguousarray(batch["done"], np.float32),  # ba3clint: disable=A13
        behavior_log_probs=np.ascontiguousarray(  # ba3clint: disable=A13
            batch["behavior_log_probs"], np.float32
        ),
        behavior_values=np.ascontiguousarray(  # ba3clint: disable=A13
            batch["behavior_values"], np.float32
        ),
        bootstrap_state=np.ascontiguousarray(  # ba3clint: disable=A13
            batch["bootstrap_state"], np.uint8
        ),
    )
    if block_sharding is None:
        return leaves
    return jax.tree_util.tree_map(jax.device_put, leaves, block_sharding)


class StalenessGate:
    """Measure every block's params lag; bound it when asked.

    ``admit(block_version, current_version)`` returns the measured lag
    (>= 0), or None when the block is beyond ``max_staleness`` — rejected
    with the ``stale_blocks_rejected_total`` typed counter and a flight
    event, never an exception: the consuming loop must keep draining so
    host backpressure cannot build behind a burst of stale blocks.
    ``max_staleness=None`` measures without bounding (the histogram and
    the SLO gauges still export).
    """

    def __init__(
        self, max_staleness: Optional[int] = None, tele_role: str = "learner"
    ):
        self.max_staleness = (
            None if max_staleness is None else int(max_staleness)
        )
        tele = telemetry.registry(tele_role)
        self._h_lag = tele.histogram("params_lag", unit=1)
        self._c_rejected = tele.counter("stale_blocks_rejected_total")
        self._g_bound = tele.gauge("pod_max_staleness")
        self._g_bound.set(-1 if self.max_staleness is None else self.max_staleness)
        self._g_last_lag = tele.gauge("params_lag_last")

    def admit(
        self,
        block_version: int,
        current_version: int,
        host: Optional[int] = None,
    ) -> Optional[int]:
        lag = max(0, int(current_version) - int(block_version))
        self._h_lag.observe(lag)
        self._g_last_lag.set(lag)
        if self.max_staleness is not None and lag > self.max_staleness:
            self._c_rejected.inc()
            telemetry.record(
                "stale_block_rejected",
                lag=lag,
                bound=self.max_staleness,
                host=host,
                block_version=int(block_version),
                learner_version=int(current_version),
            )
            return None
        return lag


class PodLearner:
    """Versioned consumption of stamped blocks: gate → update → publish.

    One instance, one consuming thread (the pod learner loop). ``state``
    is device_put with the step's sharding here; hyperparameters are
    plain mutable attributes (the pod loop owns its schedule)."""

    def __init__(
        self,
        step: Callable,
        state: TrainState,
        cfg: BA3CConfig,
        publisher: Optional[Any] = None,
        max_staleness: Optional[int] = None,
        publish_every: int = 1,
        tele_role: str = "learner",
        stager_slots: int = 4,
    ):
        self.step = step
        self.state = jax.device_put(state, step.state_sharding)
        self.cfg = cfg
        self.publisher = publisher
        self.publish_every = max(1, int(publish_every))
        if (
            max_staleness is not None
            and max_staleness < self.publish_every
        ):
            # lag is measured in UPDATES but hosts can only be stamped
            # with PUBLISHED versions: just before each publish a
            # perfectly-current host's blocks carry apparent lag up to
            # publish_every - 1, so a tighter bound would shed healthy
            # experience forever — a config lie, refused at construction
            raise ValueError(
                f"max_staleness {max_staleness} < publish_every "
                f"{self.publish_every}: blocks are stamped with published "
                "versions, so the bound must cover at least one publish "
                "interval or a healthy pod persistently rejects fresh "
                "experience"
            )
        self.entropy_beta = cfg.entropy_beta
        self.learning_rate = cfg.learning_rate
        self.version = 0
        # staged ingest (data/staging.py): ONE copy per block into a
        # reused per-shape buffer replaces batch_to_block's seven fresh
        # ascontiguousarray allocations; hand this same stager to
        # PodIngest so the wire→staging write runs on the receive thread,
        # overlapping the learner's step (docs/ingest.md). When wired
        # into an ingest, ``stager_slots`` must cover the ingest DEPTH
        # (every buffered StampedBatch holds a slot) or the backlogged
        # regime degrades to per-block transient allocations — the very
        # cost the stager removes (orchestrate/pod.py sizes it depth+2)
        from distributed_ba3c_tpu.data.staging import BlockStager

        self.stager = BlockStager(slots=stager_slots, tele_role=tele_role)
        self.gate = StalenessGate(max_staleness, tele_role=tele_role)
        self._tele_role = tele_role
        tele = telemetry.registry(tele_role)
        self._c_updates = tele.counter("pod_updates_total")
        self._c_epoch_mismatch = tele.counter("epoch_mismatch_blocks_total")
        self._g_version = tele.gauge("pod_learner_version")
        self._g_lag_mae = tele.gauge("value_lag_mae")
        self.last_metrics: Optional[dict] = None
        if publisher is not None:
            # version 0 goes out immediately: actor hosts need SOME policy
            # before the first update exists (the late-joiner fetch answers
            # with this same snapshot)
            self._publish()

    def _publish(self) -> None:
        # device_get AFTER the last dispatched update resolves (it blocks
        # on the param futures) and BEFORE the next step call donates the
        # buffers — the same anti-aliasing contract as fused.prep, paid
        # here as one host copy per publish interval
        self.publisher.publish(
            self.version,
            jax.device_get(self.state.params),
            step=int(self.state.step),
        )

    def consume(self, stamped) -> Optional[dict]:
        """Gate + update on one ingest batch (pod/ingest.py StampedBatch);
        returns the update's metrics, or None when the block was rejected."""
        ref = getattr(stamped, "trace", None)
        if (
            self.publisher is not None
            and getattr(stamped, "epoch", 0)
            and stamped.epoch != self.publisher.epoch
        ):
            # a block stamped under a DIFFERENT publisher lifetime (the
            # host outlived a learner restart, or a foreign learner's
            # host misdelivered): its version counts in a lineage this
            # learner does not own, so no lag can honestly be measured —
            # typed rejection, and the host's cache will adopt OUR epoch
            # from the next broadcast
            self._c_epoch_mismatch.inc()
            telemetry.record(
                "pod_epoch_mismatch",
                host=stamped.host,
                block_epoch=stamped.epoch,
                learner_epoch=self.publisher.epoch,
            )
            if ref is not None:
                # same visibility contract as the staleness-gate
                # rejection below: a rejected block's trace ENDS with a
                # verdict span, never a silent disappearance
                ref.hop(
                    "epoch_gate", self._tele_role,
                    tags={"rejected": True, "reason": "epoch_mismatch"},
                )
            self._release_staged(stamped)
            return None
        lag = self.gate.admit(stamped.version, self.version, stamped.host)
        if lag is None:
            if ref is not None:
                # the trace ends HERE, visibly: a rejected block's last
                # span is the gate verdict, not a silent disappearance
                ref.hop(
                    "staleness_gate", self._tele_role,
                    tags={"rejected": True, "lag": "over_bound"},
                )
            self._release_staged(stamped)
            return None
        if ref is not None:
            ref = ref.hop(
                "staleness_gate", self._tele_role, tags={"lag": lag}
            )
        block = self._stage_block(stamped)
        if ref is not None:
            ref = ref.hop("pod_ingest_stage", self._tele_role)
        out = self._update(block)
        if ref is not None:
            ref.hop("pod_learner_step", self._tele_role)
        return out

    def _stage_block(self, stamped) -> TrajBlock:
        """Admitted block → device TrajBlock through the staging path: the
        wire views (or a receive-thread pre-staged block, pod/ingest.py)
        cross the host exactly once."""
        from distributed_ba3c_tpu.data.staging import StagedBlock

        staged = stamped.batch
        if not isinstance(staged, StagedBlock):
            staged = self.stager.copy_in(staged)
        return self.stager.to_device(staged, self.step.block_sharding)

    def _release_staged(self, stamped) -> None:
        """A rejected block's receive-thread staging slot must go back in
        rotation without a transfer."""
        from distributed_ba3c_tpu.data.staging import StagedBlock

        if isinstance(stamped.batch, StagedBlock):
            self.stager.cancel(stamped.batch)

    def consume_block(self, block: TrajBlock, block_version: int,
                      host: Optional[int] = None) -> Optional[dict]:
        """Gate + update on an already-device-resident TrajBlock (the
        LaggedBlockDriver path)."""
        lag = self.gate.admit(block_version, self.version, host)
        if lag is None:
            return None
        return self._update(block)

    def _update(self, block: TrajBlock) -> dict:
        self.state, metrics = self.step(
            self.state, block, self.entropy_beta, self.learning_rate
        )
        self.version += 1
        self._c_updates.inc()
        self._g_version.set(self.version)
        # the SLO gauge reads the latest update's fetched value — one
        # scalar fetch per update; the pod learner loop is host-paced
        # (ingest wait dominates), so this sync is not a schedule hazard
        self._g_lag_mae.set(float(metrics["value_lag_mae"]))
        self.last_metrics = metrics
        if self.publisher is not None and self.version % self.publish_every == 0:
            self._publish()
        return metrics


class LaggedBlockDriver:
    """Drive rollout at the policy of ``lag`` versions ago, device-free.

    The overlap split's schedule generalized: a ring of ``lag + 1`` params
    snapshots (each taken through the overlap step's ``prep`` program —
    never aliasing learner-donated buffers) hands the actor program the
    OLDEST version's snapshot, and the learner consumes each block stamped
    with that version. At ``lag=0`` the schedule is exactly the overlap
    lag-0 sequence, which is the fused step's — the parity anchor. The
    first ``lag`` iterations ramp (the ring is still filling), which the
    ``params_lag`` histogram shows honestly.
    """

    def __init__(self, overlap_step, learner: PodLearner, lag: int):
        if lag < 0:
            raise ValueError(f"lag must be >= 0, got {lag}")
        self.actor_jit = overlap_step.actor_jit
        self.prep_jit = overlap_step.prep_jit
        self.learner = learner
        self.lag = int(lag)
        self.astate = None
        self._snaps: collections.deque = collections.deque()

    def prime(self, overlap_state) -> None:
        """Adopt a fresh OverlapState (overlap_step.put's output): the env
        carry drives the actor; the train state replaces the learner's."""
        self.astate = overlap_state.actor
        self.learner.state = overlap_state.train

    def iterate(self) -> Optional[dict]:
        """One rollout + one (possibly rejected) update; returns the
        update metrics or None if the gate rejected the block."""
        if self.astate is None:
            raise RuntimeError("prime() the driver with an OverlapState first")
        snap = self.prep_jit(self.learner.state.params)
        self._snaps.append((self.learner.version, snap))
        while len(self._snaps) > self.lag + 1:
            self._snaps.popleft()
        version, aparams = self._snaps[0]
        self.astate, block = self.actor_jit(aparams, self.astate)
        return self.learner.consume_block(block, version)
