"""Pod-scale async training: the bounded-staleness parameter plane.

The reference paper's whole scaling story is asynchronous distribution —
64 CPU nodes pushing gradients through parameter servers with actor/
learner staleness tolerated *silently* (Adamski et al., arXiv:1801.02852).
This package composes the pieces the repo already proved separately (tcp
fleet pipes, the ``prep`` params-snapshot decoupling point from
fused/overlap.py, the supervised actor plane, cross-host telemetry) into
the IMPALA-shaped system (Espeholt et al. 2018) that PS cluster
approximated — with the staleness *measured and corrected* instead:

- **params broadcast** (publisher.py / cache.py): the learner publishes
  versioned snapshots over a ZMQ PUB + ROUTER side-channel derived from
  the fleet port map (wire.py); each actor host serves its predictor from
  a :class:`StaleParamsCache` that refreshes asynchronously with
  retry/backoff and never blocks rollout on a fetch.
- **bounded-staleness learner** (learner.py): experience blocks arrive
  stamped with the params version they were collected under; V-trace
  corrects the per-block *measured* lag (behavior log-probs ride in the
  block, so the correction is exact at any lag — the fixed lag-1 of
  fused/overlap.py generalized), a :class:`StalenessGate` rejects blocks
  beyond ``--max_staleness`` with a typed counter, and ``value_lag_mae``
  plus the per-block ``params_lag`` histogram are first-class SLO gauges.
- **actor host** (host.py): a complete plane per host — supervised env
  servers, master, predictor-from-cache, experience shipper — run as one
  process ``python -m distributed_ba3c_tpu.pod.host``; orchestrated N at
  a time by ``orchestrate/pod.py``.

docs/pod.md documents the wire protocol, the version-stamp format and the
staleness semantics; scripts/pod_bench.py measures the scaling story.
"""

from __future__ import annotations

from distributed_ba3c_tpu.pod.wire import (  # noqa: F401
    EXPERIENCE_KEYS,
    PodEndpoints,
    pack_experience,
    pack_params,
    pod_endpoints,
    pod_role,
    unpack_experience,
    unpack_params,
)
from distributed_ba3c_tpu.pod.linkstate import (  # noqa: F401
    DEGRADED,
    PARTITIONED,
    STATES,
    UP,
    LinkHealth,
)
from distributed_ba3c_tpu.pod.publisher import ParamsPublisher  # noqa: F401
from distributed_ba3c_tpu.pod.cache import (  # noqa: F401
    StaleParamsCache,
    VersionGatedPredictor,
)
from distributed_ba3c_tpu.pod.ingest import PodIngest, StampedBatch  # noqa: F401
from distributed_ba3c_tpu.pod.learner import (  # noqa: F401
    LaggedBlockDriver,
    PodLearner,
    StalenessGate,
    batch_to_block,
    make_pod_learner_step,
)
