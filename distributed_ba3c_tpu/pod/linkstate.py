"""Per-link health state machines for the pod transport (docs/netchaos.md).

Every cross-host channel in the pod gets one :class:`LinkHealth`:
``up -> degraded -> partitioned`` driven purely by *observed contact*
(received payloads, heartbeat acks, successful sends) against monotonic
silence thresholds — the transport's own account of the network, not a
guess from the fault injector. Transitions are flight-recorded
(``link_state`` events, so a postmortem dump shows exactly when a link
died and healed) and exported as ``link_state_<name>`` gauges
(0 = up, 1 = degraded, 2 = partitioned) on the owning role's registry.

The machine never *acts*; it only *names* the condition. The actions live
at the call sites: the params cache re-arms its bounded-backoff fetch when
its SUB channel degrades (the asymmetric-partition self-heal), the
VersionGatedPredictor sheds through the staleness gate when BOTH params
channels are partitioned (a host that cannot know its lag must not
pretend it is fresh), and the experience shipper spills to its bounded
drop-oldest buffer while its PUSH link refuses sends — rollout never
wedges on any of it.
"""

from __future__ import annotations

import re
import time
from typing import Optional

from distributed_ba3c_tpu import telemetry

#: canonical state names, index == gauge value
STATES = ("up", "degraded", "partitioned")
UP, DEGRADED, PARTITIONED = STATES

_NAME_RE = re.compile(r"[^A-Za-z0-9_]+")


def metric_link_name(raw) -> str:
    """Sanitize an arbitrary link/ident name into the Prometheus-safe
    metric suffix (the telemetry plane's ASCII-grammar lesson: one junk
    name would poison the whole scrape). Capped so stray senders on a
    bound port cannot mint unbounded-length series names."""
    if isinstance(raw, bytes):
        raw = raw.decode("utf-8", "replace")
    return _NAME_RE.sub("_", str(raw)).strip("_")[:32] or "link"


class LinkHealth:
    """One link's ``up/degraded/partitioned`` machine.

    ``beat()`` on every observed contact; ``poll()`` re-derives the state
    from monotonic silence and returns it (recording the transition the
    first time it is observed). Both are safe from any thread: the hot
    half is one monotonic read + one float store (GIL-atomic), and state
    transitions only happen inside ``poll`` — worst case two racing
    pollers record the same transition twice, never a torn state.
    """

    def __init__(
        self,
        link: str,
        role: str,
        degraded_after_s: float = 3.0,
        partitioned_after_s: float = 10.0,
        gauge_name: Optional[str] = None,
    ):
        if not 0 < degraded_after_s <= partitioned_after_s:
            raise ValueError(
                f"need 0 < degraded_after_s <= partitioned_after_s, got "
                f"{degraded_after_s}/{partitioned_after_s}"
            )
        self.link = str(link)
        self.role = str(role)
        self.degraded_after_s = float(degraded_after_s)
        self.partitioned_after_s = float(partitioned_after_s)
        self._last_contact = time.monotonic()
        self._state = UP
        name = gauge_name or f"link_state_{metric_link_name(link)}"
        self._gauge = telemetry.registry(role).gauge(name)
        self._gauge.set(0.0)
        self._c_transitions = telemetry.registry(role).counter(
            "link_transitions_total"
        )

    # -- inputs -------------------------------------------------------------
    def beat(self) -> None:
        """Contact observed (payload received, send accepted, ack seen)."""
        self._last_contact = time.monotonic()
        if self._state != UP:
            self._transition(UP, 0.0)

    # -- outputs ------------------------------------------------------------
    def silent_s(self) -> float:
        return time.monotonic() - self._last_contact

    def poll(self) -> str:
        """Current state, re-derived from silence; records transitions."""
        silent = self.silent_s()
        if silent >= self.partitioned_after_s:
            state = PARTITIONED
        elif silent >= self.degraded_after_s:
            state = DEGRADED
        else:
            state = UP
        if state != self._state:
            self._transition(state, silent)
        return state

    @property
    def state(self) -> str:
        """Last derived state (no re-derivation — use :meth:`poll` on any
        path that must observe fresh silence)."""
        return self._state

    def partitioned(self) -> bool:
        return self.poll() == PARTITIONED

    # -- internals ----------------------------------------------------------
    def _transition(self, state: str, silent: float) -> None:
        prev, self._state = self._state, state
        self._gauge.set(float(STATES.index(state)))
        self._c_transitions.inc()
        telemetry.record(
            "link_state",
            link=self.link,
            role=self.role,
            frm=prev,
            to=state,
            silent_s=round(silent, 3),
        )
