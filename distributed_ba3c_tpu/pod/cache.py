"""StaleParamsCache: the actor host's end of the params plane.

The pod's core asymmetry (docs/pod.md): rollout NEVER waits for
parameters. The cache serves the predictor from the last version it
received; a refresh thread subscribes to the learner's broadcasts and,
when it holds nothing yet (fresh spawn, respawn after a host-loss chaos
kill), fetches the current snapshot with retry/backoff over the ROUTER
side-channel. Staleness is therefore a *measured* property of the
experience (every shipped block is stamped with ``cache.version``), not a
synchronization point — exactly the IMPALA inversion of the reference's
blocking parameter-server pull.

:class:`VersionGatedPredictor` is the host-side half of the
``--max_staleness`` bound: when the cache KNOWS it has fallen more than
the bound behind the latest *seen* version (broadcasts arriving faster
than the predictor swap can apply them, or a wedged apply callback), new
predict tasks are shed with a typed :class:`~distributed_ba3c_tpu.predict
.server.ShedReject` — the masters answer sheds with the true
uniform-random fallback policy, so the lockstep env servers keep stepping
(never parked in ``recv()``) and the behavior log-probs stay exact for
V-trace. Blocks the host cannot know are over-stale (a silent partition)
are caught by the learner-side :class:`~distributed_ba3c_tpu.pod.learner
.StalenessGate`, where version truth lives.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

import numpy as np
import zmq

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.telemetry import tracing
from distributed_ba3c_tpu.pod.linkstate import PARTITIONED, UP, LinkHealth
from distributed_ba3c_tpu.pod.wire import (
    PodEndpoints,
    pod_role,
    unpack_params_full,
)
from distributed_ba3c_tpu.utils import logger
from distributed_ba3c_tpu.utils.concurrency import StoppableThread
from distributed_ba3c_tpu.utils.serialize import CorruptFrameError


class StaleParamsCache:
    """Hold the last received params version; refresh asynchronously.

    ``on_update(params, version)`` callbacks run on the refresh thread —
    the sanctioned versioned publish path into a predictor
    (``predictor.update_params`` is an atomic ref swap, so the rollout
    thread never observes a torn update). ba3clint rule A10 flags
    update_params calls anywhere OUTSIDE this plane precisely so no code
    path can bypass the version accounting silently.
    """

    def __init__(
        self,
        endpoints: PodEndpoints,
        host: int = 0,
        fetch_backoff_s: float = 0.2,
        fetch_backoff_max_s: float = 5.0,
        tele_role: Optional[str] = None,
        heartbeat_s: float = 1.0,
        degraded_after_s: float = 3.0,
        partitioned_after_s: float = 10.0,
    ):
        self.endpoints = endpoints
        self.host = int(host)
        self._backoff0 = fetch_backoff_s
        self._backoff_max = fetch_backoff_max_s
        self._heartbeat_s = max(0.05, float(heartbeat_s))
        self._params: Optional[Dict[str, Any]] = None
        self.version = -1  # nothing received yet
        self.seen_version = -1  # newest version observed on the wire
        self.epoch: Optional[int] = None  # publisher lifetime adopted
        self.learner_step = 0
        self._have_first = threading.Event()
        self._callbacks: list = []
        self._lock = threading.Lock()

        role = tele_role or pod_role(host)
        self.tele_role = role
        tele = telemetry.registry(role)
        self._c_refreshes = tele.counter("params_refreshes_total")
        self._c_retries = tele.counter("params_fetch_retries_total")
        self._c_malformed = tele.counter("params_malformed_total")
        self._c_corrupt = tele.counter("params_corrupt_total")
        self._g_version = tele.gauge("params_version")
        self._g_behind = tele.gauge("params_behind", fn=self.behind)
        # one health machine PER CHANNEL (docs/netchaos.md): an asymmetric
        # partition — broadcasts dead, fetch path alive — is precisely the
        # state where the cache must keep refreshing over the ROUTER
        # side-channel, so collapsing both into one link would erase the
        # distinction the recovery logic runs on
        self.sub_link = LinkHealth(
            "params_sub", role,
            degraded_after_s=degraded_after_s,
            partitioned_after_s=partitioned_after_s,
        )
        self.fetch_link = LinkHealth(
            "params_fetch", role,
            degraded_after_s=degraded_after_s,
            partitioned_after_s=partitioned_after_s,
        )

        self.context = zmq.Context()
        self._sub = self.context.socket(zmq.SUB)
        self._sub.setsockopt(zmq.LINGER, 0)
        self._sub.setsockopt(zmq.SUBSCRIBE, b"")
        # keep at most a couple of snapshots queued: applying the NEWEST
        # is all that matters, backlog is just memory
        self._sub.set_hwm(2)
        self._sub.connect(endpoints.params_pub)
        self._dealer = self.context.socket(zmq.DEALER)
        self._dealer.setsockopt(zmq.LINGER, 0)
        # stable per-host identity: the publisher names its per-host
        # link_state gauges from it, and a respawned host re-enters as the
        # SAME link (the publisher's ROUTER runs HANDOVER for exactly the
        # reason the actor plane's does — docs/actor_plane.md)
        self._dealer.setsockopt(zmq.IDENTITY, f"pod-host-{self.host}".encode())
        self._dealer.connect(endpoints.params_fetch)

        self._thread = StoppableThread(
            target=self._refresh_loop, daemon=True,
            name=f"pod-params-cache-h{host}",
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._thread.stop()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread.is_alive():
            self._thread.join(timeout)

    def close(self) -> None:
        self.stop()
        self.join(timeout=2)
        for s in (self._sub, self._dealer):
            try:
                s.close(0)
            except zmq.ZMQError:
                pass
        self.context.term()

    # -- the serving surface ------------------------------------------------
    @property
    def params(self) -> Optional[Dict[str, Any]]:
        return self._params

    def behind(self) -> int:
        """How many versions the APPLIED params trail the newest version
        seen on the wire (0 when current; 0 before the first receive —
        a host that has seen nothing cannot claim a measured lag)."""
        return max(0, self.seen_version - self.version)

    def params_partitioned(self) -> bool:
        """True when BOTH params channels are partitioned — total loss of
        contact with the publisher. ``behind()`` cannot grow during a
        partition (no broadcasts arrive to raise ``seen_version``), so
        this is the signal the VersionGatedPredictor sheds on instead: a
        host that cannot measure its lag must not serve as if it were
        fresh (docs/netchaos.md degraded-mode semantics)."""
        return (
            self.sub_link.poll() == PARTITIONED
            and self.fetch_link.poll() == PARTITIONED
        )

    def on_update(self, cb: Callable[[Any, int], None]) -> None:
        """Register a callback for every applied refresh (refresh-thread
        context). Registered AFTER a first version arrived, the callback
        fires immediately with the current params — a predictor built
        from ``wait_first`` must not miss the version it was built at."""
        with self._lock:
            self._callbacks.append(cb)
            p, v = self._params, self.version
        if p is not None:
            cb(p, v)

    def wait_first(self, timeout: Optional[float] = None) -> bool:
        """Block (caller's thread, NOT rollout) until the first snapshot
        lands; the ONE sanctioned wait in the pod host's startup path —
        there is nothing to roll out before any policy exists."""
        return self._have_first.wait(timeout)

    # -- refresh internals ---------------------------------------------------
    def _apply(self, payload) -> bool:
        """Apply one snapshot payload; True when it advanced the cache
        (a same-or-older fetch reply is contact, not progress — the
        backoff only resets on progress, so a degraded link's probe
        fetches stay at the capped cadence instead of hammering)."""
        epoch, version, step, params, tr = unpack_params_full(payload)
        # a sampled publish carries a trace context: handshake the
        # learner's clock and park the ref so the apply leg below is
        # attributed (publisher -> cache fetch, docs/observability.md)
        ref = None
        out = tracing.receive_context(
            tracing.decode_context(tr), peer="pod-learner",
            role=self.tele_role, wire_name="params_wire",
        )
        if out is not None:
            ref = tracing.TraceRef(*out)
        if epoch != self.epoch:
            # a NEW publisher lifetime (first contact, or a restarted
            # learner whose versions regressed to 0): adopt it outright —
            # version ordering only means anything WITHIN an epoch, and
            # refusing the "older" number would freeze this host on the
            # dead lineage's policy forever
            self.epoch = epoch
            self.seen_version = version
        else:
            self.seen_version = max(self.seen_version, version)
            if version <= self.version:
                return False  # stale broadcast (fetch raced a publish)
        with self._lock:
            self._params = params
            self.version = version
            self.learner_step = step
            cbs = list(self._callbacks)
        for cb in cbs:
            try:
                cb(params, version)
            except Exception as e:  # a bad consumer must not kill refresh
                logger.error("params cache on_update raised %r", e)
        if ref is not None:
            # decode + predictor swap, on this host's timeline
            ref.hop("params_apply", self.tele_role, tags={"version": version})
        self._c_refreshes.inc()
        self._g_version.set(version)
        self._have_first.set()
        return True

    def _refresh_loop(self) -> None:
        import time

        t = threading.current_thread()
        assert isinstance(t, StoppableThread)
        poller = zmq.Poller()
        poller.register(self._sub, zmq.POLLIN)
        poller.register(self._dealer, zmq.POLLIN)
        backoff = self._backoff0
        next_fetch = 0.0  # monotonic time of the next fetch (re)attempt
        next_hb = 0.0  # monotonic time of the next heartbeat probe
        first_attempt = True
        while not t.stopped():
            now = time.monotonic()
            # fetch when we hold nothing (the late-joiner path) OR when
            # the broadcast channel has gone silent past its degraded
            # threshold (the asymmetric-partition self-heal: broadcasts
            # lost, ROUTER side-channel possibly alive). Either way the
            # cadence is the same bounded backoff — a partitioned learner
            # is probed at ``fetch_backoff_max_s``, never hammered, and a
            # heal is adopted on the first reply that lands (a restarted
            # learner's new epoch included — the rejoin contract _apply
            # owns). DEALER sends never block rollout.
            if (
                self._params is None or self.sub_link.poll() != UP
            ) and now >= next_fetch:
                try:
                    self._dealer.send(b"fetch", zmq.NOBLOCK)
                except zmq.ZMQError:
                    pass
                if not first_attempt:
                    self._c_retries.inc()
                first_attempt = False
                next_fetch = now + backoff
                backoff = min(self._backoff_max, backoff * 2)
            if now >= next_hb:
                # heartbeat probe on the fetch channel: the publisher
                # beats this host's per-link machine and acks with an
                # empty frame, so BOTH ends keep a live account of the
                # link even between real fetches (docs/netchaos.md)
                try:
                    self._dealer.send(b"hb", zmq.NOBLOCK)
                except zmq.ZMQError:
                    pass
                next_hb = now + self._heartbeat_s
            try:
                events = dict(poller.poll(100))
                if self._dealer in events:
                    reply = self._dealer.recv()
                    # ANY reply — snapshot, empty pre-first-publish frame,
                    # or a heartbeat ack — is contact on the fetch channel
                    self.fetch_link.beat()
                    if reply and self._apply_safe(reply):
                        backoff = self._backoff0
                        next_fetch = 0.0
                if self._sub in events:
                    payload = self._sub.recv()
                    self.sub_link.beat()
                    self._apply_safe(payload)
            except (zmq.ContextTerminated, zmq.ZMQError):
                return

    def _apply_safe(self, payload) -> bool:
        """Apply one payload; True only when it ADVANCED the cache. A
        malformed frame (port-band collision, learner/host message-format
        skew) must COUNT and keep the refresh loop alive, not kill the
        one thread that could ever recover — same contract as PodIngest's
        malformed-block handling. A CRC-failed frame counts under its own
        typed ``params_corrupt_total`` (bytes changed in flight, not a
        sender bug — the runbook branches on the distinction)."""
        try:
            return self._apply(payload)
        except CorruptFrameError as e:
            self._c_corrupt.inc()
            telemetry.record(
                "corrupt_frame", wire="pod-params", role=self.tele_role,
                error=str(e)[:200],
            )
            logger.error("pod params cache dropped a corrupt payload: %r", e)
            return False
        except Exception as e:  # msgpack raises its own hierarchy too
            self._c_malformed.inc()
            logger.error(
                "pod params cache dropped a malformed payload: %r", e
            )
            return False


class VersionGatedPredictor:
    """Shed predict tasks when the cache is provably over-stale.

    Wraps a :class:`~distributed_ba3c_tpu.predict.server.BatchedPredictor`
    surface (put_task / put_block_task / num_actions). When
    ``behind_fn() > max_staleness`` the task is answered immediately with
    a typed ``ShedReject("stale_params")`` through its shed callback — the
    masters' uniform-fallback path keeps every lockstep server stepping,
    and the recorded uniform log-prob keeps V-trace exact. The learner
    would have rejected blocks collected this far behind anyway; shedding
    here spends zero device time producing them.
    """

    def __init__(
        self,
        predictor,
        behind_fn: Callable[[], int],
        max_staleness: int,
        tele_role: str = "pod.host0",
        partitioned_fn: Optional[Callable[[], bool]] = None,
    ):
        """``partitioned_fn`` (typically ``cache.params_partitioned``)
        extends the gate to total params loss: during a partition no
        broadcast can raise ``seen_version``, so ``behind_fn`` reads 0
        exactly when the host is MOST stale — the link-state machine is
        the signal that survives, and shedding through the same typed
        path keeps every lockstep server stepping on uniform fallback
        instead of wedging (docs/netchaos.md)."""
        self._pred = predictor
        self._behind = behind_fn
        self._partitioned = partitioned_fn
        self.max_staleness = int(max_staleness)
        self._c_stale_sheds = telemetry.registry(tele_role).counter(
            "stale_params_sheds_total"
        )

    @property
    def num_actions(self) -> int:
        return self._pred.num_actions

    def update_params(self, params, policy: str = "default") -> None:
        # versioned path only: the cache's on_update is the publisher into
        # the wrapped predictor (sanctioned A10 site — inside pod/)
        self._pred.update_params(params, policy=policy)

    def _stale(self) -> bool:
        if self._behind() > self.max_staleness:
            return True
        return self._partitioned is not None and self._partitioned()

    def _shed(self, k: int, shed_callback) -> bool:
        from distributed_ba3c_tpu.predict.server import ShedReject

        self._c_stale_sheds.inc(k)
        if shed_callback is not None:
            shed_callback(ShedReject("stale_params"))
        return False

    def put_task(self, state, callback, *, shed_callback=None, **kw) -> bool:
        if self._stale():
            return self._shed(1, shed_callback)
        return self._pred.put_task(
            state, callback, shed_callback=shed_callback, **kw
        )

    def put_block_task(
        self, states: np.ndarray, callback, *, shed_callback=None, **kw
    ) -> bool:
        if self._stale():
            return self._shed(int(states.shape[0]), shed_callback)
        return self._pred.put_block_task(
            states, callback, shed_callback=shed_callback, **kw
        )

    def predict_batch(self, states):
        return self._pred.predict_batch(states)

    # lifecycle passthrough (StartProcOrThread protocol)
    def start(self) -> None:
        self._pred.start()

    def stop(self) -> None:
        self._pred.stop()

    def join(self, timeout=None) -> None:
        self._pred.join(timeout)

    def warmup(self, state_shape, dtype=np.uint8) -> None:
        self._pred.warmup(state_shape, dtype)
