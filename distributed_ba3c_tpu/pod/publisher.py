"""ParamsPublisher: the learner's versioned-snapshot broadcast service.

The ``prep`` snapshot program (fused/overlap.py) already proved the
decoupling point: the learner's donated param buffers must never be read
by anyone else, so every publish starts from a COPY. This class is that
decoupling pushed across the process boundary — the pod's replacement for
the reference's parameter-server pull (SURVEY.md §3.4), with the roles
inverted: the learner PUSHES versioned snapshots, actor hosts keep a
stale cache (pod/cache.py), and nobody ever blocks a training step on a
parameter round-trip.

Two sockets, one contract (docs/pod.md):

- PUB: every :meth:`publish` broadcasts the full ``pack_params`` payload.
  PUB drops for slow/absent subscribers by design — a host that misses a
  broadcast stays on its last version, which is exactly the bounded-
  staleness semantics the learner's gate measures and enforces.
- ROUTER: answers ``[b"fetch"]`` requests with the LATEST payload (or an
  empty frame before the first publish) — the late-joiner/rejoin path a
  respawned host's cache retries with backoff. Served by a small
  StoppableThread; the latest payload is an atomic ref swap away from the
  publishing thread.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import zmq

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.telemetry import tracing
from distributed_ba3c_tpu.pod.linkstate import LinkHealth, metric_link_name
from distributed_ba3c_tpu.pod.wire import PodEndpoints, pack_params
from distributed_ba3c_tpu.utils import logger
from distributed_ba3c_tpu.utils.concurrency import StoppableThread

#: per-host link machines are capped like every untrusted-ident table in
#: the telemetry plane: a stray sender churning fresh idents on the bound
#: port must not mint unbounded gauges (the 4096-ident piggyback lesson)
_MAX_HOST_LINKS = 256


class ParamsPublisher:
    """Bind the pod params channels and serve versioned snapshots.

    Satisfies the StartProcOrThread protocol (start/stop/join/close) so a
    learner assembly can append it to its startables list.
    """

    def __init__(
        self,
        endpoints: PodEndpoints,
        tele_role: str = "learner",
        epoch: Optional[int] = None,
    ):
        self.endpoints = endpoints
        # the epoch names THIS publisher lifetime: a relaunched learner's
        # versions restart at 0, and without it every surviving cache
        # would drop the "older" broadcasts forever (pod/wire.py)
        self.epoch = (
            int.from_bytes(os.urandom(4), "little") if epoch is None
            else int(epoch)
        )
        self.context = zmq.Context()
        self._pub = self.context.socket(zmq.PUB)
        self._pub.setsockopt(zmq.LINGER, 0)
        # a slow subscriber sheds broadcasts instead of ballooning the
        # learner's memory: the fetch channel is the catch-up path
        self._pub.set_hwm(4)
        self._pub.bind(endpoints.params_pub)
        self._router = self.context.socket(zmq.ROUTER)
        self._router.setsockopt(zmq.LINGER, 0)
        # a respawned host reconnects under its slot-stable DEALER
        # identity; without HANDOVER libzmq keeps the ident bound to the
        # dead predecessor's half-open pipe and silently rejects the new
        # peer — the exact wedge the actor plane's chaos bench found
        self._router.setsockopt(zmq.ROUTER_HANDOVER, 1)
        self._router.bind(endpoints.params_fetch)
        self._latest: Optional[bytes] = None  # atomic ref swap
        self.version = 0

        tele = telemetry.registry(tele_role)
        self.tele_role = tele_role
        self._c_publishes = tele.counter("pod_params_publishes_total")
        self._c_fetches = tele.counter("pod_params_fetches_total")
        self._c_heartbeats = tele.counter("pod_params_heartbeats_total")
        self._g_version = tele.gauge("pod_params_version")
        # learner-side per-host link machines, driven by fetch/heartbeat
        # arrivals on the ROUTER channel: the publisher cannot see its PUB
        # subscribers, but every healthy cache heartbeats this channel, so
        # ``link_state_<host>`` on the LEARNER's scrape endpoint is the
        # operator's one-stop partition map (docs/netchaos.md)
        self._links: Dict[bytes, LinkHealth] = {}
        self.heartbeat_degraded_s = 3.0
        self.heartbeat_partitioned_s = 10.0

        self._thread = StoppableThread(
            target=self._serve_fetches, daemon=True, name="pod-params-fetch"
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._thread.start()
        logger.info(
            "pod params plane up: pub %s, fetch %s",
            self.endpoints.params_pub, self.endpoints.params_fetch,
        )

    def stop(self) -> None:
        self._thread.stop()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread.is_alive():
            self._thread.join(timeout)

    def close(self) -> None:
        self.stop()
        self.join(timeout=2)
        for s in (self._pub, self._router):
            try:
                s.close(0)
            except zmq.ZMQError:
                pass
        self.context.term()

    # -- the publish path --------------------------------------------------
    def publish(self, version: int, params: Any, step: Optional[int] = None) -> None:
        """Broadcast one versioned snapshot (and arm the fetch channel).

        ``params`` must already be host-side and learner-decoupled (the
        caller device_gets its own snapshot — this class never touches
        donated device buffers; see PodLearner.publish for the sanctioned
        sequence). 1-in-N sampled publishes (by version — deterministic,
        tracing.py) carry a trace context so every subscribing cache's
        fetch/apply leg lands on one cross-host timeline."""
        trace = None
        if tracing.enabled() and tracing.sampled(version):
            trace = tracing.encode_context(
                tracing.make_id("params", self.epoch, version),
                tracing.make_id("params", self.epoch, version, "origin"),
            )
        payload = pack_params(
            version, params, step=step, epoch=self.epoch, trace=trace
        )
        self._latest = payload
        self.version = int(version)
        self._g_version.set(self.version)
        self._c_publishes.inc()
        try:
            self._pub.send(payload, zmq.NOBLOCK)
        except zmq.Again:
            # every subscriber is beyond its HWM: they stay stale and the
            # fetch channel (or the next publish) catches them up
            pass

    def _beat_link(self, ident: bytes) -> None:
        link = self._links.get(ident)
        if link is None:
            if len(self._links) >= _MAX_HOST_LINKS:
                return  # cap: junk idents must not mint unbounded gauges
            link = self._links[ident] = LinkHealth(
                ident, self.tele_role,
                degraded_after_s=self.heartbeat_degraded_s,
                partitioned_after_s=self.heartbeat_partitioned_s,
                gauge_name=f"link_state_{metric_link_name(ident)}",
            )
        link.beat()

    def link_states(self) -> Dict[str, str]:
        """Freshly polled per-host link states (operator/bench surface).
        Snapshots the table first — the serve thread mints links for
        first-contact idents concurrently."""
        return {
            metric_link_name(i): l.poll()
            for i, l in list(self._links.items())
        }

    def _serve_fetches(self) -> None:
        import threading

        t = threading.current_thread()
        assert isinstance(t, StoppableThread)
        poller = zmq.Poller()
        poller.register(self._router, zmq.POLLIN)
        while not t.stopped():
            try:
                if not poller.poll(200):
                    # silence is information too: re-derive every host's
                    # link state so the gauges (and flight transitions)
                    # move even while no host can reach us
                    for link in self._links.values():
                        link.poll()
                    continue
                frames = self._router.recv_multipart()
            except (zmq.ContextTerminated, zmq.ZMQError):
                return
            ident = frames[0]
            self._beat_link(ident)
            if len(frames) > 1 and bytes(frames[1]) == b"hb":
                # heartbeat probe (pod/cache.py): ack with an empty frame
                # so the cache's fetch_link beats on the round-trip; never
                # ship a whole snapshot for a liveness check
                self._c_heartbeats.inc()
                try:
                    self._router.send_multipart([ident, b""])
                except zmq.ZMQError:
                    pass
                continue
            latest = self._latest
            self._c_fetches.inc()
            try:
                self._router.send_multipart([ident, latest or b""])
            except zmq.ZMQError:
                pass  # requester went away; it will retry with backoff
