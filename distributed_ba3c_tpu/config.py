"""Hyperparameter configuration for BA3C training.

Defaults follow SURVEY.md §2.9 (recalled Tensorpack/BA3C defaults, confidence
[M]/[L] — the reference mount was empty so they could not be re-read from
``src/train.py``; every one of them is overridable from the CLI, see
:mod:`distributed_ba3c_tpu.train.config` and the repo-root ``train.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass
class BA3CConfig:
    """All hyperparameters of the BA3C algorithm and its runtime.

    Reference equivalents: module-level constants + argparse defaults in
    ``src/train.py`` (SURVEY.md §2.9) and ``TrainConfig`` fields
    (``tensorpack/train/config.py``, SURVEY.md §2.5 #13).
    """

    # --- environment / observation ---------------------------------------
    image_size: Tuple[int, int] = (84, 84)   # IMAGE_SIZE
    frame_history: int = 4                   # FRAME_HISTORY (stacked as channels)
    frame_skip: int = 4                      # ALE frameskip
    channels: int = 1                        # grayscale channels per frame
    episode_length_cap: int = 40000          # LimitLengthPlayer cap [L]

    # --- algorithm --------------------------------------------------------
    gamma: float = 0.99                      # GAMMA
    local_time_max: int = 5                  # LOCAL_TIME_MAX (n-step truncation)
    reward_clip: float = 0.0                 # clip rewards to [-c, c] (0 = off);
                                             # standard A3C stabilizer for games
                                             # with multi-scale scores
    entropy_beta: float = 0.01               # entropy bonus coefficient
    value_loss_coef: float = 0.5             # weight on the L2 value loss
    value_huber_delta: float | None = None   # Huber value loss if set (robust)
    grad_clip_norm: float = 0.5              # global-norm clip [M]

    # --- optimizer --------------------------------------------------------
    learning_rate: float = 1e-3              # Adam LR (scheduled down during run)
    adam_epsilon: float = 1e-3               # reference tweaked Adam eps [L]
    batch_size: int = 128                    # learner batch per step (per host)

    # --- actor system -----------------------------------------------------
    simulator_procs: int = 50                # SIMULATOR_PROC per worker
    predict_batch_size: int = 16             # PREDICT_BATCH_SIZE
    predictor_threads: int = 2               # predictor worker threads

    # --- model ------------------------------------------------------------
    num_actions: int = 6                     # set from the env at build time
    fc_units: int = 512

    def __post_init__(self):
        assert self.reward_clip >= 0, (
            f"reward_clip must be >= 0, got {self.reward_clip}"
        )

    @property
    def state_shape(self) -> Tuple[int, int, int]:
        """(H, W, C) of the stacked observation fed to the network."""
        h, w = self.image_size
        return (h, w, self.frame_history * self.channels)

    def replace(self, **kw) -> "BA3CConfig":
        return dataclasses.replace(self, **kw)
