"""CLI assembly: flags → wired-up training/eval run.

Reference equivalent: ``src/train.py`` ``main``/``get_config`` (SURVEY.md §2.1
#1, §1 L7). The flag surface mirrors the reference's
(``--job_name/--task_index/--ps_hosts/--worker_hosts`` cluster flags, the
hyperparameter flags, ``--load``, ``--task``), and the trainer-selection slot
BASELINE.json pins is here: ``--trainer=tpu_sync_ba3c`` (default) selects the
mesh-sharded synchronous learner; ``--trainer=tpu_vtrace_ba3c`` the V-trace
off-policy variant.

PS-compat note: with the parameter-server plane gone (gradients are a psum
over ICI, SURVEY.md §2.12), ``--job_name ps`` is accepted and exits
immediately with an explanatory message — cluster launch scripts that spawn
ps tasks keep working, the ps tasks just have nothing to host.
"""

from __future__ import annotations

import argparse
import functools
import os
import queue
from typing import Optional

from distributed_ba3c_tpu.config import BA3CConfig


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="TPU-native Distributed-BA3C",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    # -- reference cluster-spec surface (SURVEY.md §1 L7) ------------------
    p.add_argument("--job_name", choices=["ps", "worker"], default="worker")
    p.add_argument("--task_index", type=int, default=0)
    p.add_argument("--ps_hosts", default="", help="accepted for CLI compat; unused (no parameter servers on TPU)")
    p.add_argument("--worker_hosts", default="", help="comma-separated worker host list (multi-host DCN bootstrap)")
    # -- trainer selection slot (BASELINE.json gate) -----------------------
    p.add_argument(
        "--trainer",
        default="tpu_sync_ba3c",
        choices=["tpu_sync_ba3c", "tpu_vtrace_ba3c", "tpu_fused_ba3c"],
        help="learner backend: sync psum A2C, V-trace off-policy, or fully on-device fused rollout+update",
    )
    # -- run mode ----------------------------------------------------------
    p.add_argument("--task", default="train", choices=["train", "eval", "play"])
    p.add_argument("--env", default="fake", help="fake | jax:<name> (on-device env, e.g. jax:pong) | cpp:<name> (native batched core) | gym:<name> (gymnasium adapter) | zmq:<game> (REMOTE env-server fleets play <game> and connect to --pipe_c2s/--pipe_s2c; no local simulators)")
    p.add_argument(
        "--wire",
        default="auto",
        choices=["auto", "block-shm", "block", "per-env"],
        help="actor-plane wire protocol for batched env servers (cpp:*): "
        "block-shm = tiny control messages + obs through a /dev/shm ring "
        "(same-host, fastest); block = one zero-copy multipart message per "
        "server per step (the tcp:// remote-fleet wire); per-env = B "
        "separate msgpack messages per step (reference-compatible compat "
        "foil); auto = block-shm when /dev/shm is available, else block "
        "(docs/actor_plane.md). The master autodetects per message, so "
        "mixed fleets work; per-process simulators (fake/gym:/jax:) always "
        "speak per-env",
    )
    p.add_argument(
        "--wire_crc",
        action="store_true",
        help="CRC32 integrity framing on every wire codec (block, "
        "block-shm control, per-env, pod params/experience): a corrupted "
        "or truncated frame becomes a typed corrupt_frame reject at the "
        "receiver instead of a silently wrong array. Exported as "
        "BA3C_WIRE_CRC=1 so spawned env servers / pod hosts agree "
        "(docs/netchaos.md); worth ~one memory pass per message — "
        "recommended for any real-DCN fleet, off by default on loopback",
    )
    p.add_argument("--load", default=None, help="checkpoint dir to resume from")
    p.add_argument("--logdir", default="train_log/ba3c")
    # -- hyperparams (reference argparse defaults, SURVEY.md §2.9) ---------
    p.add_argument("--learning_rate", type=float, default=None)
    p.add_argument("--entropy_beta", type=float, default=None)
    p.add_argument("--gamma", type=float, default=None)
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--local_time_max", type=int, default=None)
    p.add_argument("--simulator_procs", type=int, default=None)
    p.add_argument("--predict_batch_size", type=int, default=None)
    p.add_argument("--predictor_threads", type=int, default=None)
    p.add_argument("--fc_units", type=int, default=None)
    p.add_argument("--image_size", type=int, default=None, help="square observation size")
    p.add_argument("--frame_history", type=int, default=None)
    p.add_argument("--grad_clip_norm", type=float, default=None)
    p.add_argument("--adam_epsilon", type=float, default=None)
    p.add_argument("--reward_clip", type=float, default=None, help="clip learning rewards to [-c, c] (0=off); episode scores stay raw")
    # -- loop shape --------------------------------------------------------
    p.add_argument("--steps_per_epoch", type=int, default=1000)
    p.add_argument("--max_epoch", type=int, default=100)
    # None sentinel so external-fleet mode can tell an EXPLICIT --nr_eval
    # (worth a warning when dropped) from the default
    p.add_argument("--nr_eval", type=int, default=None)
    p.add_argument("--eval_every", type=int, default=1, help="epochs between Evaluator runs")
    p.add_argument("--eval_max_steps", type=int, default=10000, help="greedy-eval step horizon (fused trainer; must cover a full episode)")
    p.add_argument("--num_actions", type=int, default=4)
    p.add_argument("--mesh_data", type=int, default=None, help="data-axis size (defaults to all devices)")
    p.add_argument("--publish_every", type=int, default=1)
    p.add_argument("--rollout_len", type=int, default=20, help="fused-trainer rollout length per update")
    p.add_argument("--grad_chunk_samples", type=int, default=4096, help="fused-trainer learner chunk size (HBM activation cap)")
    p.add_argument("--actor_timeout", type=float, default=120.0, help="seconds of actor silence before its state is dropped (0=off)")
    p.add_argument("--entropy_beta_final", type=float, default=None, help="anneal entropy beta to this over max_epoch (ScheduledHyperParamSetter)")
    p.add_argument("--learning_rate_final", type=float, default=None, help="anneal LR to this over max_epoch (ScheduledHyperParamSetter)")
    p.add_argument("--anneal", default="linear", choices=["linear", "exp"], help="shape of the *_final anneals: linear or geometric (exp)")
    p.add_argument("--anneal_lr", default=None, choices=["linear", "exp"], help="override --anneal for learning_rate only (β and lr want different shapes: β drops early, lr holds through the mid-game)")
    p.add_argument("--anneal_beta", default=None, choices=["linear", "exp"], help="override --anneal for entropy_beta only")
    # -- multi-fleet macro-batching (docs/actor_plane.md) ------------------
    p.add_argument(
        "--fleets", type=int, default=1,
        help="N independent actor fleets feeding this learner (ZMQ-plane "
        "trainers, train task): each fleet gets its own pipe pair (derived "
        "from --pipe_c2s/--pipe_s2c or the ipc defaults), master, "
        "predictor, supervisor and telemetry identity (master.f<k>...); "
        "per-fleet queues merge through a fair round-robin collator and "
        "the learner runs the gradient-accumulation MACRO step — N "
        "full-recipe sub-batches, one update, fleet axis sharded over "
        "chips so every chip steps at its full-occupancy batch "
        "(docs/actor_plane.md). --simulator_procs is the TOTAL across "
        "fleets and must divide evenly",
    )
    p.add_argument(
        "--fleet_accum", type=int, default=1,
        help="fused --overlap only: rollout windows accumulated per "
        "update via the fused.macro_learner program — the fused half of "
        "multi-fleet macro-batching (per-update effective batch grows "
        "K-fold at unchanged per-window occupancy; V-trace corrects the "
        "1..K-update behavior lag)",
    )
    # -- elastic fleet orchestration (docs/orchestration.md) ---------------
    p.add_argument(
        "--fleet_min", type=int, default=0,
        help="autoscaler LOWER bound, in env-server processes (0 = the "
        "launch size). Local fleets (cpp:/fake/gym:/jax:) only — external "
        "zmq: fleets are supervised on their own hosts "
        "(scripts/launch_env_fleet.py)",
    )
    p.add_argument(
        "--fleet_max", type=int, default=0,
        help="autoscaler UPPER bound, in env-server processes (0 = the "
        "launch size). fleet_max > fleet_min enables the telemetry-driven "
        "autoscaler: the fleet grows when the train queue starves and "
        "shrinks under blocked-put backpressure (docs/orchestration.md)",
    )
    p.add_argument(
        "--autoscale_interval", type=float, default=2.0,
        help="seconds between autoscaler policy ticks",
    )
    # -- SLO-aware serving plane (docs/serving.md) -------------------------
    p.add_argument(
        "--serve_slo_ms", type=float, default=0.0,
        help="predictor serving deadline budget in ms (0 = off). Every "
        "queued predict task gets deadline = admit + slo; tasks the "
        "scheduler proves can't make it are SHED with a typed reject "
        "(masters fall back to a uniform-random action) and a full "
        "admission queue rejects fast instead of queueing unboundedly",
    )
    p.add_argument(
        "--canary_load", default=None,
        help="checkpoint dir served as the 'canary' policy on "
        "--canary_fraction of live predict traffic (multi-policy serving; "
        "per-policy rows on the telemetry endpoint)",
    )
    p.add_argument(
        "--canary_fraction", type=float, default=0.0,
        help="fraction of predict traffic routed to --canary_load "
        "(deterministic group-granular deficit split, no RNG, batch "
        "occupancy preserved)",
    )
    p.add_argument(
        "--shadow_load", default=None,
        help="checkpoint dir served as the 'shadow' policy: mirrors EVERY "
        "served batch, results dropped before any caller — pure "
        "observability (tele/predictor/shadow_* series)",
    )
    p.add_argument(
        "--serve_replicas", type=int, default=1,
        help="serve each fleet's predict traffic from R replicated "
        "serving planes behind the SLO router (predict/router.py): "
        "least-loaded dispatch with deadline-aware overflow, per-replica "
        "health from their telemetry series, typed re-shed of a dead "
        "replica's traffic. 1 = the single PR-9 plane, unchanged",
    )
    p.add_argument(
        "--serve_replicas_max", type=int, default=0,
        help="enable the serving autoscaler up to this replica bound "
        "(requires --serve_slo_ms; grows from the --serve_replicas base, "
        "routing the plane even at a base of 1): replicas are "
        "added on served-p99/shed-rate SLO pressure and retired on "
        "slack, every decision flight-recorded (orchestrate/serving.py). "
        "0 = fixed replica count",
    )
    p.add_argument(
        "--canary_autopromote", action="store_true",
        help="hand the --canary_load candidate to the PromotionController "
        "(requires --serve_replicas > 1, --serve_slo_ms and --fleets 1): "
        "auto-ROLLBACK on canary SLO breach is armed from live "
        "latency/shed evidence; reward-based auto-PROMOTION additionally "
        "needs a reward feed (PromotionController.observe_reward — see "
        "docs/serving.md). Off = the canary split is static, as before",
    )
    p.add_argument("--profiler_port", type=int, default=0, help="start jax.profiler server on this port (0=off)")
    p.add_argument("--telemetry_port", type=int, default=0, help="serve the telemetry scrape endpoint on this port (0=off): /metrics Prometheus text, /json raw snapshots, /flight the live flight-recorder ring, /trace the span buffer (docs/observability.md)")
    p.add_argument("--trace_sample", type=int, default=0, help="trace 1 in N block steps through the distributed trace plane (0=off): sampled causal spans env-step->learner-step with per-hop hop_<name>_s histograms, scraped at /trace and rendered by scripts/trace_dump.py (docs/observability.md)")
    p.add_argument("--pipe_c2s", default=None, help="master experience-plane bind address, e.g. tcp://0.0.0.0:5555 (default: per-pid ipc://)")
    p.add_argument("--pipe_s2c", default=None, help="master action-plane bind address, e.g. tcp://0.0.0.0:5556 (default: per-pid ipc://)")
    p.add_argument("--max_to_keep", type=int, default=3, help="checkpoints retained (besides best); raise to keep every eval-epoch checkpoint for post-hoc crossing verification")
    p.add_argument("--steps_per_dispatch", type=int, default=1, help="fused trainer: wrap K update steps in one lax.scan program (one host dispatch per K updates; must divide --steps_per_epoch). Removes per-step dispatch overhead without relying on host pipelining. With --overlap, K actor/learner dispatch PAIRS per facade call instead")
    p.add_argument("--overlap", action="store_true", help="fused trainer: split the single fused program into two overlapped compiled programs — rollout k+1 runs concurrently with learner k (policy lag 1, V-trace-corrected; docs/overlap.md)")
    p.add_argument("--rollout_dtype", default="float32", choices=["float32", "bfloat16", "int8"], help="rollout/serving forward precision, END TO END (the learner always keeps f32): with --overlap it is the actor program's params-snapshot dtype; on the ZMQ trainers it is the BatchedPredictor's param storage (every policy publish casts on device). bfloat16 halves the forward's param-read bandwidth; int8 quarters it with per-channel symmetric weight quantization (requires a calibration source: --quant_spec or --quant_calibrate; heads stay f32; docs/ingest.md). Audit-pinned as predict.server_bf16 / fused.actor_bf16 / predict.server_int8 / fused.actor_int8")
    p.add_argument("--quant_spec", default=None, help="int8 rung: path to a frozen QuantSpec JSON (quantize/spec.py) carrying the per-layer activation scales — the offline/pre-frozen calibration source. Exactly one of --quant_spec / --quant_calibrate with --rollout_dtype int8")
    p.add_argument("--quant_calibrate", type=int, default=0, help="int8 rung: calibrate activation scales live from the first N served batches (ZMQ trainers: the PR-9 shadow tap observes real traffic, serving stays f32 until the spec freezes, then the plane switches to int8 in place; fused --overlap trainer: N f32 rollout windows through the actor's own scan body before the int8 program is built). 0 = off")
    p.add_argument("--ingest_staging", default="on", choices=["on", "off"], help="ZMQ trainers: zero-copy pinned-staging ingest (data/staging.py) — collate writes obs bytes straight into preallocated double-buffered staging arrays (ONE host copy per block, ingest_copies_total proves it) and the next batch's H2D dispatches behind the running step. off = the legacy materialize->collate->device_put chain (the plane_bench --ingest foil)")
    p.add_argument("--rank_stall_timeout", type=float, default=0, help="multi-host: seconds without proven progress (beats land after the dispatch-window metrics fetch, after eval, and after the collective save) before a rank declares a peer dead and exits 75 (0 = default 600s when multi-host; -1 disables the watchdog; the limit self-raises to 2x the slowest healthy window). Relaunch with --load to resume")
    p.add_argument("--seed", type=int, default=0, help="fused trainer: PRNG seed for params/envs/action sampling (whole-trajectory determinism per seed; multi-seed runs disclose seed selection in RESULTS.md)")
    p.add_argument(
        "--dump_topology", action="store_true",
        help="print the TopologySpec JSON this flag set describes and "
        "exit (migration aid toward `python -m "
        "distributed_ba3c_tpu.orchestrate --topology spec.json`; "
        "docs/topology.md)",
    )
    p.add_argument("--tpu_lock", default="wait", choices=["wait", "fail", "off"], help="host-local TPU-claim mutex (utils/devicelock.py): wait = queue behind the current holder, fail = exit with the holder's pid/run, off = no guard. CPU-platform runs never take the lock")
    return p


def env_num_actions(args) -> int:
    """Derive the action-space size from the selected env (every trainer must
    build the policy head against the ENV's space, not the flag default)."""
    if args.env.startswith(("jax:", "cpp:", "zmq:")) and args.env != "zmq:":
        # jaxenv and the C++ core keep identical action maps (tested
        # parity); zmq:<game> names the game the EXTERNAL fleets play, so
        # the policy head still gets the right action space. An unknown
        # zmq: game fails LOUDLY — a silent --num_actions fallback would
        # train a wrong-sized policy head against the fleet.
        from distributed_ba3c_tpu.envs import jaxenv

        try:
            return jaxenv.get_env(args.env.split(":", 1)[1]).num_actions
        except ValueError:
            if not args.env.startswith("zmq:"):
                raise
            raise SystemExit(
                f"--env {args.env}: unknown game — for fleets playing a "
                "game this build doesn't know, use bare '--env zmq:' plus "
                "an explicit --num_actions"
            )
    return args.num_actions


def build_config(args) -> BA3CConfig:
    cfg = BA3CConfig()
    over = {}
    for f in (
        "learning_rate entropy_beta gamma batch_size local_time_max "
        "simulator_procs predict_batch_size predictor_threads fc_units "
        "frame_history grad_clip_norm adam_epsilon reward_clip"
    ).split():
        v = getattr(args, f)
        if v is not None:
            over[f] = v
    if args.image_size is not None:
        over["image_size"] = (args.image_size, args.image_size)
    over["num_actions"] = env_num_actions(args)
    return cfg.replace(**over)


def _build_player_factory(args, cfg: BA3CConfig):
    if args.env == "fake" or args.env.startswith("fake:"):
        from distributed_ba3c_tpu.envs.fake import build_fake_player

        return functools.partial(
            build_fake_player,
            image_size=cfg.image_size,
            frame_history=cfg.frame_history,
            num_actions=cfg.num_actions,
        )
    if args.env.startswith("jax:"):
        try:
            from distributed_ba3c_tpu.envs.jaxenv.host_adapter import (
                build_jax_player,
            )
        except ImportError as e:
            raise SystemExit(
                f"--env {args.env}: on-device env module unavailable ({e})"
            )
        return functools.partial(
            build_jax_player,
            name=args.env.split(":", 1)[1],
            frame_history=cfg.frame_history,
        )
    if args.env.startswith("cpp:"):
        from distributed_ba3c_tpu.envs import native

        if not native.available():
            raise SystemExit(
                f"--env {args.env}: native core not built — run `make -C cpp`"
            )
        return functools.partial(
            native.build_cpp_player,
            name=args.env.split(":", 1)[1],
            frame_history=cfg.frame_history,
        )
    if args.env.startswith("gym:"):
        from distributed_ba3c_tpu.envs.gym_adapter import build_gym_player

        return functools.partial(
            build_gym_player,
            name=args.env.split(":", 1)[1],
            frame_history=cfg.frame_history,
            image_size=cfg.image_size,
        )
    if args.env.startswith("zmq:"):
        # external env servers already speak the simulator wire protocol —
        # there is no in-process player to build (train mode handles zmq:
        # before calling this; only --task eval/play land here)
        raise SystemExit(
            "--env zmq: has no in-process player (external fleets own the "
            "envs) — --task eval/play need a local env, e.g. --env cpp:pong"
        )
    raise ValueError(f"unknown --env {args.env!r}")


def main(argv: Optional[list] = None) -> int:
    parser = make_parser()
    args = parser.parse_args(argv)
    nr_eval_explicit = args.nr_eval is not None
    if args.nr_eval is None:
        args.nr_eval = 8

    if args.job_name == "ps":
        print(
            "ps job is obsolete on TPU: parameters are replicated in HBM and "
            "gradients ride a psum over ICI (no parameter servers). Exiting."
        )
        return 0

    # Spec-level validation BEFORE the lock: in wait mode a misconfigured
    # run would otherwise queue for hours behind the holder only to fail on
    # a check that needs no device (jax-touching validation stays below —
    # env-module imports may init the backend, which must not precede the
    # lock). The rules themselves live in TopologySpec (orchestrate/
    # topology.py) — the flag surface and a --topology document reject the
    # SAME impossible deployments, as clean exit-2 usage errors.
    from distributed_ba3c_tpu.orchestrate.topology import (
        TopologyError,
        TopologySpec,
    )

    try:
        topo = TopologySpec.from_flags(args)
    except TopologyError as e:
        parser.error(str(e))
    if args.dump_topology:
        print(topo.to_json())
        return 0

    # Take the host-local TPU claim BEFORE the first jax backend touch: two
    # concurrent claimants don't error, they wedge the exclusive pool
    # (OPERATIONS.md; utils/devicelock.py). No-op on the CPU platform.
    from distributed_ba3c_tpu.utils.devicelock import guard_tpu

    _tpu_lock = guard_tpu(args.logdir, mode=args.tpu_lock)  # noqa: F841 — held for process lifetime

    import jax

    # Honor JAX_PLATFORMS even when a sitecustomize force-registers a TPU
    # plugin and overrides the env var (this container's axon setup does).
    _plat = os.environ.get("JAX_PLATFORMS", "")
    if _plat and "," not in _plat:
        jax.config.update("jax_platforms", _plat)

    # Multi-host bootstrap BEFORE any device is touched (reference: the
    # ClusterSpec/Server must exist before graph placement, SURVEY.md §3.1).
    from distributed_ba3c_tpu.parallel.distributed import (
        initialize_from_flags,
        is_chief,
        local_batch_slice,
        make_global_mesh,
    )

    _multi_host = len([h for h in args.worker_hosts.split(",") if h]) > 1
    if (_plat == "cpu" or not _plat) and _multi_host:
        # CPU cross-process collectives need gloo. Only when actually
        # multi-process: recent jaxlib builds gloo against the distributed
        # runtime client, and single-host (client=None) fails backend init
        # (found by the BA3C_SANITIZE=1 e2e job — the backend error
        # predates any actor traffic).
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
    distributed = initialize_from_flags(args.worker_hosts, args.task_index)
    # base (chief) logdir: shared artifacts — checkpoints (orbax collective
    # saves need ONE path on every process) and hyper.txt (all hosts must
    # read the SAME live-hyperparam file or their updates diverge)
    base_logdir = args.logdir
    if distributed and not is_chief():
        # non-chief hosts keep their own log dir (chief owns stat.json)
        args.logdir = f"{args.logdir}-worker{args.task_index}"
    # shared checkpoint dir for ALL trainers incl. fused (collective saves)
    args.shared_ckpt_dir = os.path.join(base_logdir, "checkpoints")
    # ONE hyper.txt for every host (fused loop live overrides; the ZMQ
    # trainers' HumanHyperParamSetter gets the same dir below)
    args.shared_hyper_dir = base_logdir

    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer
    from distributed_ba3c_tpu.parallel.mesh import make_mesh
    from distributed_ba3c_tpu.parallel.train_step import (
        create_train_state,
        make_train_step,
    )
    from distributed_ba3c_tpu.utils import logger

    cfg = build_config(args)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    optimizer = make_optimizer(
        cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm
    )

    if args.profiler_port:
        from distributed_ba3c_tpu.utils.profiling import start_server

        start_server(args.profiler_port)

    # telemetry plane (docs/observability.md): postmortem dumps land in the
    # logdir, and a launcher's SIGTERM stall-kill leaves the flight ring on
    # disk instead of a truncated log
    from distributed_ba3c_tpu import telemetry

    telemetry.configure(args.logdir)
    if args.logdir:
        # spawned children (env servers, simulators) read this at import —
        # without it their postmortem dumps land in /tmp, not the logdir
        os.environ["BA3C_FLIGHT_DIR"] = args.logdir
    if args.trace_sample > 0:
        # arm the trace plane here AND in the env var: spawned env-server
        # children read BA3C_TRACE at import, exactly the BA3C_TELEMETRY
        # inheritance idiom (telemetry/tracing.py)
        telemetry.tracing.set_sampling(args.trace_sample)
        os.environ["BA3C_TRACE"] = str(args.trace_sample)
    if args.wire_crc:
        # arm CRC framing here AND in the env var: spawned env servers and
        # pod hosts read BA3C_WIRE_CRC at import — a fleet where only one
        # side frames would reject nothing and verify nothing
        from distributed_ba3c_tpu.utils.serialize import set_wire_crc

        set_wire_crc(True)
        os.environ["BA3C_WIRE_CRC"] = "1"
    if args.task == "train":
        telemetry.install_signal_dump()

    if args.task == "eval":
        state = create_train_state(jax.random.PRNGKey(0), model, cfg, optimizer)
        return _run_eval(args, cfg, model, state)
    if args.task == "play":
        state = create_train_state(jax.random.PRNGKey(0), model, cfg, optimizer)
        return _run_play(args, cfg, model, state)

    if args.trainer == "tpu_fused_ba3c":
        return _run_fused(args, cfg, model, optimizer)

    state = create_train_state(jax.random.PRNGKey(0), model, cfg, optimizer)

    if distributed:
        mesh = make_global_mesh(num_model=1)
    else:
        mesh = make_mesh(num_data=args.mesh_data, num_model=1)

    from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
    from distributed_ba3c_tpu.actors.simulator import (
        SimulatorProcess,
        default_pipes,
    )
    from distributed_ba3c_tpu.actors.vtrace_master import VTraceSimulatorMaster
    from distributed_ba3c_tpu.data.dataflow import (
        FleetMergeFeed,
        RolloutFeed,
        TrainFeed,
        collate_rollout,
        collate_train,
    )
    from distributed_ba3c_tpu.parallel.train_step import make_macro_train_step
    from distributed_ba3c_tpu.parallel.vtrace_step import (
        make_vtrace_macro_step,
        make_vtrace_train_step,
    )
    from distributed_ba3c_tpu.predict.server import BatchedPredictor
    from distributed_ba3c_tpu.train.callbacks import (
        Evaluator,
        HumanHyperParamSetter,
        MaxSaver,
        ModelSaver,
        PeriodicTrigger,
        ScheduledHyperParamSetter,
        StartProcOrThread,
        StatPrinter,
    )
    from distributed_ba3c_tpu.train.trainer import Trainer, TrainLoopConfig

    # --env zmq: = REMOTE actor fleets (BASELINE config #3's topology): no
    # local simulators — external env servers (CppEnvServerProcess or any
    # wire-compatible speaker) connect to this learner's tcp:// pipes.
    external_fleet = args.env.startswith("zmq:")
    if external_fleet:
        # endpoint presence was validated pre-lock at the top of main()
        build_player = None
    else:
        build_player = _build_player_factory(args, cfg)
        # train-mode episode guards (reference get_player(train=True) stacked
        # PreventStuck + LimitLength around the simulators; eval unguarded)
        from distributed_ba3c_tpu.envs.wrappers import guarded_player

        sim_build_player = functools.partial(
            guarded_player,
            base_build=build_player,
            episode_length_cap=cfg.episode_length_cap,
            stuck_limit=30,
            stuck_action=1,
        )
    # explicit pipe addresses (tcp:// for cross-host fleets) override the
    # per-pid ipc:// defaults; the master BINDS, env servers connect.
    # --fleets > 1 derives per-fleet pairs from this base (actors/fleet.py
    # fleet_pipes: fleet 0 keeps it verbatim)
    if args.pipe_c2s and args.pipe_s2c:
        c2s, s2c = args.pipe_c2s, args.pipe_s2c
    elif args.pipe_c2s or args.pipe_s2c:
        raise SystemExit("--pipe_c2s and --pipe_s2c must be given together")
    else:
        c2s, s2c = default_pipes()
    score_q: queue.Queue = queue.Queue(maxsize=4096)
    n_data = mesh.shape["data"]
    n_hosts = jax.process_count()
    n_fleets = args.fleets
    multi_fleet = n_fleets > 1
    if multi_fleet and distributed:
        raise SystemExit(
            "--fleets > 1 runs N fleets behind ONE single-host learner — "
            "for multi-host deployments run one learner (with its fleets) "
            "per host, or use --worker_hosts with --fleets 1"
        )
    if multi_fleet and n_fleets % n_data:
        raise SystemExit(
            f"--fleets {n_fleets} must be divisible by the mesh data axis "
            f"({n_data}): the macro step assigns whole fleets to chips — "
            "set --mesh_data to a divisor of --fleets"
        )
    if multi_fleet and cfg.simulator_procs % n_fleets:
        raise SystemExit(
            f"--simulator_procs {cfg.simulator_procs} must split evenly "
            f"across --fleets {n_fleets}"
        )

    # per-fleet predictor factory: every fleet serves the same policy
    # table (canary/shadow included), each behind its own scheduler
    from distributed_ba3c_tpu.actors.fleet import (
        FanoutPredictors,
        build_fleet_planes,
    )

    _policy_extras = []
    if args.canary_load or args.shadow_load:
        from distributed_ba3c_tpu.train.checkpoint import CheckpointManager

        def _policy_params(ckpt_dir):
            return CheckpointManager(ckpt_dir).restore(
                jax.device_get(state)
            ).params

        if args.canary_load:
            _policy_extras.append(
                ("canary", _policy_params(args.canary_load),
                 args.canary_fraction)
            )
        if args.shadow_load:
            _policy_extras.append(
                ("shadow", _policy_params(args.shadow_load), None)
            )

    # int8 rung: a frozen spec file is loaded ONCE and shared by every
    # replica (one calibration per plane); --quant_calibrate instead hands
    # each replica a live CalibrationTap over its own served traffic
    _quant_spec = None
    if args.quant_spec:
        from distributed_ba3c_tpu.quantize import QuantSpec

        _quant_spec = QuantSpec.load(args.quant_spec)

    def _build_replica(tele_role_r: str):
        # THE sanctioned serving factory: handed to the fleet assembly
        # (and to the ReplicaSet under --serve_replicas), lifecycle owned
        # by cli's startables / the router's owned ReplicaSet
        return BatchedPredictor(  # ba3clint: disable=A14 — the sanctioned fleet-assembly factory
            model,
            state.params,
            batch_size=cfg.predict_batch_size,
            num_threads=cfg.predictor_threads,
            slo_ms=args.serve_slo_ms,
            tele_role=tele_role_r,
            # the quantized rollout forward (--rollout_dtype bfloat16/int8):
            # serving-side param storage only — the learner publishes and
            # keeps full precision (audit entries predict.server_bf16 /
            # predict.server_int8)
            rollout_dtype=args.rollout_dtype,
            quant_spec=_quant_spec,
            quant_calibrate=args.quant_calibrate,
        )

    # serving-plane control loops grown by the routed path (the per-fleet
    # ReplicaAutoscaler, the fleet-0 PromotionController) and the routed
    # ReplicaSets themselves — all reconciler resources, named here
    serving_extras = []
    replica_sets = []

    def make_predictor(k: int, tele_role: str):
        R = args.serve_replicas
        # --serve_replicas_max above the base count forces the ROUTED
        # plane even at R == 1: the autoscaler needs a router/ReplicaSet
        # to grow into, so the modifier is honored, never silently dropped
        routed = R > 1 or bool(
            args.serve_replicas_max and args.serve_replicas_max > R
        )
        if not routed:
            pred = _build_replica(tele_role)
            # multi-policy serving (docs/serving.md): canary/shadow
            # checkpoints are pinned policies behind the one scheduler —
            # the learner's update_params publishes only touch 'default'
            for name, params_k, fraction in _policy_extras:
                pred.add_policy(name, params_k)
                if name == "canary":
                    pred.set_canary("canary", fraction)
                else:
                    pred.set_shadow("shadow")
            # precompile every serving bucket now — a first-time bucket
            # compile mid-training stalls the whole actor plane
            pred.warmup(cfg.state_shape)
            return pred
        # the ROUTED plane (ISSUE 15, docs/serving.md): R replicas behind
        # the SLO router; the master holds "a predictor" either way
        from distributed_ba3c_tpu.orchestrate.serving import (
            PromotionController,
            ReplicaAutoscaler,
            ReplicaSet,
            ServingScalerPolicy,
        )
        from distributed_ba3c_tpu.predict.router import (
            ServingRouter,
            replica_role,
        )

        router = ServingRouter(
            tele_role=tele_role.replace("predictor", "router")
        )
        rs = ReplicaSet(
            router,
            factory=lambda idx: _build_replica(replica_role(tele_role, idx)),
            min_replicas=R,
            max_replicas=max(R, args.serve_replicas_max or R),
            warm=lambda p: p.warmup(cfg.state_shape),
        )
        # the topology reconciler owns the dead-replica sweep (its
        # ServingResource ticks rs.reconcile) — no per-set corpse thread
        rs.start(R, reconcile_thread=False)
        replica_sets.append((k, rs))
        # ONE startable handle for the whole routed plane: router.stop()
        # closes its owned ReplicaSet (replicas included)
        router.replica_set = rs
        # policies live at ROUTER level so autoscale-grown replicas are
        # seeded with the same table before they take traffic
        for name, params_k, fraction in _policy_extras:
            if name == "canary" and args.canary_autopromote:
                continue  # the PromotionController owns the canary below
            router.add_policy(name, params_k)
            if name == "canary":
                router.set_canary("canary", fraction)
            else:
                router.set_shadow("shadow")
        if args.serve_replicas_max and args.serve_replicas_max > R:
            serving_extras.append((f"serving-autoscaler-f{k}", ReplicaAutoscaler(
                rs,
                ServingScalerPolicy(slo_ms=args.serve_slo_ms),
                interval_s=args.autoscale_interval,
            )))
        if args.canary_autopromote and k == 0:
            ctrl = PromotionController(
                router,
                fraction=args.canary_fraction,
                slo_ms=args.serve_slo_ms,
            )
            canary_params = next(
                p for n, p, _ in _policy_extras if n == "canary"
            )
            ctrl.start_canary(canary_params)
            serving_extras.append(("canary-promotion", ctrl))
        return router

    if args.trainer == "tpu_vtrace_ba3c":
        # segments per fleet sub-batch: ~batch_size transitions. Single
        # fleet keeps the data-axis rounding (segment axis shards over
        # chips); multi-fleet needs none — the FLEET axis shards, and each
        # chip runs whole full-recipe sub-batches (macro-batching)
        if multi_fleet:
            step = make_vtrace_macro_step(
                model, optimizer, cfg, mesh, n_fleets=n_fleets
            )
            n_seg = max(1, cfg.batch_size // cfg.local_time_max)
        else:
            step = make_vtrace_train_step(model, optimizer, cfg, mesh)
            n_seg = max(1, cfg.batch_size // cfg.local_time_max)
            n_seg = max(n_data, (n_seg // n_data) * n_data)
            assert n_seg % n_hosts == 0, (n_seg, n_hosts)
        per_fleet_items = n_seg // n_hosts
        samples_per_step = n_fleets * n_seg * cfg.local_time_max

        def make_master(k, c2s_k, s2c_k, pred, tele_role):
            m = VTraceSimulatorMaster(
                c2s_k,
                s2c_k,
                pred,
                unroll_len=cfg.local_time_max,
                score_queue=score_q,
                actor_timeout=args.actor_timeout or None,
                reward_clip=cfg.reward_clip,
                tele_role=tele_role,
            )
            # ring-safety input: the feed's per-fleet collate holder pins
            # ring views too
            m.feed_batch = per_fleet_items
            return m

    else:
        if multi_fleet:
            step = make_macro_train_step(
                model, optimizer, cfg, mesh, n_fleets=n_fleets
            )
        else:
            step = make_train_step(model, optimizer, cfg, mesh)
            if distributed:
                local_batch_slice(cfg.batch_size)  # asserts host divisibility
        per_fleet_items = cfg.batch_size // n_hosts
        samples_per_step = n_fleets * cfg.batch_size

        def make_master(k, c2s_k, s2c_k, pred, tele_role):
            m = BA3CSimulatorMaster(
                c2s_k,
                s2c_k,
                pred,
                gamma=cfg.gamma,
                local_time_max=cfg.local_time_max,
                score_queue=score_q,
                actor_timeout=args.actor_timeout or None,
                reward_clip=cfg.reward_clip,
                tele_role=tele_role,
            )
            # ring-safety input: the feed's per-fleet collate holder pins
            # ring views too
            m.feed_batch = per_fleet_items
            return m

    # Local fleets are owned by a FleetSupervisor (docs/orchestration.md):
    # crashed/wedged servers respawn with backoff behind a restart-budget
    # circuit breaker, stale shm rings are reclaimed at spawn, and
    # --fleet_min/--fleet_max attach the telemetry-driven autoscaler
    # (PER-FLEET bounds when --fleets > 1 — each fleet gets its own
    # supervisor + policy loop over its own master's signals).
    from distributed_ba3c_tpu.orchestrate import (
        Autoscaler,
        FleetSpec,
        FleetSupervisor,
        master_signals,
    )

    def _fleet_bounds(n_servers: int) -> tuple:
        lo = args.fleet_min or n_servers
        hi = args.fleet_max or n_servers
        if not lo <= n_servers <= hi:
            raise SystemExit(
                f"launch fleet size {n_servers} servers is outside "
                f"[--fleet_min {lo}, --fleet_max {hi}] — size the launch "
                "fleet (--simulator_procs, split per fleet) inside the "
                "bounds"
            )
        return lo, hi

    def _maybe_autoscaler(supervisor, m):
        if supervisor.spec.fleet_max > supervisor.spec.fleet_min:
            # elastic bounds requested: the policy loop watches THIS
            # fleet's master backpressure signals (never its own heartbeats)
            return Autoscaler(
                supervisor,
                master_signals(m),
                interval_s=args.autoscale_interval,
            )
        return None

    make_supervision = None
    if external_fleet:
        # remote fleets own the envs; nothing to start (or supervise)
        # locally — scripts/launch_env_fleet.py supervises on its host
        pass
    elif args.env.startswith("cpp:"):
        # batched native servers: each process hosts up to 16 envs in lockstep
        from distributed_ba3c_tpu.envs import native

        game = args.env.split(":", 1)[1]
        wire = args.wire
        if wire == "auto":
            from distributed_ba3c_tpu.utils import shm

            wire = "block-shm" if shm.available() else "block"
        total = cfg.simulator_procs // n_fleets  # envs per fleet
        per = min(16, total)
        if wire != "per-env" and per > cfg.predict_batch_size:
            # fail at startup, not as an exception inside the master's
            # receive loop mid-run: a block must fit the serving bucket
            raise SystemExit(
                f"--predict_batch_size {cfg.predict_batch_size} is smaller "
                f"than the env-server block size {per}: the block wire "
                "serves a whole block in one predictor call — raise "
                f"--predict_batch_size to >= {per} or use --wire per-env"
            )

        def ring_cap(m, b: int):
            # size each server's shm ring for THIS run's actual buffering
            # (queue + feed holder + flush horizon) so the master's check
            # never refuses a config the defaults could have sized for;
            # 25% headroom. Every input is read off the fleet's master and
            # fed to the SAME utils/shm.py formula the master's attach-time
            # check uses — sizing and refusal cannot drift
            if wire != "block-shm":
                return None
            from distributed_ba3c_tpu.utils.shm import min_safe_cap

            need = min_safe_cap(
                b,
                int(getattr(m.queue, "maxsize", 0)),
                int(getattr(m, "feed_batch", 0)),
                int(getattr(m, "ring_steps_per_item", 1)),
                int(
                    getattr(m, "local_time_max", 0)
                    or getattr(m, "unroll_len", 0)
                ),
                cfg.frame_history,
            )
            return max(
                native.CppEnvServerProcess.SHM_RING_MIN_CAP,
                native.CppEnvServerProcess.SHM_RING_STEPS // max(1, b),
                int(need * 1.25) + 1,
            )

        n_servers = (total + per - 1) // per
        lo, hi = _fleet_bounds(n_servers)

        def make_supervision(k, c2s_k, s2c_k, m):
            # fleet-tagged ident prefixes keep the telemetry sender table
            # (and prune-event slot mapping) distinct across fleets; ring
            # names namespace themselves through the per-fleet c2s hash
            # (utils/shm.py ring_name)
            def prefix(i):
                return (
                    f"f{k}-cppsim-{i}" if multi_fleet else f"cppsim-{i}"
                )

            def cpp_factory(i):
                # ragged last INITIAL slot keeps the per-fleet env count
                # exact; slots grown past it host the full block. Ring
                # caps are sized per-slot from the run's actual buffering.
                n = per
                remaining = total - i * per
                if 0 < remaining < n:
                    n = remaining
                # construction only parameterizes the slot — the
                # FleetSupervisor this factory is handed to owns the spawn
                return native.CppEnvServerProcess(  # ba3clint: disable=A8
                    i,
                    c2s_k,
                    s2c_k,
                    game=game,
                    n_envs=n,
                    frame_history=cfg.frame_history,
                    wire=wire,
                    shm_ring_cap=ring_cap(m, n),
                    ident_prefix=prefix(i),
                )

            sup = FleetSupervisor(
                FleetSpec(
                    pipe_c2s=c2s_k, pipe_s2c=s2c_k, game=game,
                    envs_per_server=per, frame_history=cfg.frame_history,
                    wire=wire, fleet_size=n_servers, fleet_min=lo,
                    fleet_max=hi,
                ),
                factory=cpp_factory,
                ident_prefix=prefix,
            )
            return sup, _maybe_autoscaler(sup, m)

    else:
        per_fleet_sims = cfg.simulator_procs // n_fleets
        lo, hi = _fleet_bounds(per_fleet_sims)

        def make_supervision(k, c2s_k, s2c_k, m):
            # per-fleet global index stride keeps python-simulator idents
            # ("simulator-<idx>") distinct across fleets — SimulatorProcess
            # derives its wire ident from idx alone
            base = k * 10000

            sup = FleetSupervisor(
                FleetSpec(
                    pipe_c2s=c2s_k, pipe_s2c=s2c_k, envs_per_server=1,
                    frame_history=cfg.frame_history, wire="per-env",
                    fleet_size=per_fleet_sims, fleet_min=lo, fleet_max=hi,
                ),
                # same parameterize-only contract as cpp_factory above
                factory=lambda i: SimulatorProcess(  # ba3clint: disable=A8
                    base + i, c2s_k, s2c_k, sim_build_player
                ),
                ident_prefix=lambda i: f"simulator-{base + i}",
            )
            return sup, _maybe_autoscaler(sup, m)

    planes = build_fleet_planes(  # ba3clint: disable=A8 — factories above only parameterize; each fleet's FleetSupervisor owns its spawns
        n_fleets, c2s, s2c, make_predictor, make_master, make_supervision
    )
    if external_fleet:
        for pl in planes:
            logger.info(
                "external-fleet mode (fleet %d): master pipes bound at %s "
                "(c2s) / %s (s2c) — waiting for env servers to connect",
                pl.fleet, pl.pipe_c2s, pl.pipe_s2c,
            )
    masters = [pl.master for pl in planes]
    # the staged-ingest plane (docs/ingest.md): one HostStagingRing the
    # feed's collate writes into (one host copy per block), wrapped by a
    # DeviceIngest that dispatches the NEXT batch's H2D behind the
    # running step (Trainer.run_step's prefetch call)
    staging_on = args.ingest_staging == "on"
    staging_ring = None
    if staging_on:
        from distributed_ba3c_tpu.data.staging import (
            DeviceIngest,
            HostStagingRing,
        )

        staging_ring = HostStagingRing()
    if multi_fleet:
        # fair round-robin merge of the per-fleet queues into stacked
        # [K, ...] macro batches (data/dataflow.py) — the layout the macro
        # step shards fleet-major over the mesh
        feed = FleetMergeFeed(
            [m.queue for m in masters],
            per_fleet_items,
            collate=(
                collate_rollout
                if args.trainer == "tpu_vtrace_ba3c"
                else collate_train
            ),
            staging=staging_ring,
        )
        predictor = FanoutPredictors([pl.predictor for pl in planes])
    else:
        if args.trainer == "tpu_vtrace_ba3c":
            feed = RolloutFeed(
                masters[0].queue, per_fleet_items, staging=staging_ring
            )
        else:
            feed = TrainFeed(
                masters[0].queue, per_fleet_items, staging=staging_ring
            )
        predictor = planes[0].predictor
    if staging_on:
        feed = DeviceIngest(feed, step.batch_sharding)

    # Order matters: Evaluator adds its stats BEFORE StatPrinter finalizes the
    # epoch record, and MaxSaver reads the monitored stat from that record.
    chief = is_chief()
    # Where an Evaluator runs, keep-best follows the GREEDY eval score (the
    # reference MaxSaver kept the Evaluator's best); otherwise fall back to
    # the sampling-policy mean.
    run_eval = chief and args.nr_eval > 0 and build_player is not None
    if chief and nr_eval_explicit and args.nr_eval > 0 and build_player is None:
        # external-fleet mode (--env zmq:) has no local player to evaluate
        # with: say so instead of silently changing the keep-best policy
        logger.warn(
            "--nr_eval %d ignored: no local player in --env %s mode; "
            "MaxSaver keep-best falls back to the sampling-policy mean_score",
            args.nr_eval, args.env,
        )
    # scrape endpoint: start/stop with the rest of the plane (it satisfies
    # the StartProcOrThread protocol — start/stop/join/close)
    tele_servers = (
        [telemetry.TelemetryServer(args.telemetry_port)]
        if args.telemetry_port
        else []
    )
    # start order: every fleet's predictor+master, then the merge feed,
    # then ONE reconciler over every supervised resource (spawning servers
    # before their master's receive loop is live would park the whole
    # fleet in its first recv)
    startables = [pl.predictor for pl in planes]
    if multi_fleet:
        # the fan-out facade owns pump threads: it rides the same
        # lifecycle, FIRST so its pumps stop before any predictor they
        # publish into does (start() is a no-op — pumps run from ctor)
        startables.insert(0, predictor)
    startables += masters
    startables.append(feed)
    # Every controller that used to ride the startables list on its own
    # thread — fleet supervisors, fleet autoscalers, routed ReplicaSets'
    # corpse sweep, the serving autoscaler/promotion loops — is now a
    # resource of ONE generic reconcile loop (orchestrate/reconcile.py):
    # observe → diff → act under the spec's backoff + restart-budget
    # policy, every heal decision flight-recorded with its snapshot.
    from distributed_ba3c_tpu.orchestrate import (
        FleetResource,
        PolicyResource,
        Reconciler,
        ServingResource,
    )

    reconciler = Reconciler(policy=topo.reconcile)
    for pl in planes:
        if pl.supervisor is not None:
            reconciler.add(FleetResource(f"fleet{pl.fleet}", pl.supervisor))
        if pl.autoscaler is not None:
            reconciler.add(PolicyResource(
                f"fleet-autoscaler-f{pl.fleet}", pl.autoscaler,
                interval_s=pl.autoscaler.interval_s,
            ))
    for k, rs in replica_sets:
        reconciler.add(ServingResource(f"serving-f{k}", rs))
    for name, ctrl in serving_extras:
        reconciler.add(PolicyResource(
            name, ctrl, interval_s=ctrl.interval_s,
        ))
    if reconciler.resources():
        startables.append(reconciler)
    callbacks = [
        StartProcOrThread(startables + tele_servers),
        HumanHyperParamSetter("learning_rate", shared_dir=base_logdir),
        HumanHyperParamSetter("entropy_beta", shared_dir=base_logdir),
        StatPrinter(),
        # ONE checkpoint dir for every host: orbax saves are collective and
        # must target the same path on all processes
        ModelSaver(
            ckpt_dir=os.path.join(base_logdir, "checkpoints"),
            max_to_keep=args.max_to_keep,
        ),
        MaxSaver(monitor="eval_mean_score" if run_eval else "mean_score"),
    ]
    if run_eval:
        # chief-only eval, matching the reference's chief-worker summary
        # role; MUST run before StatPrinter so eval stats land in THIS
        # epoch's record (MaxSaver reads that record)
        stat_printer_idx = next(
            i for i, cb in enumerate(callbacks) if isinstance(cb, StatPrinter)
        )
        callbacks.insert(
            stat_printer_idx,
            PeriodicTrigger(
                Evaluator(args.nr_eval, build_player),
                every_k_epochs=args.eval_every,
            ),
        )
    # reference-signature LR/β schedules (SURVEY.md §2.9), CLI-activated
    if args.learning_rate_final is not None:
        callbacks.append(
            ScheduledHyperParamSetter(
                "learning_rate",
                [(1, cfg.learning_rate), (args.max_epoch, args.learning_rate_final)],
                interp=args.anneal_lr or args.anneal,
            )
        )
    if args.entropy_beta_final is not None:
        callbacks.append(
            ScheduledHyperParamSetter(
                "entropy_beta",
                [(1, cfg.entropy_beta), (args.max_epoch, args.entropy_beta_final)],
                interp=args.anneal_beta or args.anneal,
            )
        )
    from distributed_ba3c_tpu.train.experiment import ExperimentLogger

    callbacks.append(ExperimentLogger())
    trainer = Trainer(
        TrainLoopConfig(
            steps_per_epoch=args.steps_per_epoch,
            max_epoch=args.max_epoch,
            log_dir=args.logdir,
            publish_every=args.publish_every,
            rank_stall_timeout=args.rank_stall_timeout,
        ),
        cfg,
        step,
        state,
        feed,
        callbacks,
        predictor=predictor,
        score_queue=score_q,
        is_chief=chief,
        samples_per_step=samples_per_step,
    )
    if args.load:
        trainer.restore(args.load)
    trainer.train()
    return 0


def _run_eval(args, cfg, model, state) -> int:
    import jax

    from distributed_ba3c_tpu.predict.server import BatchedPredictor
    from distributed_ba3c_tpu.train.checkpoint import CheckpointManager
    from distributed_ba3c_tpu.train.eval import eval_model
    from distributed_ba3c_tpu.utils import logger

    if args.load:
        mgr = CheckpointManager(args.load)
        state = mgr.restore(jax.device_get(state))
    # synchronous single-user eval tooling, not the serving tier: only
    # predict_batch is ever called, no routed traffic exists to bypass
    predictor = BatchedPredictor(  # ba3clint: disable=A14 — sync eval tool, predict_batch only
        model, state.params, batch_size=max(args.nr_eval, 1), greedy=True
    )
    build_player = _build_player_factory(args, cfg)

    def predict(states):
        actions, _, _ = predictor.predict_batch(states)
        return actions

    mean, mx = eval_model(predict, build_player, args.nr_eval)
    logger.info("eval over %d episodes: mean=%.2f max=%.2f", args.nr_eval, mean, mx)
    print(f"mean_score={mean:.3f} max_score={mx:.3f}")
    return 0


def _run_play(args, cfg, model, state) -> int:
    """Replay mode (reference ``play_n_episodes``): run ``--nr_eval`` greedy
    episodes one at a time, printing per-step action/reward so a human can
    watch the policy (no render surface in this build: the step trace IS the
    visualization)."""
    import jax
    import numpy as np

    from distributed_ba3c_tpu.predict.server import BatchedPredictor
    from distributed_ba3c_tpu.train.checkpoint import CheckpointManager

    if args.load:
        mgr = CheckpointManager(args.load)
        state = mgr.restore(jax.device_get(state))
    predictor = BatchedPredictor(model, state.params, batch_size=1, greedy=True)  # ba3clint: disable=A14 — sync play tool, predict_batch only
    build_player = _build_player_factory(args, cfg)

    for ep in range(max(args.nr_eval, 1)):
        player = build_player(ep)
        score, t = 0.0, 0
        while True:
            s = np.asarray(player.current_state())[None]
            actions, values, _ = predictor.predict_batch(s)
            a = int(actions[0])
            r, is_over = player.action(a)
            score += r
            if r != 0 or t % 50 == 0:
                print(
                    f"episode {ep} step {t:5d} | action {a} | reward {r:+.1f} "
                    f"| score {score:+.1f} | V(s) {float(values[0]):+.3f}"
                )
            t += 1
            if is_over or t >= cfg.episode_length_cap:
                break
        print(f"episode {ep} finished: score {score:+.1f} in {t} steps")
    return 0


def _run_fused(args, cfg, model, optimizer) -> int:
    try:
        from distributed_ba3c_tpu.fused.loop import run_fused_training
    except ImportError:
        raise SystemExit(
            "--trainer=tpu_fused_ba3c requires the on-device env module "
            "(distributed_ba3c_tpu.fused); not available in this build"
        )
    return run_fused_training(args, cfg, model, optimizer)
