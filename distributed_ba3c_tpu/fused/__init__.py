"""Fused on-device actor+learner: rollout and update in ONE compiled program.

BASELINE.json config #5 and the performance centerpiece of the rebuild: where
the reference burns a 64-node CPU cluster shuttling experience over ZMQ and
gradients over gRPC (SURVEY.md §3.2-3.4), this path keeps everything — env
physics, rendering, action sampling, n-step returns, loss, psum, Adam — in a
single jitted XLA computation per iteration. Zero host round-trips; the only
host traffic is scalar metrics.

``--overlap`` (fused/overlap.py, docs/overlap.md) splits that one program
into two overlapped compiled programs — a collective-free actor producing
double-buffered trajectory blocks at policy k-1, and a lag-1
V-trace-corrected learner — so the rollout's low-occupancy forwards hide
behind the learner instead of adding to it.
"""

from distributed_ba3c_tpu.fused.loop import (
    FusedState,
    create_fused_state,
    make_fused_step,
    run_fused_training,
)
from distributed_ba3c_tpu.fused.overlap import (
    ActorState,
    OverlapState,
    TrajBlock,
    make_overlap_step,
)

__all__ = [
    "ActorState",
    "FusedState",
    "OverlapState",
    "TrajBlock",
    "create_fused_state",
    "make_fused_step",
    "make_overlap_step",
    "run_fused_training",
]
