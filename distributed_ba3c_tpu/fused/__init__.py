"""Fused on-device actor+learner: rollout and update in ONE compiled program.

BASELINE.json config #5 and the performance centerpiece of the rebuild: where
the reference burns a 64-node CPU cluster shuttling experience over ZMQ and
gradients over gRPC (SURVEY.md §3.2-3.4), this path keeps everything — env
physics, rendering, action sampling, n-step returns, loss, psum, Adam — in a
single jitted XLA computation per iteration. Zero host round-trips; the only
host traffic is scalar metrics.
"""

from distributed_ba3c_tpu.fused.loop import (
    FusedState,
    create_fused_state,
    make_fused_step,
    run_fused_training,
)

__all__ = [
    "FusedState",
    "create_fused_state",
    "make_fused_step",
    "run_fused_training",
]
