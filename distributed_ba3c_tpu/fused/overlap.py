"""Overlapped rollout/learner programs: the fused step split in two.

The fused step (fused/loop.py) serializes, inside ONE compiled program, the
small-batch low-occupancy rollout forwards with the large-batch learner
fwd+bwd — so the rollout's ~1.6 us/sample residual (PERF.md round 3
attribution) is ADDED to the learner instead of hidden behind it. This
module splits the step into two overlapped compiled programs with double
buffering:

    actor program   (``fused.actor``):   rollout scan over T steps at the
        policy of update k-1, producing a trajectory block (states,
        actions, clipped rewards, dones, behavior log-probs, bootstrap
        stack) into a device-resident slot. Donation-aliased on its env
        carry; collective-free (everything it touches is per-shard).
    learner program (``fused.learner``): V-trace-corrected fwd+bwd on the
        block from step k-1 (policy lag 1 — exactly the staleness
        ops/vtrace.py's clipped importance weights correct, the IMPALA
        result the ISSUE leans on), gradient psum, Adam. Donates the
        train state.

Schedule per iteration (host dispatches, all async — NO host sync between
them; ba3clint rule J6 ``overlap-sync-hazard`` guards this):

    aparams     = prep(train.params)            # snapshot (copy or bf16 cast)
    astate, b'  = actor(aparams, astate)        # rollout k+1   (donates astate)
    train, m    = learner(train, b, beta, lr)   # learner k     (donates train)
    b = b'

The ``prep`` snapshot is load-bearing, not a convenience: the learner
donates the param buffers, and a donated write cannot begin while another
in-flight program still reads the same buffers — an actor reading
``train.params`` directly would serialize the learner behind the whole
rollout. Reading a SNAPSHOT breaks that anti-dependency, so the two big
programs share no buffers at all and the runtime is free to execute
rollout k+1 concurrently with learner k. In bf16 mode the snapshot IS the
cast (params -> bf16), which also halves the actor's param-read bandwidth;
the policy heads stay f32 (models/a3c.py), so behavior log-probs are f32
either way and V-trace clips whatever precision noise the cast adds.

Double buffering falls out of donation: block k is a live device slot
while the actor writes block k+1 into fresh buffers; when the learner
(which does NOT donate the block — its buffers alias no output) finishes,
block k's refcount drops and XLA reuses the slot for block k+2. Two block
allocations alternate; nothing is copied.

Lag:
    lag=1 (default)  rollout k+1 runs concurrently with learner k; the
                     behavior policy is one update stale and V-trace
                     corrects it.
    lag=0            actor and learner run back-to-back on the SAME block
                     (no overlap). With frozen params this is bit-exact
                     with the fused step — the parity contract
                     tests/test_overlap.py pins.
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ba3c_tpu.audit import tripwire_jit
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.fused.loop import (
    CUMULATIVE_METRICS,
    FusedState,
    make_put_batched,
    make_rollout_body,
)
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import grad_summaries, inject_learning_rate
from distributed_ba3c_tpu.ops.vtrace import vtrace_returns
from distributed_ba3c_tpu.parallel.mesh import (
    DATA_AXIS,
    axis_size,
    grad_allreduce,
    shard_map,
)
from distributed_ba3c_tpu.parallel.train_step import (
    TrainState,
    macro_accumulate,
)

import optax

ROLLOUT_DTYPES = ("float32", "bfloat16", "int8")


def make_block_grads(
    model: BA3CNet, cfg: BA3CConfig, grad_chunk_samples: int = 4096
) -> Callable:
    """Per-block V-trace grads + aux (env-column chunked) — the ONE
    gradient body the overlap learner, the multi-fleet macro learner AND
    the pod's bounded-staleness learner (pod/learner.py) all run. The
    correction reads the block's recorded behavior log-probs, so it is
    exact at ANY measured params lag — lag never enters the program, only
    the data; that is what lets the pod generalize the overlap split's
    fixed lag-1 without a new gradient path to re-verify."""

    def block_grads(params, block: TrajBlock, entropy_beta):
        T, B = block.actions.shape

        # chunk over ENV COLUMNS, not the flat [T*B] batch: V-trace's
        # reverse scan couples a whole env column in time but columns are
        # independent, so mean-of-column-chunk grads equals the full-batch
        # gradient (same HBM-activation-cap role as the fused learner's
        # flat chunks). At the flagship 128x20 shape T*B=2560 <=
        # grad_chunk_samples, so the expected path is one chunk.
        # clamp to B FIRST: an env column (T samples) is the smallest
        # chunk this layout can make, and a start value above B would
        # never find a divisor (the rounding loop below walks upward)
        n_chunks = min(max(1, -(-(T * B) // grad_chunk_samples)), B)
        while B % n_chunks:
            n_chunks += 1
        Bc = B // n_chunks

        def chunk_loss(pp, chunk):
            states_c, actions_c, rewards_c, dones_c, mu_lp_c, mu_v_c, boot_c = chunk
            # one big forward over T*Bc + Bc states (conv batch stays
            # MXU-sized; the bootstrap is valued under the TARGET policy)
            flat = states_c.reshape((T * Bc, *states_c.shape[2:]))
            all_states = jnp.concatenate([flat, boot_c], axis=0)
            out = model.apply({"params": pp}, all_states)
            logits = out.logits[: T * Bc].reshape((T, Bc, -1))
            values = out.value[: T * Bc].reshape((T, Bc))
            bootstrap_value = out.value[T * Bc:]

            log_probs = jax.nn.log_softmax(logits, axis=-1)
            probs = jax.nn.softmax(logits, axis=-1)
            target_lp = jnp.take_along_axis(
                log_probs, actions_c[..., None].astype(jnp.int32), axis=-1
            )[..., 0]

            vt = vtrace_returns(
                behaviour_log_probs=mu_lp_c,
                target_log_probs=jax.lax.stop_gradient(target_lp),
                rewards=rewards_c,
                dones=dones_c,
                values=jax.lax.stop_gradient(values),
                bootstrap_value=jax.lax.stop_gradient(bootstrap_value),
                gamma=cfg.gamma,
            )

            # loss forms mirror ops/loss.py's a3c_loss (incl. the optional
            # Huber value loss) so a lag-0 run optimizes the same objective
            # as the fused step — at zero lag rho == c == 1 and the V-trace
            # targets reduce exactly to the n-step returns.
            policy_loss = -jnp.mean(target_lp * vt.pg_advantages)
            if cfg.value_huber_delta is not None:
                from distributed_ba3c_tpu.ops.symbolic import huber_loss

                value_loss = jnp.mean(
                    huber_loss(values - vt.vs, cfg.value_huber_delta)
                )
            else:
                value_loss = 0.5 * jnp.mean(jnp.square(values - vt.vs))
            entropy = -jnp.mean(jnp.sum(probs * log_probs, axis=-1))
            total = (
                policy_loss
                + cfg.value_loss_coef * value_loss
                - entropy_beta * entropy
            )
            aux = {
                "loss": total,
                "policy_loss": policy_loss,
                "value_loss": value_loss,
                "entropy": entropy,
                "mean_rho": jnp.mean(vt.clipped_rhos),
                "pred_value": jnp.mean(values),
                # how far the value function moved across the policy lag —
                # the observable the lag correction story rests on (and
                # it keeps every block input live in the compiled program)
                "value_lag_mae": jnp.mean(
                    jnp.abs(jax.lax.stop_gradient(values) - mu_v_c)
                ),
            }
            return total, aux

        def chunk_grad(pp, chunk):
            return jax.value_and_grad(chunk_loss, has_aux=True)(pp, chunk)

        def col_chunk(x):
            # [T, B, ...] -> [n_chunks, T, Bc, ...] (chunk c = env columns
            # c*Bc:(c+1)*Bc — matches boot.reshape(n_chunks, Bc) below)
            return x.reshape(T, n_chunks, Bc, *x.shape[2:]).swapaxes(0, 1)

        full_chunk = (
            block.states, block.actions, block.rewards, block.dones,
            block.behavior_log_probs, block.behavior_values,
            block.bootstrap_state,
        )
        if n_chunks == 1:
            (_, aux), grads = chunk_grad(params, full_chunk)
        else:
            boot_c = block.bootstrap_state.reshape(
                n_chunks, Bc, *block.bootstrap_state.shape[1:]
            )
            chunks = (
                col_chunk(block.states), col_chunk(block.actions),
                col_chunk(block.rewards), col_chunk(block.dones),
                col_chunk(block.behavior_log_probs),
                col_chunk(block.behavior_values), boot_c,
            )

            def acc_body(carry, chunk):
                g_acc, aux_acc = carry
                (_, aux), g = chunk_grad(params, chunk)
                g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                aux_acc = jax.tree_util.tree_map(jnp.add, aux_acc, aux)
                return (g_acc, aux_acc), None

            first = jax.tree_util.tree_map(lambda x: x[0], chunks)
            (_, aux0), g0 = chunk_grad(params, first)
            rest = jax.tree_util.tree_map(lambda x: x[1:], chunks)
            (grads, aux_sum), _ = jax.lax.scan(acc_body, (g0, aux0), rest)
            grads = jax.tree_util.tree_map(lambda g: g / n_chunks, grads)
            aux = jax.tree_util.tree_map(lambda a: a / n_chunks, aux_sum)
        return grads, aux

    return block_grads


def make_finish_update(optimizer: optax.GradientTransformation) -> Callable:
    """The learner tail — ONE definition for the single, macro and pod
    programs (psum + mean + LR injection + Adam + pmean'd metrics): a tail
    fix applied to one copy must not silently diverge the others (review
    finding, extended to pod/learner.py)."""

    def finish_update(train: TrainState, grads, aux, rewards, learning_rate):
        grads = grad_allreduce(grads, DATA_AXIS)
        n_data = axis_size(DATA_AXIS)
        grads = jax.tree_util.tree_map(lambda g: g / n_data, grads)

        opt_state = inject_learning_rate(train.opt_state, learning_rate)
        updates, new_opt_state = optimizer.update(
            grads, opt_state, train.params
        )
        new_params = optax.apply_updates(train.params, updates)
        new_train = TrainState(
            step=train.step + 1, params=new_params, opt_state=new_opt_state
        )
        metrics = {
            **aux,
            **grad_summaries(grads),
            "reward_per_step": jnp.mean(rewards),
        }
        metrics = {k: jax.lax.pmean(v, DATA_AXIS) for k, v in metrics.items()}
        return new_train, metrics

    return finish_update


class ActorState(struct.PyTreeNode):
    """The env-side carry of the actor program (FusedState minus train)."""

    env_state: Any            # batched env pytree, leaves [B_global, ...]
    obs_stack: jax.Array      # [B_global, H, W, hist] uint8
    key: jax.Array            # [n_shards] typed PRNG keys, data-sharded
    ep_return: jax.Array      # [B_global] running episode return
    ep_count: jax.Array       # [B_global] int32 completed episodes per env
    ep_return_sum: jax.Array  # [B_global] f32 sum of completed returns


class TrajBlock(struct.PyTreeNode):
    """One rollout's trajectory — the device-resident slot the two
    programs hand off. Time-major to match the V-trace reverse scan."""

    states: jax.Array              # [T, B, H, W, hist] uint8
    actions: jax.Array             # [T, B] int32
    rewards: jax.Array             # [T, B] f32 (clipped learning rewards)
    dones: jax.Array               # [T, B] f32
    behavior_log_probs: jax.Array  # [T, B] f32  log mu(a_t|s_t)
    behavior_values: jax.Array     # [T, B] f32  V_mu(s_t) (lag diagnostic)
    bootstrap_state: jax.Array     # [B, H, W, hist] uint8 (post-rollout)


class OverlapState(struct.PyTreeNode):
    """What the overlap step threads through the epoch loop."""

    train: TrainState
    actor: ActorState
    block: Any = None  # TrajBlock in flight (lag=1) or None (lag=0/fresh)


def make_overlap_step(
    model: BA3CNet,
    optimizer: optax.GradientTransformation,
    cfg: BA3CConfig,
    mesh: Mesh,
    env,
    rollout_len: int = 20,
    grad_chunk_samples: int = 4096,
    steps_per_dispatch: int = 1,
    lag: int = 1,
    rollout_dtype: str = "float32",
    macro_fleets: int = 1,
    quant_spec=None,
) -> Callable:
    """Build the overlapped two-program step facade.

    Same call shape as ``make_fused_step``'s step — fn(state, beta, lr) ->
    (state, metrics) — so ``run_fused_training``'s epoch loop drives either
    interchangeably. ``steps_per_dispatch`` here is the number of
    actor/learner iteration PAIRS dispatched per facade call (all async;
    the epoch loop's metrics fetch is the only sync).

    ``macro_fleets`` > 1 is the fused half of multi-fleet macro-batching
    (docs/actor_plane.md): the actor program runs K rollout windows per
    update — K "fleets" of trajectory blocks under one params snapshot —
    and a MACRO learner (``fused.macro_learner``) accumulates their
    gradients into ONE update. Per-update effective batch grows K-fold
    while every fwd+bwd still runs at the single-window full-occupancy
    shape (the macro-batching contract); behavior lag within the window
    spans 1..K updates and V-trace's clipped importance weights correct
    it exactly as they do the lag-1 schedule.

    ``rollout_dtype="int8"`` builds the quantized actor program (audit
    entry ``fused.actor_int8``): ``quant_spec`` (a calibrated
    :class:`~distributed_ba3c_tpu.quantize.spec.QuantSpec`) is REQUIRED,
    the prep step becomes quantize-on-snapshot (``quantize_params``) and
    the rollout body's forward runs the dequant-free int8 mirror
    (quantize/qforward.py). The learner half is untouched — f32
    throughout, exactly like the bf16 rung.
    """
    if lag not in (0, 1):
        raise ValueError(f"lag must be 0 or 1, got {lag}")
    if rollout_dtype not in ROLLOUT_DTYPES:
        raise ValueError(
            f"rollout_dtype must be one of {ROLLOUT_DTYPES}, got {rollout_dtype!r}"
        )
    if rollout_dtype == "int8" and quant_spec is None:
        raise ValueError(
            "rollout_dtype='int8' needs a calibrated quant_spec (load one "
            "with QuantSpec.load, or calibrate via quantize.calibrate)"
        )
    if macro_fleets < 1:
        raise ValueError(f"macro_fleets must be >= 1, got {macro_fleets}")
    if rollout_dtype == "int8":
        from distributed_ba3c_tpu.quantize import (
            make_quant_apply,
            quantize_params,
        )

        quant_apply = make_quant_apply(model, arm="auto")
    else:
        quant_apply = None

    # ---------------- actor program (fused.actor) -------------------------
    def local_actor(params, astate: ActorState):
        key = astate.key[0]  # this shard's scalar key
        rollout_body = make_rollout_body(
            model, cfg, env, params, record_log_probs=True,
            apply_fn=quant_apply,
        )
        carry0 = (
            astate.env_state,
            astate.obs_stack,
            key,
            astate.ep_return,
            astate.ep_count,
            astate.ep_return_sum,
        )
        (env_state, stack, key, ep_ret, ep_cnt, ep_sum), traj = jax.lax.scan(
            rollout_body, carry0, None, length=rollout_len
        )
        states_t, actions_t, rewards_t, dones_t, lp_t, bv_t = traj
        new_astate = ActorState(
            env_state=env_state,
            obs_stack=stack,
            key=key[None],
            ep_return=ep_ret,
            ep_count=ep_cnt,
            ep_return_sum=ep_sum,
        )
        block = TrajBlock(
            states=states_t,
            actions=actions_t,
            rewards=rewards_t,
            dones=dones_t,
            behavior_log_probs=lp_t,
            behavior_values=bv_t,
            bootstrap_state=stack,
        )
        # NO bootstrap forward and NO psums here: the learner values the
        # bootstrap stack under the TARGET policy (vtrace_step idiom), and
        # episode metrics are aggregated by the tiny ep_stats program at
        # window boundaries — the actor stays collective-free (T3) so the
        # single-chip schedule has nothing to wait on.
        return new_astate, block

    batch_spec = P(DATA_AXIS)
    env_state_struct = jax.eval_shape(env.reset, jax.random.PRNGKey(0))
    actor_specs = ActorState(
        env_state=jax.tree_util.tree_map(lambda _: batch_spec, env_state_struct),
        obs_stack=batch_spec,
        key=P(DATA_AXIS),
        ep_return=batch_spec,
        ep_count=batch_spec,
        ep_return_sum=batch_spec,
    )
    tb_spec = P(None, DATA_AXIS)  # time-major leaves
    block_specs = TrajBlock(
        states=tb_spec,
        actions=tb_spec,
        rewards=tb_spec,
        dones=tb_spec,
        behavior_log_probs=tb_spec,
        behavior_values=tb_spec,
        bootstrap_state=batch_spec,
    )
    actor_sharded = shard_map(
        local_actor,
        mesh=mesh,
        in_specs=(P(), actor_specs),
        out_specs=(actor_specs, block_specs),
    )
    # registered audit entry point (distributed_ba3c_tpu/audit.py):
    # donation-aliased env carry, collective-free program
    actor_jit = tripwire_jit("fused.actor", actor_sharded, donate_argnums=(1,))

    # ---------------- prep: the params snapshot ----------------------------
    if rollout_dtype == "int8":
        def prep_fn(params):
            # quantize-on-snapshot: the f32 learner params become the
            # int8 serving table (per-channel weight scales + the frozen
            # activation scales riding in) — every cast lives in
            # quantize/qforward.py behind the fused.actor_int8 audit
            return quantize_params(params, quant_spec)
    elif rollout_dtype == "bfloat16":
        def prep_fn(params):
            # the cast IS the snapshot: bf16 actor-side forward (the
            # block only feeds behavior logits that V-trace clips)
            return jax.tree_util.tree_map(
                lambda x: x.astype(jnp.bfloat16)  # ba3clint: disable=A16 — THE audited publish cast (entry fused.actor_bf16)
                if x.dtype == jnp.float32 else x,
                params,
            )
    else:
        def prep_fn(params):
            # a plain device copy — see the module docstring for why the
            # actor must NOT read the learner-donated buffers directly
            return jax.tree_util.tree_map(jnp.copy, params)

    prep_jit = tripwire_jit("fused.prep", prep_fn)

    # ---------------- learner program (fused.learner) ----------------------
    # the gradient body and the update tail are the module-level factories
    # (make_block_grads / make_finish_update) shared with the pod's
    # bounded-staleness learner — pure code motion, identical jaxprs, so
    # the audit manifest's fused.* entries are unchanged
    block_grads = make_block_grads(model, cfg, grad_chunk_samples)
    finish_update = make_finish_update(optimizer)

    def local_learner(train: TrainState, block: TrajBlock, entropy_beta,
                      learning_rate):
        grads, aux = block_grads(train.params, block, entropy_beta)
        return finish_update(train, grads, aux, block.rewards, learning_rate)

    learner_sharded = shard_map(
        local_learner,
        mesh=mesh,
        in_specs=(P(), block_specs, P(), P()),
        out_specs=(P(), P()),
    )
    # registered audit entry point: donated train state, exactly-once grad
    # psum census. The block is deliberately NOT donated — its buffers
    # alias no learner output, and keeping them live is what double
    # buffering means
    learner_jit = tripwire_jit(
        "fused.learner", learner_sharded, donate_argnums=(0,)
    )

    # ---------------- macro learner (fused.macro_learner) ------------------
    # K trajectory blocks -> ONE update: per-block grads (the SAME
    # block_grads body the single learner runs, chunking included) are
    # accumulated with a lax.scan over the stacked fleet axis, then a
    # single psum + Adam. Mean-of-equal-window grads == the [T, K*B]
    # full-batch gradient (V-trace couples time, never envs) — the
    # chunked-vs-full equivalence gate extended over the fleet axis
    # (tests/test_fleet.py pins it against the single learner on
    # env-concatenated blocks).
    macro_learner_jit = None
    if macro_fleets > 1:
        K = macro_fleets

        def local_macro_learner(train: TrainState, blocks, entropy_beta,
                                learning_rate):
            # stack K blocks fleet-major INSIDE the program (XLA fuses the
            # concat into the scan's gather; the facade ships the blocks
            # as-is, no host-side copies), accumulate with the SAME scan
            # idiom as the ZMQ macro steps, finish with the shared tail
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *blocks
            )

            def loss_grad_one(params, blk):
                g, aux = block_grads(params, blk, entropy_beta)
                return (None, aux), g  # macro_accumulate's ((_, aux), g)

            grads, aux = macro_accumulate(
                loss_grad_one, train.params, stacked, K
            )
            return finish_update(
                train, grads, aux, stacked.rewards, learning_rate
            )

        macro_learner_sharded = shard_map(
            local_macro_learner,
            mesh=mesh,
            in_specs=(P(), (block_specs,) * K, P(), P()),
            out_specs=(P(), P()),
        )
        # registered audit entry point: donated train state, exactly-once
        # grad psum for the WHOLE macro batch; the K blocks stay undonated
        # for the same double-buffer reason as the single learner's block
        macro_learner_jit = tripwire_jit(
            "fused.macro_learner", macro_learner_sharded, donate_argnums=(0,)
        )

    # ---------------- ep_stats: window-boundary episode metrics -----------
    def local_ep_stats(ep_cnt, ep_sum):
        return (
            jax.lax.psum(jnp.sum(ep_cnt), DATA_AXIS),
            jax.lax.psum(jnp.sum(ep_sum), DATA_AXIS),
        )

    ep_stats_jit = tripwire_jit(
        "fused.ep_stats",
        shard_map(
            local_ep_stats,
            mesh=mesh,
            in_specs=(batch_spec, batch_spec),
            out_specs=(P(), P()),
        ),
    )

    # ---------------- the facade ------------------------------------------
    def step(state: OverlapState, entropy_beta, learning_rate=None):
        if learning_rate is None:
            learning_rate = cfg.learning_rate
        beta_arr = jnp.asarray(entropy_beta, jnp.float32)
        lr_arr = jnp.asarray(learning_rate, jnp.float32)
        train, astate, block = state.train, state.actor, state.block

        def roll(aparams, astate):
            # macro mode: K rollout windows ("fleets") under ONE snapshot,
            # all dispatches async — the env carry chains through, so the
            # K blocks tile time with no gaps. Single-window mode returns
            # the bare block (the single learner's input shape).
            blocks = []
            for _ in range(macro_fleets):
                astate, b = actor_jit(aparams, astate)
                blocks.append(b)
            return astate, blocks[0] if macro_fleets == 1 else tuple(blocks)

        learn = macro_learner_jit if macro_fleets > 1 else learner_jit
        if lag and block is None:
            # prime the pipeline: one rollout window (or K of them) before
            # the first update so learner k always has its k-1 input resident
            aparams = prep_jit(train.params)
            astate, block = roll(aparams, astate)
        ms = []
        for _ in range(steps_per_dispatch):
            aparams = prep_jit(train.params)
            if lag:
                # the two dispatches the whole module exists for: rollout
                # k+1 (reading only the snapshot) enqueued back-to-back
                # with learner k — no host sync in between (J6)
                astate, next_block = roll(aparams, astate)
                train, m = learn(train, block, beta_arr, lr_arr)
                block = next_block
            else:
                astate, block0 = roll(aparams, astate)
                train, m = learn(train, block0, beta_arr, lr_arr)
            ms.append(m)
        if len(ms) == 1:
            metrics = dict(ms[0])
        else:
            metrics = jax.tree_util.tree_map(
                lambda *xs: jnp.mean(jnp.stack(xs)), *ms
            )
        # cumulative-in-state metrics (fused/loop.py CUMULATIVE_METRICS
        # contract): read once per facade call off the latest env carry —
        # NOT inside the iteration pair, where a cross-shard psum would
        # couple the two programs
        episodes, ep_return_sum = ep_stats_jit(
            astate.ep_count, astate.ep_return_sum
        )
        metrics["episodes"] = episodes
        metrics["episode_return_sum"] = ep_return_sum
        assert set(CUMULATIVE_METRICS) <= set(metrics)
        return (
            OverlapState(train=train, actor=astate, block=block if lag else None),
            metrics,
        )

    replicated = NamedSharding(mesh, P())
    batched = NamedSharding(mesh, batch_spec)
    _put_batched = make_put_batched(batched)

    def put(state: FusedState) -> OverlapState:
        """device_put a host FusedState (create_fused_state's layout) with
        the overlap step's shardings, split into train + actor carry."""
        return OverlapState(
            train=jax.device_put(state.train, replicated),
            actor=ActorState(
                env_state=jax.tree_util.tree_map(_put_batched, state.env_state),
                obs_stack=_put_batched(state.obs_stack),
                key=_put_batched(state.key),
                ep_return=_put_batched(state.ep_return),
                ep_count=_put_batched(state.ep_count),
                ep_return_sum=_put_batched(state.ep_return_sum),
            ),
            block=None,
        )

    def reset_episode_stats(state: OverlapState, n_envs: int) -> OverlapState:
        return state.replace(
            actor=state.actor.replace(
                ep_count=_put_batched(jnp.zeros(n_envs, jnp.int32)),
                ep_return_sum=_put_batched(jnp.zeros(n_envs, jnp.float32)),
            )
        )

    def probe_overlap(state: OverlapState, entropy_beta, learning_rate=None,
                      reps: int = 3):
        """Measure the two programs solo and overlapped; returns
        (advanced_state, measurement dict) and publishes the telemetry
        series (tele/learner/actor_program_ms, learner_program_ms,
        overlap_pair_ms, overlap_efficiency — docs/observability.md).

        This is the ONE sanctioned host-sync site between the two
        dispatches: it exists to measure the very serialization J6
        forbids, runs a handful of iterations OUTSIDE the training hot
        loop (bench warmup / scripts/profile_split.py --overlap), and
        advances the state it was given so no experience is replayed.
        ``overlap_efficiency`` is the learner-hidden fraction of the actor
        program: (t_actor + t_learner - t_pair) / t_actor.
        """
        if macro_fleets > 1:
            raise NotImplementedError(
                "probe_overlap measures the single-window actor/learner "
                "pair — run it on a macro_fleets=1 step (the macro "
                "learner's cost profile is pinned by its own audit entry)"
            )
        if learning_rate is None:
            learning_rate = cfg.learning_rate
        beta_arr = jnp.asarray(entropy_beta, jnp.float32)
        lr_arr = jnp.asarray(learning_rate, jnp.float32)
        train, astate, block = state.train, state.actor, state.block
        if block is None:
            aparams = prep_jit(train.params)
            astate, block = actor_jit(aparams, astate)
            jax.block_until_ready(block)  # ba3clint: disable=J6
        t_actor, t_learner, t_pair = [], [], []
        for _ in range(max(1, reps)):
            # solo actor (fully synced — measurement, not training)
            aparams = prep_jit(train.params)
            jax.block_until_ready(aparams)  # ba3clint: disable=J1
            t0 = time.perf_counter()
            astate, next_block = actor_jit(aparams, astate)
            # measurement fence: the probe times the actor ALONE
            jax.block_until_ready(next_block)  # ba3clint: disable=J1
            t_actor.append(time.perf_counter() - t0)
            # solo learner
            t0 = time.perf_counter()
            train, m = learner_jit(train, block, beta_arr, lr_arr)
            jax.block_until_ready(train)  # ba3clint: disable=J1
            t_learner.append(time.perf_counter() - t0)
            block = next_block
            # overlapped pair: both enqueued, one sync at the end
            aparams = prep_jit(train.params)
            jax.block_until_ready(aparams)  # ba3clint: disable=J1
            t0 = time.perf_counter()
            astate, next_block = actor_jit(aparams, astate)
            train, m = learner_jit(train, block, beta_arr, lr_arr)
            jax.block_until_ready((next_block, train))  # ba3clint: disable=J1
            t_pair.append(time.perf_counter() - t0)
            block = next_block
        med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
        a_ms, l_ms, p_ms = (
            med(t_actor) * 1e3, med(t_learner) * 1e3, med(t_pair) * 1e3
        )
        hidden = (a_ms + l_ms - p_ms) / a_ms if a_ms > 0 else 0.0
        # the device-free proxy gate quantity (ISSUE 8): how much of the
        # actor's wall time the learner window is LONG enough to hide —
        # computed HERE so bench.py and profile_split report one number
        coverage = round(min(1.0, l_ms / a_ms), 4) if a_ms > 0 else None
        from distributed_ba3c_tpu import telemetry

        reg = telemetry.registry("learner")
        reg.gauge("actor_program_ms").set(a_ms)
        reg.gauge("learner_program_ms").set(l_ms)
        reg.gauge("overlap_pair_ms").set(p_ms)
        reg.gauge("overlap_efficiency").set(hidden)
        out = {
            "actor_ms": round(a_ms, 3),
            "learner_ms": round(l_ms, 3),
            "pair_ms": round(p_ms, 3),
            "overlap_efficiency": round(hidden, 4),
            "learner_window_coverage": coverage,
            "reps": max(1, reps),
        }
        return OverlapState(train=train, actor=astate, block=block), out

    step.put = put
    step.put_batched = _put_batched
    step.replicated_sharding = replicated
    step.batch_sharding = batched
    step.mesh = mesh
    step.rollout_len = rollout_len
    step.steps_per_dispatch = steps_per_dispatch
    step.lag = lag
    step.rollout_dtype = rollout_dtype
    step.quant_spec = quant_spec
    step.macro_fleets = macro_fleets
    step.reset_episode_stats = reset_episode_stats
    step.probe_overlap = probe_overlap
    # tools/ba3caudit traces THESE programs (two entries, one step;
    # three with the macro learner)
    step.actor_jit = actor_jit
    step.learner_jit = learner_jit
    step.macro_learner_jit = macro_learner_jit
    # the params-snapshot program: the pod's lagged driver
    # (pod/learner.py LaggedBlockDriver) snapshots THROUGH this same
    # program so its version ring never aliases learner-donated buffers
    step.prep_jit = prep_jit
    return step
