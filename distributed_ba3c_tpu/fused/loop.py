"""The fused rollout+update step and its training loop.

Structure of one fused step (all inside one jit, shard_map'd over the mesh's
``data`` axis; B envs per device):

    lax.scan over T rollout steps:
        forward policy on the frame stack  (bf16 convs on the MXU)
        sample actions (on-device categorical)
        vmap(env.step): physics + uint8 render for B envs
        update frame stacks, episode-return accumulators
    bootstrap value on the final stacks
    n-step returns (reverse scan, done-masked)   ops/returns.py
    a3c loss over the [T*B] flat batch           ops/loss.py
    grads → mean over data axis → Adam update    (the one collective)

The rollout forward runs without gradient tracking; the loss recomputes the
forward over the collected stacks — standard A2C, and on TPU the recompute is
cheaper than storing activations (HBM-bandwidth-bound regime).

Actor/learner lag is ZERO here (perfectly on-policy), so the plain A3C loss
is exact; the V-trace path exists for the lagged ZMQ plane.

RNG layout: ``FusedState.key`` is a [n_shards] typed-key array sharded over
the data axis — each shard consumes its own stream, so no two devices roll
identical envs. Episode stats are per-env arrays (sharded with the env
batch) and psum'd into scalars only inside the metrics.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ba3c_tpu.audit import tripwire_jit
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import grad_summaries, inject_learning_rate
from distributed_ba3c_tpu.ops.loss import a3c_loss
from distributed_ba3c_tpu.ops.returns import n_step_returns
from distributed_ba3c_tpu.parallel.mesh import (
    DATA_AXIS,
    axis_size,
    grad_allreduce,
    shard_map,
    to_varying,
)
from distributed_ba3c_tpu.parallel.train_step import TrainState

#: metrics that accumulate IN STATE across an epoch (reset by the outer
#: loop): the K-step scan reduction takes their LAST value, every other
#: metric is mean-averaged over the dispatch window. local_step asserts
#: each of these is in its metrics dict so the two sites cannot
#: desynchronize (ADVICE r4 #3).
CUMULATIVE_METRICS = ("episodes", "episode_return_sum")


class FusedState(struct.PyTreeNode):
    train: TrainState
    env_state: Any            # batched env pytree, leaves [B_global, ...]
    obs_stack: jax.Array      # [B_global, H, W, hist] uint8
    key: jax.Array            # [n_shards] typed PRNG keys, sharded on data axis
    ep_return: jax.Array      # [B_global] running episode return
    ep_count: jax.Array       # [B_global] int32 completed episodes per env
    ep_return_sum: jax.Array  # [B_global] float32 sum of completed returns per env


def make_rollout_body(model, cfg: BA3CConfig, env, params,
                      record_log_probs: bool = False, apply_fn=None):
    """The per-step rollout scan body — ONE implementation shared by the
    fused step and the overlap actor program (fused/overlap.py).

    Sharing it is what makes the overlap path's lag-0 parity test a real
    contract: both programs consume the identical key sequence and action
    sampling math, so a frozen-params run is bit-exact across them. With
    ``record_log_probs`` the trajectory tuple grows a fifth element —
    log mu(a_t|s_t) of the sampled action (the V-trace behavior term);
    without it the emitted jaxpr is unchanged from the pre-split fused
    body (the audit manifest pins that).

    ``apply_fn(params, stack) -> PolicyValue`` overrides the forward
    while keeping the key sequence/sampling math identical — the int8
    actor program (quantize/qforward.py) passes its quantized apply and
    ``params`` becomes the int8 serving table.
    """
    if apply_fn is None:
        apply_fn = lambda p, stack: model.apply({"params": p}, stack)  # noqa: E731

    def rollout_body(carry, _):
        env_state, stack, key, ep_ret, ep_cnt, ep_sum = carry
        B = stack.shape[0]
        out = apply_fn(params, stack)
        key, k_act, k_env = jax.random.split(key, 3)
        actions = jax.random.categorical(k_act, out.logits, axis=-1).astype(
            jnp.int32
        )
        env_keys = jax.random.split(k_env, B)
        env_state, obs, reward, done = jax.vmap(env.step)(
            env_state, actions, env_keys
        )
        # a done frame must not leak history into the new episode: zero
        # the carried history via a mask multiply (single fused pass —
        # cheaper than building a zeroed copy and where-selecting)
        keep = (~done).astype(stack.dtype)[:, None, None, None]
        new_stack = jnp.concatenate(
            [stack[..., 1:] * keep, obs[..., None]], axis=-1
        )
        # episode bookkeeping (done ⇒ env auto-restarted inside step);
        # scores accumulate RAW rewards, the learner sees clipped ones
        ep_ret = ep_ret + reward
        donef = done.astype(jnp.float32)
        ep_sum = ep_sum + ep_ret * donef
        ep_cnt = ep_cnt + done.astype(jnp.int32)
        ep_ret = ep_ret * (1.0 - donef)
        r_learn = (
            jnp.clip(reward, -cfg.reward_clip, cfg.reward_clip)
            if cfg.reward_clip
            else reward
        )
        ys = (stack, actions, r_learn, donef)
        if record_log_probs:
            # behavior log-prob of the SAMPLED action at the ROLLOUT
            # policy — the mu term of the V-trace correction — plus the
            # behavior value (the learner's value-drift-across-lag
            # diagnostic, and it keeps the value head LIVE in the actor
            # program so jit input pruning cannot renumber the donated
            # leaves the T2 audit pins). The heads always emit f32
            # (models/a3c.py), so both stay f32 even under a bf16
            # rollout-forward snapshot.
            lp = jnp.take_along_axis(
                jax.nn.log_softmax(out.logits, axis=-1),
                actions[:, None], axis=-1,
            )[:, 0]
            ys = ys + (lp, out.value)
        return (env_state, new_stack, key, ep_ret, ep_cnt, ep_sum), ys

    return rollout_body


def make_put_batched(batched: "NamedSharding"):
    """Host array (GLOBAL shape) -> array sharded on the data axis.

    Multi-host: every process builds the identical global state (same
    PRNG seed) and contributes its host-major row block — the mesh's
    data axis is laid out host-major (parallel/distributed.py), so the
    local rows are exactly this process's slice. Shared by the fused and
    overlap steps so their multi-host placement cannot drift."""

    def _put_batched(x):
        n_proc = jax.process_count()
        if n_proc == 1:
            return jax.device_put(x, batched)
        x = np.asarray(x)
        B = x.shape[0]
        assert B % n_proc == 0, (B, n_proc)
        per = B // n_proc
        k = jax.process_index()
        return jax.make_array_from_process_local_data(
            batched, x[k * per : (k + 1) * per]
        )

    return _put_batched


def create_fused_state(
    rng: jax.Array,
    model: BA3CNet,
    cfg: BA3CConfig,
    optimizer: optax.GradientTransformation,
    env,
    n_envs: int,
    n_shards: int = 1,
) -> FusedState:
    """Build the global fused state (host-side; ``jax.device_put`` it with the
    step's ``state_sharding`` before use)."""
    from distributed_ba3c_tpu.parallel.train_step import create_train_state

    train = create_train_state(rng, model, cfg, optimizer)
    keys = jax.random.split(jax.random.fold_in(rng, 1), n_envs)
    env_state = jax.vmap(env.reset)(keys)
    obs = jax.vmap(env.render)(env_state)  # [B, H, W]
    stack = jnp.zeros((n_envs, *obs.shape[1:], cfg.frame_history), jnp.uint8)
    stack = stack.at[..., -1].set(obs)
    shard_keys = jax.vmap(
        lambda i: jax.random.fold_in(jax.random.fold_in(rng, 2), i)
    )(jnp.arange(n_shards))
    return FusedState(
        train=train,
        env_state=env_state,
        obs_stack=stack,
        key=shard_keys,
        ep_return=jnp.zeros(n_envs, jnp.float32),
        ep_count=jnp.zeros(n_envs, jnp.int32),
        ep_return_sum=jnp.zeros(n_envs, jnp.float32),
    )


def make_fused_step(
    model: BA3CNet,
    optimizer: optax.GradientTransformation,
    cfg: BA3CConfig,
    mesh: Mesh,
    env,
    rollout_len: int = 20,
    grad_chunk_samples: int = 4096,
    steps_per_dispatch: int = 1,
) -> Callable:
    """Build fn(state, entropy_beta, lr) -> (state, metrics), fully on-device.

    ``grad_chunk_samples`` bounds the per-fwd+bwd batch in the learner (HBM
    activation cap). Measured on the 16 GB v5e (PERF.md): 5120 fits inside
    the full fused program, 10240 OOMs; throughput is flat across 1024-5120
    (the convs' MXU utilization is channel-count-bound, not batch-bound), so
    the default stays comfortably under the cliff.

    ``steps_per_dispatch`` > 1 wraps that many full update steps in one
    ``lax.scan`` inside the jitted program: one host dispatch per K updates.
    At small per-step programs (the flagship 128x20 shape runs ~13 ms of
    device work) the per-dispatch host/tunnel overhead is a real tax unless
    host pipelining hides it; scanning removes the dependence on pipelining
    entirely (PERF.md round 4). β/lr are scan-carried scalars, so one
    dispatch spans only steps sharing a hyperparam setting (the epoch loop
    already changes them per epoch only).
    """

    def local_step(state: FusedState, entropy_beta, learning_rate):
        params = state.train.params
        key = state.key[0]  # this shard's scalar key

        rollout_body = make_rollout_body(model, cfg, env, params)

        carry0 = (
            state.env_state,
            state.obs_stack,
            key,
            state.ep_return,
            state.ep_count,
            state.ep_return_sum,
        )
        (env_state, stack, key, ep_ret, ep_cnt, ep_sum), traj = jax.lax.scan(
            rollout_body, carry0, None, length=rollout_len
        )
        states_t, actions_t, rewards_t, dones_t = traj  # [T, B, ...]

        # bootstrap from the post-rollout stack (no gradient)
        bootstrap = model.apply({"params": params}, stack).value
        returns_t = n_step_returns(
            rewards_t, dones_t, jax.lax.stop_gradient(bootstrap), cfg.gamma
        )

        T, B = actions_t.shape

        # Learner: fwd+bwd over the FLAT [T*B] batch in as few chunks as HBM
        # allows. Profile-driven (see PERF.md): at B=1024 per-timestep chunks
        # ran the convs at ~30% MFU (180.7ms) while one flat 20480-sample
        # fwd+bwd hit ~80% MFU (69.0ms) on a v5e — batch size per matmul is
        # the whole game. Chunking (equal sizes) only bounds activation
        # memory; mean-of-chunk-grads equals the full-batch gradient.
        def chunk_grad(p, chunk):
            states_c, actions_c, returns_c = chunk

            def loss_fn(pp):
                out = model.apply({"params": pp}, states_c)
                loss = a3c_loss(
                    out.logits,
                    out.value,
                    actions_c,
                    returns_c,
                    entropy_beta=entropy_beta,
                    value_loss_coef=cfg.value_loss_coef,
                    huber_delta=cfg.value_huber_delta,
                )
                return loss.total, loss

            return jax.value_and_grad(loss_fn, has_aux=True)(p)

        flat = lambda x: x.reshape(T * B, *x.shape[2:])  # noqa: E731
        states_f, actions_f, returns_f = (
            flat(states_t),
            flat(actions_t),
            flat(returns_t),
        )
        n_chunks = max(1, -(-(T * B) // grad_chunk_samples))
        while (T * B) % n_chunks:
            n_chunks += 1
        if n_chunks == 1:
            (_, aux), grads = chunk_grad(
                params, (states_f, actions_f, returns_f)
            )
        else:
            C = (T * B) // n_chunks
            chunked = lambda x: x.reshape(n_chunks, C, *x.shape[1:])  # noqa: E731

            def acc_body(carry, chunk):
                g_acc, aux_acc = carry
                (_, aux), g = chunk_grad(params, chunk)
                g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                aux_acc = jax.tree_util.tree_map(jnp.add, aux_acc, aux)
                return (g_acc, aux_acc), None

            (_, aux0), g0 = chunk_grad(
                params,
                (chunked(states_f)[0], chunked(actions_f)[0], chunked(returns_f)[0]),
            )
            (grads, aux_sum), _ = jax.lax.scan(
                acc_body,
                (g0, aux0),
                (
                    chunked(states_f)[1:],
                    chunked(actions_f)[1:],
                    chunked(returns_f)[1:],
                ),
            )
            grads = jax.tree_util.tree_map(lambda g: g / n_chunks, grads)
            aux = jax.tree_util.tree_map(lambda a: a / n_chunks, aux_sum)
        grads = grad_allreduce(grads, DATA_AXIS)
        n_data = axis_size(DATA_AXIS)
        grads = jax.tree_util.tree_map(lambda g: g / n_data, grads)

        opt_state = inject_learning_rate(state.train.opt_state, learning_rate)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)

        new_state = FusedState(
            train=TrainState(
                step=state.train.step + 1,
                params=new_params,
                opt_state=new_opt_state,
            ),
            env_state=env_state,
            obs_stack=stack,
            key=key[None],
            ep_return=ep_ret,
            ep_count=ep_cnt,
            ep_return_sum=ep_sum,
        )
        metrics = {
            "loss": aux.total,
            "policy_loss": aux.policy_loss,
            "value_loss": aux.value_loss,
            "entropy": aux.entropy,
            "pred_value": aux.pred_value,
            **grad_summaries(grads),
            "reward_per_step": jnp.mean(rewards_t),
        }
        metrics = {k: jax.lax.pmean(v, DATA_AXIS) for k, v in metrics.items()}
        # cumulative-in-state metrics MUST be listed in CUMULATIVE_METRICS:
        # that's what tells the K>1 scan reduction to take the last value
        # instead of the window mean (ADVICE r4 #3)
        metrics["episodes"] = jax.lax.psum(jnp.sum(ep_cnt), DATA_AXIS)
        metrics["episode_return_sum"] = jax.lax.psum(jnp.sum(ep_sum), DATA_AXIS)
        assert set(CUMULATIVE_METRICS) <= set(metrics)
        return new_state, metrics

    def multi_step(state: FusedState, entropy_beta, learning_rate):
        if steps_per_dispatch == 1:
            return local_step(state, entropy_beta, learning_rate)

        def body(s, _):
            return local_step(s, entropy_beta, learning_rate)

        state, ms = jax.lax.scan(body, state, None, length=steps_per_dispatch)
        # cumulative-in-state metrics (reset once per epoch by the outer
        # loop): the LAST step's psum is "so far"; loss-like metrics
        # average over the dispatch window
        metrics = {
            k: (v[-1] if k in CUMULATIVE_METRICS else jnp.mean(v, axis=0))
            for k, v in ms.items()
        }
        return state, metrics

    batch_spec = P(DATA_AXIS)
    env_state_struct = jax.eval_shape(env.reset, jax.random.PRNGKey(0))
    # pytree-prefix specs: train=P() replicates the whole TrainState subtree
    state_specs = FusedState(
        train=P(),
        env_state=jax.tree_util.tree_map(lambda _: batch_spec, env_state_struct),
        obs_stack=batch_spec,
        key=P(DATA_AXIS),
        ep_return=batch_spec,
        ep_count=batch_spec,
        ep_return_sum=batch_spec,
    )

    sharded = shard_map(
        multi_step,
        mesh=mesh,
        in_specs=(state_specs, P(), P()),
        out_specs=(state_specs, P()),
    )
    # registered audit entry point (distributed_ba3c_tpu/audit.py)
    jitted = tripwire_jit("fused.step", sharded, donate_argnums=(0,))

    def step(state, entropy_beta, learning_rate=None):
        if learning_rate is None:
            learning_rate = cfg.learning_rate
        return jitted(
            state,
            jnp.asarray(entropy_beta, jnp.float32),
            jnp.asarray(learning_rate, jnp.float32),
        )

    replicated = NamedSharding(mesh, P())
    batched = NamedSharding(mesh, batch_spec)
    _put_batched = make_put_batched(batched)

    def put(state: FusedState) -> FusedState:
        """device_put a host FusedState with the step's shardings."""
        return FusedState(
            train=jax.device_put(state.train, replicated),
            env_state=jax.tree_util.tree_map(_put_batched, state.env_state),
            obs_stack=_put_batched(state.obs_stack),
            key=_put_batched(state.key),
            ep_return=_put_batched(state.ep_return),
            ep_count=_put_batched(state.ep_count),
            ep_return_sum=_put_batched(state.ep_return_sum),
        )

    def reset_episode_stats(state: FusedState, n_envs: int) -> FusedState:
        """Zero the per-env episode accumulators for the next epoch window.

        A step-provided hook because the overlap step keeps these fields
        inside its ActorState (fused/overlap.py) — the epoch loop calls the
        hook instead of reaching into the state layout."""
        return state.replace(
            ep_count=_put_batched(jnp.zeros(n_envs, jnp.int32)),
            ep_return_sum=_put_batched(jnp.zeros(n_envs, jnp.float32)),
        )

    step.put = put
    step.put_batched = _put_batched
    step.replicated_sharding = replicated
    step.batch_sharding = batched
    step.mesh = mesh
    step.rollout_len = rollout_len
    step.steps_per_dispatch = steps_per_dispatch
    step.reset_episode_stats = reset_episode_stats
    step.audit_jit = jitted  # tools/ba3caudit traces THIS program
    return step


def make_greedy_eval(
    model: BA3CNet,
    cfg: BA3CConfig,
    mesh: Mesh,
    env,
    n_envs: int,
    max_steps: int = 3000,
) -> Callable:
    """Build fn(params, key) -> (mean_return, max_return, n_episodes).

    The fused trainer's Evaluator (reference ``Evaluator``/``eval_with_funcs``,
    SURVEY.md §3.5): greedy (argmax) episodes, fully on-device — fresh envs
    roll in lockstep under one jit; each env contributes its FIRST completed
    episode so long-running envs don't bias the mean toward short episodes.
    """

    def local_eval(params, seed):
        B = n_envs // mesh.shape[DATA_AXIS]
        # per-shard stream from a replicated seed: axis_index-folding keeps
        # this multi-host safe (no host-side sharded key array to assemble)
        key = jax.random.fold_in(
            jax.random.PRNGKey(seed), jax.lax.axis_index(DATA_AXIS)
        )
        k_reset, key = jax.random.split(key)
        env_state = jax.vmap(env.reset)(jax.random.split(k_reset, B))
        # reset() fields built from constants are axis-INVARIANT under
        # shard_map until the first data-dependent step, which breaks the
        # env's internal scan carries — mark the whole state varying up front
        # (identity on old jax, where check_rep=False tracks no rep types)
        def _to_varying(x):
            return to_varying(x, DATA_AXIS)

        env_state = jax.tree_util.tree_map(_to_varying, env_state)
        obs = jax.vmap(env.render)(env_state)
        stack = jnp.zeros((B, *obs.shape[1:], cfg.frame_history), jnp.uint8)
        stack = stack.at[..., -1].set(obs)

        def body(carry, _):
            env_state, stack, key, ep_ret, done_ret, done_mask = carry
            out = model.apply({"params": params}, stack)
            actions = jnp.argmax(out.logits, axis=-1).astype(jnp.int32)
            key, k_env = jax.random.split(key)
            env_state, obs, reward, done = jax.vmap(env.step)(
                env_state, actions, jax.random.split(k_env, B)
            )
            ep_ret = ep_ret + reward
            first_done = done & ~done_mask
            done_ret = jnp.where(first_done, ep_ret, done_ret)
            done_mask = done_mask | done
            ep_ret = ep_ret * (1.0 - done.astype(jnp.float32))
            keep = (~done).astype(stack.dtype)[:, None, None, None]
            stack = jnp.concatenate([stack[..., 1:] * keep, obs[..., None]], -1)
            return (env_state, stack, key, ep_ret, done_ret, done_mask), None

        carry0 = (
            env_state,
            stack,
            key,
            _to_varying(jnp.zeros(B, jnp.float32)),
            _to_varying(jnp.zeros(B, jnp.float32)),
            _to_varying(jnp.zeros(B, bool)),
        )
        (_, _, _, _, done_ret, done_mask), _ = jax.lax.scan(
            body, carry0, None, length=max_steps
        )
        n = jax.lax.psum(jnp.sum(done_mask.astype(jnp.int32)), DATA_AXIS)
        s = jax.lax.psum(jnp.sum(jnp.where(done_mask, done_ret, 0.0)), DATA_AXIS)
        mx = jax.lax.pmax(
            jnp.max(jnp.where(done_mask, done_ret, -jnp.inf)), DATA_AXIS
        )
        return s / jnp.maximum(n, 1), mx, n

    sharded = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=(P(), P(), P()),
    )
    # registered audit entry point (distributed_ba3c_tpu/audit.py)
    jitted = tripwire_jit("fused.greedy_eval", sharded)

    def evaluate(params, seed):
        """``seed``: int (preferred) — PRNGKey arrays are coerced."""
        arr = np.asarray(
            jax.random.key_data(seed)
            if jnp.issubdtype(getattr(seed, "dtype", np.int32), jax.dtypes.prng_key)
            else seed
        )
        if arr.ndim:
            arr = arr.reshape(-1)[-1]
        mean, mx, n = jitted(params, jnp.uint32(arr))
        return float(mean), float(mx), int(n)

    evaluate.audit_jit = jitted  # tools/ba3caudit traces THIS program
    return evaluate


def run_fused_training(args, cfg: BA3CConfig, model, optimizer) -> int:
    """CLI driver for --trainer=tpu_fused_ba3c (env must be jax:<name>)."""
    from distributed_ba3c_tpu.envs import jaxenv
    from distributed_ba3c_tpu.parallel.mesh import make_mesh
    from distributed_ba3c_tpu.train.checkpoint import CheckpointManager
    from distributed_ba3c_tpu.utils import logger
    from distributed_ba3c_tpu.utils.stats import StatHolder

    if not args.env.startswith("jax:"):
        raise SystemExit("--trainer=tpu_fused_ba3c requires --env jax:<name>")
    env = jaxenv.get_env(args.env.split(":", 1)[1])
    cfg = cfg.replace(num_actions=env.num_actions)
    model = dataclasses.replace(model, num_actions=env.num_actions)

    if jax.process_count() > 1:
        # multi-host: global host-major mesh; every process runs this loop
        # in lockstep (the psum inside the step synchronizes the update)
        from distributed_ba3c_tpu.parallel.distributed import make_global_mesh

        mesh = make_global_mesh(num_model=1)
    else:
        mesh = make_mesh(num_data=args.mesh_data, num_model=1)
    n_data = mesh.shape[DATA_AXIS]
    rollout_len = args.rollout_len
    envs_per_device = max(1, cfg.batch_size // rollout_len)
    n_envs = envs_per_device * n_data
    k_dispatch = max(1, getattr(args, "steps_per_dispatch", 1))
    if args.steps_per_epoch % k_dispatch:
        raise SystemExit(
            f"--steps_per_dispatch {k_dispatch} must divide "
            f"--steps_per_epoch {args.steps_per_epoch}"
        )
    fleet_accum = max(1, getattr(args, "fleet_accum", 1) or 1)
    # state BEFORE the step build: the int8 rung's pre-training env
    # calibration needs the run's actual starting params (restored ones
    # on a resume — calibrating against re-initialized weights would
    # freeze scales for a policy the actor never plays)
    state = create_fused_state(
        jax.random.PRNGKey(getattr(args, "seed", 0) or 0),
        model, cfg, optimizer, env, n_envs, n_shards=n_data,
    )
    if args.load:
        mgr = CheckpointManager(args.load)
        restored = mgr.restore(jax.device_get(state.train))
        state = state.replace(train=restored)
        logger.info("resumed train state at step %d", int(restored.step))
    rollout_dtype = getattr(args, "rollout_dtype", "float32")
    quant_spec = None
    if rollout_dtype == "int8":
        # calibration source resolution (cli.py/TopologySpec validated
        # exactly-one-of): a frozen spec file, or N offline env-rollout
        # windows through the same scan body the actor program runs
        from distributed_ba3c_tpu.quantize import QuantSpec, calibrate_from_env

        if getattr(args, "quant_spec", None):
            quant_spec = QuantSpec.load(args.quant_spec)
        else:
            quant_spec = calibrate_from_env(
                model, cfg, env, state.train.params,
                jax.random.PRNGKey(getattr(args, "seed", 0) or 0),
                n_envs=n_envs,
                batches=int(getattr(args, "quant_calibrate", 0) or 0),
                rollout_len=rollout_len,
            )
        logger.info(
            "int8 rollout forward: quant spec %s (%d calibration batches)",
            quant_spec.sha256()[:12], quant_spec.calibration_batches,
        )
    if getattr(args, "overlap", False):
        # two overlapped compiled programs (rollout k+1 concurrent with
        # learner k, lag-1 V-trace correction) instead of the single fused
        # program — docs/overlap.md. --fleet_accum K adds the macro
        # learner: K rollout windows ("fleets") accumulated into ONE
        # update (docs/actor_plane.md multi-fleet macro-batching)
        from distributed_ba3c_tpu.fused.overlap import make_overlap_step

        step = make_overlap_step(
            model, optimizer, cfg, mesh, env, rollout_len,
            grad_chunk_samples=args.grad_chunk_samples,
            steps_per_dispatch=k_dispatch,
            rollout_dtype=rollout_dtype,
            macro_fleets=fleet_accum,
            quant_spec=quant_spec,
        )
    else:
        step = make_fused_step(
            model, optimizer, cfg, mesh, env, rollout_len,
            grad_chunk_samples=args.grad_chunk_samples,
            steps_per_dispatch=k_dispatch,
        )
    run_shape = {
        "steps_per_epoch": args.steps_per_epoch,
        "batch_size": cfg.batch_size,
        "rollout_len": rollout_len,
        "max_epoch": args.max_epoch,
    }
    shape_mismatch = False
    if args.load:
        # schedule-shape guard: the resumed epoch counter is
        # step // steps_per_epoch, so a different shape silently stretches
        # or shifts the anneal — warn loudly when the shapes disagree
        prev = mgr.read_run_meta()
        for k, v in run_shape.items():
            if k in prev and prev[k] != v:
                shape_mismatch = True
                logger.warn(
                    "resume shape mismatch: %s was %s at save time, now %s — "
                    "the LR/beta anneal will NOT continue where it left off",
                    k, prev[k], v,
                )
    state = step.put(state)

    holder = StatHolder(args.logdir)
    # one SHARED checkpoint dir across hosts (orbax saves are collective)
    ckpt = CheckpointManager(
        getattr(args, "shared_ckpt_dir", None) or f"{args.logdir}/checkpoints",
        max_to_keep=getattr(args, "max_to_keep", 3),
    )
    if not shape_mismatch:
        # on a MISMATCHED resume, keep the original shape on record so the
        # warning keeps firing on every later resume (overwriting here
        # would mute the guard after its first catch)
        ckpt.write_run_meta(**run_shape)
    logger.set_logger_dir(args.logdir)
    # each update consumes fleet_accum rollout windows: the fps/samples
    # account must bill every env-step or the rate under-reports K-fold
    samples_per_iter = n_envs * rollout_len * fleet_accum
    logger.info(
        "fused training: %d envs x %d rollout x %d accum windows = "
        "%d samples/iter on %d devices",
        n_envs,
        rollout_len,
        fleet_accum,
        samples_per_iter,
        n_data,
    )

    # runtime-scheduled hyperparams (reference ScheduledHyperParamSetter
    # semantics): anneal over epochs when *_final flags are given. --anneal
    # exp interpolates geometrically — it reaches the low-β/low-lr regime
    # (where Pong's endgame learning happens) in half the epochs a linear
    # ramp spends at plateau values.
    def sched(v0, v1, epoch, mode=None):
        if v1 is None or args.max_epoch <= 1:
            return v0
        from distributed_ba3c_tpu.train.callbacks import anneal_interp

        f = (epoch - 1) / (args.max_epoch - 1)
        return anneal_interp(
            v0, v1, f, mode or getattr(args, "anneal", "linear")
        )

    # greedy on-device Evaluator (reference Evaluator, SURVEY.md §3.5):
    # nr_eval envs rounded up to the mesh's data axis
    n_eval = max(n_data, (max(args.nr_eval, 1) + n_data - 1) // n_data * n_data)
    evaluate = make_greedy_eval(
        model, cfg, mesh, env, n_eval, max_steps=args.eval_max_steps
    )

    # telemetry scrape endpoint (docs/observability.md): the fused loop has
    # no actor plane, but its learner counters + flight ring are still the
    # run's live view (--telemetry_port)
    from distributed_ba3c_tpu import telemetry

    tele_server = None
    if getattr(args, "telemetry_port", 0):
        tele_server = telemetry.TelemetryServer(args.telemetry_port)
        tele_server.start()
    try:
        _fused_epoch_loop(
            args, cfg, step, state, holder, ckpt, samples_per_iter,
            n_envs, sched, evaluate,
        )
    finally:
        if tele_server is not None:
            tele_server.stop()
            tele_server.join(timeout=2)
            tele_server.close()
        holder.close()
    return 0


def _fused_epoch_loop(
    args, cfg, step, state, holder, ckpt, samples_per_iter, n_envs, sched,
    evaluate,
):
    from distributed_ba3c_tpu.utils import logger

    # Resume CONTINUES the schedule: the epoch counter derives from the
    # restored global step, so a stall-kill + --load (run_with_resume.sh)
    # picks up the anneal where it left off instead of restarting it —
    # --max_epoch is the run's TOTAL epoch budget across resumes.
    epoch0 = int(state.train.step) // max(args.steps_per_epoch, 1)
    if epoch0 > 0:
        logger.info(
            "resume: continuing at epoch %d/%d (restored step %d)",
            epoch0 + 1, args.max_epoch, int(state.train.step),
        )
    if epoch0 >= args.max_epoch:
        # a warm-start fine-tune wants a FRESH logdir (the anneal maps over
        # epochs 1..max_epoch of the loaded step count); loud, not silent
        logger.warn(
            "loaded step %d already covers --max_epoch %d x %d steps: "
            "nothing to train (raise --max_epoch to extend the run)",
            int(state.train.step), args.max_epoch, args.steps_per_epoch,
        )
    # live hyperparam overrides (reference HumanHyperParamSetter, SURVEY
    # §2.7 #21): the CHIEF reads <base_logdir>/hyper.txt each epoch and the
    # values are broadcast — per-rank file reads could race a mid-run edit
    # and silently diverge the psum'd update, so only the chief's read counts
    hyper_dir = getattr(args, "shared_hyper_dir", None) or args.logdir
    hyper_path = os.path.join(hyper_dir, "hyper.txt") if hyper_dir else None

    def live_hyper(lr, beta):
        if hyper_path is not None and jax.process_index() == 0:
            from distributed_ba3c_tpu.train.callbacks import read_hyper_file

            overrides = read_hyper_file(hyper_path)
            lr = overrides.get("learning_rate", lr)
            beta = overrides.get("entropy_beta", beta)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            lr, beta = multihost_utils.broadcast_one_to_all(
                np.asarray([lr, beta], np.float32)
            ).tolist()
        return lr, beta

    beta_mode = getattr(args, "anneal_beta", None)
    lr_mode = getattr(args, "anneal_lr", None)
    # rank-failure detection (SURVEY §5): in multi-host runs a dead peer
    # wedges this rank in the next psum/save barrier forever — the watchdog
    # turns that undefined hang into a bounded-time nonzero exit so the
    # launcher can relaunch every rank with --load on the shared checkpoints
    from distributed_ba3c_tpu.parallel.watchdog import (
        LockstepWatchdog,
        resolve_timeout,
    )

    with LockstepWatchdog(
        resolve_timeout(getattr(args, "rank_stall_timeout", 0)),
        what=f"rank {jax.process_index()}/{jax.process_count()} epoch loop",
    ) as watchdog:
        _fused_epoch_body(
            args, cfg, step, state, holder, ckpt, samples_per_iter, n_envs,
            sched, evaluate, epoch0, live_hyper, beta_mode, lr_mode, watchdog,
        )


def _fused_epoch_body(
    args, cfg, step, state, holder, ckpt, samples_per_iter, n_envs, sched,
    evaluate, epoch0, live_hyper, beta_mode, lr_mode, watchdog,
):
    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.utils import logger

    tele = telemetry.registry("learner")
    c_steps = tele.counter("train_steps_total")
    c_samples = tele.counter("train_samples_total")
    c_episodes = tele.counter("episodes_total")
    h_epoch = tele.histogram("epoch_s", unit=1e-3)
    best = -np.inf
    first_eval_done = False
    for epoch in range(epoch0 + 1, args.max_epoch + 1):
        beta = sched(cfg.entropy_beta, args.entropy_beta_final, epoch, beta_mode)
        lr = sched(cfg.learning_rate, args.learning_rate_final, epoch, lr_mode)
        lr, beta = live_hyper(lr, beta)
        t0 = time.monotonic()
        metrics = None
        for _ in range(args.steps_per_epoch // step.steps_per_dispatch):
            state, metrics = step(state, beta, lr)
        metrics = {k: float(v) for k, v in metrics.items()}
        # the fetch above forced every dispatch's collectives to completion:
        # proven progress — don't charge the upcoming eval/save to the
        # compute window's stall budget
        watchdog.beat()
        dt = time.monotonic() - t0
        fps = args.steps_per_epoch * samples_per_iter / dt
        # one batched account per epoch window (the loop's own dispatch
        # cadence) — scrape-visible progress without per-step host syncs
        c_steps.inc(args.steps_per_epoch)
        c_samples.inc(args.steps_per_epoch * samples_per_iter)
        c_episodes.inc(int(metrics["episodes"]))
        h_epoch.observe(dt)
        mean_ret = (
            metrics["episode_return_sum"] / metrics["episodes"]
            if metrics["episodes"] > 0
            else float("nan")
        )
        # reset the per-env episode accumulators for the next window
        # (step-provided hook: the fused and overlap steps keep these
        # fields in different state layouts)
        state = step.reset_episode_stats(state, n_envs)
        if os.environ.get("BA3C_PARAM_DIGEST"):
            # divergence detector for multi-host runs: ranks log this line
            # per epoch; any mismatch across ranks means the psum'd update
            # broke lockstep (costs a params device_get — debug only)
            leaves = jax.tree_util.tree_leaves(
                # epoch-boundary debug fetch, explicitly opt-in and costed
                # in the comment above — not a per-step sync
                jax.device_get(state.train.params)  # ba3clint: disable=J1
            )
            logger.info(
                "param_digest %s",
                " ".join(f"{np.float64(np.sum(l)):.10e}" for l in leaves),
            )
        # greedy eval — the number the north-star (Pong >= 18) is defined on
        eval_mean = float("nan")
        if epoch % max(args.eval_every, 1) == 0:
            if not first_eval_done:
                # the first eval window includes the eval program's XLA
                # compile — give it the same grace as the first train
                # compile or a tightly-sized timeout 75-loops right here
                watchdog.grace()
                first_eval_done = True
            eval_mean, eval_max, eval_n = evaluate(
                state.train.params, 1000 + epoch
            )
            if eval_n > 0:
                holder.add_stat("eval_mean_score", eval_mean)
                holder.add_stat("eval_max_score", eval_max)
            else:
                # no episode finished inside the eval horizon (long rallies):
                # 0/1 would masquerade as a real score — report nothing
                eval_mean = float("nan")
            # eval done: a slow 128-episode eval must not eat into the
            # save window's stall budget
            watchdog.beat()
        holder.add_stat("epoch", epoch)
        holder.add_stat("global_step", int(state.train.step))
        holder.add_stat("fps", fps)
        if np.isfinite(mean_ret):
            holder.add_stat("mean_score", mean_ret)
        if metrics["episodes"] > 0:
            # approximate mean episode length: every env-step this epoch is
            # a training step, so samples/episodes ≈ ep length (the timid-
            # policy regression signature is this number climbing while
            # eval falls — CoinRun diagnosis, BASELINE config #5)
            holder.add_stat(
                "ep_len_approx",
                args.steps_per_epoch * samples_per_iter / metrics["episodes"],
            )
        for k in ("loss", "policy_loss", "value_loss", "entropy", "grad_norm"):
            holder.add_stat(k, metrics[k])
        for k in ("mean_rho", "value_lag_mae"):
            # overlap-mode series (fused/overlap.py): how hard V-trace is
            # clipping and how far the value fn moved across the lag
            if k in metrics:
                holder.add_stat(k, metrics[k])
        if telemetry.enabled():
            # same series the scrape endpoint serves, into stat.json/TB
            holder.add_stats(telemetry.export_scalars(roles=("learner",)))
        holder.finalize()
        logger.info(
            "epoch %d | env-steps/s %.0f | mean_score %.2f (%d eps) | eval %.2f | loss %.4f entropy %.3f",
            epoch,
            fps,
            mean_ret,
            int(metrics["episodes"]),
            eval_mean,
            metrics["loss"],
            metrics["entropy"],
        )
        # epoch-boundary checkpoint: the fetch is the save's payload, once
        # per epoch — not a per-step sync
        ckpt.save(jax.device_get(state.train), int(state.train.step))  # ba3clint: disable=J1
        telemetry.record("checkpoint", step=int(state.train.step))
        # keep-best on GREEDY EVAL (not training-policy returns): the
        # reference's MaxSaver tracked the Evaluator's number
        if np.isfinite(eval_mean) and eval_mean > best:
            best = eval_mean
            ckpt.mark_best(int(state.train.step), eval_mean)
        # global progress proven (metrics fetched + collective save done):
        # re-arm the rank-failure watchdog for the next epoch
        watchdog.beat()
