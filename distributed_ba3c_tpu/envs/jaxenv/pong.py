"""Pure-JAX Pong: ALE-Pong-compatible scoring on TPU-friendly physics.

Game rules match Atari Pong's reward structure so the reference's headline
benchmark ("Pong solved at mean score >= 18", BASELINE.md) transfers: a match
is first-to-21 points, reward +1 when the (right, agent) paddle scores, -1
when the scripted left opponent scores, episode return in [-21, 21], done
when either side reaches 21.

Action set mirrors ALE Pong's 6-action space: {0,1} no-op/"fire", {2,4} up,
{3,5} down — so policies and configs transfer between this env, the C++ env
server, and real ALE.

Everything is branch-free jnp (lax.select / masks): one vmap'd step of 4096
envs is a handful of fused elementwise kernels. Physics advances
``frame_skip`` substeps per agent step, matching ALE frameskip=4 semantics
(SURVEY.md §2.9).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

num_actions = 6
obs_shape = (84, 84)

# court geometry (unit square; render maps to 84x84)
PADDLE_H = 0.16
PADDLE_W = 0.02
AGENT_X = 0.95  # right paddle (the learner)
OPP_X = 0.05    # left paddle (scripted)
BALL_R = 0.015
PADDLE_SPEED = 0.05   # per substep
OPP_SPEED = 0.035     # scripted opponent max speed (slower => beatable)
BALL_SPEED = 0.04
WIN_SCORE = 21
FRAME_SKIP = 4


class State(NamedTuple):
    ball_xy: jax.Array    # [2] float32
    ball_v: jax.Array     # [2] float32
    agent_y: jax.Array    # [] float32
    opp_y: jax.Array      # [] float32
    agent_score: jax.Array  # [] int32
    opp_score: jax.Array    # [] int32
    t: jax.Array            # [] int32 steps in episode


def _serve(key: jax.Array, towards_agent: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Ball at center, random angle, horizontal direction per the server."""
    k1, k2 = jax.random.split(key)
    angle = jax.random.uniform(k1, (), minval=-0.7, maxval=0.7)
    vy = BALL_SPEED * jnp.sin(angle)
    vx = BALL_SPEED * jnp.cos(angle) * jnp.where(towards_agent, 1.0, -1.0)
    jitter = jax.random.uniform(k2, (), minval=-0.1, maxval=0.1)
    return jnp.array([0.5, 0.5 + jitter]), jnp.stack([vx, vy])


def reset(key: jax.Array) -> State:
    xy, v = _serve(key, jnp.bool_(True))
    return State(
        ball_xy=xy,
        ball_v=v,
        agent_y=jnp.float32(0.5),
        opp_y=jnp.float32(0.5),
        agent_score=jnp.int32(0),
        opp_score=jnp.int32(0),
        t=jnp.int32(0),
    )


def _substep(state: State, move: jax.Array, key: jax.Array) -> Tuple[State, jax.Array]:
    """One physics tick. move in {-1,0,+1}. Returns (state, point_reward)."""
    # paddles
    agent_y = jnp.clip(state.agent_y + move * PADDLE_SPEED, PADDLE_H / 2, 1 - PADDLE_H / 2)
    opp_dy = jnp.clip(state.ball_xy[1] - state.opp_y, -OPP_SPEED, OPP_SPEED)
    opp_y = jnp.clip(state.opp_y + opp_dy, PADDLE_H / 2, 1 - PADDLE_H / 2)

    # ball advance
    xy = state.ball_xy + state.ball_v
    v = state.ball_v

    # wall bounce (top/bottom)
    hit_wall = (xy[1] < BALL_R) | (xy[1] > 1 - BALL_R)
    v = v.at[1].set(jnp.where(hit_wall, -v[1], v[1]))
    xy = xy.at[1].set(jnp.clip(xy[1], BALL_R, 1 - BALL_R))

    # paddle bounce: crossing the paddle plane while vertically aligned
    def paddle_bounce(xy, v, paddle_x, paddle_y, moving_right):
        crossing = jnp.where(
            moving_right, xy[0] >= paddle_x - PADDLE_W, xy[0] <= paddle_x + PADDLE_W
        )
        aligned = jnp.abs(xy[1] - paddle_y) <= PADDLE_H / 2 + BALL_R
        hit = crossing & aligned & jnp.where(moving_right, v[0] > 0, v[0] < 0)
        # deflection angle scales with contact offset (classic Pong control)
        offset = (xy[1] - paddle_y) / (PADDLE_H / 2)
        new_vx = jnp.where(hit, -v[0], v[0])
        new_vy = jnp.where(hit, BALL_SPEED * 0.9 * offset, v[1])
        new_x = jnp.where(
            hit,
            jnp.where(moving_right, paddle_x - PADDLE_W - BALL_R, paddle_x + PADDLE_W + BALL_R),
            xy[0],
        )
        return xy.at[0].set(new_x), jnp.stack([new_vx, new_vy]), hit

    xy, v, _ = paddle_bounce(xy, v, AGENT_X, agent_y, jnp.bool_(True))
    xy, v, _ = paddle_bounce(xy, v, OPP_X, opp_y, jnp.bool_(False))

    # scoring: ball passes an end wall
    agent_point = xy[0] <= 0.0   # opponent missed
    opp_point = xy[0] >= 1.0     # agent missed
    scored = agent_point | opp_point
    reward = jnp.where(agent_point, 1.0, jnp.where(opp_point, -1.0, 0.0))

    # re-serve after a point (loser serves toward the scorer, like ALE)
    serve_xy, serve_v = _serve(key, towards_agent=opp_point)
    xy = jnp.where(scored, serve_xy, xy)
    v = jnp.where(scored, serve_v, v)

    return (
        State(
            ball_xy=xy,
            ball_v=v,
            agent_y=agent_y,
            opp_y=opp_y,
            agent_score=state.agent_score + agent_point.astype(jnp.int32),
            opp_score=state.opp_score + opp_point.astype(jnp.int32),
            t=state.t,
        ),
        reward,
    )


def _action_to_move(action: jax.Array) -> jax.Array:
    """ALE 6-action map: 2/4 -> up (-y), 3/5 -> down (+y), else hold."""
    up = (action == 2) | (action == 4)
    down = (action == 3) | (action == 5)
    return jnp.where(up, -1.0, jnp.where(down, 1.0, 0.0))


def step(state: State, action: jax.Array, key: jax.Array) -> Tuple[State, jax.Array, jax.Array, jax.Array]:
    """One agent step = FRAME_SKIP physics substeps (ALE frameskip parity).

    Returns (state, obs uint8 [84,84], reward float32, done bool); the episode
    auto-restarts when either side reaches WIN_SCORE.
    """
    move = _action_to_move(action)
    keys = jax.random.split(key, FRAME_SKIP + 1)

    def body(carry, k):
        st, acc = carry
        st, r = _substep(st, move, k)
        return (st, acc + r), None

    # accumulator derived from state so it inherits the same sharding/varying
    # axes as the carry under shard_map (a literal 0.0 would be invariant)
    zero = state.ball_xy[0] * 0.0
    (state, reward), _ = jax.lax.scan(body, (state, zero), keys[:FRAME_SKIP])
    state = state._replace(t=state.t + 1)

    done = (state.agent_score >= WIN_SCORE) | (state.opp_score >= WIN_SCORE)
    fresh = reset(keys[FRAME_SKIP])
    state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(done, new, old), fresh, state
    )
    return state, render(state), reward, done


def render(state: State) -> jax.Array:
    """Rasterize to uint8 [84, 84] (rows = y, cols = x). Pure masks, no loops."""
    h, w = obs_shape
    ys = (jnp.arange(h, dtype=jnp.float32) + 0.5) / h
    xs = (jnp.arange(w, dtype=jnp.float32) + 0.5) / w
    Y = ys[:, None]
    X = xs[None, :]

    def rect(cx, cy, half_w, half_h):
        return (jnp.abs(X - cx) <= half_w) & (jnp.abs(Y - cy) <= half_h)

    ball = rect(state.ball_xy[0], state.ball_xy[1], BALL_R, BALL_R)
    agent = rect(AGENT_X, state.agent_y, PADDLE_W, PADDLE_H / 2)
    opp = rect(OPP_X, state.opp_y, PADDLE_W, PADDLE_H / 2)
    frame = (ball | agent | opp).astype(jnp.uint8) * 255
    # dim background texture so conv nets see court bounds (walls)
    wall = (Y < 0.02) | (Y > 0.98)
    return jnp.maximum(frame, wall.astype(jnp.uint8) * 80)
