"""Pure-JAX Space Invaders: ALE-compatible reward structure, branch-free.

ALE parity choices (reference game set, BASELINE.md): 6x6 alien grid
marching horizontally and descending a row at each edge hit; row-dependent
points (top row worth most: 30,25,20,15,10,5 — ALE's 5..30 bottom-up);
one player shot in flight at a time; alien bombs; 3 lives; episode ends
when lives run out or the fleet lands. Clearing the fleet spawns a fresh
wave one row lower-start (score keeps accumulating, as in ALE).
Action set: {0}=noop {1}=fire {2}=right {3}=left {4}=right+fire
{5}=left+fire (ALE SpaceInvaders minimal set is 6 actions).

All collision logic is bitmap gather/scatter over the [6, 6] alien grid —
vmap-friendly, no data-dependent branches.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

num_actions = 6
obs_shape = (84, 84)

ROWS, COLS = 6, 6
ALIEN_W = 0.07       # half-extent of an alien cell hitbox (x)
ALIEN_H = 0.03       # half-extent (y)
GRID_DX = 0.11       # horizontal spacing between alien columns
GRID_DY = 0.07       # vertical spacing between alien rows
MARCH_SPEED = 0.004
DESCEND = 0.05
PLAYER_Y = 0.93
PLAYER_W = 0.05
PLAYER_SPEED = 0.03
SHOT_SPEED = 0.05
BOMB_SPEED = 0.025
BOMB_P = 0.06        # per-substep probability a bomb drops
N_BOMBS = 3
LIVES = 3
FRAME_SKIP = 4
MAX_T = 10000

# points by row, TOP row first (ALE: bottom row 5 ... top row 30)
ROW_POINTS = jnp.array([30.0, 25.0, 20.0, 15.0, 10.0, 5.0])


class State(NamedTuple):
    aliens: jax.Array     # [ROWS, COLS] bool
    origin: jax.Array     # [2] top-left alien center (x, y)
    dir: jax.Array        # [] float32 march direction (+1/-1)
    player_x: jax.Array   # []
    shot: jax.Array       # [2] player shot position
    shot_live: jax.Array  # [] bool
    bombs: jax.Array      # [N_BOMBS, 2]
    bombs_live: jax.Array  # [N_BOMBS] bool
    lives: jax.Array      # [] int32
    t: jax.Array          # [] int32


def reset(key: jax.Array) -> State:
    del key
    return State(
        aliens=jnp.ones((ROWS, COLS), bool),
        origin=jnp.array([0.18, 0.12]),
        dir=jnp.float32(1.0),
        player_x=jnp.float32(0.5),
        shot=jnp.zeros(2),
        shot_live=jnp.bool_(False),
        bombs=jnp.zeros((N_BOMBS, 2)),
        bombs_live=jnp.zeros(N_BOMBS, bool),
        lives=jnp.int32(LIVES),
        t=jnp.int32(0),
    )


def _alien_centers(origin: jax.Array):
    """[ROWS, COLS, 2] world positions of every grid cell."""
    cx = origin[0] + jnp.arange(COLS, dtype=jnp.float32) * GRID_DX
    cy = origin[1] + jnp.arange(ROWS, dtype=jnp.float32) * GRID_DY
    return cx, cy


def _substep(state: State, move: jax.Array, fire: jax.Array, key: jax.Array):
    k_bomb, k_col = jax.random.split(key)
    player_x = jnp.clip(
        state.player_x + move * PLAYER_SPEED, PLAYER_W, 1 - PLAYER_W
    )

    # fleet march: speed scales up as the fleet thins (classic cadence)
    n_alive = jnp.sum(state.aliens)
    speed = MARCH_SPEED * (1.0 + 2.0 * (1.0 - n_alive / (ROWS * COLS)))
    cx, cy = _alien_centers(state.origin)
    col_alive = state.aliens.any(axis=0)
    # extreme live columns decide the edge bounce
    left = jnp.min(jnp.where(col_alive, cx, jnp.inf))
    right = jnp.max(jnp.where(col_alive, cx, -jnp.inf))
    hit_edge = ((right + ALIEN_W >= 0.98) & (state.dir > 0)) | (
        (left - ALIEN_W <= 0.02) & (state.dir < 0)
    )
    new_dir = jnp.where(hit_edge, -state.dir, state.dir)
    origin = state.origin + jnp.where(
        hit_edge, jnp.array([0.0, DESCEND]), jnp.array([1.0, 0.0]) * speed * state.dir
    )

    # player shot: launch if idle and firing; fly upward
    launch = fire & ~state.shot_live
    shot = jnp.where(
        launch, jnp.stack([player_x, PLAYER_Y - 0.03]), state.shot
    )
    shot = shot.at[1].add(jnp.where(state.shot_live | launch, -SHOT_SPEED, 0.0))
    shot_live = (state.shot_live | launch) & (shot[1] > 0.0)

    # shot vs fleet. NO dynamic gathers anywhere in this env: per-env scalar
    # indexing (aliens[row, col], cx[col], .at[slot].set) lowers to
    # pathological batched gathers under vmap inside the fused program
    # (measured 6x whole-step slowdown); the uniform grid makes every lookup
    # pure arithmetic and every update a one-hot mask.
    colf = jnp.round((shot[0] - origin[0]) / GRID_DX)
    rowf = jnp.round((shot[1] - origin[1]) / GRID_DY)
    colf = jnp.clip(colf, 0.0, COLS - 1.0)
    rowf = jnp.clip(rowf, 0.0, ROWS - 1.0)
    cx_near = origin[0] + colf * GRID_DX
    cy_near = origin[1] + rowf * GRID_DY
    in_cell = (
        (jnp.abs(cx_near - shot[0]) <= ALIEN_W)
        & (jnp.abs(cy_near - shot[1]) <= ALIEN_H)
        & shot_live
    )
    row_oh = jnp.arange(ROWS) == rowf.astype(jnp.int32)    # [ROWS]
    col_oh = jnp.arange(COLS) == colf.astype(jnp.int32)    # [COLS]
    cell = row_oh[:, None] & col_oh[None, :]               # [ROWS, COLS]
    hit = in_cell & (state.aliens & cell).any()
    reward = jnp.where(hit, jnp.sum(ROW_POINTS * row_oh), 0.0)
    aliens = state.aliens & ~(cell & hit)
    shot_live = shot_live & ~hit

    # bombs: lowest live alien of a random column may drop one
    bomb_col = jax.random.randint(k_bomb, (), 0, COLS)
    bcol_oh = jnp.arange(COLS) == bomb_col                 # [COLS]
    alien_col = (aliens & bcol_oh[None, :]).any(axis=1)    # [ROWS]
    col_has = alien_col.any()
    low_row = jnp.max(jnp.where(alien_col, jnp.arange(ROWS), -1))
    drop = (
        (jax.random.uniform(k_col) < BOMB_P)
        & col_has
        & ~state.bombs_live.all()
    )
    slot_oh = jnp.arange(N_BOMBS) == jnp.argmin(state.bombs_live)
    new_bomb = jnp.stack(
        [
            origin[0] + bomb_col.astype(jnp.float32) * GRID_DX,
            origin[1] + low_row.astype(jnp.float32) * GRID_DY + ALIEN_H,
        ]
    )
    place = slot_oh & drop
    bombs = jnp.where(place[:, None], new_bomb[None, :], state.bombs)
    bombs_live = state.bombs_live | place
    bombs = bombs.at[:, 1].add(jnp.where(bombs_live, BOMB_SPEED, 0.0))

    # bombs vs player
    hit_player = (
        bombs_live
        & (jnp.abs(bombs[:, 0] - player_x) <= PLAYER_W)
        & (bombs[:, 1] >= PLAYER_Y - 0.02)
    )
    lives = state.lives - jnp.any(hit_player).astype(jnp.int32)
    bombs_live = bombs_live & ~hit_player & (bombs[:, 1] < 1.0)

    # fleet landed -> all lives lost (game over); use the POST-march row
    # positions so an edge-descend triggers this substep, matching the C++
    # mirror's ordering
    _, cy_post = _alien_centers(origin)
    landed = jnp.any(
        aliens & ((cy_post[:, None] + ALIEN_H) >= PLAYER_Y - 0.02)
    )
    lives = jnp.where(landed, 0, lives)

    # wave cleared -> fresh fleet, slightly lower start
    cleared = ~aliens.any()
    aliens = jnp.where(cleared, jnp.ones_like(aliens), aliens)
    origin = jnp.where(cleared, jnp.array([0.18, 0.16]), origin)

    return (
        State(
            aliens=aliens,
            origin=origin,
            dir=new_dir,
            player_x=player_x,
            shot=shot,
            shot_live=shot_live,
            bombs=bombs,
            bombs_live=bombs_live,
            lives=lives,
            t=state.t,
        ),
        reward,
    )


def step(state: State, action: jax.Array, key: jax.Array):
    """One agent step = FRAME_SKIP substeps; auto-restarts on done."""
    move = jnp.where(
        (action == 2) | (action == 4),
        1.0,
        jnp.where((action == 3) | (action == 5), -1.0, 0.0),
    )
    fire = (action == 1) | (action == 4) | (action == 5)
    keys = jax.random.split(key, FRAME_SKIP + 1)

    def body(carry, k):
        st, acc = carry
        st, r = _substep(st, move, fire, k)
        return (st, acc + r), None

    zero = state.player_x * 0.0
    (state, reward), _ = jax.lax.scan(body, (state, zero), keys[:FRAME_SKIP])
    state = state._replace(t=state.t + 1)

    done = (state.lives <= 0) | (state.t >= MAX_T)
    fresh = reset(keys[FRAME_SKIP])
    state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(done, new, old), fresh, state
    )
    return state, render(state), reward, done


def render(state: State) -> jax.Array:
    h, w = obs_shape
    ys = (jnp.arange(h, dtype=jnp.float32) + 0.5) / h
    xs = (jnp.arange(w, dtype=jnp.float32) + 0.5) / w
    Y = ys[:, None]
    X = xs[None, :]

    cx, cy = _alien_centers(state.origin)
    # gather-free fleet raster: the indices would depend on the MOVING
    # origin (unlike breakout's static brick grid), and dynamic per-env
    # gathers are pathological under vmap — instead separability gives
    # in_alien = rowhit @ aliens @ colhit^T as two tiny matmuls
    rowhit = (jnp.abs(ys[:, None] - cy[None, :]) <= ALIEN_H)   # [h, ROWS]
    colhit = (jnp.abs(xs[:, None] - cx[None, :]) <= ALIEN_W)   # [w, COLS]
    m = rowhit.astype(jnp.float32) @ state.aliens.astype(jnp.float32)
    in_alien = (m @ colhit.astype(jnp.float32).T) > 0.0        # [h, w]

    player = (jnp.abs(X - state.player_x) <= PLAYER_W) & (
        jnp.abs(Y - PLAYER_Y) <= 0.02
    )
    shot = (
        state.shot_live
        & (jnp.abs(X - state.shot[0]) <= 0.006)
        & (jnp.abs(Y - state.shot[1]) <= 0.015)
    )
    bombs = jnp.zeros_like(player)
    for i in range(N_BOMBS):
        bombs = bombs | (
            state.bombs_live[i]
            & (jnp.abs(X - state.bombs[i, 0]) <= 0.006)
            & (jnp.abs(Y - state.bombs[i, 1]) <= 0.015)
        )
    frame = (player | shot).astype(jnp.uint8) * 255
    frame = jnp.maximum(frame, in_alien.astype(jnp.uint8) * 180)
    frame = jnp.maximum(frame, bombs.astype(jnp.uint8) * 120)
    return frame
