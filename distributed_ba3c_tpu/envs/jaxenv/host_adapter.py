"""Host-side player adapter for the pure-JAX envs.

Lets the on-device envs (envs/jaxenv/) serve the HOST actor plane too — a
SimulatorProcess child or the Evaluator can run `jax:pong` through the same
player protocol as FakeEnv/ALE (envs/base.py).

Backend policy (ADVICE r1): simulator CHILDREN force the CPU platform via the
environment variable before jax is first imported — they must never grab the
(single) TPU. In the TRAINER process (Evaluator / --task eval) the global
platform is NEVER mutated; the env's tiny step is merely pinned to a CPU
device with ``jax.default_device`` so eval cannot flip the trainer's backend
mid-training.
"""

from __future__ import annotations

import multiprocessing
import os

import numpy as np


def _in_child_process() -> bool:
    return multiprocessing.parent_process() is not None


def build_jax_player(idx: int, name: str = "pong", frame_history: int = 4):
    if _in_child_process() and "jax" not in __import__("sys").modules:
        # spawned simulator child: safe to force CPU before jax exists
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if _in_child_process() and jax.default_backend() != "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    from distributed_ba3c_tpu.envs.base import RLEnvironment
    from distributed_ba3c_tpu.envs.jaxenv import get_env
    from distributed_ba3c_tpu.envs.wrappers import HistoryFramePlayer

    env = get_env(name)
    step = jax.jit(env.step)
    # pin the per-step computation to CPU WITHOUT touching global config:
    # one env step is host-scale work; dispatching it to the TPU would
    # serialize against training for no gain.
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        cpu = None

    class _JaxPlayer(RLEnvironment):
        def __init__(self):
            with jax.default_device(cpu):
                self.key = jax.random.PRNGKey(idx)
                self.state = env.reset(self.key)
                self.obs = np.asarray(env.render(self.state))
            self.score = 0.0
            super().__init__()

        def current_state(self):
            return self.obs

        def get_action_space_size(self):
            return env.num_actions

        def action(self, act):
            with jax.default_device(cpu):
                self.key, k = jax.random.split(self.key)
                self.state, obs, r, d = step(self.state, np.int32(act), k)
                self.obs = np.asarray(obs)
            r, d = float(r), bool(d)
            self.score += r
            if d:
                self.finish_episode(self.score)
                self.score = 0.0
            return r, d

        def restart_episode(self):
            with jax.default_device(cpu):
                self.key, k = jax.random.split(self.key)
                self.state = env.reset(k)
                self.obs = np.asarray(env.render(self.state))
            self.score = 0.0

    return HistoryFramePlayer(_JaxPlayer(), frame_history)
