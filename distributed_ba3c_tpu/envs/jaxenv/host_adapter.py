"""Host-side player adapter for the pure-JAX envs.

Lets the on-device envs (envs/jaxenv/) serve the HOST actor plane too — a
SimulatorProcess child or the Evaluator can run `jax:pong` through the same
player protocol as FakeEnv/ALE (envs/base.py). Forces the CPU backend in the
child: simulator children must never grab the (single) TPU.
"""

from __future__ import annotations

import os

import numpy as np


def build_jax_player(idx: int, name: str = "pong", frame_history: int = 4):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    if jax.default_backend() != "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    from distributed_ba3c_tpu.envs.base import RLEnvironment
    from distributed_ba3c_tpu.envs.jaxenv import get_env
    from distributed_ba3c_tpu.envs.wrappers import HistoryFramePlayer

    env = get_env(name)
    step = jax.jit(env.step)

    class _JaxPlayer(RLEnvironment):
        def __init__(self):
            self.key = jax.random.PRNGKey(idx)
            self.state = env.reset(self.key)
            self.obs = np.asarray(env.render(self.state))
            self.score = 0.0
            super().__init__()

        def current_state(self):
            return self.obs

        def get_action_space_size(self):
            return env.num_actions

        def action(self, act):
            self.key, k = jax.random.split(self.key)
            self.state, obs, r, d = step(self.state, np.int32(act), k)
            self.obs = np.asarray(obs)
            r, d = float(r), bool(d)
            self.score += r
            if d:
                self.finish_episode(self.score)
                self.score = 0.0
            return r, d

        def restart_episode(self):
            self.key, k = jax.random.split(self.key)
            self.state = env.reset(k)
            self.obs = np.asarray(env.render(self.state))
            self.score = 0.0

    return HistoryFramePlayer(_JaxPlayer(), frame_history)
