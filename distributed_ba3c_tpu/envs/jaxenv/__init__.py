"""On-device vectorized environments (pure JAX, gymnax-style).

The TPU-native addition the reference never had (SURVEY.md §7 step 10,
BASELINE.json config #5): env physics and rendering as jit/vmap-able pure
functions, so thousands of envs step per device inside the SAME compiled
program as the learner — zero host round-trips, no ZMQ, no pickle, the
whole actor-learner loop is one XLA computation.

Env functional protocol (unbatched; vmap at the call site):
    env.reset(key) -> state                       (pytree of arrays)
    env.step(state, action, key) -> (state, obs uint8 [H,W], reward, done)
    env.num_actions: int
Episodes auto-restart on done (same contract as the host player protocol,
envs/base.py) so rollout scans never branch.

Env-authoring rule (measured, v5e): NO per-env dynamic scalar indexing —
``grid[row, col]``, ``centers[idx]``, ``.at[slot].set`` with traced scalars
become batched dynamic gathers/scatters under vmap and ran the WHOLE fused
step 6x slower (space_invaders, before the rewrite). Use one-hot masks,
uniform-grid arithmetic, or separable mask matmuls instead; gathers with
STATE-INDEPENDENT (constant) index arrays are fine (breakout's brick
raster).
"""

from distributed_ba3c_tpu.envs.jaxenv import (
    assault,
    boxing,
    breakout,
    coinrun,
    pong,
    qbert,
    seaquest,
    space_invaders,
)


def get_env(name: str):
    envs = {
        "pong": pong,
        "breakout": breakout,
        "seaquest": seaquest,
        "qbert": qbert,
        "coinrun": coinrun,
        "space_invaders": space_invaders,
        "boxing": boxing,
        "assault": assault,
    }
    if name not in envs:
        raise ValueError(f"unknown jax env {name!r}; have {sorted(envs)}")
    return envs[name]
