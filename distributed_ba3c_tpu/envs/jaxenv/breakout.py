"""Pure-JAX Breakout: ALE-compatible reward structure on branch-free physics.

Atari-Breakout parity choices (so BASELINE.md's "Breakout to ~300 mean score"
transfers): 6 rows x 18 columns of bricks, row-dependent points
(bottom-up 1,1,4,4,7,7 like ALE), 5 lives, losing the ball costs a life,
clearing the wall re-fills it (ALE continues to a second wall; score caps
around 864), done when lives run out. Action set: {0}=noop {1}=fire
{2}=right {3}=left (ALE Breakout minimal set is 4 actions).

Brick state is a [6, 18] bool bitmap inside the env state — collision and
scoring are pure gather/scatter ops, vmap-friendly.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

num_actions = 4
obs_shape = (84, 84)

ROWS, COLS = 6, 18
BRICK_TOP = 0.15     # y of the top brick row
BRICK_H = 0.03
BRICK_REGION_H = ROWS * BRICK_H
PADDLE_Y = 0.92
PADDLE_H = 0.02
PADDLE_W = 0.08
BALL_R = 0.012
PADDLE_SPEED = 0.04
BALL_SPEED = 0.035
LIVES = 5
FRAME_SKIP = 4
MAX_T = 10000  # safety cap on episode length (agent steps)

# ALE row scores, top row first (top rows worth most)
ROW_POINTS = jnp.array([7.0, 7.0, 4.0, 4.0, 1.0, 1.0])


class State(NamedTuple):
    ball_xy: jax.Array   # [2]
    ball_v: jax.Array    # [2]
    paddle_x: jax.Array  # []
    bricks: jax.Array    # [ROWS, COLS] bool
    lives: jax.Array     # [] int32
    in_play: jax.Array   # [] bool (ball launched?)
    t: jax.Array         # [] int32


def reset(key: jax.Array) -> State:
    del key
    return State(
        ball_xy=jnp.array([0.5, PADDLE_Y - 0.05]),
        ball_v=jnp.zeros(2),
        paddle_x=jnp.float32(0.5),
        bricks=jnp.ones((ROWS, COLS), bool),
        lives=jnp.int32(LIVES),
        in_play=jnp.bool_(False),
        t=jnp.int32(0),
    )


def _launch(key: jax.Array) -> jax.Array:
    angle = jax.random.uniform(key, (), minval=0.25 * jnp.pi, maxval=0.75 * jnp.pi)
    return jnp.stack([BALL_SPEED * jnp.cos(angle), -BALL_SPEED * jnp.sin(angle)])


def _brick_index(xy: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(row, col, inside) for a ball position."""
    row = jnp.floor((xy[1] - BRICK_TOP) / BRICK_H).astype(jnp.int32)
    col = jnp.floor(xy[0] * COLS).astype(jnp.int32)
    inside = (row >= 0) & (row < ROWS) & (col >= 0) & (col < COLS)
    return jnp.clip(row, 0, ROWS - 1), jnp.clip(col, 0, COLS - 1), inside


def _substep(state: State, move: jax.Array, fire: jax.Array, key: jax.Array):
    paddle_x = jnp.clip(
        state.paddle_x + move * PADDLE_SPEED, PADDLE_W / 2, 1 - PADDLE_W / 2
    )

    # serve: ball rides the paddle until fire
    launch_v = _launch(key)
    v = jnp.where(state.in_play, state.ball_v, jnp.where(fire, launch_v, jnp.zeros(2)))
    in_play = state.in_play | fire
    xy = jnp.where(
        in_play,
        state.ball_xy + v,
        jnp.stack([paddle_x, PADDLE_Y - 0.05]),
    )

    # walls
    hit_side = (xy[0] < BALL_R) | (xy[0] > 1 - BALL_R)
    v = v.at[0].set(jnp.where(hit_side, -v[0], v[0]))
    xy = xy.at[0].set(jnp.clip(xy[0], BALL_R, 1 - BALL_R))
    hit_top = xy[1] < BALL_R
    v = v.at[1].set(jnp.where(hit_top, -v[1], v[1]))
    xy = xy.at[1].set(jnp.clip(xy[1], BALL_R, 1.0))

    # paddle
    aligned = jnp.abs(xy[0] - paddle_x) <= PADDLE_W / 2 + BALL_R
    hit_paddle = (xy[1] >= PADDLE_Y - PADDLE_H) & (v[1] > 0) & aligned & in_play
    offset = (xy[0] - paddle_x) / (PADDLE_W / 2)
    v = jnp.where(
        hit_paddle,
        jnp.stack([BALL_SPEED * offset, -jnp.abs(v[1])]),
        v,
    )
    xy = xy.at[1].set(jnp.where(hit_paddle, PADDLE_Y - PADDLE_H - BALL_R, xy[1]))

    # bricks
    row, col, inside = _brick_index(xy)
    brick_alive = state.bricks[row, col] & inside & in_play
    reward = jnp.where(brick_alive, ROW_POINTS[row], 0.0)
    bricks = state.bricks.at[row, col].set(
        jnp.where(brick_alive, False, state.bricks[row, col])
    )
    # reflect AND expel the ball from the cell, else it drills through the
    # wall destroying a brick per substep
    from_below = v[1] < 0
    expel_y = jnp.where(
        from_below,
        BRICK_TOP + (row + 1).astype(jnp.float32) * BRICK_H + BALL_R,
        BRICK_TOP + row.astype(jnp.float32) * BRICK_H - BALL_R,
    )
    xy = xy.at[1].set(jnp.where(brick_alive, expel_y, xy[1]))
    v = v.at[1].set(jnp.where(brick_alive, -v[1], v[1]))

    # wall cleared -> refill (ALE second wall)
    cleared = ~bricks.any()
    bricks = jnp.where(cleared, jnp.ones_like(bricks), bricks)

    # ball lost
    lost = xy[1] >= 1.0 - 1e-6
    lives = state.lives - lost.astype(jnp.int32)
    in_play = in_play & ~lost
    xy = jnp.where(lost, jnp.stack([paddle_x, PADDLE_Y - 0.05]), xy)
    v = jnp.where(lost, jnp.zeros(2), v)

    return (
        State(
            ball_xy=xy,
            ball_v=v,
            paddle_x=paddle_x,
            bricks=bricks,
            lives=lives,
            in_play=in_play,
            t=state.t,
        ),
        reward,
    )


def step(state: State, action: jax.Array, key: jax.Array):
    """One agent step = FRAME_SKIP substeps. Auto-restarts when lives hit 0."""
    move = jnp.where(action == 2, 1.0, jnp.where(action == 3, -1.0, 0.0))
    fire = action == 1
    keys = jax.random.split(key, FRAME_SKIP + 1)

    def body(carry, k):
        st, acc = carry
        st, r = _substep(st, move, fire, k)
        return (st, acc + r), None

    # accumulator derived from state so it inherits the same sharding/varying
    # axes as the carry under shard_map (a literal 0.0 would be invariant)
    zero = state.ball_xy[0] * 0.0
    (state, reward), _ = jax.lax.scan(body, (state, zero), keys[:FRAME_SKIP])
    state = state._replace(t=state.t + 1)

    done = (state.lives <= 0) | (state.t >= MAX_T)
    fresh = reset(keys[FRAME_SKIP])
    state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(done, new, old), fresh, state
    )
    return state, render(state), reward, done


def render(state: State) -> jax.Array:
    h, w = obs_shape
    ys = (jnp.arange(h, dtype=jnp.float32) + 0.5) / h
    xs = (jnp.arange(w, dtype=jnp.float32) + 0.5) / w
    Y = ys[:, None]
    X = xs[None, :]

    # bricks: map each pixel to its (row, col); lit if alive and in region
    prow = jnp.floor((Y - BRICK_TOP) / BRICK_H).astype(jnp.int32)
    pcol = jnp.floor(X * COLS).astype(jnp.int32)
    in_region = (prow >= 0) & (prow < ROWS) & (pcol >= 0) & (pcol < COLS)
    alive = state.bricks[
        jnp.clip(prow, 0, ROWS - 1), jnp.clip(pcol, 0, COLS - 1)
    ]
    brick_px = in_region & alive

    ball = (jnp.abs(X - state.ball_xy[0]) <= BALL_R) & (
        jnp.abs(Y - state.ball_xy[1]) <= BALL_R
    )
    paddle = (jnp.abs(X - state.paddle_x) <= PADDLE_W / 2) & (
        jnp.abs(Y - PADDLE_Y) <= PADDLE_H
    )
    frame = (ball | paddle).astype(jnp.uint8) * 255
    frame = jnp.maximum(frame, brick_px.astype(jnp.uint8) * 180)
    wall = Y < 0.02
    return jnp.maximum(frame, wall.astype(jnp.uint8) * 80)
