"""Pure-JAX Seaquest-like env (Atari-4 set, BASELINE.json config #3).

Simplified-but-faithful Seaquest mechanics: the submarine moves in 2D under
water, enemy fish stream across in lanes, torpedoes destroy them for points,
and an oxygen meter forces periodic surfacing — the core control/credit
structure of ALE Seaquest (dive, shoot, manage oxygen) without the sprite
minutiae. Branch-free jnp throughout; FRAME_SKIP=4 agent steps.

Actions (6, ALE-minimal-like): 0 noop, 1 fire, 2 up, 3 down, 4 left, 5 right.
Reward: +20 per fish destroyed (ALE's base fish value), oxygen depletion
death / fish collision costs a life; 3 lives per episode.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

num_actions = 6
obs_shape = (84, 84)

N_LANES = 4           # enemy lanes at fixed depths
LANE_Y = jnp.array([0.35, 0.5, 0.65, 0.8])
SURFACE_Y = 0.15      # above this = surfacing (refills oxygen)
SUB_SPEED = 0.03
FISH_SPEED = 0.02
TORP_SPEED = 0.08
SUB_R = 0.03          # collision half-extent
FISH_R = 0.025
OXY_MAX = 200.0       # substeps of oxygen
OXY_SURFACE_REFILL = 8.0
LIVES = 3
FISH_POINTS = 20.0
FRAME_SKIP = 4
MAX_T = 5000


class State(NamedTuple):
    sub_xy: jax.Array      # [2]
    fish_x: jax.Array      # [N_LANES] x position of the lane's fish
    fish_dir: jax.Array    # [N_LANES] -1/+1
    fish_alive: jax.Array  # [N_LANES] bool
    torp_xy: jax.Array     # [2] torpedo position
    torp_dir: jax.Array    # [] -1/+1 (fires horizontally, sub's facing)
    torp_live: jax.Array   # [] bool
    facing: jax.Array      # [] -1/+1 last horizontal direction
    oxygen: jax.Array      # [] float
    lives: jax.Array       # [] int32
    t: jax.Array           # [] int32


def reset(key: jax.Array) -> State:
    k1, k2 = jax.random.split(key)
    return State(
        sub_xy=jnp.array([0.5, 0.5]),
        fish_x=jax.random.uniform(k1, (N_LANES,)),
        fish_dir=jnp.where(jax.random.bernoulli(k2, 0.5, (N_LANES,)), 1.0, -1.0),
        fish_alive=jnp.ones(N_LANES, bool),
        torp_xy=jnp.zeros(2),
        torp_dir=jnp.float32(1.0),
        torp_live=jnp.bool_(False),
        facing=jnp.float32(1.0),
        oxygen=jnp.float32(OXY_MAX),
        lives=jnp.int32(LIVES),
        t=jnp.int32(0),
    )


def _substep(state: State, action: jax.Array, key: jax.Array) -> Tuple[State, jax.Array, jax.Array]:
    up = action == 2
    down = action == 3
    left = action == 4
    right = action == 5
    fire = action == 1

    dx = jnp.where(right, 1.0, 0.0) - jnp.where(left, 1.0, 0.0)
    dy = jnp.where(down, 1.0, 0.0) - jnp.where(up, 1.0, 0.0)
    facing = jnp.where(dx != 0, jnp.sign(dx), state.facing)
    sub = jnp.stack(
        [
            jnp.clip(state.sub_xy[0] + dx * SUB_SPEED, 0.05, 0.95),
            jnp.clip(state.sub_xy[1] + dy * SUB_SPEED, 0.08, 0.92),
        ]
    )

    # fish advance; respawn (alive again, random-ish x via key) when off-screen
    fish_x = state.fish_x + state.fish_dir * FISH_SPEED
    off = (fish_x < -0.05) | (fish_x > 1.05)
    respawn_x = jax.random.uniform(key, (N_LANES,))
    fish_x = jnp.where(off, jnp.where(state.fish_dir > 0, -0.05, 1.05), fish_x)
    fish_alive = state.fish_alive | off  # dead fish respawn on wraparound
    # keep deterministic-ish motion; respawn_x reserved for variety on kill
    del respawn_x

    # torpedo
    torp_live = state.torp_live | (fire & ~state.torp_live)
    torp_xy = jnp.where(
        state.torp_live,
        state.torp_xy.at[0].add(state.torp_dir * TORP_SPEED),
        jnp.where(fire, jnp.stack([sub[0], sub[1]]), state.torp_xy),
    )
    torp_dir = jnp.where(state.torp_live, state.torp_dir, facing)
    torp_live = torp_live & (torp_xy[0] > 0.0) & (torp_xy[0] < 1.0)

    # torpedo hits fish (same lane band, x overlap)
    hit = (
        fish_alive
        & torp_live
        & (jnp.abs(fish_x - torp_xy[0]) < FISH_R + 0.02)
        & (jnp.abs(LANE_Y - torp_xy[1]) < 0.04)
    )
    reward = jnp.sum(hit) * FISH_POINTS
    fish_alive = fish_alive & ~hit
    torp_live = torp_live & ~hit.any()

    # fish hits sub
    collide = (
        fish_alive
        & (jnp.abs(fish_x - sub[0]) < FISH_R + SUB_R)
        & (jnp.abs(LANE_Y - sub[1]) < FISH_R + SUB_R)
    ).any()

    # oxygen
    surfaced = sub[1] <= SURFACE_Y
    oxygen = jnp.where(
        surfaced,
        jnp.minimum(state.oxygen + OXY_SURFACE_REFILL, OXY_MAX),
        state.oxygen - 1.0,
    )
    suffocate = oxygen <= 0.0

    lost_life = collide | suffocate
    lives = state.lives - lost_life.astype(jnp.int32)
    # life reset: sub to center, oxygen refilled
    sub = jnp.where(lost_life, jnp.array([0.5, 0.5]), sub)
    oxygen = jnp.where(lost_life, OXY_MAX, oxygen)

    new_state = State(
        sub_xy=sub,
        fish_x=fish_x,
        fish_dir=state.fish_dir,
        fish_alive=fish_alive,
        torp_xy=torp_xy,
        torp_dir=torp_dir,
        torp_live=torp_live,
        facing=facing,
        oxygen=oxygen,
        lives=lives,
        t=state.t,
    )
    return new_state, reward, lost_life


def step(state: State, action: jax.Array, key: jax.Array):
    keys = jax.random.split(key, FRAME_SKIP + 1)
    zero = state.sub_xy[0] * 0.0

    def body(carry, k):
        st, acc = carry
        st, r, _ = _substep(st, action, k)
        return (st, acc + r), None

    (state, reward), _ = jax.lax.scan(body, (state, zero), keys[:FRAME_SKIP])
    state = state._replace(t=state.t + 1)
    done = (state.lives <= 0) | (state.t >= MAX_T)
    fresh = reset(keys[FRAME_SKIP])
    state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(done, new, old), fresh, state
    )
    return state, render(state), reward, done


def render(state: State) -> jax.Array:
    h, w = obs_shape
    Y = ((jnp.arange(h, dtype=jnp.float32) + 0.5) / h)[:, None]
    X = ((jnp.arange(w, dtype=jnp.float32) + 0.5) / w)[None, :]

    def rect(cx, cy, hw_, hh_):
        return (jnp.abs(X - cx) <= hw_) & (jnp.abs(Y - cy) <= hh_)

    frame = jnp.zeros((h, w), jnp.uint8)
    # surface line
    frame = jnp.maximum(frame, (jnp.abs(Y - SURFACE_Y) < 0.012).astype(jnp.uint8) * 80)
    # oxygen bar along the top, width proportional to oxygen
    frac = jnp.clip(state.oxygen / OXY_MAX, 0.0, 1.0)
    frame = jnp.maximum(
        frame, ((Y < 0.04) & (X < frac)).astype(jnp.uint8) * 140
    )
    # fish per lane
    fish = jnp.zeros((h, w), bool)
    for i in range(N_LANES):
        fish = fish | (
            rect(state.fish_x[i], LANE_Y[i], FISH_R, FISH_R)
            & state.fish_alive[i]
        )
    frame = jnp.maximum(frame, fish.astype(jnp.uint8) * 180)
    # torpedo
    frame = jnp.maximum(
        frame,
        (rect(state.torp_xy[0], state.torp_xy[1], 0.015, 0.008) & state.torp_live).astype(jnp.uint8) * 220,
    )
    # submarine
    frame = jnp.maximum(
        frame, rect(state.sub_xy[0], state.sub_xy[1], SUB_R, SUB_R).astype(jnp.uint8) * 255
    )
    return frame
