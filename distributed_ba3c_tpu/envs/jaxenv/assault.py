"""Pure-JAX Assault: ALE-compatible reward structure, branch-free physics.

ALE parity choices (reference game set, BASELINE.md): a mothership cruises
the top of the screen spawning attackers that descend in three lanes and
strafe toward the player's turret; the turret moves horizontally and fires
upward. Points: 21 per attacker destroyed, bonus 42 for a direct
mothership hit (ALE Assault scores in 21-point quanta). Sustained fire
overheats the cannon — a heat gauge charges per shot and cooling forces a
firing pause (the game's signature mechanic). 4 lives; an attacker
reaching the turret row or a bomb hit costs one. Action set: {0}=noop
{1}=fire {2}=up(vent heat) {3}=right {4}=left {5}=right+fire
{6}=left+fire (ALE Assault minimal set is 7 actions).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

num_actions = 7
obs_shape = (84, 84)

N_LANES = 3
LANE_X = jnp.array([0.25, 0.5, 0.75])
MOTHER_Y = 0.08
MOTHER_W = 0.10
MOTHER_SPEED = 0.006
ATTACKER_W = 0.035
ATTACKER_H = 0.025
DESCEND_SPEED = 0.008
STRAFE = 0.006
SPAWN_P = 0.08
PLAYER_Y = 0.93
PLAYER_W = 0.05
PLAYER_SPEED = 0.03
SHOT_SPEED = 0.06
BOMB_SPEED = 0.02
BOMB_P = 0.04
HEAT_PER_SHOT = 0.45   # a few consecutive shot-cycles overheat
COOL = 0.015           # slower than the ~0.45/15-substep firing duty cycle
VENT_COOL = 0.12
LIVES = 4
FRAME_SKIP = 4
MAX_T = 10000

ATTACKER_POINTS = 21.0
MOTHER_POINTS = 42.0


class State(NamedTuple):
    mother_x: jax.Array     # []
    mother_dir: jax.Array   # []
    att_pos: jax.Array      # [N_LANES, 2] attacker positions
    att_live: jax.Array     # [N_LANES] bool
    bomb: jax.Array         # [2]
    bomb_live: jax.Array    # [] bool
    player_x: jax.Array     # []
    shot: jax.Array         # [2]
    shot_live: jax.Array    # [] bool
    heat: jax.Array         # [] float32 in [0, 1+]; >=1 means jammed
    jammed: jax.Array      # [] bool
    lives: jax.Array        # [] int32
    t: jax.Array            # [] int32


def reset(key: jax.Array) -> State:
    del key
    return State(
        mother_x=jnp.float32(0.5),
        mother_dir=jnp.float32(1.0),
        att_pos=jnp.stack([LANE_X, jnp.full((N_LANES,), MOTHER_Y + 0.05)], -1),
        att_live=jnp.zeros(N_LANES, bool),
        bomb=jnp.zeros(2),
        bomb_live=jnp.bool_(False),
        player_x=jnp.float32(0.5),
        shot=jnp.zeros(2),
        shot_live=jnp.bool_(False),
        heat=jnp.float32(0.0),
        jammed=jnp.bool_(False),
        lives=jnp.int32(LIVES),
        t=jnp.int32(0),
    )


def _substep(state: State, move, fire, vent, key: jax.Array):
    k_spawn, k_lane, k_bomb = jax.random.split(key, 3)
    player_x = jnp.clip(
        state.player_x + move * PLAYER_SPEED, PLAYER_W, 1 - PLAYER_W
    )

    # mothership patrol
    mother_x = state.mother_x + state.mother_dir * MOTHER_SPEED
    bounce = (mother_x > 1 - MOTHER_W) | (mother_x < MOTHER_W)
    mother_dir = jnp.where(bounce, -state.mother_dir, state.mother_dir)
    mother_x = jnp.clip(mother_x, MOTHER_W, 1 - MOTHER_W)

    # spawn an attacker in a random free lane, dropping from the mothership
    # (one-hot lane mask, not att_live[lane]/.at[lane]: per-env scalar
    # gathers/scatters are pathological under vmap — see package rule)
    lane = jax.random.randint(k_lane, (), 0, N_LANES)
    lane_oh = jnp.arange(N_LANES) == lane
    can = ~jnp.any(state.att_live & lane_oh)
    spawn = (jax.random.uniform(k_spawn) < SPAWN_P) & can
    spawn_oh = lane_oh & spawn
    att_pos = jnp.where(
        spawn_oh[:, None],
        jnp.stack([mother_x, MOTHER_Y + 0.05])[None, :],
        state.att_pos,
    )
    att_live = state.att_live | spawn_oh

    # attackers descend and strafe toward the player
    dx = jnp.sign(player_x - att_pos[:, 0]) * STRAFE
    att_pos = att_pos.at[:, 0].add(jnp.where(att_live, dx, 0.0))
    att_pos = att_pos.at[:, 1].add(jnp.where(att_live, DESCEND_SPEED, 0.0))

    # cannon heat: venting (action up) cools fast; a jam persists until the
    # gauge cools below 0.3, and trips when a shot pushes it to the cap
    heat = jnp.maximum(
        state.heat - jnp.where(vent, VENT_COOL, COOL), 0.0
    )
    jammed = state.jammed & (heat > 0.3)
    can_fire = fire & ~state.shot_live & ~jammed
    heat = heat + jnp.where(can_fire, HEAT_PER_SHOT, 0.0)
    jammed = jammed | (heat >= 1.0)
    heat = jnp.minimum(heat, 1.0)

    shot = jnp.where(
        can_fire, jnp.stack([player_x, PLAYER_Y - 0.03]), state.shot
    )
    shot = shot.at[1].add(
        jnp.where(state.shot_live | can_fire, -SHOT_SPEED, 0.0)
    )
    shot_live = (state.shot_live | can_fire) & (shot[1] > 0.0)

    # shot vs attackers
    hit_att = (
        att_live
        & shot_live
        & (jnp.abs(att_pos[:, 0] - shot[0]) <= ATTACKER_W)
        & (jnp.abs(att_pos[:, 1] - shot[1]) <= ATTACKER_H)
    )
    reward = jnp.sum(hit_att) * ATTACKER_POINTS
    att_live = att_live & ~hit_att
    shot_live = shot_live & ~jnp.any(hit_att)

    # shot vs mothership
    hit_mom = (
        shot_live
        & (jnp.abs(mother_x - shot[0]) <= MOTHER_W)
        & (shot[1] <= MOTHER_Y + 0.02)
    )
    reward = reward + jnp.where(hit_mom, MOTHER_POINTS, 0.0)
    shot_live = shot_live & ~hit_mom

    # bombs from a random live attacker (one-hot contraction, not
    # att_pos[bsrc]: per-env scalar gathers are pathological under vmap)
    src_oh = (jnp.arange(N_LANES) == jnp.argmax(att_live)).astype(jnp.float32)
    src_pos = (att_pos * src_oh[:, None]).sum(axis=0)
    drop = (
        (jax.random.uniform(k_bomb) < BOMB_P)
        & att_live.any()
        & ~state.bomb_live
    )
    bomb = jnp.where(drop, src_pos, state.bomb)
    bomb = bomb.at[1].add(jnp.where(state.bomb_live | drop, BOMB_SPEED, 0.0))
    bomb_live = (state.bomb_live | drop) & (bomb[1] < 1.0)

    # hits on the player: bomb, or an attacker reaching the turret row
    bomb_hit = (
        bomb_live
        & (jnp.abs(bomb[0] - player_x) <= PLAYER_W)
        & (bomb[1] >= PLAYER_Y - 0.02)
    )
    reached = att_live & (att_pos[:, 1] >= PLAYER_Y - 0.02)
    lives = state.lives - (bomb_hit | reached.any()).astype(jnp.int32)
    bomb_live = bomb_live & ~bomb_hit
    att_live = att_live & ~reached

    return (
        State(
            mother_x=mother_x,
            mother_dir=mother_dir,
            att_pos=att_pos,
            att_live=att_live,
            bomb=bomb,
            bomb_live=bomb_live,
            player_x=player_x,
            shot=shot,
            shot_live=shot_live,
            heat=heat,
            jammed=jammed,
            lives=lives,
            t=state.t,
        ),
        reward,
    )


def step(state: State, action: jax.Array, key: jax.Array):
    move = jnp.where(
        (action == 3) | (action == 5),
        1.0,
        jnp.where((action == 4) | (action == 6), -1.0, 0.0),
    )
    fire = (action == 1) | (action == 5) | (action == 6)
    vent = action == 2
    keys = jax.random.split(key, FRAME_SKIP + 1)

    def body(carry, k):
        st, acc = carry
        st, r = _substep(st, move, fire, vent, k)
        return (st, acc + r), None

    zero = state.player_x * 0.0
    (state, reward), _ = jax.lax.scan(body, (state, zero), keys[:FRAME_SKIP])
    state = state._replace(t=state.t + 1)

    done = (state.lives <= 0) | (state.t >= MAX_T)
    fresh = reset(keys[FRAME_SKIP])
    state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(done, new, old), fresh, state
    )
    return state, render(state), reward, done


def render(state: State) -> jax.Array:
    h, w = obs_shape
    ys = (jnp.arange(h, dtype=jnp.float32) + 0.5) / h
    xs = (jnp.arange(w, dtype=jnp.float32) + 0.5) / w
    Y = ys[:, None]
    X = xs[None, :]

    mother = (jnp.abs(X - state.mother_x) <= MOTHER_W) & (
        jnp.abs(Y - MOTHER_Y) <= 0.02
    )
    atts = jnp.zeros_like(mother)
    for i in range(N_LANES):
        atts = atts | (
            state.att_live[i]
            & (jnp.abs(X - state.att_pos[i, 0]) <= ATTACKER_W)
            & (jnp.abs(Y - state.att_pos[i, 1]) <= ATTACKER_H)
        )
    player = (jnp.abs(X - state.player_x) <= PLAYER_W) & (
        jnp.abs(Y - PLAYER_Y) <= 0.02
    )
    shot = (
        state.shot_live
        & (jnp.abs(X - state.shot[0]) <= 0.006)
        & (jnp.abs(Y - state.shot[1]) <= 0.015)
    )
    bomb = (
        state.bomb_live
        & (jnp.abs(X - state.bomb[0]) <= 0.008)
        & (jnp.abs(Y - state.bomb[1]) <= 0.012)
    )
    # heat gauge strip on the right edge; full height = jammed
    gauge = (X > 0.97) & (Y > 1.0 - state.heat)

    frame = (player | shot).astype(jnp.uint8) * 255
    frame = jnp.maximum(frame, mother.astype(jnp.uint8) * 200)
    frame = jnp.maximum(frame, atts.astype(jnp.uint8) * 160)
    frame = jnp.maximum(frame, bomb.astype(jnp.uint8) * 120)
    return jnp.maximum(frame, gauge.astype(jnp.uint8) * 90)
