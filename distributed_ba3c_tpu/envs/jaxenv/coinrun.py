"""Pure-JAX CoinRun-like procgen env (BASELINE.json config #5).

The procgen hallmark: every episode's level is PROCEDURALLY GENERATED from
the reset PRNG key — terrain heights (random walk), gaps, spikes, goal
distance and hazard density all differ per episode, so the policy must
generalize across levels instead of memorizing one. Mechanics follow
CoinRun: run right across a side-scrolling platform world, jump gaps and
spikes, touch the coin for +10; falling into a gap or hitting a spike ends
the episode (reward 0).

Per-level DIFFICULTY is part of the distribution (as in procgen, whose
level generator varies section count and hazards): the goal sits
6..62 tiles out (the deliberately easy 6-tile floor — fully protected,
hazard-free levels — is what makes the +10 reachable by exploration at
all) and gap/spike densities scale by a per-level draw. That
spread is what makes the sparse +10 learnable at all — uniform-random play
finishes the short easy levels occasionally (measured: ~37k uniform
episodes on fixed 64-tile max-difficulty levels produced ZERO coins), and
the policy climbs the difficulty distribution from there.

Branch-free jnp platformer physics + scrolling raster render; FRAME_SKIP=1
(procgen-style, no frameskip). Actions (5): 0 noop, 1 left, 2 right, 3 jump,
4 right+jump.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

num_actions = 5
obs_shape = (84, 84)

LEVEL_LEN = 64        # tiles
MAX_HEIGHT = 5.0      # terrain height in tiles
GAP_P = 0.12          # per-tile gap probability
SPIKE_P = 0.10        # per-tile spike probability (on ground tiles)
GRAVITY = 0.02
JUMP_V = 0.22
RUN_V = 0.12          # tiles per tick
COIN_REWARD = 10.0
MAX_T = 1000
FRAME_SKIP = 1

VIEW_TILES = 12.0     # horizontal tiles visible
VIEW_H_TILES = 8.0    # vertical tiles visible


class State(NamedTuple):
    xy: jax.Array        # [2] (x tiles, y tiles above ground-0)
    vy: jax.Array        # [] vertical velocity
    heights: jax.Array   # [LEVEL_LEN] terrain height (0 = gap)
    spikes: jax.Array    # [LEVEL_LEN] bool
    goal: jax.Array      # [] float32 coin tile (6..LEVEL_LEN-2)
    t: jax.Array         # [] int32


def _gen_level(key: jax.Array):
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    # per-level difficulty: goal distance and hazard density both vary
    goal = jax.random.randint(k4, (), 6, LEVEL_LEN - 1).astype(jnp.float32)
    diff = jax.random.uniform(k5, (), minval=0.0, maxval=1.0)
    steps = jax.random.randint(k1, (LEVEL_LEN,), -1, 2)  # -1/0/+1 walk
    heights = jnp.clip(2.0 + jnp.cumsum(steps).astype(jnp.float32), 1.0, MAX_HEIGHT)
    gaps = jax.random.bernoulli(k2, GAP_P * diff, (LEVEL_LEN,))
    # spawn platform and everything from the coin platform on stays solid;
    # no double gaps
    idx = jnp.arange(LEVEL_LEN)
    protected = (idx < 4) | (idx.astype(jnp.float32) >= goal - 2.0)
    gaps = gaps & ~protected & ~jnp.roll(gaps, 1)
    heights = jnp.where(gaps, 0.0, heights)
    spikes = (
        jax.random.bernoulli(k3, SPIKE_P * diff, (LEVEL_LEN,))
        & ~gaps
        & ~protected
        & ~jnp.roll(gaps, 1)
        & ~jnp.roll(gaps, -1)
    )
    return heights, spikes, goal


def reset(key: jax.Array) -> State:
    heights, spikes, goal = _gen_level(key)
    return State(
        xy=jnp.array([1.5, heights[1]]),
        vy=jnp.float32(0.0),
        heights=heights,
        spikes=spikes,
        goal=goal,
        t=jnp.int32(0),
    )


def _ground_at(heights: jax.Array, x: jax.Array) -> jax.Array:
    return heights[jnp.clip(x.astype(jnp.int32), 0, LEVEL_LEN - 1)]


def step(state: State, action: jax.Array, key: jax.Array):
    left = action == 1
    right = (action == 2) | (action == 4)
    jump = (action == 3) | (action == 4)

    x, y = state.xy[0], state.xy[1]
    ground = _ground_at(state.heights, x)
    grounded = (y <= ground + 1e-4) & (ground > 0)

    vx = jnp.where(right, RUN_V, 0.0) - jnp.where(left, RUN_V, 0.0)
    vy = jnp.where(grounded & jump, JUMP_V, state.vy - GRAVITY)
    vy = jnp.where(grounded & ~jump, jnp.maximum(vy, 0.0), vy)

    new_x = jnp.clip(x + vx, 0.5, LEVEL_LEN - 0.5)
    new_ground = _ground_at(state.heights, new_x)
    new_y = y + vy
    # land on terrain (only when falling onto it)
    landing = (vy <= 0) & (new_y <= new_ground) & (new_ground > 0)
    new_y = jnp.where(landing, new_ground, new_y)
    vy = jnp.where(landing, 0.0, vy)
    # can't run through a wall higher than current altitude: stay put
    blocked = (new_ground > y + 0.51) & (new_ground > 0)
    new_x = jnp.where(blocked, x, new_x)
    new_ground = _ground_at(state.heights, new_x)

    # deaths: fell into a gap below zero, or touched a spike while grounded
    fell = new_y < -0.5
    on_spike = (
        state.spikes[jnp.clip(new_x.astype(jnp.int32), 0, LEVEL_LEN - 1)]
        & (new_y <= new_ground + 0.1)
    )
    # win: reach this level's coin platform
    won = new_x >= state.goal - 0.5
    reward = jnp.where(won, COIN_REWARD, 0.0)

    t = state.t + 1
    done = fell | on_spike | won | (t >= MAX_T)

    new_state = State(
        xy=jnp.stack([new_x, new_y]),
        vy=vy,
        heights=state.heights,
        spikes=state.spikes,
        goal=state.goal,
        t=t,
    )
    fresh = reset(key)  # NEW procedurally generated level every episode
    new_state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(done, new, old), fresh, new_state
    )
    return new_state, render(new_state), reward, done


def render(state: State) -> jax.Array:
    """Scrolling viewport centered on the agent."""
    h, w = obs_shape
    x0 = state.xy[0] - VIEW_TILES / 2
    # world coords of each pixel
    wx = x0 + (jnp.arange(w, dtype=jnp.float32) + 0.5) * (VIEW_TILES / w)  # [W]
    wy = (VIEW_H_TILES - (jnp.arange(h, dtype=jnp.float32) + 0.5) * (VIEW_H_TILES / h))  # [H] top-down

    tile = jnp.clip(wx.astype(jnp.int32), 0, LEVEL_LEN - 1)
    col_h = state.heights[tile]          # [W]
    col_spike = state.spikes[tile]       # [W]

    ground_px = wy[:, None] <= col_h[None, :]
    frame = ground_px.astype(jnp.uint8) * 110
    spike_px = ground_px & col_spike[None, :] & (wy[:, None] > col_h[None, :] - 0.6)
    frame = jnp.maximum(frame, spike_px.astype(jnp.uint8) * 180)

    # coin at this level's goal platform (one-hot height lookup — no
    # dynamic scalar gather, per the envs/jaxenv authoring rule)
    coin_x = state.goal
    goal_oh = (jnp.arange(LEVEL_LEN).astype(jnp.float32) == coin_x)
    coin_y = jnp.sum(state.heights * goal_oh) + 0.6
    coin = (jnp.abs(wx[None, :] - coin_x) <= 0.4) & (
        jnp.abs(wy[:, None] - coin_y) <= 0.4
    )
    frame = jnp.maximum(frame, coin.astype(jnp.uint8) * 220)

    # agent
    agent = (jnp.abs(wx[None, :] - state.xy[0]) <= 0.35) & (
        jnp.abs(wy[:, None] - (state.xy[1] + 0.45)) <= 0.45
    )
    frame = jnp.maximum(frame, agent.astype(jnp.uint8) * 255)
    return frame
