"""Pure-JAX Boxing: ALE-compatible scoring on branch-free ring physics.

ALE parity choices (reference game set, BASELINE.md): two boxers in a
top-down ring; +1 reward per punch landed on the opponent, -1 per punch
taken (ALE Boxing reward = own score delta minus opponent's); KO —
episode ends — when either side reaches 100 landed punches; otherwise a
round lasts "two minutes" (MAX_T agent steps). A perfect agent approaches
+100. Action set: {0}=noop {1}=punch {2}=up {3}=right {4}=left {5}=down
{6..9}=diagonals {10..17}=punch+move (18 actions — ALE Boxing uses the
full set).

The opponent is a scripted pursuer with a punch cooldown and a random
sidestep, the same role ALE's built-in game AI plays; its parameters set
the difficulty of the reward landscape, not the framework surface.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

num_actions = 18
obs_shape = (84, 84)

RING_LO, RING_HI = 0.08, 0.92
MOVE = 0.022
OPP_MOVE = 0.014
PUNCH_RANGE = 0.10
PUNCH_CD = 4          # substeps between punches
OPP_PUNCH_P = 0.25    # per-substep punch attempt probability when in range
                      # (calibrated so random play nets ~0, like ALE's AI)
KO = 100
FRAME_SKIP = 4
MAX_T = 2000

# action -> (dx, dy, punch): move rows for actions 2..9, punch variants 10..17
_MOVES = jnp.array(
    [
        [0, 0], [0, 0],                      # noop, punch
        [0, -1], [1, 0], [-1, 0], [0, 1],    # up right left down
        [1, -1], [-1, -1], [1, 1], [-1, 1],  # diagonals (ALE order approx)
    ],
    jnp.float32,
)


def _decode(action: jax.Array):
    punch_combo = action >= 10
    base = jnp.where(punch_combo, action - 8, action)  # 10..17 -> 2..9
    base = jnp.clip(base, 0, 9)
    # one-hot contraction, not _MOVES[base]: per-env scalar gathers lower
    # to pathological batched gathers under vmap in the fused program
    oh = (jnp.arange(10) == base).astype(jnp.float32)
    d = oh @ _MOVES
    punch = (action == 1) | punch_combo
    return d[0], d[1], punch


class State(NamedTuple):
    me: jax.Array        # [2] player position
    opp: jax.Array       # [2]
    my_score: jax.Array  # [] int32 punches landed
    op_score: jax.Array  # [] int32
    my_cd: jax.Array     # [] int32 punch cooldown
    op_cd: jax.Array     # [] int32
    t: jax.Array         # [] int32


def reset(key: jax.Array) -> State:
    del key
    return State(
        me=jnp.array([0.3, 0.5]),
        opp=jnp.array([0.7, 0.5]),
        my_score=jnp.int32(0),
        op_score=jnp.int32(0),
        my_cd=jnp.int32(0),
        op_cd=jnp.int32(0),
        t=jnp.int32(0),
    )


def _substep(state: State, dx, dy, punch, key: jax.Array):
    k_side, k_punch = jax.random.split(key)
    me = jnp.clip(
        state.me + jnp.stack([dx, dy]) * MOVE, RING_LO, RING_HI
    )

    # opponent AI: pursue with a random lateral jitter
    delta = me - state.opp
    dist = jnp.linalg.norm(delta) + 1e-6
    chase = delta / dist * OPP_MOVE
    jitter = (jax.random.uniform(k_side, (2,)) - 0.5) * OPP_MOVE
    opp = jnp.clip(state.opp + chase + jitter, RING_LO, RING_HI)

    in_range = jnp.linalg.norm(me - opp) <= PUNCH_RANGE
    my_land = punch & in_range & (state.my_cd <= 0)
    op_try = jax.random.uniform(k_punch) < OPP_PUNCH_P
    op_land = op_try & in_range & (state.op_cd <= 0)

    # landing a punch knocks the punched boxer AWAY from the puncher
    # (delta = me - opp, so -delta/dist points from me toward opp)
    knock = jnp.where(dist > 0, delta / dist, jnp.zeros(2)) * 0.05
    opp = jnp.clip(opp - jnp.where(my_land, knock, 0.0), RING_LO, RING_HI)
    me = jnp.clip(me + jnp.where(op_land, knock, 0.0), RING_LO, RING_HI)

    reward = my_land.astype(jnp.float32) - op_land.astype(jnp.float32)
    return (
        State(
            me=me,
            opp=opp,
            my_score=state.my_score + my_land.astype(jnp.int32),
            op_score=state.op_score + op_land.astype(jnp.int32),
            my_cd=jnp.where(my_land, PUNCH_CD, jnp.maximum(state.my_cd - 1, 0)),
            op_cd=jnp.where(op_land, PUNCH_CD, jnp.maximum(state.op_cd - 1, 0)),
            t=state.t,
        ),
        reward,
    )


def step(state: State, action: jax.Array, key: jax.Array):
    dx, dy, punch = _decode(action)
    keys = jax.random.split(key, FRAME_SKIP + 1)

    def body(carry, k):
        st, acc = carry
        st, r = _substep(st, dx, dy, punch, k)
        return (st, acc + r), None

    zero = state.me[0] * 0.0
    (state, reward), _ = jax.lax.scan(body, (state, zero), keys[:FRAME_SKIP])
    state = state._replace(t=state.t + 1)

    done = (
        (state.my_score >= KO)
        | (state.op_score >= KO)
        | (state.t >= MAX_T)
    )
    fresh = reset(keys[FRAME_SKIP])
    state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(done, new, old), fresh, state
    )
    return state, render(state), reward, done


def render(state: State) -> jax.Array:
    h, w = obs_shape
    ys = (jnp.arange(h, dtype=jnp.float32) + 0.5) / h
    xs = (jnp.arange(w, dtype=jnp.float32) + 0.5) / w
    Y = ys[:, None]
    X = xs[None, :]

    ring = (
        (jnp.abs(X - RING_LO) < 0.008)
        | (jnp.abs(X - RING_HI) < 0.008)
        | (jnp.abs(Y - RING_LO) < 0.008)
        | (jnp.abs(Y - RING_HI) < 0.008)
    )
    me = (jnp.abs(X - state.me[0]) <= 0.03) & (jnp.abs(Y - state.me[1]) <= 0.03)
    opp = (jnp.abs(X - state.opp[0]) <= 0.03) & (
        jnp.abs(Y - state.opp[1]) <= 0.03
    )
    # score bars along the top edge (white=mine, grey=opponent) so the net
    # can see the count, like ALE's on-screen score
    my_bar = (Y < 0.04) & (X < state.my_score.astype(jnp.float32) / KO)
    op_bar = (Y > 0.96) & (X < state.op_score.astype(jnp.float32) / KO)

    frame = me.astype(jnp.uint8) * 255
    frame = jnp.maximum(frame, opp.astype(jnp.uint8) * 150)
    frame = jnp.maximum(frame, ring.astype(jnp.uint8) * 80)
    frame = jnp.maximum(frame, my_bar.astype(jnp.uint8) * 255)
    return jnp.maximum(frame, op_bar.astype(jnp.uint8) * 120)
