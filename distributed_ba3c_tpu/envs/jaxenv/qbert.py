"""Pure-JAX Q*bert-like env (Atari-4 set, BASELINE.json config #3).

Core Q*bert structure: a 6-row pyramid of 21 cubes; hopping onto a cube
flips its color (+25 points the first time, like ALE); flipping every cube
clears the board (+bonus, board refills); hopping off the pyramid or meeting
the bouncing enemy ball costs a life. Branch-free jnp; FRAME_SKIP agent
steps are single hops (Q*bert's hop IS the time quantum, so FRAME_SKIP=1
here — the ALE frameskip corresponds to the hop animation).

Actions (5): 0 noop, 1 up-right, 2 down-right, 3 down-left, 4 up-left
(diagonal hops on the pyramid lattice).

Cube addressing: row r in [0,6), position c in [0,r], flattened index
r*(r+1)/2 + c (21 cubes total).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

num_actions = 5
obs_shape = (84, 84)

ROWS = 6
N_CUBES = ROWS * (ROWS + 1) // 2  # 21
CUBE_POINTS = 25.0
CLEAR_BONUS = 100.0
LIVES = 3
FRAME_SKIP = 1
MAX_T = 2000

_ROW_OF = jnp.array([r for r in range(ROWS) for _ in range(r + 1)])
_COL_OF = jnp.array([c for r in range(ROWS) for c in range(r + 1)])


def _flat(row: jax.Array, col: jax.Array) -> jax.Array:
    return (row * (row + 1)) // 2 + col


class State(NamedTuple):
    pos: jax.Array      # [2] (row, col) of the agent, int32
    flipped: jax.Array  # [N_CUBES] bool
    ball: jax.Array     # [2] (row, col) of the enemy ball, int32
    ball_live: jax.Array  # [] bool
    lives: jax.Array    # [] int32
    boards: jax.Array   # [] int32 boards cleared (difficulty counter)
    t: jax.Array        # [] int32


def reset(key: jax.Array) -> State:
    del key
    return State(
        pos=jnp.array([0, 0], jnp.int32),
        flipped=jnp.zeros(N_CUBES, bool),
        ball=jnp.array([1, 0], jnp.int32),
        ball_live=jnp.bool_(False),
        lives=jnp.int32(LIVES),
        boards=jnp.int32(0),
        t=jnp.int32(0),
    )


def _hop(pos: jax.Array, action: jax.Array) -> jax.Array:
    """Diagonal lattice moves: rows grow downward; (dr, dc) per action."""
    dr = jnp.where((action == 2) | (action == 3), 1, jnp.where((action == 1) | (action == 4), -1, 0))
    dc = jnp.where(action == 2, 1, jnp.where((action == 4) | (action == 3), 0, jnp.where(action == 1, 0, 0)))
    # up-right (1): (-1, 0); down-right (2): (+1, +1); down-left (3): (+1, 0);
    # up-left (4): (-1, -1)
    dc = jnp.where(action == 1, 0, dc)
    dc = jnp.where(action == 4, -1, dc)
    return pos + jnp.stack([dr, dc])


def step(state: State, action: jax.Array, key: jax.Array):
    k_ball, k_reset = jax.random.split(key)

    new_pos = _hop(state.pos, action)
    moved = action != 0
    row, col = new_pos[0], new_pos[1]
    on_board = (row >= 0) & (row < ROWS) & (col >= 0) & (col <= row)
    fell = moved & ~on_board
    pos = jnp.where(on_board, new_pos, state.pos)

    # flip the landed cube
    idx = _flat(pos[0], pos[1])
    newly = moved & on_board & ~state.flipped[idx]
    flipped = state.flipped.at[idx].set(state.flipped[idx] | (moved & on_board))
    reward = jnp.where(newly, CUBE_POINTS, 0.0)

    # board clear
    cleared = flipped.all()
    reward = reward + jnp.where(cleared, CLEAR_BONUS, 0.0)
    flipped = jnp.where(cleared, jnp.zeros_like(flipped), flipped)
    boards = state.boards + cleared.astype(jnp.int32)

    # enemy ball: spawns at the top, hops downward randomly; falls off bottom
    spawn = ~state.ball_live
    bdc = jax.random.bernoulli(k_ball, 0.5).astype(jnp.int32)
    ball = jnp.where(
        spawn,
        jnp.array([1, 0], jnp.int32),
        state.ball + jnp.stack([jnp.int32(1), bdc]),
    )
    ball_live = ball[0] < ROWS
    ball = jnp.where(ball_live, ball, jnp.array([1, 0], jnp.int32))
    # clamp col onto the row
    ball = ball.at[1].set(jnp.clip(ball[1], 0, ball[0]))

    caught = ball_live & (ball == pos).all()
    lost_life = fell | caught
    lives = state.lives - lost_life.astype(jnp.int32)
    pos = jnp.where(lost_life, jnp.array([0, 0], jnp.int32), pos)

    t = state.t + 1
    done = (lives <= 0) | (t >= MAX_T)
    new_state = State(
        pos=pos,
        flipped=flipped,
        ball=ball,
        ball_live=ball_live | spawn,
        lives=lives,
        boards=boards,
        t=t,
    )
    fresh = reset(k_reset)
    new_state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(done, new, old), fresh, new_state
    )
    return new_state, render(new_state), reward, done


def render(state: State) -> jax.Array:
    """Isometric-ish pyramid: cube (r,c) centered at
    x = 0.5 + (c - r/2) * 0.13, y = 0.18 + r * 0.13."""
    h, w = obs_shape
    Y = ((jnp.arange(h, dtype=jnp.float32) + 0.5) / h)[:, None]
    X = ((jnp.arange(w, dtype=jnp.float32) + 0.5) / w)[None, :]

    cx = 0.5 + (_COL_OF.astype(jnp.float32) - _ROW_OF.astype(jnp.float32) / 2) * 0.13
    cy = 0.18 + _ROW_OF.astype(jnp.float32) * 0.13

    # cubes: dim if unflipped, bright if flipped  [N,H,W] -> max over N
    inx = jnp.abs(X[None] - cx[:, None, None]) <= 0.05
    iny = jnp.abs(Y[None] - cy[:, None, None]) <= 0.045
    cube_px = inx & iny
    shade = jnp.where(state.flipped, 200, 100).astype(jnp.uint8)
    frame = jnp.max(cube_px * shade[:, None, None], axis=0).astype(jnp.uint8)

    def at(pos):
        px = 0.5 + (pos[1].astype(jnp.float32) - pos[0].astype(jnp.float32) / 2) * 0.13
        py = 0.18 + pos[0].astype(jnp.float32) * 0.13 - 0.05
        return px, py

    ax, ay = at(state.pos)
    agent = (jnp.abs(X - ax) <= 0.025) & (jnp.abs(Y - ay) <= 0.025)
    frame = jnp.maximum(frame, agent.astype(jnp.uint8) * 255)
    bx, by = at(state.ball)
    ball = (jnp.abs(X - bx) <= 0.02) & (jnp.abs(Y - by) <= 0.02) & state.ball_live
    frame = jnp.maximum(frame, ball.astype(jnp.uint8) * 160)
    return frame
