"""ctypes binding for the C++ batched env core + ZMQ env-server process.

Reference equivalent: the ALE C++ emulator + its Python binding
(``ale_python_interface``/``atari_py``, SURVEY.md §2.10) — here the native
core is ``cpp/env_core.cc`` (build: ``make -C cpp``), exposing a BATCHED
step API so one process drives dozens of envs per call instead of the
reference's one-ALE-per-process layout.

Three integration surfaces:
- :class:`CppBatchedEnv` — raw batched stepper (numpy in/out, zero copies
  beyond the ctypes call).
- :func:`build_cpp_player` — single-env player (envs/base.py protocol) for
  wrappers/eval/SimulatorProcess parity paths.
- :class:`CppEnvServerProcess` — one OS process hosting B envs in lockstep,
  speaking the simulator wire protocol over ZMQ with one DEALER identity per
  env (the master cannot tell it apart from B SimulatorProcesses). Transport
  is thin pyzmq glue — the image ships no zmq.h, so the native side stays
  dependency-free and every hot cycle (physics + render) is C++.
"""

from __future__ import annotations

import ctypes
import multiprocessing as mp
import os
from typing import Optional, Tuple

import numpy as np

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "cpp",
    "libba3c_env.so",
)

_lib = None


def _try_build() -> bool:
    """Attempt `make -C cpp` once (the .so is a build artifact, not committed)."""
    import subprocess

    try:
        subprocess.run(
            ["make", "-C", os.path.dirname(_LIB_PATH)],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (OSError, subprocess.SubprocessError):
        return False
    return os.path.isfile(_LIB_PATH)


def _load():
    global _lib
    if _lib is None:
        if not os.path.isfile(_LIB_PATH) and not _try_build():
            raise ImportError(
                f"native env core not built: {_LIB_PATH} missing (run `make -C cpp`)"
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.ba3c_env_create.restype = ctypes.c_void_p
        lib.ba3c_env_create.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64]
        lib.ba3c_env_destroy.argtypes = [ctypes.c_void_p]
        lib.ba3c_env_num_actions.argtypes = [ctypes.c_void_p]
        lib.ba3c_env_num_actions.restype = ctypes.c_int
        lib.ba3c_env_size.argtypes = [ctypes.c_void_p]
        lib.ba3c_env_size.restype = ctypes.c_int
        lib.ba3c_env_reset.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8)]
        lib.ba3c_env_step.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.ba3c_obs_height.restype = ctypes.c_int
        lib.ba3c_obs_width.restype = ctypes.c_int
        _lib = lib
    return _lib


def available() -> bool:
    return os.path.isfile(_LIB_PATH) or _try_build()


class CppBatchedEnv:
    """N native envs stepped in one call. Obs are uint8 [N, 84, 84]."""

    def __init__(self, name: str, n: int, seed: int = 0):
        lib = _load()
        self._lib = lib
        self._handle = lib.ba3c_env_create(name.encode(), n, seed)
        if not self._handle:
            raise ValueError(f"unknown native env {name!r}")
        self.n = n
        self.h = lib.ba3c_obs_height()
        self.w = lib.ba3c_obs_width()
        self.num_actions = lib.ba3c_env_num_actions(self._handle)
        self._obs = np.zeros((n, self.h, self.w), np.uint8)
        self._rew = np.zeros(n, np.float32)
        self._done = np.zeros(n, np.uint8)

    def reset(self) -> np.ndarray:
        self._lib.ba3c_env_reset(
            self._handle,
            self._obs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return self._obs

    def step(
        self, actions: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """actions int32 [N] -> (obs [N,84,84] u8, rewards [N] f32, dones [N] u8).

        Returned arrays are internal buffers reused every call — copy if kept.
        """
        actions = np.ascontiguousarray(actions, np.int32)
        assert actions.shape == (self.n,)
        self._lib.ba3c_env_step(
            self._handle,
            actions.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._obs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            self._rew.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._done.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return self._obs, self._rew, self._done

    def close(self):
        if self._handle:
            self._lib.ba3c_env_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def build_cpp_player(idx: int, name: str = "pong", frame_history: int = 4):
    """Single native env as a history-stacked player (wire-compatible with
    build_fake_player; used by SimulatorProcess/eval parity paths)."""
    from distributed_ba3c_tpu.envs.base import RLEnvironment
    from distributed_ba3c_tpu.envs.wrappers import HistoryFramePlayer

    class _CppPlayer(RLEnvironment):
        def __init__(self):
            self.env = CppBatchedEnv(name, 1, seed=idx)
            self.env.reset()
            self.score = 0.0
            super().__init__()

        def current_state(self):
            return self.env._obs[0].copy()

        def get_action_space_size(self):
            return self.env.num_actions

        def action(self, act):
            _, rew, done = self.env.step(np.array([act], np.int32))
            r, over = float(rew[0]), bool(done[0])
            self.score += r
            if over:
                self.finish_episode(self.score)
                self.score = 0.0
            return r, over

        def restart_episode(self):
            self.env.reset()
            self.score = 0.0

    return HistoryFramePlayer(_CppPlayer(), frame_history)


def _decode_actions(raw: bytes, fallback: np.ndarray, counter) -> np.ndarray:
    """Decode a batched action-reply frame; junk must not kill the loop.

    The env server's lockstep loop is supervisor-owned: a corrupt or
    short reply frame (PR 14 class) repeats the previous actions, makes
    the drop visible on ``corrupt_action_replies_total``, and keeps the
    loop alive instead of raising out of ``_run_block*``.
    """
    try:
        actions = np.frombuffer(raw, np.int32)
    except Exception:
        counter.inc()
        return fallback
    if actions.shape != fallback.shape:
        counter.inc()
        return fallback
    return actions


def _decode_action(raw: bytes, fallback: int, counter) -> int:
    """Per-env twin of :func:`_decode_actions` for the ``per-env`` wire."""
    from distributed_ba3c_tpu.utils.serialize import loads

    try:
        return int(loads(raw))
    except Exception:
        counter.inc()
        return fallback


class CppEnvServerProcess(mp.get_context("spawn").Process):  # type: ignore[misc]
    """One process, B native envs, lockstep-batched stepping, ZMQ transport.

    Three wire modes (docs/actor_plane.md):

    - ``wire="block-shm"`` (default where available): control over ZMQ,
      observation bytes through a /dev/shm ring (utils/shm.py). ONE tiny
      multipart message per STEP — ``[header, rewards[B], dones[B]]``,
      where the header names the ring and the step's slot — and one raw
      ``int32[B]`` action reply. The obs bytes never cross a socket: the
      server memcpys each step's plane into ``ring[step % cap]`` and the
      master reads frame-history windows as numpy views. Same-host only
      (the learner's ipc:// or localhost pipes).
    - ``wire="block"``: ONE multipart message per STEP for the whole block
      — ``[header, obs[hist,B,H,W], rewards[B], dones[B]]`` as raw
      zero-copy frames — and one raw ``int32[B]`` action reply, routed by
      the block's single DEALER identity ``<prefix>*block``. The history
      stack lives in ``[hist, B, H, W]`` layout so the per-step shift is a
      contiguous memmove (~78 us/block vs ~4 ms for the channel-last shift
      at B=32 — measured on this container) and the wire frame is the
      buffer itself; the master consumes transposed VIEWS, so no side of
      the hot path ever materializes the channel-last interleave. This is
      the wire for REMOTE (tcp://) actor fleets.
    - ``wire="per-env"``: the compat/correctness foil — each env gets its
      own DEALER identity ``<prefix>-<i>`` and the per-env msgpack protocol
      matches SimulatorProcess exactly (SURVEY.md §3.2): send
      [ident, stacked_state, reward, isOver], await action. 2·B Python
      socket ops + B msgpack encodes per step; kept because any
      wire-compatible speaker (the reference's own simulators) can
      interleave with it on the same pipes.
    """

    #: default block-shm ring sizing: capacity (in steps) chosen so the
    #: ring is ~8192 env-steps deep regardless of B (~57 MB at 84x84),
    #: which keeps the master's attach-time safety check satisfied for
    #: train queues up to ~8k items at any block size (utils/shm.py)
    SHM_RING_STEPS = 8192
    SHM_RING_MIN_CAP = 64

    def __init__(
        self,
        idx: int,
        pipe_c2s: str,
        pipe_s2c: str,
        game: str = "pong",
        n_envs: int = 16,
        frame_history: int = 4,
        ident_prefix: Optional[str] = None,
        wire: str = "block",
        shm_ring_cap: Optional[int] = None,
    ):
        super().__init__(daemon=True, name=f"cpp-env-server-{idx}")
        assert wire in ("block-shm", "block", "per-env"), wire
        self.idx = idx
        self.c2s = pipe_c2s
        self.s2c = pipe_s2c
        self.game = game
        self.n_envs = n_envs
        self.frame_history = frame_history
        self.ident_prefix = ident_prefix or f"cppsim-{idx}"
        self.wire = wire
        self.shm_ring_cap = shm_ring_cap or max(
            self.SHM_RING_MIN_CAP, self.SHM_RING_STEPS // max(1, n_envs)
        )

    def run(self) -> None:  # child process: no jax
        if self.wire == "block-shm":
            self._run_block_shm()
        elif self.wire == "block":
            self._run_block()
        else:
            self._run_per_env()

    def _tele_setup(self):
        """Child-side telemetry: counters + the piggyback delta tracker.

        Returns ``(count_step, piggyback, extend_meta, c_bad)``:
        ``c_bad`` is the ``corrupt_action_replies_total`` reject counter
        fed to the ``_decode_action*`` helpers; ``count_step``
        is called once per lockstep block step; ``piggyback(step)``
        returns the deltas dict to append to the wire header (or None —
        which keeps the header at its OLD length, so telemetry-disabled
        fleets exercise the pre-telemetry wire format end-to-end);
        ``extend_meta(meta, step, env_us)`` appends the length-versioned
        tail — the deltas element and, on 1-in-N sampled steps, the trace
        context (telemetry/tracing.py) carrying this server's monotonic
        stamp (clock handshake) and its last env-step duration."""
        from distributed_ba3c_tpu import telemetry
        from distributed_ba3c_tpu.telemetry import tracing

        tele = telemetry.registry("simulator")
        c_steps = tele.counter("env_steps_total")
        c_eps = tele.counter("episodes_total")
        # reward split by sign: raw Atari rewards go NEGATIVE (Pong -1),
        # and a decreasing series exported as a Prometheus counter reads
        # as a counter reset (rate() spikes). Two monotonic halves keep
        # counter semantics; net reward = pos - neg at query time.
        c_rew_pos = tele.counter("reward_pos_sum")
        c_rew_neg = tele.counter("reward_neg_sum")
        c_bad = tele.counter("corrupt_action_replies_total")
        tracker = telemetry.DeltaTracker(tele)
        B = self.n_envs

        def count_step(rew, dn) -> None:
            c_steps.inc(B)
            n_done = int(dn.sum())
            if n_done:
                c_eps.inc(n_done)
            pos = float(rew[rew > 0].sum())
            neg = -float(rew[rew < 0].sum())
            if pos:
                c_rew_pos.inc(pos)
            if neg:
                c_rew_neg.inc(neg)

        def piggyback(step: int):
            if not telemetry.enabled():
                return None
            if step == 0 or step % telemetry.PIGGYBACK_EVERY:
                return None
            return tracker.deltas() or None

        ident = f"{self.ident_prefix}*block".encode()

        def extend_meta(meta: list, step: int, env_us: int) -> None:
            # THE one layout implementation lives in tracing.py — the
            # python simulator sender calls the same helper
            tracing.stamp_wire_meta(
                meta, ident, step, piggyback(step), env_us
            )

        return count_step, piggyback, extend_meta, c_bad

    def _run_block_shm(self) -> None:
        import signal

        import zmq

        from distributed_ba3c_tpu.utils.serialize import pack_block
        from distributed_ba3c_tpu.utils.shm import ShmRing

        # terminate() must run the finally block so the ring file is
        # unlinked (a SIGKILLed server's stale file is truncated over at
        # the next create)
        def _term(*_):
            raise SystemExit(0)

        signal.signal(signal.SIGTERM, _term)

        env = CppBatchedEnv(self.game, self.n_envs, seed=self.idx * 10_000)
        obs = env.reset()
        B, H, W, hist = self.n_envs, env.h, env.w, self.frame_history
        cap = self.shm_ring_cap
        ident = f"{self.ident_prefix}*block".encode()
        # the ring name must be STABLE across restarts of this server slot
        # (pipe pair + prefix identify the slot; concurrent fleets differ in
        # pipe address): a crashed/SIGKILLed server leaves its ring file in
        # /dev/shm, and create()'s rename-over reclaims it only if the
        # replacement generates the SAME name — a pid in the name would
        # leak ~57 MB per crash until /dev/shm fills. The name formula is
        # shared with the supervisor's stale-ring reclaim (utils/shm.py)
        from distributed_ba3c_tpu.utils import shm as shm_mod

        ring_name = shm_mod.ring_name(self.c2s, self.ident_prefix)
        ring = ShmRing.create(ring_name, cap, B, H, W)
        rewards = np.zeros(B, np.float32)
        dones = np.zeros(B, np.uint8)
        actions = np.zeros(B, np.int32)  # fallback on a corrupt reply

        ctx = zmq.Context()
        push = ctx.socket(zmq.PUSH)
        push.set_hwm(4)
        push.connect(self.c2s)
        dealer = ctx.socket(zmq.DEALER)
        dealer.setsockopt(zmq.IDENTITY, ident)
        dealer.connect(self.s2c)

        count_step, piggyback, extend_meta, c_bad = self._tele_setup()
        from distributed_ba3c_tpu.telemetry import tracing

        step = 0
        env_us = 0  # last env.step duration, shipped in the trace context
        try:
            while True:
                # the step's obs plane goes into the ring; the wire carries
                # only the header + rewards + dones (the master rebuilds
                # frame-history windows from ring slots — docs/actor_plane.md)
                ring.arr[step % cap] = obs
                meta = [ident, step, B, ring_name, cap, H, W, hist]
                extend_meta(meta, step, env_us)  # length-versioned tail
                # lockstep protocol: parking in send/recv awaiting the
                # action reply IS the env server's contract — a dead
                # master leaves this process to its supervisor (prune +
                # respawn), never to a local timeout
                push.send_multipart(  # ba3clint: disable=A12 — lockstep park, supervisor-owned lifetime
                    pack_block(meta, [rewards, dones]),
                    copy=False,
                )
                actions = _decode_actions(dealer.recv(), actions, c_bad)  # ba3clint: disable=A12 — lockstep park
                t_env = tracing.now_us() if tracing.enabled() else 0
                obs, rew, dn = env.step(actions)
                if t_env:
                    env_us = tracing.now_us() - t_env
                rewards[:] = rew
                dones[:] = dn
                count_step(rew, dn)
                step += 1
        except (KeyboardInterrupt, SystemExit, zmq.ContextTerminated):
            pass
        finally:
            dealer.close(0)
            push.close(0)
            ctx.term()
            ring.close(unlink=True)

    def _run_block(self) -> None:
        import zmq

        from distributed_ba3c_tpu.utils.serialize import pack_block

        env = CppBatchedEnv(self.game, self.n_envs, seed=self.idx * 10_000)
        obs = env.reset()
        B, H, W, hist = self.n_envs, env.h, env.w, self.frame_history
        # [hist, B, H, W]: oldest..newest planes, contiguous — the shift is
        # one contiguous memmove and the whole stack is ONE wire frame
        stacks = np.zeros((hist, B, H, W), np.uint8)
        stacks[-1] = obs
        rewards = np.zeros(B, np.float32)
        dones = np.zeros(B, np.uint8)
        actions = np.zeros(B, np.int32)  # fallback on a corrupt reply
        ident = f"{self.ident_prefix}*block".encode()

        ctx = zmq.Context()
        push = ctx.socket(zmq.PUSH)
        push.set_hwm(4)  # blocks are big; a deep send buffer is pure RAM
        push.connect(self.c2s)
        dealer = ctx.socket(zmq.DEALER)
        dealer.setsockopt(zmq.IDENTITY, ident)
        dealer.connect(self.s2c)

        count_step, piggyback, extend_meta, c_bad = self._tele_setup()
        from distributed_ba3c_tpu.telemetry import tracing

        step = 0
        env_us = 0  # last env.step duration, shipped in the trace context
        try:
            while True:
                meta = [ident, step, B]
                extend_meta(meta, step, env_us)  # length-versioned tail
                # copy=False hands zmq the arrays' own buffers. Safe ONLY
                # because the protocol is lockstep: the master cannot reply
                # with actions before it has received (= fully copied out of
                # this process over ipc/tcp) the observation message, and we
                # do not mutate the buffers until that reply arrives.
                push.send_multipart(  # ba3clint: disable=A12 — lockstep park, supervisor-owned lifetime
                    pack_block(meta, [stacks, rewards, dones]),
                    copy=False,
                )
                actions = _decode_actions(dealer.recv(), actions, c_bad)  # ba3clint: disable=A12 — lockstep park
                t_env = tracing.now_us() if tracing.enabled() else 0
                obs, rew, dn = env.step(actions)
                if t_env:
                    env_us = tracing.now_us() - t_env
                rewards[:] = rew
                dones[:] = dn
                count_step(rew, dn)
                # shift history (contiguous memmove); clear across episode
                # boundaries so the first post-reset state is [0,...,0,obs]
                stacks[:-1] = stacks[1:]
                stacks[-1] = obs
                if dn.any():
                    d = dn.astype(bool)
                    stacks[:-1, d] = 0
                step += 1
        except (KeyboardInterrupt, zmq.ContextTerminated):
            pass
        finally:
            dealer.close(0)
            push.close(0)
            ctx.term()

    def _run_per_env(self) -> None:
        import zmq

        from distributed_ba3c_tpu.utils.serialize import dumps

        env = CppBatchedEnv(self.game, self.n_envs, seed=self.idx * 10_000)
        obs = env.reset()
        B, H, W = self.n_envs, env.h, env.w
        stacks = np.zeros((B, H, W, self.frame_history), np.uint8)
        stacks[..., -1] = obs
        rewards = np.zeros(B, np.float32)
        dones = np.zeros(B, bool)

        ctx = zmq.Context()
        push = ctx.socket(zmq.PUSH)
        push.set_hwm(B + 4)
        push.connect(self.c2s)
        idents = [f"{self.ident_prefix}-{i}".encode() for i in range(B)]
        dealers = []
        for ident in idents:
            s = ctx.socket(zmq.DEALER)
            s.setsockopt(zmq.IDENTITY, ident)
            s.connect(self.s2c)
            dealers.append(s)

        count_step, piggyback, _, c_bad = self._tele_setup()
        actions = np.zeros(B, np.int32)
        step = 0
        try:
            while True:
                tele = piggyback(step)
                # the per-env wire IS the A6 antipattern — kept on purpose
                # as the compat/correctness foil (`--wire per-env`); the
                # block path above is the production wire. Telemetry rides
                # env 0's message as an optional 5th element.
                for i in range(B):
                    msg = [idents[i], stacks[i], float(rewards[i]), bool(dones[i])]
                    if i == 0 and tele is not None:
                        msg.append(tele)
                    push.send(  # ba3clint: disable=A12 — compat foil (lockstep park), see docstring
                        dumps(msg)
                    )
                for i in range(B):
                    actions[i] = _decode_action(
                        dealers[i].recv(),  # ba3clint: disable=A6,A12 — compat foil (lockstep park)
                        int(actions[i]),
                        c_bad,
                    )
                obs, rew, dn = env.step(actions)
                rewards[:] = rew
                dones[:] = dn.astype(bool)
                count_step(rew, dn)
                step += 1
                # shift history; clear across episode boundaries
                stacks[..., :-1] = stacks[..., 1:]
                stacks[..., -1] = obs
                if dones.any():
                    stacks[dones] = 0
                    stacks[dones, :, :, -1] = obs[dones]
        except (KeyboardInterrupt, zmq.ContextTerminated):
            pass
        finally:
            for s in dealers:
                s.close(0)
            push.close(0)
            ctx.term()
