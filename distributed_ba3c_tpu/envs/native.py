"""ctypes binding for the C++ batched env core + ZMQ env-server process.

Reference equivalent: the ALE C++ emulator + its Python binding
(``ale_python_interface``/``atari_py``, SURVEY.md §2.10) — here the native
core is ``cpp/env_core.cc`` (build: ``make -C cpp``), exposing a BATCHED
step API so one process drives dozens of envs per call instead of the
reference's one-ALE-per-process layout.

Three integration surfaces:
- :class:`CppBatchedEnv` — raw batched stepper (numpy in/out, zero copies
  beyond the ctypes call).
- :func:`build_cpp_player` — single-env player (envs/base.py protocol) for
  wrappers/eval/SimulatorProcess parity paths.
- :class:`CppEnvServerProcess` — one OS process hosting B envs in lockstep,
  speaking the simulator wire protocol over ZMQ with one DEALER identity per
  env (the master cannot tell it apart from B SimulatorProcesses). Transport
  is thin pyzmq glue — the image ships no zmq.h, so the native side stays
  dependency-free and every hot cycle (physics + render) is C++.
"""

from __future__ import annotations

import ctypes
import multiprocessing as mp
import os
from typing import Optional, Tuple

import numpy as np

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "cpp",
    "libba3c_env.so",
)

_lib = None


def _try_build() -> bool:
    """Attempt `make -C cpp` once (the .so is a build artifact, not committed)."""
    import subprocess

    try:
        subprocess.run(
            ["make", "-C", os.path.dirname(_LIB_PATH)],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (OSError, subprocess.SubprocessError):
        return False
    return os.path.isfile(_LIB_PATH)


def _load():
    global _lib
    if _lib is None:
        if not os.path.isfile(_LIB_PATH) and not _try_build():
            raise ImportError(
                f"native env core not built: {_LIB_PATH} missing (run `make -C cpp`)"
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.ba3c_env_create.restype = ctypes.c_void_p
        lib.ba3c_env_create.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64]
        lib.ba3c_env_destroy.argtypes = [ctypes.c_void_p]
        lib.ba3c_env_num_actions.argtypes = [ctypes.c_void_p]
        lib.ba3c_env_num_actions.restype = ctypes.c_int
        lib.ba3c_env_size.argtypes = [ctypes.c_void_p]
        lib.ba3c_env_size.restype = ctypes.c_int
        lib.ba3c_env_reset.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8)]
        lib.ba3c_env_step.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.ba3c_obs_height.restype = ctypes.c_int
        lib.ba3c_obs_width.restype = ctypes.c_int
        _lib = lib
    return _lib


def available() -> bool:
    return os.path.isfile(_LIB_PATH) or _try_build()


class CppBatchedEnv:
    """N native envs stepped in one call. Obs are uint8 [N, 84, 84]."""

    def __init__(self, name: str, n: int, seed: int = 0):
        lib = _load()
        self._lib = lib
        self._handle = lib.ba3c_env_create(name.encode(), n, seed)
        if not self._handle:
            raise ValueError(f"unknown native env {name!r}")
        self.n = n
        self.h = lib.ba3c_obs_height()
        self.w = lib.ba3c_obs_width()
        self.num_actions = lib.ba3c_env_num_actions(self._handle)
        self._obs = np.zeros((n, self.h, self.w), np.uint8)
        self._rew = np.zeros(n, np.float32)
        self._done = np.zeros(n, np.uint8)

    def reset(self) -> np.ndarray:
        self._lib.ba3c_env_reset(
            self._handle,
            self._obs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return self._obs

    def step(
        self, actions: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """actions int32 [N] -> (obs [N,84,84] u8, rewards [N] f32, dones [N] u8).

        Returned arrays are internal buffers reused every call — copy if kept.
        """
        actions = np.ascontiguousarray(actions, np.int32)
        assert actions.shape == (self.n,)
        self._lib.ba3c_env_step(
            self._handle,
            actions.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._obs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            self._rew.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._done.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return self._obs, self._rew, self._done

    def close(self):
        if self._handle:
            self._lib.ba3c_env_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def build_cpp_player(idx: int, name: str = "pong", frame_history: int = 4):
    """Single native env as a history-stacked player (wire-compatible with
    build_fake_player; used by SimulatorProcess/eval parity paths)."""
    from distributed_ba3c_tpu.envs.base import RLEnvironment
    from distributed_ba3c_tpu.envs.wrappers import HistoryFramePlayer

    class _CppPlayer(RLEnvironment):
        def __init__(self):
            self.env = CppBatchedEnv(name, 1, seed=idx)
            self.env.reset()
            self.score = 0.0
            super().__init__()

        def current_state(self):
            return self.env._obs[0].copy()

        def get_action_space_size(self):
            return self.env.num_actions

        def action(self, act):
            _, rew, done = self.env.step(np.array([act], np.int32))
            r, over = float(rew[0]), bool(done[0])
            self.score += r
            if over:
                self.finish_episode(self.score)
                self.score = 0.0
            return r, over

        def restart_episode(self):
            self.env.reset()
            self.score = 0.0

    return HistoryFramePlayer(_CppPlayer(), frame_history)


class CppEnvServerProcess(mp.get_context("spawn").Process):  # type: ignore[misc]
    """One process, B native envs, lockstep-batched stepping, ZMQ transport.

    Each env gets its own DEALER socket with identity ``<prefix>-<i>`` so the
    ROUTER-side master multiplexes B clients from one process. Protocol per
    env matches SimulatorProcess exactly (SURVEY.md §3.2): send
    [ident, stacked_state, reward, isOver], await action. Frame-history
    stacking happens here (numpy ring buffer), matching HistoryFramePlayer.
    """

    def __init__(
        self,
        idx: int,
        pipe_c2s: str,
        pipe_s2c: str,
        game: str = "pong",
        n_envs: int = 16,
        frame_history: int = 4,
        ident_prefix: Optional[str] = None,
    ):
        super().__init__(daemon=True, name=f"cpp-env-server-{idx}")
        self.idx = idx
        self.c2s = pipe_c2s
        self.s2c = pipe_s2c
        self.game = game
        self.n_envs = n_envs
        self.frame_history = frame_history
        self.ident_prefix = ident_prefix or f"cppsim-{idx}"

    def run(self) -> None:  # child process: no jax
        import zmq

        from distributed_ba3c_tpu.utils.serialize import dumps, loads

        env = CppBatchedEnv(self.game, self.n_envs, seed=self.idx * 10_000)
        obs = env.reset()
        B, H, W = self.n_envs, env.h, env.w
        stacks = np.zeros((B, H, W, self.frame_history), np.uint8)
        stacks[..., -1] = obs
        rewards = np.zeros(B, np.float32)
        dones = np.zeros(B, bool)

        ctx = zmq.Context()
        push = ctx.socket(zmq.PUSH)
        push.set_hwm(B + 4)
        push.connect(self.c2s)
        idents = [f"{self.ident_prefix}-{i}".encode() for i in range(B)]
        dealers = []
        for ident in idents:
            s = ctx.socket(zmq.DEALER)
            s.setsockopt(zmq.IDENTITY, ident)
            s.connect(self.s2c)
            dealers.append(s)

        actions = np.zeros(B, np.int32)
        try:
            while True:
                for i in range(B):
                    push.send(
                        dumps([idents[i], stacks[i], float(rewards[i]), bool(dones[i])])
                    )
                for i in range(B):
                    actions[i] = loads(dealers[i].recv())
                obs, rew, dn = env.step(actions)
                rewards[:] = rew
                dones[:] = dn.astype(bool)
                # shift history; clear across episode boundaries
                stacks[..., :-1] = stacks[..., 1:]
                stacks[..., -1] = obs
                if dones.any():
                    stacks[dones] = 0
                    stacks[dones, :, :, -1] = obs[dones]
        except (KeyboardInterrupt, zmq.ContextTerminated):
            pass
        finally:
            for s in dealers:
                s.close(0)
            push.close(0)
            ctx.term()
