"""FakeEnv: a scripted chain MDP with a known optimal return.

The e2e smoke harness the reference never had (SURVEY.md §4, §7 step 3): the
whole actor plane (ZMQ, master, predictor, learner) is exercised against this
env with zero Atari dependency, and "does it learn" becomes an assertion
against a known optimum instead of an overnight learning curve.

MDP: positions 0..chain_len-1, start at 0. Action 1 moves right, action 0
moves left, all other actions are no-ops. Reaching the right end pays +1 and
ends the episode; episodes also end after ``max_steps``. Optimal policy
(always right) scores 1.0 per episode in chain_len-1 steps.

Observation: image_size grayscale uint8 frame; the agent's position is drawn
as a bright vertical bar (position maps to horizontal placement), so a conv
policy can read it. numpy-only — runs in simulator child processes.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from distributed_ba3c_tpu.envs.base import RLEnvironment


class FakeEnv(RLEnvironment):
    def __init__(
        self,
        chain_len: int = 4,
        max_steps: int = 16,
        image_size: Tuple[int, int] = (84, 84),
        num_actions: int = 4,
        noise: int = 10,
        seed: int = 0,
    ):
        self.chain_len = chain_len
        self.max_steps = max_steps
        self.image_size = image_size
        self.num_actions = num_actions
        self.noise = noise
        self._rng = np.random.default_rng(seed)
        super().__init__()
        self._restart()

    def _restart(self):
        self.pos = 0
        self.steps = 0
        self.score = 0.0

    def _render(self) -> np.ndarray:
        h, w = self.image_size
        frame = self._rng.integers(
            0, self.noise + 1, (h, w), dtype=np.uint8
        ) if self.noise else np.zeros((h, w), np.uint8)
        # bright bar at the column band for the current position
        band = w // self.chain_len
        lo = self.pos * band
        frame[:, lo : lo + band] = 230
        return frame

    def current_state(self) -> np.ndarray:
        return self._render()

    def get_action_space_size(self) -> int:
        return self.num_actions

    def action(self, act: int) -> Tuple[float, bool]:
        if act == 1:
            self.pos = min(self.pos + 1, self.chain_len - 1)
        elif act == 0:
            self.pos = max(self.pos - 1, 0)
        self.steps += 1

        reward = 0.0
        is_over = False
        if self.pos == self.chain_len - 1:
            reward = 1.0
            is_over = True
        elif self.steps >= self.max_steps:
            is_over = True

        self.score += reward
        if is_over:
            self.finish_episode(self.score)
            self._restart()
        return reward, is_over

    def restart_episode(self) -> None:
        self._restart()

    @property
    def optimal_score(self) -> float:
        return 1.0


def build_fake_player(
    idx: int,
    image_size: Tuple[int, int] = (84, 84),
    frame_history: int = 4,
    chain_len: int = 4,
    max_steps: int = 16,
    num_actions: int = 4,
    noise: int = 10,
):
    """Standard player assembly for FakeEnv actors (reference: ``get_player``
    in ``src/train.py`` — base env → state map → frame history)."""
    from distributed_ba3c_tpu.envs.wrappers import HistoryFramePlayer

    env = FakeEnv(
        chain_len=chain_len,
        max_steps=max_steps,
        image_size=image_size,
        num_actions=num_actions,
        noise=noise,
        seed=idx,
    )
    return HistoryFramePlayer(env, frame_history)
