"""The player protocol.

Reference equivalent: ``tensorpack/RL/envbase.py`` — ``RLEnvironment`` with
``current_state() / action(a) -> (reward, isOver) / reset_stat()`` and
``ProxyPlayer`` (SURVEY.md §1 L2 interface, §2.2 #6). Episodes auto-restart:
after ``action`` returns ``isOver=True`` the player's ``current_state()`` is
the first observation of a fresh episode — simulator loops never call reset.

Deliberately numpy-only (no jax import): this module runs inside simulator
child processes.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Tuple

import numpy as np


class RLEnvironment(ABC):
    """A single sequential environment ("player")."""

    def __init__(self):
        self.reset_stat()

    @abstractmethod
    def current_state(self) -> np.ndarray:
        """Observation for the current timestep."""

    @abstractmethod
    def action(self, act: int) -> Tuple[float, bool]:
        """Take an action. Returns (reward, isOver); restarts on episode end."""

    def reset_stat(self) -> None:
        """Reset accumulated per-episode statistics."""
        self.stats = {"score": []}

    def finish_episode(self, score: float) -> None:
        self.stats["score"].append(score)

    def get_action_space_size(self) -> int:
        raise NotImplementedError

    def restart_episode(self) -> None:
        """Force-restart the current episode (used by eval)."""
        raise NotImplementedError


class ProxyPlayer(RLEnvironment):
    """Base for wrappers: forwards everything to the wrapped player."""

    def __init__(self, player: RLEnvironment):
        self.player = player
        super().__init__()

    def current_state(self):
        return self.player.current_state()

    def action(self, act):
        return self.player.action(act)

    def reset_stat(self):
        # Called from __init__ before self.player may exist on subclasses that
        # set attributes first; ProxyPlayer.__init__ assigns player beforehand.
        self.player.reset_stat()

    @property
    def stats(self):
        return self.player.stats

    @stats.setter
    def stats(self, v):  # RLEnvironment.__init__ compatibility
        pass

    def finish_episode(self, score):
        self.player.finish_episode(score)

    def get_action_space_size(self):
        return self.player.get_action_space_size()

    def restart_episode(self):
        self.player.restart_episode()
