"""Environments and the player protocol.

Reference equivalent: ``src/tensorpack/RL/`` + ``src/atari.py`` (SURVEY.md
§2.2). The player protocol (``current_state`` / ``action`` / ``reset_stat``)
is kept so simulator processes, eval, and wrappers compose identically; the
on-device vectorized envs (``envs/jax/``) are the TPU-native addition.
"""

from distributed_ba3c_tpu.envs.base import RLEnvironment, ProxyPlayer
from distributed_ba3c_tpu.envs.fake import FakeEnv
from distributed_ba3c_tpu.envs.wrappers import (
    HistoryFramePlayer,
    LimitLengthPlayer,
    MapPlayerState,
    PreventStuckPlayer,
)

__all__ = [
    "RLEnvironment",
    "ProxyPlayer",
    "FakeEnv",
    "HistoryFramePlayer",
    "LimitLengthPlayer",
    "MapPlayerState",
    "PreventStuckPlayer",
]
