"""Player wrappers: frame-history stacking, state mapping, episode guards.

Reference equivalents (SURVEY.md §2.2 #6): ``HistoryFramePlayer``
(``RL/history.py``), ``MapPlayerState``, ``PreventStuckPlayer``,
``LimitLengthPlayer`` (``RL/common.py``). numpy-only — runs in simulator
child processes.
"""

from __future__ import annotations

from collections import deque
from typing import Callable

import numpy as np

from distributed_ba3c_tpu.envs.base import ProxyPlayer, RLEnvironment


class HistoryFramePlayer(ProxyPlayer):
    """Stack the last ``hist_len`` frames along the channel axis.

    Output shape [H, W, hist_len * C]; the stack is zero-padded at episode
    start and cleared across episode boundaries.
    """

    def __init__(self, player: RLEnvironment, hist_len: int):
        super().__init__(player)
        self.history: deque = deque(maxlen=hist_len)
        self.history.append(self.player.current_state())

    def current_state(self) -> np.ndarray:
        assert len(self.history) != 0
        diff_len = self.history.maxlen - len(self.history)
        sample = self.history[0]
        if sample.ndim == 2:
            stack = [np.zeros_like(sample)] * diff_len + list(self.history)
            return np.stack(stack, axis=-1)
        stack = [np.zeros_like(sample)] * diff_len + list(self.history)
        return np.concatenate(stack, axis=-1)

    def action(self, act):
        reward, is_over = self.player.action(act)
        if is_over:
            self.history.clear()
        self.history.append(self.player.current_state())
        return reward, is_over

    def restart_episode(self):
        super().restart_episode()
        self.history.clear()
        self.history.append(self.player.current_state())


class MapPlayerState(ProxyPlayer):
    """Apply ``func`` to every observation (e.g. resize / grayscale)."""

    def __init__(self, player: RLEnvironment, func: Callable[[np.ndarray], np.ndarray]):
        super().__init__(player)
        self.func = func

    def current_state(self):
        return self.func(self.player.current_state())


class PreventStuckPlayer(ProxyPlayer):
    """Force ``action_on_stuck`` if the observation repeats ``limit`` times.

    Anti-stuck guard for games that pause until "fire" is pressed.
    """

    def __init__(self, player: RLEnvironment, limit: int, action_on_stuck: int):
        super().__init__(player)
        self.last_obs: deque = deque(maxlen=limit)
        self.action_on_stuck = action_on_stuck

    def action(self, act):
        self.last_obs.append(hash(self.player.current_state().tobytes()))
        if (
            len(self.last_obs) == self.last_obs.maxlen
            and len(set(self.last_obs)) == 1
        ):
            act = self.action_on_stuck
        reward, is_over = self.player.action(act)
        if is_over:
            self.last_obs.clear()
        return reward, is_over

    def restart_episode(self):
        super().restart_episode()
        self.last_obs.clear()


def guarded_player(
    idx: int,
    base_build: Callable[[int], RLEnvironment],
    episode_length_cap: int = 0,
    stuck_limit: int = 0,
    stuck_action: int = 1,
) -> RLEnvironment:
    """Apply the reference's train-mode episode guards around a base player.

    Reference ``get_player(train=True)`` stacked PreventStuckPlayer +
    LimitLengthPlayer outside the history/map wrappers (SURVEY.md §2.2 #6).
    Top-level function so ``functools.partial`` of it stays picklable for
    spawned SimulatorProcess children.
    """
    p = base_build(idx)
    if stuck_limit:
        p = PreventStuckPlayer(p, stuck_limit, stuck_action)
    if episode_length_cap:
        p = LimitLengthPlayer(p, episode_length_cap)
    return p


class LimitLengthPlayer(ProxyPlayer):
    """Cap episode length at ``limit`` steps (reference cap: 40000)."""

    def __init__(self, player: RLEnvironment, limit: int):
        super().__init__(player)
        self.limit = limit
        self.cnt = 0

    def action(self, act):
        reward, is_over = self.player.action(act)
        self.cnt += 1
        if self.cnt >= self.limit and not is_over:
            is_over = True
            self.player.restart_episode()
        if is_over:
            self.cnt = 0
        return reward, is_over

    def restart_episode(self):
        super().restart_episode()
        self.cnt = 0
