"""Gymnasium adapter to the player protocol.

Reference equivalent: ``tensorpack/RL/gymenv.py`` ``GymEnv`` (SURVEY.md §2.2
#7) — wraps any gym env into the ``current_state/action/reset_stat`` player
protocol so the simulator/eval plumbing works unchanged. ALE is not installed
in this image; classic-control envs (and anything else gymnasium ships) work,
with an optional ``state_map`` to imageize observations for the conv net.
numpy-only at import (gymnasium imported lazily) — safe in simulator children.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

from distributed_ba3c_tpu.envs.base import RLEnvironment


class GymEnv(RLEnvironment):
    def __init__(
        self,
        name: str,
        seed: int = 0,
        state_map: Optional[Callable[[np.ndarray], np.ndarray]] = None,
    ):
        import gymnasium

        self.gymenv = gymnasium.make(name)
        self._seed = seed
        self.state_map = state_map or (lambda s: s)
        self.score = 0.0
        super().__init__()
        self._obs, _ = self.gymenv.reset(seed=seed)

    def current_state(self) -> np.ndarray:
        return self.state_map(np.asarray(self._obs))

    def get_action_space_size(self) -> int:
        return int(self.gymenv.action_space.n)

    def action(self, act: int) -> Tuple[float, bool]:
        obs, r, terminated, truncated, _ = self.gymenv.step(act)
        self._obs = obs
        is_over = bool(terminated or truncated)
        self.score += float(r)
        if is_over:
            self.finish_episode(self.score)
            self.score = 0.0
            self._obs, _ = self.gymenv.reset()
        return float(r), is_over

    def restart_episode(self) -> None:
        self._obs, _ = self.gymenv.reset()
        self.score = 0.0
