"""Gymnasium adapter to the player protocol.

Reference equivalent: ``tensorpack/RL/gymenv.py`` ``GymEnv`` (SURVEY.md §2.2
#7) — wraps any gym env into the ``current_state/action/reset_stat`` player
protocol so the simulator/eval plumbing works unchanged. ALE is not installed
in this image; classic-control envs (and anything else gymnasium ships) work,
with an optional ``state_map`` to imageize observations for the conv net.
numpy-only at import (gymnasium imported lazily) — safe in simulator children.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

from distributed_ba3c_tpu.envs.base import RLEnvironment


class GymEnv(RLEnvironment):
    def __init__(
        self,
        name: str,
        seed: int = 0,
        state_map: Optional[Callable[[np.ndarray], np.ndarray]] = None,
    ):
        import gymnasium

        self.gymenv = gymnasium.make(name)
        self._seed = seed
        self.state_map = state_map or (lambda s: s)
        self.score = 0.0
        super().__init__()
        self._obs, _ = self.gymenv.reset(seed=seed)

    def current_state(self) -> np.ndarray:
        return self.state_map(np.asarray(self._obs))

    def get_action_space_size(self) -> int:
        return int(self.gymenv.action_space.n)

    def action(self, act: int) -> Tuple[float, bool]:
        obs, r, terminated, truncated, _ = self.gymenv.step(act)
        self._obs = obs
        is_over = bool(terminated or truncated)
        self.score += float(r)
        if is_over:
            self.finish_episode(self.score)
            self.score = 0.0
            self._obs, _ = self.gymenv.reset()
        return float(r), is_over

    def restart_episode(self) -> None:
        self._obs, _ = self.gymenv.reset()
        self.score = 0.0


def imageize_obs(
    obs: np.ndarray,
    image_size: Tuple[int, int] = (84, 84),
    float_scale: float = 1.0,
) -> np.ndarray:
    """Embed any observation into a uint8 [H, W] frame for the conv net.

    Image observations are grayscaled + resized (the AtariPlayer preproc
    path); low-dimensional vectors are tanh-squashed into per-feature
    vertical bands so classic-control envs run through the unchanged
    BA3C pipeline. ``float_scale`` converts float frames to [0,255] — set
    ONCE from the env's declared observation_space (255.0 for normalized
    [0,1] spaces); per-frame autoscaling would mix intensity scales across
    the stacked history.
    """
    obs = np.asarray(obs)
    if obs.ndim >= 2:  # image-like
        import cv2

        if obs.ndim == 3:
            obs = obs.mean(axis=-1)
        if np.issubdtype(obs.dtype, np.floating):
            obs = np.clip(obs * float_scale, 0.0, 255.0)
        return cv2.resize(obs.astype(np.uint8), image_size[::-1])
    flat = obs.astype(np.float32).ravel()
    vals = (np.tanh(flat) * 127.5 + 127.5).astype(np.uint8)
    img = np.zeros(image_size, np.uint8)
    w = image_size[1]
    band = max(1, w // max(1, len(vals)))
    for i, v in enumerate(vals[: w // band]):
        img[:, i * band : (i + 1) * band] = v
    return img


def build_gym_player(
    idx: int,
    name: str = "CartPole-v1",
    frame_history: int = 4,
    image_size: Tuple[int, int] = (84, 84),
):
    """Player factory for ``--env gym:<name>`` (top-level: picklable)."""
    import functools

    from distributed_ba3c_tpu.envs.wrappers import (
        HistoryFramePlayer,
        MapPlayerState,
    )

    env = GymEnv(name, seed=idx)
    # decide float-frame scaling ONCE from the declared space bounds:
    # only a finite high > 1 means "already pixel-scaled"; normalized [0,1]
    # spaces AND envs with inf/undeclared bounds (normalizer wrappers) get
    # the x255 — per-frame autoscaling would mix scales across the history
    space = env.gymenv.observation_space
    high = np.asarray(getattr(space, "high", np.inf), np.float64)
    declared_pixel_range = np.all(np.isfinite(high)) and float(high.max()) > 1.0
    float_scale = 1.0 if declared_pixel_range else 255.0
    mapped = MapPlayerState(
        env,
        functools.partial(
            imageize_obs, image_size=image_size, float_scale=float_scale
        ),
    )
    return HistoryFramePlayer(mapped, frame_history)
