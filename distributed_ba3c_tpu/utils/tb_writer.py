"""TensorBoard scalar event plane.

Reference equivalent (SURVEY.md §5 observability): the TF summary plane —
``add_moving_summary``/``summary.py`` scalars that tensorboard renders next
to ``stat.json``. TPU-native rebuild keeps the same metric NAMES and emits
standard tfevents files via the installed ``tensorboard`` package's event
writer (no TensorFlow dependency). If tensorboard is unavailable the writer
degrades to a no-op so headless images still train.
"""

from __future__ import annotations

import time
from typing import Optional


class TBScalarWriter:
    """Minimal scalar-only event-file writer (``logdir/events.out.tfevents*``)."""

    def __init__(self, log_dir: str):
        self._writer = None
        try:
            from tensorboard.compat.proto.event_pb2 import Event  # noqa: F401
            from tensorboard.summary.writer.event_file_writer import (
                EventFileWriter,
            )

            self._writer = EventFileWriter(log_dir)
        except Exception:  # noqa: BLE001 - observability must never kill training
            from distributed_ba3c_tpu.utils import logger

            logger.warn(
                "tensorboard unavailable — scalar event plane disabled "
                "(stat.json/channels.jsonl still written)"
            )

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        if self._writer is None:
            return
        from tensorboard.compat.proto.event_pb2 import Event
        from tensorboard.compat.proto.summary_pb2 import Summary

        event = Event(
            wall_time=time.time(),
            step=int(step),
            summary=Summary(
                value=[Summary.Value(tag=tag, simple_value=float(value))]
            ),
        )
        self._writer.add_event(event)

    def add_scalars(self, record: dict, step: Optional[int] = None) -> None:
        """Emit one epoch record (the stat.json dict) as scalar events."""
        if self._writer is None:
            return
        if step is None:
            step = int(record.get("global_step", 0))
        for k, v in record.items():
            if k == "global_step":
                continue
            self.add_scalar(k, v, step)

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
