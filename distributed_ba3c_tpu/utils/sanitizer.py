"""Opt-in runtime race sanitizer for the actor plane (``BA3C_SANITIZE=1``).

ba3clint (tools/ba3clint) checks the actor plane's conventions *lexically*;
two of them can only be fully verified at runtime, so this module makes them
observable:

1. **Client-table ownership** — the master's ``clients`` table is
   structurally mutated (entries created/replaced/deleted) only by the
   thread that owns it (the master's receive loop, which calls
   :func:`claim_owner` at startup). A predictor callback resurrecting a
   pruned client via ``defaultdict.__missing__`` is exactly the cross-thread
   structural write this catches.
2. **Single-consumer queues** — each plane queue (``send_queue``, the train
   queue) is drained by exactly one thread; a second consumer means two
   components think they own the hand-off side.

On violation the sanitizer records a finding and raises
:class:`SanitizerError` immediately (fail loudly); tests additionally assert
``findings() == []`` at teardown so a swallowed exception still fails the
run.

Zero overhead when disabled: the ``wrap_*`` helpers return plain objects
unless ``BA3C_SANITIZE`` is set to a truthy value, and :func:`claim_owner`
is a no-op on unwrapped objects. The env var is read at *wrap time* so
tests can flip it per-test with ``monkeypatch.setenv``.
"""

from __future__ import annotations

import os
import queue as _queue_mod
import threading
from collections import defaultdict
from typing import Callable, List, Optional


class SanitizerError(AssertionError):
    """A machine-checked actor-plane invariant was violated."""


_findings: List[str] = []
_findings_lock = threading.Lock()


def enabled() -> bool:
    return os.environ.get("BA3C_SANITIZE", "") not in ("", "0")


def findings() -> List[str]:
    with _findings_lock:
        return list(_findings)


def reset() -> None:
    with _findings_lock:
        _findings.clear()


def _report(msg: str) -> None:
    with _findings_lock:
        _findings.append(msg)
    try:
        # postmortem BEFORE the raise: the exception may be swallowed by a
        # worker thread, but the flight dump survives on disk either way
        from distributed_ba3c_tpu import telemetry

        telemetry.record("sanitizer", finding=msg)
        telemetry.dump("SanitizerError")
    except Exception:
        pass  # telemetry must never mask the finding itself
    raise SanitizerError(msg)


class SanitizedClientTable(dict):
    """``defaultdict``-alike that restricts structural writes to one thread.

    Reads (``[]`` on an existing key, ``items()``, ``len()``) are allowed
    from any thread — the per-client *contents* are protocol-serialized and
    checked elsewhere; what must be single-threaded is the table's shape.
    """

    def __init__(self, default_factory: Callable[[], object], name: str):
        super().__init__()
        self._factory = default_factory
        self._name = name
        self._owner: Optional[threading.Thread] = None

    def claim_owner(self) -> None:
        """Declare the calling thread the structural owner (master loop)."""
        self._owner = threading.current_thread()

    def _check(self, op: str, key) -> None:
        owner = self._owner
        if owner is None:
            return  # unclaimed: setup-phase mutations are unrestricted
        t = threading.current_thread()
        if t is not owner:
            _report(
                f"{self._name}: structural {op} of {key!r} from thread "
                f"{t.name!r} but the table is owned by {owner.name!r} — "
                "cross-thread mutation without ownership transfer"
            )

    def __missing__(self, key):
        self._check("create", key)
        value = self._factory()
        dict.__setitem__(self, key, value)
        return value

    def __setitem__(self, key, value) -> None:
        self._check("set", key)
        dict.__setitem__(self, key, value)

    def __delitem__(self, key) -> None:
        self._check("delete", key)
        dict.__delitem__(self, key)

    def pop(self, key, *default):
        self._check("pop", key)
        return dict.pop(self, key, *default)

    def popitem(self):
        self._check("popitem", "*")
        return dict.popitem(self)

    def setdefault(self, key, default=None):
        if key not in self:
            self._check("create", key)
        return dict.setdefault(self, key, default)

    def update(self, *args, **kwargs):
        self._check("update", "*")
        dict.update(self, *args, **kwargs)

    def clear(self) -> None:
        self._check("clear", "*")
        dict.clear(self)


class SanitizedQueue:
    """Proxy around a ``queue.Queue`` asserting the single-consumer contract.

    A proxy (not a subclass copy) so the wrapped queue's storage is shared
    with any pre-existing references the caller holds. The consumer slot
    re-arms when the recorded consumer thread has exited, so sequential
    owners (test teardown → next test) are fine; *concurrent* second
    consumers are findings.
    """

    def __init__(self, q: _queue_mod.Queue, name: str):
        self._q = q
        self._name = name
        self._consumer: Optional[threading.Thread] = None
        self._consumer_lock = threading.Lock()

    def _check_consumer(self) -> None:
        t = threading.current_thread()
        with self._consumer_lock:
            c = self._consumer
            if c is None or c is t or not c.is_alive():
                self._consumer = t
                return
        _report(
            f"{self._name}: get() from thread {t.name!r} but "
            f"{c.name!r} is already the live consumer — a plane queue "
            "must have exactly one drain thread"
        )

    # -- consumer side (checked) ------------------------------------------
    def get(self, block: bool = True, timeout: Optional[float] = None):
        self._check_consumer()
        return self._q.get(block=block, timeout=timeout)

    def get_nowait(self):
        self._check_consumer()
        return self._q.get_nowait()

    # -- producer side / passthrough --------------------------------------
    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        return self._q.put(item, block=block, timeout=timeout)

    def put_nowait(self, item):
        return self._q.put_nowait(item)

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()

    def task_done(self) -> None:
        self._q.task_done()

    def join(self) -> None:
        self._q.join()

    @property
    def maxsize(self) -> int:
        return self._q.maxsize


def _lock_held(lock) -> bool:
    """Best-effort 'does the CALLING thread hold this lock'.

    RLocks know their owner (``_is_owned``); plain locks only know they
    are held by someone, which is the best we can check without changing
    the caller's lock type.
    """
    owned = getattr(lock, "_is_owned", None)
    if owned is not None:
        return bool(owned())
    return lock.locked()


class SanitizedGuardedDict(dict):
    """Dict whose structural writes must happen with the guard lock held.

    The serving router's replica table is read lock-free on the dispatch
    fast path but every shape change (add/remove replica) is supposed to
    go through ``self._lock`` — ba3cflow proves that for the code it can
    see; this wrapper proves it for code it can't (monkeypatched tests,
    exec'd config hooks, future callers). Reads are unrestricted.
    """

    def __init__(self, lock, name: str):
        super().__init__()
        self._guard = lock
        self._name = name

    def _check(self, op: str, key) -> None:
        if not _lock_held(self._guard):
            _report(
                f"{self._name}: structural {op} of {key!r} without "
                "holding the guarding lock — every table shape change "
                "must be lock-serialized"
            )

    def __setitem__(self, key, value) -> None:
        self._check("set", key)
        dict.__setitem__(self, key, value)

    def __delitem__(self, key) -> None:
        self._check("delete", key)
        dict.__delitem__(self, key)

    def pop(self, key, *default):
        self._check("pop", key)
        return dict.pop(self, key, *default)

    def popitem(self):
        self._check("popitem", "*")
        return dict.popitem(self)

    def setdefault(self, key, default=None):
        if key not in self:
            self._check("create", key)
        return dict.setdefault(self, key, default)

    def update(self, *args, **kwargs):
        self._check("update", "*")
        dict.update(self, *args, **kwargs)

    def clear(self) -> None:
        self._check("clear", "*")
        dict.clear(self)


class SanitizedGuardedList(list):
    """List whose structural writes must happen with the guard lock held
    (ReplicaSet's ``_live`` roster). Reads are unrestricted."""

    def __init__(self, lock, name: str):
        super().__init__()
        self._guard = lock
        self._name = name

    def _check(self, op: str) -> None:
        if not _lock_held(self._guard):
            _report(
                f"{self._name}: structural {op} without holding the "
                "guarding lock — every roster change must be "
                "lock-serialized"
            )

    def append(self, item) -> None:
        self._check("append")
        list.append(self, item)

    def extend(self, items) -> None:
        self._check("extend")
        list.extend(self, items)

    def insert(self, i, item) -> None:
        self._check("insert")
        list.insert(self, i, item)

    def remove(self, item) -> None:
        self._check("remove")
        list.remove(self, item)

    def pop(self, *index):
        self._check("pop")
        return list.pop(self, *index)

    def clear(self) -> None:
        self._check("clear")
        list.clear(self)

    def __setitem__(self, i, item) -> None:
        self._check("setitem")
        list.__setitem__(self, i, item)

    def __delitem__(self, i) -> None:
        self._check("delitem")
        list.__delitem__(self, i)


def wrap_client_table(default_factory: Callable[[], object], name: str):
    """A client table: sanitized when enabled, plain defaultdict otherwise."""
    if not enabled():
        return defaultdict(default_factory)
    return SanitizedClientTable(default_factory, name)


def wrap_queue(q: _queue_mod.Queue, name: str):
    """Wrap an actor-plane queue with the single-consumer check (when on)."""
    if not enabled():
        return q
    return SanitizedQueue(q, name)


def wrap_guarded_dict(lock, name: str):
    """A lock-guarded table: sanitized when enabled, plain dict otherwise."""
    if not enabled():
        return {}
    return SanitizedGuardedDict(lock, name)


def wrap_guarded_list(lock, name: str):
    """A lock-guarded roster: sanitized when enabled, plain list otherwise."""
    if not enabled():
        return []
    return SanitizedGuardedList(lock, name)


def claim_owner(obj) -> None:
    """Record the calling thread as ``obj``'s owner (no-op if unwrapped)."""
    claim = getattr(obj, "claim_owner", None)
    if callable(claim):
        claim()
