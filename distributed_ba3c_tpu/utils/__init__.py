"""Host-side utilities: serialization, concurrency, logging, stats.

Reference equivalent: ``src/tensorpack/utils/`` (SURVEY.md §2.8 #25-28).
"""

from distributed_ba3c_tpu.utils.serialize import dumps, loads

__all__ = ["dumps", "loads"]
