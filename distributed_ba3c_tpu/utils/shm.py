"""Shared-memory observation rings for the same-host block wire.

The ``block-shm`` wire (docs/actor_plane.md) keeps ZMQ for the CONTROL
plane — tiny header/rewards/dones messages and the int32 action replies —
and moves the observation bytes through a ``/dev/shm`` ring: the env server
writes each step's obs plane into ``ring[step % cap]`` and the master reads
frame-history WINDOWS of the ring as zero-copy numpy views.

Why not zmq frames for the obs too (the plain ``block`` wire)? On a normal
kernel they are fine; on sandboxed kernels with expensive syscalls (this
container: ~225 us per socket roundtrip, ~300-550 MB/s socket bandwidth,
measured) the obs bytes dominate the wire and cap the plane far below the
env core's rate. A ring write is one process-local memcpy; nothing else
ever copies.

Deliberately raw ``mmap`` over ``multiprocessing.shared_memory``: the
stdlib's resource tracker registers ATTACHED segments too (py3.10), so the
first process to exit unlinks a segment others still map. A file in
``/dev/shm`` has exactly the lifecycle we want: the creator unlinks it;
stale files from SIGKILLed creators are atomically RENAMED over at
re-create (never truncated in place — a master may still map the old
inode, and shrinking it would SIGBUS its next slot read).

Safety contract (enforced by the master at attach time): consumers must
drain experience fast enough that a datapoint's backing slot is not reused
— guaranteed when ``cap > (train_queue_maxsize + feed_holder) *
steps_per_item / B + flush_horizon + hist + margin`` because a full train
queue blocks the master, which stops action replies, which halts every
lockstep server within one step. Every term counts items that can still
pin ring views: the feed's collate holder took its items OUT of the queue
but holds views until its ``np.stack`` copies them (masters expose
``feed_batch`` for this), and a queued V-trace segment's
``bootstrap_state`` view trails the newest slot by a whole unroll
(``ring_steps_per_item = unroll_len``; 1 for BA3C datapoints).
"""

from __future__ import annotations

import glob
import mmap
import os

import numpy as np

SHM_DIR = "/dev/shm"


def min_safe_cap(
    b: int,
    queue_maxsize: int,
    feed_batch: int,
    steps_per_item: int,
    flush_horizon: int,
    hist: int,
    margin: int = 8,
) -> float:
    """Ring-capacity floor implied by the safety contract above.

    THE single definition of the formula — the master's attach-time check
    refuses any ring with ``cap <= min_safe_cap(...)`` and cli.py sizes the
    rings it creates from the same call, so the two sides cannot drift.
    Counts, in ring STEPS: every queued-or-held item that can pin a ring
    view ((queue + feed collate holder) x steps_per_item, spread over the
    block's B envs), the unflushed per-block step horizon, and the hist
    slots a frame-history window reaches back.
    """
    return (
        (queue_maxsize + feed_batch) * steps_per_item / max(1, b)
        + flush_horizon + hist + margin
    )


def available() -> bool:
    """The block-shm wire needs a writable /dev/shm (linux tmpfs)."""
    return os.path.isdir(SHM_DIR) and os.access(SHM_DIR, os.W_OK)


def ring_name(pipe_c2s: str, ident_prefix: str) -> str:
    """The canonical ring name for one fleet x server slot.

    THE single definition — the env server creates under this name
    (envs/native.py) and the supervisor reclaims stale files under it
    before a respawn (orchestrate/supervisor.py); computing it in two
    places would let them drift and leak ~57 MB per crashed server. The
    name must be STABLE across restarts of a slot (pipe pair + prefix
    identify the slot; concurrent fleets differ in pipe address) so a
    crashed server's stale file is renamed over, not accumulated.
    """
    import hashlib

    fleet = hashlib.sha1(pipe_c2s.encode()).hexdigest()[:8]
    return f"ba3c-ring-{fleet}-{ident_prefix}"


def reclaim_stale(name: str) -> int:
    """Remove a stale ring file (any size/shape) and its orphaned create
    temps; returns how many files went away.

    Safe ONLY when no live server owns the name — the supervisor calls it
    with the slot's process known-dead. Unlinking (vs truncating) cannot
    hurt a master still mapping the old inode: the inode lives until the
    last mapping drops, exactly like create()'s rename-over. What this
    adds over rename-over is the DIFFERENT-GEOMETRY case: a crashed
    fleet's leftover file with another cap/B must never be attachable
    between the respawned server's create and the master's attach, and
    must never count against /dev/shm space twice.
    """
    removed = 0
    path = ShmRing._path(name)
    for p in [path] + glob.glob(path + ".new-*"):
        try:
            os.unlink(p)
            removed += 1
        except OSError:
            pass
    return removed


class ShmRing:
    """A ``[cap, B, H, W]`` uint8 observation ring backed by /dev/shm.

    ``create`` (env-server side) truncates/creates the file and maps it
    writable; ``attach`` (master side) maps it read-only. The creator is
    responsible for ``close(unlink=True)``.
    """

    def __init__(self, name: str, arr: np.ndarray, mm: mmap.mmap, f, owner: bool):
        self.name = name
        self.arr = arr
        self._mm = mm
        self._f = f
        self._owner = owner

    @staticmethod
    def _path(name: str) -> str:
        if "/" in name or name.startswith("."):
            raise ValueError(f"unsafe shm ring name {name!r}")
        return os.path.join(SHM_DIR, name)

    @classmethod
    def create(cls, name: str, cap: int, b: int, h: int, w: int) -> "ShmRing":
        path = cls._path(name)
        nbytes = cap * b * h * w
        # build under a temp name and RENAME over the final path: truncating
        # the path in place would shrink an inode a master may still have
        # mapped read-only (restart-over-stale-ring within actor_timeout),
        # and its next slot read would SIGBUS. rename is atomic, the old
        # inode lives until the master unmaps it, and the master re-attaches
        # the new inode when the restarted client's state is rebuilt.
        for stale in glob.glob(path + ".new-*"):
            try:
                os.unlink(stale)  # a creator died between open and rename
            except OSError:
                pass
        tmp = f"{path}.new-{os.getpid()}"
        f = open(tmp, "w+b")
        f.truncate(nbytes)
        mm = mmap.mmap(f.fileno(), nbytes)
        arr = np.frombuffer(mm, np.uint8).reshape(cap, b, h, w)
        os.rename(tmp, path)
        return cls(name, arr, mm, f, owner=True)

    @classmethod
    def attach(cls, name: str, cap: int, b: int, h: int, w: int) -> "ShmRing":
        path = cls._path(name)
        nbytes = cap * b * h * w
        f = open(path, "rb")
        if os.fstat(f.fileno()).st_size != nbytes:
            f.close()
            raise ValueError(
                f"shm ring {name!r} has {os.path.getsize(path)} bytes, "
                f"expected {nbytes} — header/ring shape mismatch"
            )
        mm = mmap.mmap(f.fileno(), nbytes, access=mmap.ACCESS_READ)
        arr = np.frombuffer(mm, np.uint8).reshape(cap, b, h, w)
        return cls(name, arr, mm, f, owner=False)

    def close(self, unlink: bool = False) -> None:
        """Release the mapping; ``unlink=True`` (creator) removes the file.

        numpy views handed out earlier keep the mmap's BUFFER alive via
        refcounting even after ``mmap.close()`` would fail on them — so we
        drop our references and let the last view free the mapping.
        """
        self.arr = None
        try:
            # mmap.close() raises if views are still exported; tolerate —
            # the mapping is freed when the last numpy view dies
            self._mm.close()
        except BufferError:
            pass
        try:
            self._f.close()
        except OSError:
            pass
        if unlink and self._owner:
            try:
                os.unlink(self._path(self.name))
            except OSError:
                pass
