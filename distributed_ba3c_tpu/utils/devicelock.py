"""Local TPU-claim mutex: one device-acquiring process per host at a time.

Why this exists: the TPU pool behind this rig's tunnel is EXCLUSIVE — two
local processes initializing the backend concurrently don't error, they
wedge the pool itself (docs/OPERATIONS.md "the chip is exclusive"; the
round-4 bench-vs-training double-claim cost a full day of the only chip).
The reference had no equivalent problem — its 64-node CPU cluster had no
single scarce accelerator (SURVEY.md §3's `src/train.py` workers each owned
their own host) — so this guard is TPU-rig-specific failure detection in
the same spirit as ``parallel/watchdog.py``: turn an undefined wedge into a
defined, observable outcome (queue or refuse, never double-claim).

Mechanics: ``flock(2)`` on a well-known path. The kernel releases the lock
when the holder dies — any exit path, including SIGKILL — so there is no
stale-lock protocol; the holder JSON written into the file (pid / run name /
since) is advisory context for log messages only, never trusted for
liveness. Processes on the safe CPU bypass (``JAX_PLATFORMS=cpu``) never
touch the pool claim and therefore skip the lock entirely, so CPU test
suites and tooling coexist with a live TPU run.

Modes (CLI ``--tpu_lock``, default ``wait``):
  - ``wait``: block until the chip frees, logging the holder once a minute.
    A queued bench behind a finishing training run is the correct outcome;
    the round-4 alternative was a wedged pool.
  - ``fail``: exit immediately with the holder's pid/run in the message —
    for interactive use where queueing would surprise.
  - ``off``: escape hatch (multi-process single-host experiments that
    intentionally share a mesh, e.g. the CPU-mesh multihost soaks).
"""

from __future__ import annotations

import errno
import fcntl
import json
import os
import sys
import time
from typing import Callable, Optional

LOCK_PATH_ENV = "BA3C_TPU_LOCK"
DEFAULT_LOCK_PATH = "/tmp/ba3c_tpu.lock"
MODES = ("wait", "fail", "off")

# diagnostics go to STDERR: bench.py and the eval scripts print exactly one
# JSON line on stdout for machine consumption — a "[tpu-lock] waiting" line
# there would corrupt the contract. sys.stderr is resolved at CALL time —
# a functools.partial bound the import-time stream and silently wrote to a
# stale object under any later redirection (pytest capture, daemonization).
# Public: the bench scripts share the stdout-JSON contract and import this
# (ba3clint A5 forbids cross-module imports of underscore names).
def stderr_print(*args, **kwargs) -> None:
    print(*args, file=sys.stderr, flush=True, **kwargs)


_stderr_print = stderr_print  # private alias kept for in-module history


def lock_path() -> str:
    return os.environ.get(LOCK_PATH_ENV) or DEFAULT_LOCK_PATH


def tpu_lock_needed() -> bool:
    """False when this process runs on the CPU platform (never claims the
    pool). Any other platform setting — including unset, which lets the
    container's sitecustomize pick the TPU — needs the lock.

    When this returns False, ``guard_tpu`` also FORCES jax onto the CPU
    platform: the container's sitecustomize re-registers the TPU plugin and
    overrides the env var (cli.py's long-standing compensation), so trusting
    the env var alone would skip the lock while still claiming the chip —
    the exact double-claim the lock exists to prevent."""
    plat = os.environ.get("JAX_PLATFORMS", "")
    if plat and all(p.strip() == "cpu" for p in plat.split(",") if p.strip()):
        return False
    return True


def _force_cpu_platform() -> None:
    """Make the no-lock skip safe: pin jax to CPU so a sitecustomize that
    overrides JAX_PLATFORMS cannot route this (unlocked) process to the
    TPU. Importing jax is claim-free; only backend init claims."""
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # no jax in this interpreter -> nothing can claim a device


class TpuLockHeld(SystemExit):
    """Raised in ``fail`` mode; SystemExit so entry points exit non-zero
    with the message and no traceback."""


class TpuLock:
    """Holds the host-local TPU claim for this process's lifetime.

    The fd stays open until ``release()`` or process death; flock identity
    is the open file description, so children sharing the fd after fork
    would also share the lock — entry points acquire before spawning
    workers, which is the intended containment.
    """

    def __init__(self, run_name: str, path: Optional[str] = None):
        self.run_name = run_name
        self.path = path or lock_path()
        self._fd: Optional[int] = None

    @property
    def held(self) -> bool:
        return self._fd is not None

    def _read_holder(self) -> str:
        try:
            with open(self.path, "r") as f:
                info = json.load(f)
            return "pid %s (run %r, since %s)" % (
                info.get("pid"), info.get("run"), info.get("since"),
            )
        except Exception:
            return "unknown holder"

    def _try_once(self) -> bool:
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o666)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            os.close(fd)
            if e.errno in (errno.EAGAIN, errno.EACCES):
                return False
            raise
        # Holder info is advisory (for the *other* process's log message);
        # liveness is the flock itself.
        os.ftruncate(fd, 0)
        os.write(fd, json.dumps({
            "pid": os.getpid(),
            "run": self.run_name,
            "since": time.strftime("%Y-%m-%d %H:%M:%S"),
        }).encode())
        os.fsync(fd)
        self._fd = fd
        return True

    def acquire(
        self,
        mode: str = "wait",
        poll_s: float = 5.0,
        timeout_s: Optional[float] = None,
        log: Callable[[str], None] = stderr_print,
    ) -> "TpuLock":
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        if mode == "off" or self._try_once():
            return self
        holder = self._read_holder()
        if mode == "fail":
            raise TpuLockHeld(
                f"[tpu-lock] the TPU is held by {holder} ({self.path}). "
                "Two local claimants wedge the pool (OPERATIONS.md); rerun "
                "with --tpu_lock wait to queue, or stop the holder."
            )
        t0 = time.monotonic()
        last_log = 0.0
        log(f"[tpu-lock] waiting for TPU held by {holder} ({self.path})")
        while not self._try_once():
            waited = time.monotonic() - t0
            if timeout_s is not None and waited >= timeout_s:
                raise TpuLockHeld(
                    f"[tpu-lock] gave up after {waited:.0f}s; TPU still "
                    f"held by {self._read_holder()} ({self.path})"
                )
            if waited - last_log >= 60.0:
                last_log = waited
                log(
                    f"[tpu-lock] still waiting ({waited:.0f}s) — holder: "
                    f"{self._read_holder()}"
                )
            time.sleep(poll_s)
        log(f"[tpu-lock] acquired after {time.monotonic() - t0:.0f}s")
        return self

    def release(self) -> None:
        if self._fd is not None:
            try:
                # Clear advisory holder info so a later reader doesn't see
                # our stale pid next to an unlocked file.
                os.ftruncate(self._fd, 0)
            except OSError:
                pass
            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None

    def __enter__(self) -> "TpuLock":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def guard_tpu(
    run_name: str,
    mode: str = "wait",
    poll_s: float = 5.0,
    timeout_s: Optional[float] = None,
    log: Callable[[str], None] = stderr_print,
) -> Optional[TpuLock]:
    """Entry-point helper: acquire the host-local TPU claim unless this
    process is on the CPU platform (or mode='off'). Call BEFORE the first
    jax backend touch; hold for process lifetime (the kernel releases on
    death). Returns the held lock, or None when no lock is needed."""
    if mode == "off":
        return None
    if not tpu_lock_needed():
        _force_cpu_platform()
        return None
    return TpuLock(run_name).acquire(
        mode=mode, poll_s=poll_s, timeout_s=timeout_s, log=log
    )
