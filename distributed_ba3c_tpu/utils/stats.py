"""Stat aggregation: per-step counters → per-epoch records → stat.json.

Reference equivalent: ``utils/stats.py`` ``StatCounter`` +
``callbacks/stats.py`` ``StatHolder``/``StatPrinter`` (SURVEY.md §2.7 #22):
scalar stats accumulate during an epoch, get flushed as one record appended
to ``stat.json`` in the log dir, and printed to the console with the same
metric names (score mean/max, losses, fps).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np


class StatCounter:
    """Accumulates scalars; exposes average/sum/max/count."""

    def __init__(self):
        self._values: List[float] = []

    def feed(self, v: float) -> None:
        self._values.append(float(v))

    def reset(self) -> None:
        self._values = []

    @property
    def count(self) -> int:
        return len(self._values)

    @property
    def average(self) -> float:
        assert self._values
        return float(np.mean(self._values))

    @property
    def sum(self) -> float:
        assert self._values
        return float(np.sum(self._values))

    @property
    def max(self) -> float:
        assert self._values
        return float(np.max(self._values))


class StatHolder:
    """Holds the current epoch's scalar stats; finalizes to stat.json.

    ``stat.json`` is a JSON list of per-epoch dicts — the format tensorpack
    tooling reads — so downstream plotting against the reference's logs works
    unchanged.
    """

    def __init__(self, log_dir: Optional[str] = None, tensorboard: bool = True):
        self.log_dir = log_dir
        self.stat_now: Dict[str, float] = {}
        self.stat_history: List[Dict[str, float]] = []
        self._print_filter = None
        self._tb = None
        if log_dir is not None and tensorboard:
            from distributed_ba3c_tpu.utils.tb_writer import TBScalarWriter

            self._tb = TBScalarWriter(log_dir)
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
            self._path = os.path.join(log_dir, "stat.json")
            if os.path.isfile(self._path):
                try:
                    with open(self._path) as f:
                        self.stat_history = json.load(f)
                except json.JSONDecodeError:
                    self.stat_history = []
        else:
            self._path = None

    def add_stat(self, name: str, value: float) -> None:
        self.stat_now[name] = float(value)

    def add_stats(self, values: Dict[str, float]) -> None:
        """Bulk :meth:`add_stat` — the telemetry bridge's entry point
        (StatPrinter folds ``telemetry.export_scalars()`` in per epoch, so
        stat.json/TB carry the same series the scrape endpoint serves)."""
        for name, v in values.items():
            self.stat_now[name] = float(v)

    def finalize(self) -> Dict[str, float]:
        """Close the epoch: append the record, write stat.json + TB events."""
        record = dict(self.stat_now)
        self.stat_history.append(record)
        if self._path is not None:
            tmp = self._path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.stat_history, f)
            os.replace(tmp, self._path)
        if self._tb is not None:
            step = int(record.get("global_step", record.get("epoch", 0)))
            self._tb.add_scalars(record, step)
            self._tb.flush()
        self.stat_now = {}
        return record

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
