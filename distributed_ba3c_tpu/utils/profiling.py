"""Tracing/profiling hooks.

Reference equivalent (SURVEY.md §5): nothing built-in beyond
``utils/timer.py`` ``timed_operation`` — op-level profiling was offline
(VTune/TF timeline). The rebuild does better with the tools XLA ships:

- :func:`timed_operation` — the reference's host-side timer, kept API-alike.
- :func:`start_server` — ``jax.profiler`` trace server; connect TensorBoard
  or ``jax.profiler.trace`` to capture device timelines (HLO op breakdown,
  ICI collective time) from a live run.
- :func:`step_annotation` — wraps a train step in a named trace region so
  captures show per-step boundaries.
"""

from __future__ import annotations

import contextlib
import time

from distributed_ba3c_tpu.utils import logger


@contextlib.contextmanager
def timed_operation(msg: str, log_start: bool = False):
    """Log the wall-clock duration of a block (reference ``timed_operation``)."""
    if log_start:
        logger.info("start %s ...", msg)
    t0 = time.monotonic()
    try:
        yield
    finally:
        logger.info("%s finished, time:%.4f sec.", msg, time.monotonic() - t0)


def start_server(port: int) -> None:
    """Start the jax.profiler gRPC server (TensorBoard-attachable)."""
    import jax

    jax.profiler.start_server(port)
    logger.info("jax.profiler server listening on :%d", port)


@contextlib.contextmanager
def step_annotation(name: str, step: int):
    """Named trace region for one step (shows up in captured timelines)."""
    import jax

    with jax.profiler.StepTraceAnnotation(name, step_num=step):
        yield


def capture_trace(log_dir: str, seconds: float, fn, *args, **kwargs):
    """Run ``fn`` under a trace capture written to ``log_dir`` (offline use)."""
    import jax

    with jax.profiler.trace(log_dir):
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
    logger.info("trace written to %s", log_dir)
    return out
