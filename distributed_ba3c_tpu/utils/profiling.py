"""Tracing/profiling hooks.

Reference equivalent (SURVEY.md §5): nothing built-in beyond
``utils/timer.py`` ``timed_operation`` — op-level profiling was offline
(VTune/TF timeline). The rebuild does better with the tools XLA ships:

- :func:`timed_operation` — the reference's host-side timer, kept API-alike.
- :func:`start_server` — ``jax.profiler`` trace server; connect TensorBoard
  or ``jax.profiler.trace`` to capture device timelines (HLO op breakdown,
  ICI collective time) from a live run.
- :func:`step_annotation` — wraps a train step in a named trace region so
  captures show per-step boundaries.
"""

from __future__ import annotations

import contextlib
import time

from distributed_ba3c_tpu.utils import logger


@contextlib.contextmanager
def timed_operation(msg: str, log_start: bool = False):
    """Log the wall-clock duration of a block (reference ``timed_operation``)."""
    if log_start:
        logger.info("start %s ...", msg)
    t0 = time.monotonic()
    try:
        yield
    finally:
        logger.info("%s finished, time:%.4f sec.", msg, time.monotonic() - t0)


def start_server(port: int) -> None:
    """Start the jax.profiler gRPC server (TensorBoard-attachable)."""
    import jax

    jax.profiler.start_server(port)
    logger.info("jax.profiler server listening on :%d", port)


@contextlib.contextmanager
def step_annotation(
    name: str,
    step: int,
    trace_id: int = None,
    span_id: int = None,
):
    """Named trace region for one step (shows up in captured timelines).

    ``trace_id``/``span_id`` correlate a chip-session ``jax.profiler``
    capture with the host-side trace plane (telemetry/tracing.py): pass
    the active block trace's ids (``tracing.current_trace_id()``, or a
    TraceRef's fields) and the device timeline's step region carries them
    as metadata — line the Perfetto export of ``scripts/trace_dump.py``
    up against the XLA capture by matching the ids (ROADMAP item 1's
    on-chip captures land next to host spans instead of in a vacuum)."""
    import jax

    kwargs = {"step_num": step}
    if trace_id is not None:
        kwargs["trace_id"] = int(trace_id)
    if span_id is not None:
        kwargs["span_id"] = int(span_id)
    with jax.profiler.StepTraceAnnotation(name, **kwargs):
        yield


def capture_trace(log_dir: str, seconds: float, fn, *args, **kwargs):
    """Run ``fn`` under a trace capture written to ``log_dir`` (offline use)."""
    import jax

    with jax.profiler.trace(log_dir):
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
    logger.info("trace written to %s", log_dir)
    return out
