"""msgpack serialization with zero-copy numpy support.

Reference equivalent: ``tensorpack/utils/serialize.py`` — msgpack +
msgpack_numpy ``dumps``/``loads`` used for every ZMQ payload (SURVEY.md §2.8
#25, §2.12). msgpack_numpy is not installed here, so ndarrays are encoded as a
msgpack ext type carrying (dtype, shape, raw bytes); uint8 frames therefore
cross the wire at 1 byte/pixel with no base64/pickle overhead, matching the
reference's design intent.

Two codecs live here:

- :func:`dumps` / :func:`loads` — ONE msgpack byte string per message (the
  per-env wire). ``dumps`` copies every array once (``tobytes``); fine for
  one 28 KB state per message, ruinous for a whole [B, ...] block.
- :func:`pack_block` / :func:`unpack_block` — a MULTIPART message: one tiny
  msgpack header frame describing metadata + array specs, then each array's
  raw buffer as its own frame. The pack side hands zmq the arrays' own
  memory (no ``tobytes``), the unpack side returns ``np.frombuffer`` views
  over the received frames (no copy). This is the block wire's codec
  (docs/actor_plane.md).
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import msgpack
import numpy as np

_NDARRAY_EXT = 42


def _default(obj: Any):
    if isinstance(obj, np.ndarray):
        if not obj.flags["C_CONTIGUOUS"]:
            obj = np.ascontiguousarray(obj)
        header = msgpack.packb((obj.dtype.str, obj.shape), use_bin_type=True)
        return msgpack.ExtType(_NDARRAY_EXT, header + obj.tobytes())
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    raise TypeError(f"cannot serialize {type(obj)}")


def _ext_hook(code: int, data: bytes):
    if code != _NDARRAY_EXT:
        return msgpack.ExtType(code, data)
    unpacker = msgpack.Unpacker(use_list=False, raw=False)
    unpacker.feed(data)
    dtype_str, shape = unpacker.unpack()
    offset = unpacker.tell()
    arr = np.frombuffer(data, dtype=np.dtype(dtype_str), offset=offset)
    return arr.reshape(shape)


def dumps(obj: Any) -> bytes:
    """Serialize to msgpack bytes (ndarray-aware)."""
    return msgpack.packb(obj, use_bin_type=True, default=_default)


def loads(buf) -> Any:
    """Inverse of :func:`dumps`. Arrays are views over the input buffer.

    Accepts any bytes-like object (``bytes``, ``memoryview``, ``zmq.Frame``
    buffers) so non-copying ZMQ receives decode without a round-trip through
    ``bytes()``.
    """
    return msgpack.unpackb(buf, raw=False, ext_hook=_ext_hook)


def pack_block(meta: Any, arrays: Sequence[np.ndarray]) -> List[Any]:
    """Multipart zero-copy encode: ``[header, raw_buf_0, ..., raw_buf_n]``.

    ``meta`` is any msgpack-serializable object (the block wire puts the
    sender ident + step counter here). Each array contributes one frame that
    IS its buffer — no ``tobytes`` copy; non-contiguous inputs are made
    contiguous first (the one copy this path ever does, and only when the
    caller hands a strided view). The caller must not mutate the arrays
    until the message is known to have left the process — the block wire's
    lockstep send→await-actions structure guarantees exactly that.
    """
    specs: List[Tuple[str, Tuple[int, ...]]] = []
    frames: List[Any] = [b""]  # placeholder for the header
    for a in arrays:
        a = np.ascontiguousarray(a)
        specs.append((a.dtype.str, a.shape))
        frames.append(a.data)
    frames[0] = msgpack.packb(
        (meta, specs), use_bin_type=True, default=_default
    )
    return frames


def unpack_block(frames: Sequence[Any]) -> Tuple[Any, List[np.ndarray]]:
    """Inverse of :func:`pack_block`: ``(meta, arrays)``.

    ``frames`` are bytes-like (bytes, memoryview, or ``zmq.Frame.buffer``).
    Every returned array is a ``frombuffer`` VIEW over its frame — zero
    copies; the arrays keep the frames alive for as long as they are
    referenced.
    """
    meta, specs = msgpack.unpackb(frames[0], raw=False, ext_hook=_ext_hook)
    if len(specs) != len(frames) - 1:
        raise ValueError(
            f"block header declares {len(specs)} arrays but the message "
            f"carries {len(frames) - 1} payload frames"
        )
    arrays = [
        np.frombuffer(buf, dtype=np.dtype(dtype_str)).reshape(shape)
        for (dtype_str, shape), buf in zip(specs, frames[1:])
    ]
    return meta, arrays
