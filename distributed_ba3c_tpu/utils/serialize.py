"""msgpack serialization with zero-copy numpy support.

Reference equivalent: ``tensorpack/utils/serialize.py`` — msgpack +
msgpack_numpy ``dumps``/``loads`` used for every ZMQ payload (SURVEY.md §2.8
#25, §2.12). msgpack_numpy is not installed here, so ndarrays are encoded as a
msgpack ext type carrying (dtype, shape, raw bytes); uint8 frames therefore
cross the wire at 1 byte/pixel with no base64/pickle overhead, matching the
reference's design intent.
"""

from __future__ import annotations

from typing import Any

import msgpack
import numpy as np

_NDARRAY_EXT = 42


def _default(obj: Any):
    if isinstance(obj, np.ndarray):
        if not obj.flags["C_CONTIGUOUS"]:
            obj = np.ascontiguousarray(obj)
        header = msgpack.packb((obj.dtype.str, obj.shape), use_bin_type=True)
        return msgpack.ExtType(_NDARRAY_EXT, header + obj.tobytes())
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    raise TypeError(f"cannot serialize {type(obj)}")


def _ext_hook(code: int, data: bytes):
    if code != _NDARRAY_EXT:
        return msgpack.ExtType(code, data)
    unpacker = msgpack.Unpacker(use_list=False, raw=False)
    unpacker.feed(data)
    dtype_str, shape = unpacker.unpack()
    offset = unpacker.tell()
    arr = np.frombuffer(data, dtype=np.dtype(dtype_str), offset=offset)
    return arr.reshape(shape)


def dumps(obj: Any) -> bytes:
    """Serialize to msgpack bytes (ndarray-aware)."""
    return msgpack.packb(obj, use_bin_type=True, default=_default)


def loads(buf: bytes) -> Any:
    """Inverse of :func:`dumps`. Arrays are views over the input buffer."""
    return msgpack.unpackb(buf, raw=False, ext_hook=_ext_hook)
