"""msgpack serialization with zero-copy numpy support.

Reference equivalent: ``tensorpack/utils/serialize.py`` — msgpack +
msgpack_numpy ``dumps``/``loads`` used for every ZMQ payload (SURVEY.md §2.8
#25, §2.12). msgpack_numpy is not installed here, so ndarrays are encoded as a
msgpack ext type carrying (dtype, shape, raw bytes); uint8 frames therefore
cross the wire at 1 byte/pixel with no base64/pickle overhead, matching the
reference's design intent.

Two codecs live here:

- :func:`dumps` / :func:`loads` — ONE msgpack byte string per message (the
  per-env wire). ``dumps`` copies every array once (``tobytes``); fine for
  one 28 KB state per message, ruinous for a whole [B, ...] block.
- :func:`pack_block` / :func:`unpack_block` — a MULTIPART message: one tiny
  msgpack header frame describing metadata + array specs, then each array's
  raw buffer as its own frame. The pack side hands zmq the arrays' own
  memory (no ``tobytes``), the unpack side returns ``np.frombuffer`` views
  over the received frames (no copy). This is the block wire's codec
  (docs/actor_plane.md).

**Integrity framing (docs/netchaos.md):** both codecs optionally carry
CRC32s so a corrupted-in-flight frame becomes a typed
:class:`CorruptFrameError` at the receiver instead of a silently wrong
array (a bit-flipped obs buffer reshapes fine and poisons training with
zero signal; a truncated one must never reach ``frombuffer``). The block
header grows a third element — per-frame CRCs — and single-frame
messages get a 4-byte magic + CRC prefix; both are length/prefix
versioned, so CRC-off senders parse unchanged at CRC-aware receivers.
Enable fleet-wide with ``BA3C_WIRE_CRC=1`` (cli ``--wire_crc``), or per
call with ``crc=True``. ``CorruptFrameError`` subclasses ``ValueError``
so every pre-existing untrusted-wire handler already contains it; the
receive loops additionally count it as its own typed reject
(``corrupt_frames_total``).
"""

from __future__ import annotations

import binascii
import os
import struct
from typing import Any, List, Optional, Sequence, Tuple

import msgpack
import numpy as np

_NDARRAY_EXT = 42

#: prefix of a CRC-framed single-frame message: 3 magic bytes + version,
#: then the little-endian CRC32 of the payload, then the payload. No
#: legitimate ``dumps`` output starts with 0xBA (our top level is always a
#: msgpack array/map), so prefix detection cannot misfire on old senders.
_CRC_MAGIC = b"\xba\x3c\xc3\x01"


class CorruptFrameError(ValueError):
    """A frame failed its CRC32 (or CRC framing was structurally broken).

    Subclasses ValueError on purpose: every receive loop that already
    drops undecodable wire input keeps working; loops that care count it
    separately as the typed ``corrupt_frame`` reject (docs/netchaos.md).
    """


_wire_crc = os.environ.get("BA3C_WIRE_CRC", "0").lower() not in (
    "0", "", "false",
)


def wire_crc_enabled() -> bool:
    """Process-wide CRC default (``BA3C_WIRE_CRC=1`` / :func:`set_wire_crc`);
    the per-call ``crc=`` argument overrides it."""
    return _wire_crc


def set_wire_crc(flag: bool) -> None:
    """Flip the process-wide CRC default (cli.py's ``--wire_crc``; exported
    as BA3C_WIRE_CRC for child processes so a whole fleet agrees)."""
    global _wire_crc
    _wire_crc = bool(flag)


def _crc(buf) -> int:
    return binascii.crc32(buf) & 0xFFFFFFFF


def _default(obj: Any):
    if isinstance(obj, np.ndarray):
        if not obj.flags["C_CONTIGUOUS"]:
            obj = np.ascontiguousarray(obj)
        header = msgpack.packb((obj.dtype.str, obj.shape), use_bin_type=True)
        return msgpack.ExtType(_NDARRAY_EXT, header + obj.tobytes())
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    raise TypeError(f"cannot serialize {type(obj)}")


def _ext_hook(code: int, data: bytes):
    if code != _NDARRAY_EXT:
        return msgpack.ExtType(code, data)
    unpacker = msgpack.Unpacker(use_list=False, raw=False)
    unpacker.feed(data)
    dtype_str, shape = unpacker.unpack()
    offset = unpacker.tell()
    arr = np.frombuffer(data, dtype=np.dtype(dtype_str), offset=offset)
    return arr.reshape(shape)


def dumps(obj: Any, crc: Optional[bool] = None) -> bytes:
    """Serialize to msgpack bytes (ndarray-aware).

    ``crc`` (None = the :func:`wire_crc_enabled` process default) prefixes
    the payload with ``_CRC_MAGIC + crc32`` so the receiving :func:`loads`
    verifies integrity before any array view is built.
    """
    payload = msgpack.packb(obj, use_bin_type=True, default=_default)
    if wire_crc_enabled() if crc is None else crc:
        return _CRC_MAGIC + struct.pack("<I", _crc(payload)) + payload
    return payload


def loads(buf) -> Any:
    """Inverse of :func:`dumps`. Arrays are views over the input buffer.

    Accepts any bytes-like object (``bytes``, ``memoryview``, ``zmq.Frame``
    buffers) so non-copying ZMQ receives decode without a round-trip through
    ``bytes()``. CRC-framed payloads (prefix-detected) are verified first:
    a mismatch — corruption OR truncation in flight — raises the typed
    :class:`CorruptFrameError` instead of handing back a wrong object.
    """
    view = memoryview(buf)
    if len(view) >= 8 and bytes(view[:4]) == _CRC_MAGIC:
        (want,) = struct.unpack("<I", view[4:8])
        payload = view[8:]
        if _crc(payload) != want:
            raise CorruptFrameError(
                f"single-frame payload failed CRC32 ({len(payload)} bytes)"
            )
        return msgpack.unpackb(payload, raw=False, ext_hook=_ext_hook)
    return msgpack.unpackb(view, raw=False, ext_hook=_ext_hook)


def pack_block(
    meta: Any, arrays: Sequence[np.ndarray], crc: Optional[bool] = None
) -> List[Any]:
    """Multipart zero-copy encode: ``[header, raw_buf_0, ..., raw_buf_n]``.

    ``meta`` is any msgpack-serializable object (the block wire puts the
    sender ident + step counter here). Each array contributes one frame that
    IS its buffer — no ``tobytes`` copy; non-contiguous inputs are made
    contiguous first (the one copy this path ever does, and only when the
    caller hands a strided view). The caller must not mutate the arrays
    until the message is known to have left the process — the block wire's
    lockstep send→await-actions structure guarantees exactly that.

    ``crc`` (None = the process default) appends a third, length-versioned
    header element: per-frame CRC32s, covering the header's own bytes-to-be
    indirectly through msgpack structure and every payload frame exactly.
    Still zero-copy — the CRC is one read-only pass over buffers zmq is
    about to read anyway.
    """
    use_crc = wire_crc_enabled() if crc is None else crc
    specs: List[Tuple[str, Tuple[int, ...]]] = []
    frames: List[Any] = [b""]  # placeholder for the header
    crcs: List[int] = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        specs.append((a.dtype.str, a.shape))
        frames.append(a.data)
        if use_crc:
            crcs.append(_crc(a.data))
    header: Tuple = (meta, specs, crcs) if use_crc else (meta, specs)
    packed = msgpack.packb(header, use_bin_type=True, default=_default)
    if use_crc:
        # the header frame carries its OWN prefix CRC too: a flipped bit
        # in meta/specs would otherwise mis-route or mis-shape silently —
        # the payload CRCs cannot vouch for the frame that declares them
        packed = _CRC_MAGIC + struct.pack("<I", _crc(packed)) + packed
    frames[0] = packed
    return frames


def unpack_block(frames: Sequence[Any]) -> Tuple[Any, List[np.ndarray]]:
    """Inverse of :func:`pack_block`: ``(meta, arrays)``.

    ``frames`` are bytes-like (bytes, memoryview, or ``zmq.Frame.buffer``).
    Every returned array is a ``frombuffer`` VIEW over its frame — zero
    copies; the arrays keep the frames alive for as long as they are
    referenced.

    A 3-element header carries per-frame CRC32s (length-versioned: the
    2-element form parses exactly as before): every payload frame is
    verified BEFORE any ``frombuffer`` view is built, and a mismatch
    raises the typed :class:`CorruptFrameError` — a truncated or
    bit-flipped frame must never become an array.
    """
    hview = memoryview(frames[0])
    if len(hview) >= 8 and bytes(hview[:4]) == _CRC_MAGIC:
        (want,) = struct.unpack("<I", hview[4:8])
        hview = hview[8:]
        if _crc(hview) != want:
            raise CorruptFrameError(
                f"block header failed CRC32 ({len(hview)} bytes)"
            )
    header = msgpack.unpackb(hview, raw=False, ext_hook=_ext_hook)
    if not isinstance(header, (tuple, list)) or len(header) not in (2, 3):
        raise ValueError(
            f"block header is not a (meta, specs[, crcs]) tuple: "
            f"{type(header).__name__}/{len(header) if isinstance(header, (tuple, list)) else '?'}"
        )
    meta, specs = header[0], header[1]
    crcs = header[2] if len(header) == 3 else None
    if len(specs) != len(frames) - 1:
        raise ValueError(
            f"block header declares {len(specs)} arrays but the message "
            f"carries {len(frames) - 1} payload frames"
        )
    if crcs is not None:
        if len(crcs) != len(frames) - 1:
            raise CorruptFrameError(
                f"block header carries {len(crcs)} CRCs for "
                f"{len(frames) - 1} payload frames"
            )
        for i, (want, buf) in enumerate(zip(crcs, frames[1:])):
            if _crc(buf) != want:
                raise CorruptFrameError(
                    f"block payload frame {i} failed CRC32 "
                    f"({len(memoryview(buf))} bytes on the wire)"
                )
    arrays = [
        np.frombuffer(buf, dtype=np.dtype(dtype_str)).reshape(shape)
        for (dtype_str, shape), buf in zip(specs, frames[1:])
    ]
    return meta, arrays
