"""Console + file logger with auto log-dir.

Reference equivalent: ``tensorpack/utils/logger.py`` (SURVEY.md §2.8 #27).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_LOGGER = logging.getLogger("ba3c")
_LOGGER.propagate = False
LOG_DIR: Optional[str] = None

_COLORS = {"WARNING": "\033[33m", "ERROR": "\033[31m", "CRITICAL": "\033[31m"}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record):
        msg = super().format(record)
        color = _COLORS.get(record.levelname)
        if color and sys.stderr.isatty():
            return f"{color}{msg}{_RESET}"
        return msg


def _ensure_console_handler():
    if not _LOGGER.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(
            _ColorFormatter("[%(asctime)s %(levelname)s] %(message)s", "%H:%M:%S")
        )
        _LOGGER.addHandler(h)
        _LOGGER.setLevel(logging.INFO)


def set_logger_dir(dirname: str, action: str = "k") -> None:
    """Attach a file handler writing to ``dirname/log.log``; create the dir."""
    global LOG_DIR
    _ensure_console_handler()
    os.makedirs(dirname, exist_ok=True)
    LOG_DIR = dirname
    fh = logging.FileHandler(os.path.join(dirname, "log.log"))
    fh.setFormatter(
        logging.Formatter("[%(asctime)s %(levelname)s] %(message)s", "%H:%M:%S")
    )
    _LOGGER.addHandler(fh)


def info(msg, *a):
    _ensure_console_handler()
    _LOGGER.info(msg, *a)


def warn(msg, *a):
    _ensure_console_handler()
    _LOGGER.warning(msg, *a)


def error(msg, *a):
    _ensure_console_handler()
    _LOGGER.error(msg, *a)


def exception(msg, *a):
    """Error + the current exception's traceback (call from an ``except``
    block — the stdlib ``Logger.exception`` contract)."""
    _ensure_console_handler()
    _LOGGER.exception(msg, *a)
