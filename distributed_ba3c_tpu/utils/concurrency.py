"""Process/thread lifecycle helpers for the actor plane.

Reference equivalent: ``tensorpack/utils/concurrency.py`` —
``ensure_proc_terminate``, ``StoppableThread``, ``LoopThread``, SIGINT masking
in children (SURVEY.md §2.8 #26). Concurrency safety here, as in the
reference, is by construction: message passing between processes, queues
between threads, no shared mutable state. That convention is no longer just
this docstring — ``python -m tools.ba3clint`` enforces it statically (bare
threads, blocking queue ops, wall-clock timeouts; see
docs/static_analysis.md) and ``utils/sanitizer.py`` (BA3C_SANITIZE=1) spot
checks it at runtime in tests.
"""

from __future__ import annotations

import atexit
import collections
import multiprocessing as mp
import queue
import signal
import threading
import time
import weakref
from contextlib import contextmanager
from typing import Callable, Iterable, Optional, Union


class FastQueue:
    """Bounded FIFO without locks or condition variables.

    ``queue.Queue`` takes a mutex and signals a condition variable on every
    operation; under producer/consumer contention each op degrades to a
    futex syscall. On sandboxed kernels where syscalls are expensive (this
    container: measured 37-56 us PER PUT at plane rates — more than the
    whole block wire's per-datapoint budget), that makes ``queue.Queue``
    itself the actor plane's throughput ceiling (~20k items/s).

    This queue uses a plain ``collections.deque`` — ``append``/``popleft``
    are GIL-atomic, ~0.2 us — and bounded SLEEP-POLLING instead of
    condition variables when empty/full. The trade: a few ms of wakeup
    latency when a side actually has to wait, which is the right deal for
    a queue that is never supposed to be empty or full in steady state
    (the train queue at 40k+ datapoints/s).

    Implements the ``queue.Queue`` subset the actor plane uses (``put``/
    ``get`` with block/timeout, ``*_nowait``, ``qsize``/``empty``/``full``,
    ``maxsize``). The bound is approximate under multiple producers (two
    racing puts can overshoot by one item each) — backpressure, not an
    exact invariant.
    """

    _POLL_S = 0.002

    def __init__(self, maxsize: int = 0):
        self.maxsize = maxsize
        self._d: collections.deque = collections.deque()

    def qsize(self) -> int:
        return len(self._d)

    def empty(self) -> bool:
        return not self._d

    def full(self) -> bool:
        return self.maxsize > 0 and len(self._d) >= self.maxsize

    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        if self.maxsize > 0 and len(self._d) >= self.maxsize:
            if not block:
                raise queue.Full
            deadline = (
                None if timeout is None else time.monotonic() + timeout
            )
            while len(self._d) >= self.maxsize:
                if deadline is not None and time.monotonic() >= deadline:
                    raise queue.Full
                time.sleep(self._POLL_S)
        self._d.append(item)

    def put_nowait(self, item):
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        try:
            return self._d.popleft()
        except IndexError:
            pass
        if not block:
            raise queue.Empty
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self._d.popleft()
            except IndexError:
                if deadline is not None and time.monotonic() >= deadline:
                    raise queue.Empty
                time.sleep(self._POLL_S)

    def get_nowait(self):
        return self.get(block=False)


def queue_put_stoppable(
    q: queue.Queue, obj, stop_evt: threading.Event, timeout: float = 0.5
) -> bool:
    """Put, retrying until success or ``stop_evt``; returns False if stopped.

    The ONE sanctioned way to put on a bounded actor-plane queue: bounded
    waits that re-check the stop flag, so backpressure can never wedge
    shutdown (ba3clint rule A2).
    """
    while not stop_evt.is_set():
        try:
            q.put(obj, timeout=timeout)
            return True
        except queue.Full:
            pass
    return False


def queue_get_stoppable(
    q: queue.Queue, stop_evt: threading.Event, timeout: float = 0.5
):
    """Get, retrying until success or ``stop_evt``; returns None if stopped."""
    while not stop_evt.is_set():
        try:
            return q.get(timeout=timeout)
        except queue.Empty:
            pass
    return None


class StoppableThread(threading.Thread):
    """Thread with a cooperative stop flag and stop-aware queue helpers."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._stop_evt = threading.Event()

    def stop(self) -> None:
        self._stop_evt.set()

    def stopped(self) -> bool:
        return self._stop_evt.is_set()

    def queue_put_stoppable(self, q: queue.Queue, obj, timeout: float = 0.5) -> bool:
        """Put, retrying until success or stop(); returns False if stopped."""
        return queue_put_stoppable(q, obj, self._stop_evt, timeout)

    def queue_get_stoppable(self, q: queue.Queue, timeout: float = 0.5):
        """Get, retrying until success or stop(); returns None if stopped."""
        return queue_get_stoppable(q, self._stop_evt, timeout)


class LatestWinsPump(StoppableThread):
    """Asynchronous per-key latest-wins apply worker.

    ``publish(key, value)`` NEVER blocks: it overwrites the key's pending
    slot and wakes the worker thread, which calls ``apply(key, value)`` on
    its own time. Values a slow consumer missed are coalesced away —
    latest wins per key — which is exactly right for monotone streams
    like parameter publishes: serving an intermediate version nobody will
    ever read again is pure wasted device time, and a wedged consumer
    must stall only ITSELF, never the publisher (actors/fleet.py
    ``FanoutPredictors`` and predict/router.py run one pump per target
    for precisely that isolation).

    ``apply`` exceptions are routed to ``on_error`` (or swallowed) — the
    pump thread must survive one bad publish. ``flush(timeout)`` waits
    until every pending/busy item has been applied (tests, teardown
    barriers); it is the ONLY blocking call here.
    """

    def __init__(
        self,
        apply: Callable[[object, object], None],
        name: str = "latest-pump",
        on_coalesce: Optional[Callable[[], None]] = None,
        on_error: Optional[Callable[[Exception], None]] = None,
    ):
        super().__init__(daemon=True, name=name)
        self._apply = apply
        self._on_coalesce = on_coalesce
        self._on_error = on_error
        self._cond = threading.Condition()
        self._pending: dict = {}  # key -> latest value
        self._busy = 0

    def publish(self, key, value) -> None:
        with self._cond:
            if key in self._pending and self._on_coalesce is not None:
                self._on_coalesce()
            self._pending[key] = value
            self._cond.notify()

    def run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self.stopped():
                    self._cond.wait(0.2)
                if self.stopped():
                    # teardown drops what's pending: the targets are being
                    # torn down too, and an apply against a dying consumer
                    # is what wedges joins
                    self._pending.clear()
                    self._cond.notify_all()
                    return
                items = list(self._pending.items())
                self._pending.clear()
                self._busy = len(items)
            for key, value in items:
                try:
                    self._apply(key, value)
                except Exception as e:
                    if self._on_error is not None:
                        try:
                            self._on_error(e)
                        except Exception:
                            pass
                finally:
                    with self._cond:
                        self._busy -= 1
                        self._cond.notify_all()

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait until everything published so far has been applied.
        Returns False on timeout (the consumer is wedged — which is the
        situation the pump exists to keep OFF the publisher's thread)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._pending or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.2))
        return True


class LoopThread(StoppableThread):
    """Calls ``func`` in a loop until stopped."""

    def __init__(self, func: Callable[[], None], daemon: bool = True):
        super().__init__(daemon=daemon)
        self._func = func

    def run(self) -> None:
        while not self.stopped():
            self._func()


def ensure_proc_terminate(
    proc: Union[mp.Process, Iterable[mp.Process]],
) -> None:
    """Register an atexit hook that terminates the process(es).

    Simulator processes must not outlive the trainer (the reference had the
    same problem with 50 ALE processes per worker).
    """
    if not isinstance(proc, mp.process.BaseProcess):
        for p in proc:
            ensure_proc_terminate(p)
        return

    ref = weakref.ref(proc)

    def stop():
        p = ref()
        if p is None or not p.is_alive():
            return
        p.terminate()
        p.join(timeout=5)
        if p.is_alive():
            p.kill()

    atexit.register(stop)


@contextmanager
def mask_sigint():
    """Block SIGINT so forked children don't receive the trainer's Ctrl-C."""
    if threading.current_thread() is threading.main_thread():
        old = signal.signal(signal.SIGINT, signal.SIG_IGN)
        try:
            yield
        finally:
            signal.signal(signal.SIGINT, old)
    else:
        yield


def start_proc_mask_signal(
    procs: Union[mp.Process, Iterable[mp.Process]],
) -> None:
    """Start process(es) with SIGINT masked (children ignore Ctrl-C)."""
    if isinstance(procs, mp.process.BaseProcess):
        procs = [procs]
    with mask_sigint():
        for p in procs:
            p.start()
