"""Exporters: the scrape endpoint + stat.json/TB bridge.

:class:`TelemetryServer` is a stdlib ``http.server`` on ``--telemetry_port``
serving every role registry in the process (master, predictor, learner,
fleet):

- ``GET /metrics`` — Prometheus text exposition (``ba3c_*`` series, one
  ``role`` label; histograms as cumulative ``_bucket{le=...}`` +
  ``_sum``/``_count``).
- ``GET /json``    — the raw :func:`metrics.all_snapshots` document.
- ``GET /flight``  — the flight recorder's current ring (live, no dump);
  ``?since=<t_monotonic>&kind=<kind>`` filters via ``events_since``.
- ``GET /trace``   — the trace plane's buffered spans + clock offsets
  (telemetry/tracing.py; feed to ``scripts/trace_dump.py``).
- ``GET /``        — a one-line index.

The stat.json/TB bridge is :func:`export_scalars` — StatPrinter folds it
into each epoch record, so existing dashboards keep reading stat.json/TB
events while scrapers move to the endpoint.
"""

from __future__ import annotations

import http.server
import json
import threading
import urllib.parse
from typing import Dict, Optional

from distributed_ba3c_tpu.telemetry import metrics, recorder, tracing


def prometheus_text(snapshots: Optional[Dict[str, Dict[str, dict]]] = None) -> str:
    """Render every registry as Prometheus text exposition format.

    Series are grouped per METRIC FAMILY (one ``# TYPE`` line, then every
    role's sample), not per (role, metric): the same name in two roles —
    ``episodes_total`` lives in learner, simulator and fleet by design —
    must not emit a second TYPE line, which the Prometheus text parser
    rejects for the whole scrape. A name that appears with conflicting
    types keeps the first type and drops the mismatched role's sample
    (rendering it would equally poison the scrape).
    """
    if snapshots is None:
        snapshots = metrics.all_snapshots()
    # family name -> [(role, collected)], insertion-ordered by sorted walk
    families: Dict[str, list] = {}
    for role, series in sorted(snapshots.items()):
        for name, m in sorted(series.items()):
            safe = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
            families.setdefault(safe, []).append((role, m))
    lines = []
    for safe in sorted(families):
        members = families[safe]
        ftype = members[0][1]["type"]
        lines.append(f"# TYPE ba3c_{safe} {ftype}")
        for role, m in members:
            if m["type"] != ftype:
                continue
            if m["type"] in ("counter", "gauge"):
                lines.append(f'ba3c_{safe}{{role="{role}"}} {m["value"]}')
            else:  # histogram: cumulative le-buckets over the log2 bounds
                unit, cum = m["unit"], 0
                for i, c in enumerate(m["buckets"]):
                    cum += c
                    if c:
                        le = unit * (1 << i)
                        lines.append(
                            f'ba3c_{safe}_bucket{{role="{role}",le="{le:g}"}} {cum}'
                        )
                lines.append(
                    f'ba3c_{safe}_bucket{{role="{role}",le="+Inf"}} {m["count"]}'
                )
                lines.append(f'ba3c_{safe}_sum{{role="{role}"}} {m["sum"]}')
                lines.append(f'ba3c_{safe}_count{{role="{role}"}} {m["count"]}')
    return "\n".join(lines) + "\n"


def export_scalars(
    roles=("master", "predictor", "router", "learner", "fleet",
           "orchestrator", "pod"),
    prefix: str = "tele/",
) -> Dict[str, float]:
    """Counters + gauges flattened to ``{"tele/<role>/<name>": value}`` for
    the stat.json/TB writers (histograms export their _count/_sum).

    Each requested role matches itself AND its dotted sub-roles: the
    per-fleet scheme (``master`` also exports ``master.f0``/``master.f1``
    — telemetry.fleet_role) and the pod's per-host scheme (``pod``
    exports ``pod.host0``/``pod.host1``/... — pod/wire.py pod_role, the
    learner-side mirror of each actor host's progress), so multi-fleet
    and pod runs grow their per-component series without every caller
    enumerating fleets or hosts.
    """
    out: Dict[str, float] = {}
    regs = metrics.all_registries()
    for base in roles:
        for role in sorted(regs):
            if role != base and not role.startswith(f"{base}."):
                continue
            for name, v in regs[role].scalars().items():
                out[f"{prefix}{role}/{name}"] = v
    return out


class _Handler(http.server.BaseHTTPRequestHandler):
    def _send(self, body: str, content_type: str) -> None:
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (stdlib API name)
        try:
            path, _, query = self.path.partition("?")
            if path.startswith("/metrics"):
                self._send(prometheus_text(), "text/plain; version=0.0.4")
            elif path.startswith("/json"):
                self._send(
                    json.dumps(metrics.all_snapshots()), "application/json"
                )
            elif path.startswith("/flight"):
                self._send(json.dumps(self._flight(query)), "application/json")
            elif path.startswith("/trace"):
                # the trace plane's scrape: buffered spans + per-peer
                # clock offsets + the monotonic/wall anchor pair —
                # scripts/trace_dump.py merges one or more of these into
                # Chrome trace-event / Perfetto JSON
                self._send(
                    json.dumps(tracing.tracer().document()),
                    "application/json",
                )
            elif path == "/":
                self._send(
                    "ba3c telemetry: /metrics (prometheus), /json, "
                    "/flight[?since=&kind=], /trace\n",
                    "text/plain",
                )
            else:
                self.send_error(404)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-response

    @staticmethod
    def _flight(query: str) -> list:
        """The flight ring, optionally filtered: ``?since=<t_monotonic>``
        and/or ``?kind=<event kind>`` expose the recorder's existing
        ``events_since`` filter over HTTP — a postmortem poll that wants
        "prunes since my last scrape" no longer re-downloads (and
        re-diffs) the whole ring. Junk params read as unfiltered/ignored
        rather than erroring the scrape."""
        params = urllib.parse.parse_qs(query)
        kind = params.get("kind", [None])[0] or None
        since = params.get("since", [None])[0]
        if since is None and kind is None:
            return recorder.flight_recorder().snapshot()
        try:
            t = float(since) if since is not None else float("-inf")
        except ValueError:
            t = float("-inf")
        return [
            {"t_monotonic": ev[0], "kind": ev[1], **ev[2]}
            for ev in recorder.flight_recorder().events_since(t, kind)
        ]

    def log_message(self, fmt, *args):  # scrapes must not spam the run log
        pass


class TelemetryServer:
    """The scrape endpoint, start/stop/join/close-compatible with
    StartProcOrThread (train/callbacks.py) so cli.py can just append it to
    the startables list."""

    def __init__(self, port: int, host: str = "0.0.0.0"):
        # ThreadingHTTPServer: a wedged scraper connection must not block
        # the next scrape. daemon_threads so per-request threads never
        # outlive the process.
        self._httpd = http.server.ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]  # resolved when port=0
        from distributed_ba3c_tpu.utils.concurrency import StoppableThread

        # the loop is serve_forever, unblocked by shutdown() in stop() —
        # the StoppableThread flag is for StartProcOrThread's protocol
        self._thread = StoppableThread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            daemon=True,
            name="telemetry-scrape",
        )

    def start(self) -> None:
        self._thread.start()
        from distributed_ba3c_tpu.utils import logger

        logger.info(
            "telemetry scrape endpoint on :%d "
            "(/metrics, /json, /flight, /trace)",
            self.port,
        )

    def stop(self) -> None:
        self._thread.stop()
        # shutdown() blocks on an event only serve_forever() sets — calling
        # it on a server whose thread never started (teardown after an
        # earlier startable failed to start) would wedge forever
        if self._thread.is_alive():
            self._httpd.shutdown()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread.is_alive():
            self._thread.join(timeout)

    def close(self) -> None:
        self._httpd.server_close()
