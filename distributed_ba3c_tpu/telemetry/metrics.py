"""Sharded metrics core: Counter / Gauge / log2-bucket Histogram + Registry.

Built for the 52.8k env-steps/s/host hot path (``runs/plane_bench_r6.json``):
no locks on the write side, aggregation at read time. Each metric keeps one
cell PER WRITER THREAD; a thread only ever mutates its own cell, and
mutating a Python int/list slot under the GIL is atomic enough — a reader
summing cells mid-increment sees a value that was true a moment ago, which
is all a monitoring plane needs. The ONE rule: never take a lock, never
make a syscall on the increment path (the futex-per-op cost class that
made ``queue.Queue`` the plane's ceiling — utils/concurrency.py).

Increment cost budget: a ``Counter.inc`` is a ``threading.get_ident()`` +
dict get + int add (~0.3 us). Hot-path call sites amortize further by
incrementing ONCE PER BATCH (a block flush adds its whole datapoint count
in one ``inc(n)``), so per-env-step overhead is nanoseconds — the
``scripts/plane_bench.py --telemetry both`` gate pins the total at <=2%
(runs/plane_bench_r7.json).

Registries are per ROLE, not per process: the trainer process hosts the
``master``, ``predictor`` and ``learner`` registries side by side, plus a
``fleet`` registry the master fills from env-server piggyback deltas
(telemetry/wire.py). Exporters (telemetry/exporters.py) walk
:func:`all_registries`.

``BA3C_TELEMETRY=0`` (or :func:`set_enabled`) turns every write into a
cheap branch-and-return — the A/B lever the plane-bench overhead gate
measures against.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional

#: number of log2 buckets a histogram keeps. With unit=1e-6 (microseconds)
#: bucket 39 covers ~2^39 us ≈ 6.4 days — nothing a run produces overflows.
N_BUCKETS = 40

_enabled = os.environ.get("BA3C_TELEMETRY", "1") not in ("", "0")


def enabled() -> bool:
    return _enabled


def fleet_role(base: str, fleet: Optional[int] = None) -> str:
    """The canonical telemetry role for one fleet's plane component.

    THE single formula (docs/observability.md): ``master``/``predictor``/
    ``fleet`` for a single-fleet run (every existing dashboard keeps
    working), ``master.f<k>`` etc. when a learner hosts several fleets —
    the per-fleet scrape label ``http_signals``/``/json`` consumers key on.
    Deriving it in two places would let the exporter and the autoscaler
    address different registries.
    """
    return base if fleet is None else f"{base}.f{int(fleet)}"


def set_enabled(flag: bool) -> None:
    """Flip the process-wide write switch (child processes inherit the
    ``BA3C_TELEMETRY`` env var instead — set both when spawning)."""
    global _enabled
    _enabled = bool(flag)


class Counter:
    """Monotonic counter, sharded per writer thread.

    ``inc(n)`` touches only the calling thread's cell; ``value()`` sums all
    cells. Creating a missing cell mutates the dict, which is safe: dict
    ``__setitem__`` is GIL-atomic and each key has exactly one writer.
    """

    __slots__ = ("name", "_cells")

    def __init__(self, name: str):
        self.name = name
        self._cells: Dict[int, List[float]] = {}

    def inc(self, n: float = 1) -> None:
        if not _enabled:
            return
        tid = threading.get_ident()
        cell = self._cells.get(tid)
        if cell is None:
            self._cells[tid] = cell = [0]
        cell[0] += n

    def value(self) -> float:
        # list() snapshots the cells: a reader racing another thread's
        # FIRST inc (which inserts a new key) must not die with
        # "dictionary changed size during iteration"
        return sum(c[0] for c in list(self._cells.values()))

    def collect(self) -> dict:
        return {"type": "counter", "value": self.value()}

    def reset(self) -> None:
        self._cells = {}


class Gauge:
    """Point-in-time value: either ``set()`` by writers (last write wins,
    assignment is atomic) or backed by a zero-argument callable evaluated at
    READ time (``fn=...``) — the right shape for queue depths and client
    counts, which would otherwise need a hot-path write per change."""

    __slots__ = ("name", "_value", "_fn")

    def __init__(self, name: str, fn: Optional[Callable[[], float]] = None):
        self.name = name
        self._value = 0.0
        self._fn = fn

    def set(self, v: float) -> None:
        if not _enabled:
            return
        self._value = v

    def set_fn(self, fn: Optional[Callable[[], float]]) -> None:
        """(Re)bind the read-time callable (last binder wins — a new master
        replacing a closed one takes over the series)."""
        self._fn = fn

    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                # a gauge over a torn-down object (closed queue, dead
                # master) must read 0, not kill the scrape
                return 0.0
        return float(self._value)

    def collect(self) -> dict:
        return {"type": "gauge", "value": self.value()}

    def reset(self) -> None:
        self._value = 0.0


class Histogram:
    """log2-bucket histogram, sharded per writer thread.

    Bucket ``i`` counts observations ``v`` with ``v/unit`` in
    ``[2^(i-1), 2^i)`` (bucket 0 takes everything below ``unit``). log2 is
    one ``int.bit_length()`` — no float math, no branching search — and 40
    buckets span nine decades, plenty for queue waits (us..minutes) and
    batch occupancies alike. ``unit`` picks the resolution floor: 1e-6 for
    second-valued latencies, 1 for counts.
    """

    __slots__ = ("name", "unit", "_cells")

    def __init__(self, name: str, unit: float = 1e-6):
        self.name = name
        self.unit = unit
        # per-thread cell: [count, sum, b0..b39]
        self._cells: Dict[int, List[float]] = {}

    def observe(self, v: float) -> None:
        if not _enabled:
            return
        tid = threading.get_ident()
        cell = self._cells.get(tid)
        if cell is None:
            self._cells[tid] = cell = [0, 0.0] + [0] * N_BUCKETS
        cell[0] += 1
        cell[1] += v
        q = int(v / self.unit)
        b = q.bit_length() if q > 0 else 0
        cell[2 + (b if b < N_BUCKETS else N_BUCKETS - 1)] += 1

    @property
    def count(self) -> int:
        # list(): see Counter.value — snapshot against first-observe races
        return int(sum(c[0] for c in list(self._cells.values())))

    @property
    def sum(self) -> float:
        return float(sum(c[1] for c in list(self._cells.values())))

    def buckets(self) -> List[int]:
        """Per-bucket (non-cumulative) counts, aggregated over threads."""
        out = [0] * N_BUCKETS
        for c in list(self._cells.values()):
            for i in range(N_BUCKETS):
                out[i] += c[2 + i]
        return out

    def collect(self) -> dict:
        return {
            "type": "histogram",
            "count": self.count,
            "sum": self.sum,
            "unit": self.unit,
            "buckets": self.buckets(),
        }

    def reset(self) -> None:
        self._cells = {}


class Registry:
    """One role's named metrics; get-or-create, read-side aggregation."""

    def __init__(self, role: str):
        self.role = role
        self._metrics: Dict[str, object] = {}
        # creation is rare (wiring time) — a lock here costs nothing and
        # keeps get-or-create race-free; the hot path never enters it
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str, fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self._get(name, Gauge)
        if fn is not None:
            g.set_fn(fn)
        return g

    def histogram(self, name: str, unit: float = 1e-6) -> Histogram:
        return self._get(name, lambda n: Histogram(n, unit=unit))

    def _get(self, name: str, ctor):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    self._metrics[name] = m = ctor(name)
        return m

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def collect(self) -> Dict[str, dict]:
        """``{name: {"type": ..., "value"/"buckets": ...}}`` snapshot."""
        return {n: self._metrics[n].collect() for n in self.names()}

    def scalars(self) -> Dict[str, float]:
        """Counters + gauges as plain floats (histograms as _count/_sum) —
        the stat.json/TB export shape (utils/stats.py)."""
        out: Dict[str, float] = {}
        for n in self.names():
            m = self._metrics[n]
            if isinstance(m, Histogram):
                out[f"{n}_count"] = float(m.count)
                out[f"{n}_sum"] = m.sum
            else:
                out[n] = float(m.value())
        return out


_registries: Dict[str, Registry] = {}
_registries_lock = threading.Lock()


def registry(role: str) -> Registry:
    """The process-wide registry for ``role`` (get-or-create)."""
    r = _registries.get(role)
    if r is None:
        with _registries_lock:
            r = _registries.get(role)
            if r is None:
                _registries[role] = r = Registry(role)
    return r


def all_registries() -> Dict[str, Registry]:
    with _registries_lock:
        return dict(_registries)


def all_snapshots() -> Dict[str, Dict[str, dict]]:
    """``{role: {name: collected}}`` over every live registry."""
    return {role: r.collect() for role, r in sorted(all_registries().items())}


def reset_all() -> None:
    """Drop every registered metric (bench harness between same-session
    runs; objects still held by old masters keep working, just unexported)."""
    with _registries_lock:
        for r in _registries.values():
            r._metrics = {}
    # the fleet-aggregation sender table must reset with the registries:
    # block-wire idents are stable per fleet x slot, so a back-to-back
    # same-process bench run would otherwise count the PREVIOUS run's
    # senders in reporting_clients for up to the liveness window
    from distributed_ba3c_tpu.telemetry import tracing, wire

    wire._FLEET_SEEN.clear()
    # buffered spans and peer clock offsets are per-run evidence the same
    # way counters are: a back-to-back bench session must not export the
    # previous run's spans (or align against its dead senders' clocks)
    tracing.reset()
