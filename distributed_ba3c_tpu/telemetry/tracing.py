"""Distributed trace plane: causal block-lifetime spans across processes.

The metrics core answers "how fast" and the flight recorder answers "what
broke"; neither answers "WHERE did this block's wall-clock go". The single
``e2e_ingest_latency_s`` blob (actors/simulator.py) collapses six hops —
env step, wire transit, predictor dispatch/fetch, unroll flush, queue
wait, collate, device ingest — into one number, and the pod plane adds a
whole cross-process leg no series attributes at all. This module is the
decomposition: sampled, causal, span-based tracing with the SAME
lock-free per-thread-sharded design as the metrics core.

Design constraints (the metrics core's, inherited verbatim):

- **No locks, no syscalls on the hot path.** A finished span is one
  ``time.monotonic_ns`` pair + an append to the calling thread's own
  bounded cell (deque appends are GIL-atomic). Readers aggregate at
  scrape time.
- **1-in-N block sampling.** Tracing is off (``sample_n == 0``) unless
  ``--trace_sample N`` / ``BA3C_TRACE=N`` arms it; the untraced
  (N-1)/N of block steps pay ONE modulo per block message. The sampling
  decision is deterministic in the block step counter, so a trace is
  reproducible and the off/on overhead gate
  (``scripts/plane_bench.py --trace both``) is an honest A/B.
- **``BA3C_TELEMETRY=0`` kills this plane too** — tracing is a telemetry
  layer, not a second switch to audit.

Wire format (the telemetry-delta piggyback pattern, telemetry/wire.py):
a sampled block carries a compact **trace context** as a new
length-versioned element on the existing block / block-shm / per-env
headers, and as an optional ``"tr"`` key on the pod wire's stamped
messages (pod/wire.py). The context is a plain msgpack list::

    [version, trace_id, span_id, send_mono_us, origin_dur_us]

- ``version``: integer codec version (:data:`CTX_VERSION`). A receiver
  accepts any version >= 1 and reads only the fields it knows — unknown
  NEWER versions with extra fields parse fine (forward tolerance), and
  junk parses to None without touching the receive loop.
- ``trace_id`` / ``span_id``: 63-bit ids; the span id names the sender's
  originating span so the receiver's first span parents onto it.
- ``send_mono_us``: the sender's ``time.monotonic`` in µs at send time —
  the **clock-alignment handshake**. The receiver records
  ``local_recv - send_mono_us`` per peer and keeps the MINIMUM observed
  (transit latency only ever inflates the difference, so the min
  converges on true_offset + min_transit); :func:`align` maps any remote
  stamp onto the local monotonic timeline through that offset.
- ``origin_dur_us``: how long the sender's own originating hop took
  (e.g. the env server's ``env.step``), so the receiver can synthesize
  the origin span without the sender needing a scrape endpoint.

Exports: ``GET /trace`` on the TelemetryServer returns
:func:`trace_document` (spans + per-peer clock offsets + a
monotonic/wall anchor pair); ``scripts/trace_dump.py`` merges one or
more such documents into Chrome trace-event / Perfetto JSON. Every
finished span ALSO folds its duration into a per-hop latency histogram
``hop_<name>_s`` in its role registry — the sampled breakdown that
retires the single e2e blob into named hops on ``/metrics``.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from distributed_ba3c_tpu.telemetry import metrics as _metrics

#: trace-context codec version (bump when APPENDING fields; receivers
#: read prefix fields only, so old receivers parse new contexts)
CTX_VERSION = 1

#: spans kept PER WRITER THREAD before drop-oldest engages — a scrape
#: cadence of seconds at sampled rates never fills this; a stuck scraper
#: costs bounded memory, never a stalled hot path
DEFAULT_SPAN_CAPACITY = 4096

#: 63-bit id space: msgpack encodes them as positive fixints/uint64 and
#: they survive JSON round-trips without sign surprises
_ID_MASK = (1 << 63) - 1


def _env_sample_n() -> int:
    try:
        return max(0, int(os.environ.get("BA3C_TRACE", "0") or 0))
    except ValueError:
        return 0


_sample_n = _env_sample_n()


def sample_n() -> int:
    """The process-wide 1-in-N block sampling rate (0 = tracing off)."""
    return _sample_n


def set_sampling(n: int) -> None:
    """Arm (or disarm, n=0) sampling process-wide. Child processes
    inherit the ``BA3C_TRACE`` env var instead — set both when spawning
    (the cli.py / bench.py idiom for BA3C_TELEMETRY)."""
    global _sample_n
    _sample_n = max(0, int(n))


def enabled() -> bool:
    """Tracing is live: telemetry on AND a sampling rate armed."""
    return _sample_n > 0 and _metrics.enabled()


def sampled(step: int, n: Optional[int] = None) -> bool:
    """The deterministic 1-in-N sampling decision for block ``step``.

    Deterministic in the step counter (not RNG): the same run traces the
    same steps, the overhead gate's off arm skips exactly what the on
    arm samples, and a test can predict which steps carry context."""
    n = _sample_n if n is None else n
    return n > 0 and step % n == 0


def now_us() -> int:
    """Local monotonic µs — THE span timebase (wall clock jumps; A4)."""
    return time.monotonic_ns() // 1000


def make_id(*parts) -> int:
    """Deterministic 63-bit id from hashable parts (ident, step) — the
    trace id an env server mints without an RNG in its hot loop."""
    h = 1469598103934665603  # FNV-1a offset basis
    for p in parts:
        for b in repr(p).encode():
            h = ((h ^ b) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h & _ID_MASK or 1


class SpanBuffer:
    """Bounded per-thread-sharded store of finished spans.

    A span is the tuple ``(trace_id, span_id, parent_id, name, role,
    t_start_us, dur_us, tags)`` — appended to the calling thread's own
    ``deque(maxlen=...)`` (GIL-atomic, no lock, no syscall). Readers
    snapshot all cells; drop-oldest per cell bounds memory under a
    stalled scraper. ``dropped`` counts evicted spans (read-side
    estimate: appends beyond capacity)."""

    def __init__(self, capacity: int = DEFAULT_SPAN_CAPACITY):
        self.capacity = capacity
        # tid -> [append_count, deque]: ONE dict, fetched ONCE per add —
        # a concurrent reset() swapping the dict leaves a mid-add writer
        # on its old (consistent) cell instead of KeyError-ing between
        # two parallel tables (the single-dict metrics-core pattern)
        self._cells: Dict[int, list] = {}

    def add(self, span: tuple) -> None:
        tid = threading.get_ident()
        cell = self._cells.get(tid)
        if cell is None:
            self._cells[tid] = cell = [
                0, collections.deque(maxlen=self.capacity)
            ]
        cell[1].append(span)
        cell[0] += 1

    def __len__(self) -> int:
        return sum(len(c[1]) for c in list(self._cells.values()))

    @property
    def dropped(self) -> int:
        cells = list(self._cells.values())
        return max(0, sum(c[0] for c in cells) - sum(len(c[1]) for c in cells))

    def snapshot(self) -> List[dict]:
        """All buffered spans as JSON-ready dicts, sorted by start time
        (cells are per-thread, so a global causal read needs the sort)."""
        out = []
        for cell in list(self._cells.values()):
            for (tr, sp, parent, name, role, t0, dur, tags) in list(cell[1]):
                d = {
                    "trace_id": tr, "span_id": sp, "parent_id": parent,
                    "name": name, "role": role, "ts_us": t0, "dur_us": dur,
                }
                if tags:
                    d["tags"] = tags
                out.append(d)
        out.sort(key=lambda d: d["ts_us"])
        return out

    def reset(self) -> None:
        self._cells = {}


class Tracer:
    """One process's span sink + peer clock-offset table.

    ``finish_span`` is the ONE write path: it stores the span and folds
    the duration into the role registry's ``hop_<name>_s`` histogram, so
    the sampled per-hop breakdown shows up on ``/metrics`` next to the
    unsampled counters without a second instrumentation pass."""

    def __init__(self, capacity: int = DEFAULT_SPAN_CAPACITY):
        self.spans = SpanBuffer(capacity)
        # peer -> min observed (local - remote) µs; one writer thread per
        # peer in practice (the receive loop that owns that wire), and a
        # racing double-store of two near-equal minima is harmless
        self._offsets: Dict[str, int] = {}
        self._seq = [0]  # span-id nonce (GIL-atomic += under one writer)

    # -- ids ---------------------------------------------------------------
    def next_span_id(self) -> int:
        self._seq[0] += 1
        return make_id(os.getpid(), threading.get_ident(), self._seq[0])

    # -- clock alignment ---------------------------------------------------
    def observe_remote_clock(
        self, peer: str, remote_us: int, local_us: Optional[int] = None
    ) -> int:
        """Fold one handshake stamp into ``peer``'s offset; returns the
        current offset estimate (local = remote + offset). Min-filtered:
        transit latency only ever ADDS to the observed difference."""
        if local_us is None:
            local_us = now_us()
        obs = int(local_us) - int(remote_us)
        cur = self._offsets.get(peer)
        if cur is None or obs < cur:
            self._offsets[peer] = obs
            return obs
        return cur

    def clock_offset(self, peer: str) -> Optional[int]:
        return self._offsets.get(peer)

    def align(self, peer: str, remote_us: int) -> int:
        """Map a peer's monotonic stamp onto the LOCAL timeline (identity
        when no handshake has been observed yet)."""
        return int(remote_us) + self._offsets.get(peer, 0)

    # -- spans -------------------------------------------------------------
    def finish_span(
        self,
        trace_id: int,
        name: str,
        role: str,
        t_start_us: int,
        t_end_us: Optional[int] = None,
        parent_id: int = 0,
        span_id: Optional[int] = None,
        tags: Optional[dict] = None,
    ) -> int:
        """Record one completed span; returns its span id (the parent for
        the next hop). Durations clamp at >= 0: a cross-process start
        aligned through a still-converging offset must never emit a
        negative-length span into the export.

        ``BA3C_TELEMETRY=0`` gates the WRITE here, at the single sink:
        a remote sender stamping contexts at a telemetry-disabled
        receiver must not fill its span buffer (the kill-switch
        contract) — the id still mints so callers' chains stay
        well-formed if telemetry flips mid-trace."""
        if span_id is None:
            span_id = self.next_span_id()
        if not _metrics.enabled():
            return span_id
        if t_end_us is None:
            t_end_us = now_us()
        dur = max(0, int(t_end_us) - int(t_start_us))
        self.spans.add(
            (trace_id, span_id, parent_id, name, role, int(t_start_us),
             dur, tags)
        )
        # the per-hop histogram: sampled latencies, but the same log2
        # buckets/los as every other series — docs/observability.md
        _metrics.registry(role).histogram(f"hop_{name}_s").observe(dur / 1e6)
        return span_id

    def document(self) -> dict:
        """The ``/trace`` endpoint body: spans + offsets + anchor pair.

        ``anchor_monotonic_us``/``anchor_wall`` let offline tooling map
        this process's monotonic timeline to wall time (the flight
        recorder's anchor idiom); ``clock_offsets_us`` carries the
        measured per-peer handshake offsets so ``trace_dump.py`` can
        merge several processes' documents onto one timeline."""
        return {
            "pid": os.getpid(),
            "sample_n": _sample_n,
            "anchor_monotonic_us": now_us(),
            "anchor_wall": time.time(),
            "clock_offsets_us": dict(self._offsets),
            "dropped_spans": self.spans.dropped,
            "spans": self.spans.snapshot(),
        }

    def reset(self) -> None:
        self.spans.reset()
        self._offsets = {}


_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def tracer() -> Tracer:
    """The process's tracer (get-or-create)."""
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                _tracer = Tracer()
    return _tracer


def reset() -> None:
    """Drop buffered spans and offsets (bench harness between runs)."""
    if _tracer is not None:
        _tracer.reset()


# -- the active-trace thread-local (flight-recorder correlation) -----------

_active = threading.local()


def current_trace_id() -> Optional[int]:
    """The trace id in scope on this thread, if any — the flight
    recorder stamps it onto events so postmortem dumps correlate with
    traces (telemetry/recorder.py)."""
    return getattr(_active, "trace_id", None)


class trace_scope:
    """Context manager marking ``trace_id`` active on this thread (no
    span is recorded — pair with :meth:`Tracer.finish_span` for that)."""

    __slots__ = ("trace_id", "_prev")

    def __init__(self, trace_id: Optional[int]):
        self.trace_id = trace_id

    def __enter__(self):
        self._prev = getattr(_active, "trace_id", None)
        _active.trace_id = self.trace_id
        return self

    def __exit__(self, *exc):
        _active.trace_id = self._prev
        return False


class span:
    """Context-manager span: ``with tracing.span(trace, "collate",
    "learner", parent=p) as s: ...`` records on exit and exposes
    ``s.span_id`` for parenting the next hop. The ba3clint A11 rule
    (orphan-span) wants exactly this shape — or an explicit
    ``finish()`` on every exit path."""

    __slots__ = ("trace_id", "name", "role", "parent_id", "tags",
                 "t_start_us", "span_id", "_done")

    def __init__(self, trace_id, name, role, parent=0, tags=None):
        self.trace_id = trace_id
        self.name = name
        self.role = role
        self.parent_id = parent
        self.tags = tags
        self.t_start_us = now_us()
        self.span_id = tracer().next_span_id()
        self._done = False

    def __enter__(self):
        return self

    def finish(self) -> int:
        if not self._done:
            self._done = True
            tracer().finish_span(
                self.trace_id, self.name, self.role, self.t_start_us,
                parent_id=self.parent_id, span_id=self.span_id,
                tags=self.tags,
            )
        return self.span_id

    def __exit__(self, *exc):
        self.finish()
        return False


# -- the wire context codec ------------------------------------------------

class TraceContext:
    """Decoded wire context (see module docstring for the field story)."""

    __slots__ = ("version", "trace_id", "span_id", "send_us", "origin_dur_us")

    def __init__(self, trace_id, span_id, send_us, origin_dur_us=0,
                 version=CTX_VERSION):
        self.version = int(version)
        self.trace_id = int(trace_id) & _ID_MASK
        self.span_id = int(span_id) & _ID_MASK
        self.send_us = int(send_us)
        self.origin_dur_us = max(0, int(origin_dur_us))


def encode_context(
    trace_id: int,
    span_id: int,
    send_us: Optional[int] = None,
    origin_dur_us: int = 0,
) -> list:
    """The header element a sampled sender appends (plain ints — the
    msgpack header codec must not meet numpy scalars here, the
    DeltaTracker lesson)."""
    return [
        CTX_VERSION,
        int(trace_id) & _ID_MASK,
        int(span_id) & _ID_MASK,
        int(send_us if send_us is not None else now_us()),
        int(origin_dur_us),
    ]


def decode_context(elem: Any) -> Optional[TraceContext]:
    """Tolerant inverse of :func:`encode_context`.

    Wire input is untrusted (the block decoder's posture): anything that
    is not a >= 4-element list of ints headed by a version >= 1 decodes
    to None — never an exception into a receive loop. A version NEWER
    than ours with extra trailing fields decodes fine (prefix read)."""
    if not isinstance(elem, (list, tuple)) or len(elem) < 4:
        return None
    try:
        ver = int(elem[0])
        if ver < 1:
            return None
        dur = int(elem[4]) if len(elem) > 4 else 0
        return TraceContext(
            int(elem[1]), int(elem[2]), int(elem[3]), dur, version=ver
        )
    except (TypeError, ValueError):
        return None


def stamp_wire_meta(
    meta: list,
    ident,
    step: int,
    deltas: Optional[dict] = None,
    origin_dur_us: int = 0,
) -> None:
    """Sender-side: append the length-versioned wire tail in one place.

    The rule (telemetry/wire.py + this module, receiver mirror in
    ``SimulatorMaster._on_block_frames``): the piggybacked ``deltas``
    element rides when present; on 1-in-N sampled steps the trace
    context is appended AFTER it with the deltas slot PINNED (possibly
    ``{}``) so receiver positions never shift under either feature
    alone. ONE implementation for every sender — the python simulators
    and the C++ env-server wrapper must not re-derive the layout."""
    if enabled() and sampled(step):
        meta.append(deltas if deltas is not None else {})
        meta.append(encode_context(
            make_id(ident, step),
            make_id(ident, step, "origin"),
            origin_dur_us=origin_dur_us,
        ))
    elif deltas is not None:
        meta.append(deltas)


# -- receive-side helpers --------------------------------------------------

def receive_context(
    ctx: Optional[TraceContext],
    peer: str,
    role: str,
    origin_name: str = "env_step",
    wire_name: str = "wire",
    origin_always: bool = False,
) -> Optional[Tuple[int, int]]:
    """Fold one received context into the local tracer: handshake the
    clock offset, then synthesize the sender-side origin span (duration
    shipped in the context) and the wire-transit span on the LOCAL
    timeline. Returns ``(trace_id, parent_span_id)`` for the receiver's
    own hops, or None when ``ctx`` is None.

    This is what lets env servers (and pod hosts) participate in traces
    without exposing a scrape endpoint: their two numbers ride the
    header, the receiver owns the spans. The SENDER owns the sampling
    decision (a receiver without ``--trace_sample`` still serves
    remotely-sampled traces), but ``BA3C_TELEMETRY=0`` kills the
    receive side too — no handshake, no spans, None out."""
    if ctx is None or not _metrics.enabled():
        return None
    t = tracer()
    recv_us = now_us()
    t.observe_remote_clock(peer, ctx.send_us, recv_us)
    send_local = t.align(peer, ctx.send_us)
    parent = ctx.span_id
    if ctx.origin_dur_us or origin_always:
        # origin_always: the experience wires synthesize the env_step
        # span even at 0 µs (a sub-µs fake env must not break chain
        # completeness); context kinds with no origin hop (pod params /
        # experience ship) leave it off and skip on zero
        parent = t.finish_span(
            ctx.trace_id, origin_name, role,
            send_local - ctx.origin_dur_us, send_local,
            parent_id=ctx.span_id,
        )
    parent = t.finish_span(
        ctx.trace_id, wire_name, role,
        min(send_local, recv_us), recv_us, parent_id=parent,
    )
    return ctx.trace_id, parent


class TraceRef:
    """A live trace's (trace_id, parent_span_id, t_mark_us) handoff —
    what rides BlockStep / segment dicts / feed batches between hops.
    ``t_mark_us`` is the previous hop's end, so the next hop's span can
    start where the last one finished (gap-free causal chain)."""

    __slots__ = ("trace_id", "parent_id", "t_mark_us")

    def __init__(self, trace_id: int, parent_id: int,
                 t_mark_us: Optional[int] = None):
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.t_mark_us = t_mark_us if t_mark_us is not None else now_us()

    def hop(self, name: str, role: str,
            t_end_us: Optional[int] = None,
            tags: Optional[dict] = None) -> "TraceRef":
        """Record the span from the last mark to now (or ``t_end_us``)
        and advance the chain: returns a new ref parented on the span
        just recorded."""
        end = t_end_us if t_end_us is not None else now_us()
        sid = tracer().finish_span(
            self.trace_id, name, role, self.t_mark_us, end,
            parent_id=self.parent_id, tags=tags,
        )
        return TraceRef(self.trace_id, sid, end)
