"""Fleet aggregation: metric deltas piggybacked on the existing actor wire.

Simulator servers live in their own processes; giving each a scrape port
(or a new socket pair back to the master) would multiply the plane's file
descriptors and syscalls for data that already has a perfectly good pipe.
Instead the servers piggyback a compact ``{name: delta}`` dict on the wire
messages they already send, every :data:`PIGGYBACK_EVERY` steps:

- block wires: appended as ONE extra element on the ``pack_block`` header
  meta (``[ident, step, B, (tele)]`` / the 8-element block-shm meta + tele).
  The header is version-bumped BY LENGTH — a master reads the tele element
  only when the meta is longer than the base layout, so old headers (and
  telemetry-disabled senders, which keep the old layout) still parse.
- per-env wire: appended as an optional 5th element on the msgpack message
  (``[ident, state, reward, isOver, tele]``), same length-based versioning.

DELTAS, not cumulative values: the master just adds them into the ``fleet``
registry, so a server restart (fresh counters) loses at most one piggyback
window instead of double-counting or going backwards.
"""

from __future__ import annotations

import math
import re
import time
from typing import Dict, Optional

from distributed_ba3c_tpu.telemetry import metrics

#: steps between piggybacks. At the block wire's ~100 block-steps/s/server
#: this is ~2 Hz of ~100-byte payloads — invisible next to the obs bytes.
PIGGYBACK_EVERY = 64


class DeltaTracker:
    """Sender side: counter deltas of one registry since the last call."""

    def __init__(self, reg: Optional[metrics.Registry] = None):
        self.reg = reg or metrics.registry("simulator")
        self._last: Dict[str, float] = {}

    def deltas(self) -> Dict[str, float]:
        """``{name: delta}`` for every counter that moved (possibly {})."""
        out: Dict[str, float] = {}
        for name, m in list(self.reg._metrics.items()):
            if not isinstance(m, metrics.Counter):
                continue
            v = m.value()
            d = v - self._last.get(name, 0.0)
            if d:
                # plain python floats/ints: the msgpack header codec must
                # not meet numpy scalars here
                out[name] = int(d) if float(d).is_integer() else float(d)
                self._last[name] = v
        return out


#: master side: last-seen monotonic per (fleet role, sender ident), so the
#: fleet client count reflects senders that piggybacked recently (not all
#: time) — and a multi-fleet learner's per-fleet gauges count only their
#: own senders. Keyed by (role, ident), NOT ident alone: two fleets'
#: senders may legitimately share an ident (external fleets launched with
#: launch_env_fleet's default cppsim-* prefixes collide across hosts —
#: the master knows the fleet by which pipe the message arrived on), and
#: an ident-keyed table would flap the stored role between fleets,
#: corroding BOTH reporting_clients gauges toward 0 with every server
#: healthy. ONE table across fleets: the 4096 cap is a process budget.
_FLEET_SEEN: Dict[tuple, float] = {}
_FLEET_WINDOW_S = 120.0

#: hard cap on distinct fleet series (the shipped instrumentation uses a
#: handful; 256 leaves room for growth while bounding what a stray sender
#: on the bound port can mint)
_FLEET_MAX_SERIES = 256

#: the Prometheus metric-name grammar (ASCII), minus the colon namespace
_NAME_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")

#: hard cap on tracked sender idents (a 64-node fleet is ~couple hundred
#: server slots; 4096 bounds what ident churn or a stray sender can cost)
_FLEET_MAX_SENDERS = 4096


def _fleet_clients(role: str = "fleet") -> int:
    now = time.monotonic()
    # read-time pruning of long-dead senders bounds the table under ident
    # churn (a restarting fleet cycles idents); entries get a long grace
    # past the liveness window so a stalled-then-recovered sender is not
    # forgotten between scrapes
    dead = [
        k for k, t in list(_FLEET_SEEN.items())
        if now - t > 10 * _FLEET_WINDOW_S
    ]
    for k in dead:
        _FLEET_SEEN.pop(k, None)
    return sum(
        1
        for (r, _), t in list(_FLEET_SEEN.items())
        if r == role and now - t < _FLEET_WINDOW_S
    )


def apply_fleet_deltas(ident: bytes, deltas, role: str = "fleet") -> None:
    """Fold one sender's piggybacked deltas into the ``role`` registry
    (``fleet`` for a single-fleet master, ``fleet.f<k>`` per fleet when a
    learner hosts several — telemetry.fleet_role is the name formula).

    Wire input is untrusted (same posture as the block decoder): anything
    that is not a {str: number} mapping is dropped without touching the
    receive loop.
    """
    if not isinstance(deltas, dict):
        return
    reg = metrics.registry(role)
    reg.gauge(
        "reporting_clients", fn=lambda r=role: _fleet_clients(r)
    )
    key = (role, bytes(ident))
    if key in _FLEET_SEEN or len(_FLEET_SEEN) < _FLEET_MAX_SENDERS:
        # bounded like the series table: a stray sender minting fresh
        # idents must not grow the table (and the gauge's O(n) read)
        # without limit — known idents always refresh
        _FLEET_SEEN[key] = time.monotonic()
    for name, d in deltas.items():
        if not isinstance(name, str) or not isinstance(d, (int, float)):
            continue
        if isinstance(d, bool) or not math.isfinite(d) or not 0 < d <= 1e15:
            # counters only move UP by finite amounts: one NaN folded into
            # a cell poisons the series for the rest of the run, and
            # negative deltas break the monotonic contract rate() needs
            continue
        if len(name) > 64 or not _NAME_RE.fullmatch(name):
            # junk names must not mint junk series: ASCII-only — str.isalnum
            # passes Unicode letters, and ONE non-grammar metric name in the
            # registry would poison every subsequent /metrics scrape
            continue
        if name not in reg._metrics and len(reg._metrics) >= _FLEET_MAX_SERIES:
            # cardinality cap, PER fleet registry: a stray sender on a
            # bound port must not be able to grow its fleet's registry
            # (and the /metrics payload) without bound by minting fresh
            # names. The cap stays per-registry rather than global because
            # fleet ROLES are trusted — only a master's configured
            # tele_role mints one — so the process total is bounded by
            # K x 256 with K operator-chosen, while a global budget would
            # let one fleet's junk senders crowd a later fleet's
            # legitimate series out entirely
            continue
        reg.counter(name).inc(d)
