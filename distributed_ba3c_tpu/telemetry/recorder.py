"""Flight recorder: a fixed-size ring of structured events + postmortem dump.

The PR-4 actor plane has real production failure modes — client drops on
refused rings, block-granular prunes, incarnation resets after SIGKILL,
backpressure stalls — that used to be visible only in DEBUG logs that a
multi-hour wedge truncates. The recorder keeps the last ``capacity``
structured events in memory (a ``deque(maxlen=...)`` append is GIL-atomic —
no locks on the record path) and writes them all out as one JSON file when
something dies: SanitizerError/AuditError (utils/sanitizer.py, audit.py),
a watchdog kill (parallel/watchdog.py), SIGTERM (:func:`install_signal_dump`),
or any explicit :func:`dump` call at a failure site (prunes, drops,
incarnation resets dump inline — they ARE the evidence the next wedged run
needs).

Event kinds in the shipped instrumentation (docs/observability.md has the
full catalog): ``block_recv``, ``queue_wait``, ``prune``, ``client_drop``,
``block_reject``, ``ring_refusal``, ``incarnation_reset``, ``retrace``,
``sanitizer``, ``checkpoint``, ``watchdog``, ``sigterm``.

Timestamps are ``time.monotonic()`` (the wall clock jumps — ba3clint A4);
each dump carries one (monotonic, wall) anchor pair so offline tooling can
map event times to wall time.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time
from typing import Optional

from distributed_ba3c_tpu.telemetry import metrics as _metrics
from distributed_ba3c_tpu.telemetry import tracing as _tracing

DEFAULT_CAPACITY = 4096

_dump_dir: Optional[str] = os.environ.get("BA3C_FLIGHT_DIR") or None


def configure(dump_dir: Optional[str]) -> None:
    """Set where postmortem dumps land (cli.py points this at --logdir;
    the ``BA3C_FLIGHT_DIR`` env var seeds it for child processes)."""
    global _dump_dir
    _dump_dir = dump_dir


class FlightRecorder:
    """The ring. One per process is plenty (events carry their component)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._dumps = 0
        # serializes DUMPS only (two failure paths racing a file write);
        # record() never takes it
        self._dump_lock = threading.Lock()

    def record(self, kind: str, **fields) -> None:
        """Append one event — a single deque append, safe from any thread.

        When a sampled trace is in scope on this thread (tracing.py
        ``trace_scope``), the event is stamped with its trace id so a
        postmortem dump correlates with the ``/trace`` spans — one
        thread-local read on the record path, nothing more."""
        if not _metrics.enabled():
            return
        tr = _tracing.current_trace_id()
        if tr is not None and "trace_id" not in fields:
            fields["trace_id"] = tr
        self._ring.append((time.monotonic(), kind, fields))

    def snapshot(self) -> list:
        """The ring's current events, oldest first, as JSON-ready dicts."""
        return [
            {"t_monotonic": t, "kind": kind, **fields}
            for t, kind, fields in list(self._ring)
        ]

    def events_since(self, t: float, kind: Optional[str] = None) -> list:
        """Raw ``(t_monotonic, kind, fields)`` tuples newer than ``t``,
        oldest first — the cheap polling read (no dict building) the fleet
        supervisor uses to watch the master's prune stream without keeping
        its own duplicate heartbeats (orchestrate/supervisor.py). ``kind``
        filters to one event kind."""
        return [
            ev
            for ev in list(self._ring)
            if ev[0] > t and (kind is None or ev[1] == kind)
        ]

    def dump(
        self, reason: str, path: Optional[str] = None, quiet: bool = False,
    ) -> Optional[str]:
        """Write the whole ring as one JSON file; returns the path.

        Never raises — a failing postmortem writer must not mask the
        failure being postmortemed. Repeated dumps overwrite the same file
        (the ring always contains the most recent history; ``dumps`` counts
        how many times evidence was written).

        ``quiet=True`` is the signal-handler mode (install_signal_dump):
        no logger call — the logging module's handler locks are not
        reentrant, and a SIGTERM delivered while the main thread holds one
        must lose the log line, not deadlock the process.
        """
        try:
            # timeout, not a bare acquire: a signal handler interrupting a
            # frame that already holds this lock would otherwise deadlock
            # the main thread forever (the holder can never resume)
            if not self._dump_lock.acquire(timeout=2.0):
                return None
            try:
                self._dumps += 1
                if path is None:
                    d = _dump_dir or tempfile.gettempdir()
                    os.makedirs(d, exist_ok=True)
                    path = os.path.join(
                        d, f"flight-{os.getpid()}.json"
                    )
                doc = {
                    "reason": reason,
                    "pid": os.getpid(),
                    "dumps": self._dumps,
                    # anchor pair: map monotonic event times to wall time
                    "anchor_monotonic": time.monotonic(),
                    "anchor_wall": time.time(),
                    "events": self.snapshot(),
                }
                tmp = f"{path}.tmp-{os.getpid()}"
                with open(tmp, "w") as fh:
                    json.dump(doc, fh)
                os.replace(tmp, path)
            finally:
                self._dump_lock.release()
            if not quiet:
                from distributed_ba3c_tpu.utils import logger

                logger.warn(
                    "flight recorder dumped %d events to %s (reason: %s)",
                    len(doc["events"]), path, reason,
                )
            return path
        except Exception:
            return None


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def flight_recorder() -> FlightRecorder:
    """The process's recorder (get-or-create)."""
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def record(kind: str, **fields) -> None:
    """Module-level convenience: record on the process recorder."""
    flight_recorder().record(kind, **fields)


def dump(reason: str, path: Optional[str] = None) -> Optional[str]:
    """Module-level convenience: dump the process recorder (never raises)."""
    return flight_recorder().dump(reason, path)


def install_signal_dump() -> None:
    """Chain a SIGTERM handler that dumps the ring before the old handler
    (or default exit) runs — a launcher's stall-kill leaves evidence.
    Main-thread only (signal module restriction); no-op elsewhere."""
    import signal

    if threading.current_thread() is not threading.main_thread():
        return
    # materialize the singleton BEFORE the handler can run: a SIGTERM
    # landing inside flight_recorder()'s creation lock would deadlock the
    # handler's own flight_recorder() call on this same thread
    flight_recorder()
    prev = signal.getsignal(signal.SIGTERM)

    def _on_term(signum, frame):
        record("sigterm")
        flight_recorder().dump("SIGTERM", quiet=True)
        if prev is signal.SIG_IGN:
            # the run was launched with SIGTERM ignored (SIG_IGN survives
            # exec): keep ignoring after the dump — chaining to "default"
            # here would INVERT the disposition and kill the process
            return
        if callable(prev):
            prev(signum, frame)
        else:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    signal.signal(signal.SIGTERM, _on_term)
