"""Fleet telemetry plane (docs/observability.md).

Four layers over the PR-4 actor plane:

1. **Metrics core** (telemetry/metrics.py) — GIL-atomic per-thread-sharded
   Counter/Gauge/log2 Histogram, one :class:`Registry` per role (master,
   predictor, learner, simulator, fleet). No locks on increment;
   aggregation at read time — cheap enough for the 52.8k env-steps/s/host
   hot path (<=2% overhead, gated by ``scripts/plane_bench.py``).
2. **Flight recorder** (telemetry/recorder.py) — fixed-size ring of
   structured events, dumped as postmortem JSON on SanitizerError /
   AuditError / watchdog kill / SIGTERM / plane failure events.
3. **Fleet aggregation** (telemetry/wire.py) — simulator servers piggyback
   counter deltas on the existing wire headers (length-versioned; old
   headers still parse); the master folds them into the ``fleet`` registry.
4. **Exporters** (telemetry/exporters.py) — ``--telemetry_port`` scrape
   endpoint (Prometheus text + /json + /flight + /trace) and the
   stat.json/TB bridge StatPrinter uses.
5. **Trace plane** (telemetry/tracing.py) — sampled causal block-lifetime
   spans with per-hop latency attribution; context rides the same wire
   headers as the fleet deltas, exported via ``/trace`` and
   ``scripts/trace_dump.py`` (Perfetto).

The usual import is the package itself::

    from distributed_ba3c_tpu import telemetry
    steps = telemetry.registry("master").counter("env_steps_total")
    steps.inc(B)
    telemetry.record("prune", ident=str(ident))
"""

from __future__ import annotations

from distributed_ba3c_tpu.telemetry.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    all_registries,
    all_snapshots,
    enabled,
    fleet_role,
    registry,
    reset_all,
    set_enabled,
)
from distributed_ba3c_tpu.telemetry.recorder import (  # noqa: F401
    FlightRecorder,
    configure,
    dump,
    flight_recorder,
    install_signal_dump,
    record,
)
from distributed_ba3c_tpu.telemetry.exporters import (  # noqa: F401
    TelemetryServer,
    export_scalars,
    prometheus_text,
)
from distributed_ba3c_tpu.telemetry.wire import (  # noqa: F401
    PIGGYBACK_EVERY,
    DeltaTracker,
    apply_fleet_deltas,
)
from distributed_ba3c_tpu.telemetry import tracing  # noqa: F401
