"""V-trace off-policy correction (IMPALA, Espeholt et al. 2018).

The reference has no off-policy correction — its async parameter-server updates
simply tolerate staleness (SURVEY.md §2.5 #15, §3.4). The TPU rebuild's learner
is synchronous, so actor/learner policy lag shows up explicitly; V-trace is the
principled correction for it (BASELINE.json config #4). Implemented as a
reverse ``lax.scan`` over time-major tensors, jit/vmap friendly.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class VTraceOut(NamedTuple):
    vs: jax.Array                 # [T, B] V-trace value targets
    pg_advantages: jax.Array      # [T, B] policy-gradient advantages
    clipped_rhos: jax.Array       # [T, B] clipped importance weights


def vtrace_returns(
    behaviour_log_probs: jax.Array,
    target_log_probs: jax.Array,
    rewards: jax.Array,
    dones: jax.Array,
    values: jax.Array,
    bootstrap_value: jax.Array,
    gamma: float,
    rho_clip: float = 1.0,
    c_clip: float = 1.0,
) -> VTraceOut:
    """Compute V-trace targets and advantages.

    Args:
      behaviour_log_probs: [T, B] log mu(a_t|s_t) of the actor policy.
      target_log_probs:    [T, B] log pi(a_t|s_t) of the learner policy.
      rewards:             [T, B].
      dones:               [T, B] episode-termination flags (after step t).
      values:              [T, B] learner V(s_t).
      bootstrap_value:     [B]    learner V(s_{T}).
      gamma:               discount.
      rho_clip, c_clip:    IW clip thresholds (rho_bar >= c_bar per the paper).

    Returns:
      VTraceOut with value targets vs_t and pg advantages
      rho_t * (r_t + gamma * vs_{t+1} - V(s_t)).
    """
    rhos = jnp.exp(target_log_probs - behaviour_log_probs)
    clipped_rhos = jnp.minimum(rho_clip, rhos)
    cs = jnp.minimum(c_clip, rhos)
    discounts = gamma * (1.0 - dones.astype(values.dtype))

    values_tp1 = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_tp1 - values)

    def step(acc, xs):
        delta_t, discount_t, c_t = xs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        step,
        jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs),
        reverse=True,
    )
    vs = vs_minus_v + values

    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_advantages = clipped_rhos * (rewards + discounts * vs_tp1 - values)
    return VTraceOut(vs=vs, pg_advantages=pg_advantages, clipped_rhos=clipped_rhos)
