"""n-step discounted return computation.

Reference equivalent: ``MySimulatorMaster._parse_memory`` in ``src/train.py``
(SURVEY.md §2.1 #3, §3.2) — a Python loop that walks a client's
``TransitionExperience`` memory backwards accumulating
``R = r_t + GAMMA * R`` seeded with the bootstrap value of the last state.

TPU-native design: the device-side version is a reverse ``lax.scan`` so it can
run inside a jitted/fused actor-learner loop over whole rollout batches with
static shapes; the numpy version is for the host-side actor plane
(SimulatorMaster), where rollouts are short (LOCAL_TIME_MAX ≈ 5) python lists.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def discounted_returns(rewards: jax.Array, bootstrap: jax.Array, discounts: jax.Array) -> jax.Array:
    """Reverse-scan discounted returns.

    R_t = r_t + discount_t * R_{t+1},  R_T = bootstrap.

    Args:
      rewards:   [T, ...] rewards (time-major).
      bootstrap: [...] value estimate of the state after the last transition.
      discounts: [T, ...] per-step discount (gamma * (1 - done)).

    Returns:
      [T, ...] discounted returns.
    """

    def step(carry, xs):
        r, d = xs
        ret = r + d * carry
        return ret, ret

    _, returns = jax.lax.scan(step, bootstrap, (rewards, discounts), reverse=True)
    return returns


def n_step_returns(
    rewards: jax.Array,
    dones: jax.Array,
    bootstrap_value: jax.Array,
    gamma: float,
) -> jax.Array:
    """n-step returns over a [T, B] rollout with episode-boundary resets.

    The discount is zeroed at terminal steps so credit does not leak across
    episode boundaries (matching the reference's per-episode memory flush in
    ``SimulatorMaster._on_episode_over``, SURVEY.md §3.2).
    """
    discounts = gamma * (1.0 - dones.astype(rewards.dtype))
    return discounted_returns(rewards, bootstrap_value, discounts)


def discounted_returns_np(
    rewards: np.ndarray, bootstrap: float, gamma: float
) -> np.ndarray:
    """Host-side scalar-loop version for short actor-side rollouts.

    Mirrors the reference's ``_parse_memory`` accumulation exactly: the rollout
    is either episode-terminated (bootstrap = 0) or truncated at LOCAL_TIME_MAX
    (bootstrap = V(s_T) from the most recent inference).
    """
    returns = np.empty(len(rewards), dtype=np.float32)
    acc = float(bootstrap)
    for t in range(len(rewards) - 1, -1, -1):
        acc = float(rewards[t]) + gamma * acc
        returns[t] = acc
    return returns
