"""The BA3C loss: policy gradient + value regression + entropy bonus.

Reference equivalent: ``Model._build_graph`` in ``src/train.py``
(SURVEY.md §2.1 #2):

    L = -log pi(a|s) * stop_grad(R - V)  +  c * L2(V, R)  -  beta * H(pi)

TPU-native design: a single pure function over batched logits/values so the
whole loss + grad fuses into one XLA computation; all reductions are batch
means (stable under per-device sharding: the DP train step psum-averages
gradients, see parallel/train_step.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class A3CLossOut(NamedTuple):
    total: jax.Array        # scalar loss to differentiate
    policy_loss: jax.Array  # scalar, for logging
    value_loss: jax.Array   # scalar
    entropy: jax.Array      # scalar mean policy entropy (positive)
    advantage: jax.Array    # scalar mean advantage
    pred_value: jax.Array   # scalar mean predicted value


def a3c_loss(
    logits: jax.Array,
    values: jax.Array,
    actions: jax.Array,
    returns: jax.Array,
    entropy_beta: float | jax.Array = 0.01,
    value_loss_coef: float | jax.Array = 0.5,
    huber_delta: float | None = None,
) -> A3CLossOut:
    """Compute the A3C objective over a flat batch.

    Args:
      logits:  [B, A] unnormalised policy logits.
      values:  [B] state-value predictions V(s).
      actions: [B] int32 actions taken by the behaviour policy.
      returns: [B] n-step discounted returns R.
      entropy_beta: entropy bonus coefficient (scheduled at runtime, so it may
        be a traced scalar — reference schedules it via HyperParamSetter).
      value_loss_coef: weight on the value L2 term.
      huber_delta: if set, the value loss is Huber(delta) instead of L2 — the
        reference's symbolic_functions.huber_loss variant (outlier-robust
        value regression for high-variance returns).

    All statistics are means over the batch, so the loss is invariant to how
    the batch is sharded across devices.
    """
    logits = logits.astype(jnp.float32)
    values = values.astype(jnp.float32)
    returns = returns.astype(jnp.float32)

    log_probs = jax.nn.log_softmax(logits, axis=-1)
    probs = jax.nn.softmax(logits, axis=-1)

    action_log_probs = jnp.take_along_axis(
        log_probs, actions.astype(jnp.int32)[:, None], axis=-1
    )[:, 0]

    advantage = returns - jax.lax.stop_gradient(values)
    policy_loss = -jnp.mean(action_log_probs * advantage)

    if huber_delta is not None:
        from distributed_ba3c_tpu.ops.symbolic import huber_loss

        value_loss = jnp.mean(huber_loss(values - returns, huber_delta))
    else:
        value_loss = 0.5 * jnp.mean(jnp.square(values - returns))

    entropy = -jnp.mean(jnp.sum(probs * log_probs, axis=-1))

    total = policy_loss + value_loss_coef * value_loss - entropy_beta * entropy
    return A3CLossOut(
        total=total,
        policy_loss=policy_loss,
        value_loss=value_loss,
        entropy=entropy,
        advantage=jnp.mean(advantage),
        pred_value=jnp.mean(values),
    )
