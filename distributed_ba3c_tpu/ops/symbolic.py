"""Symbolic helper functions.

Reference equivalent: ``tensorpack/tfutils/symbolic_functions.py`` (SURVEY.md
§2.6 #18) — the grab-bag of loss/metric helpers the model code pulls from
(huber loss, prediction error counts). Pure jnp functions here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def huber_loss(x: jax.Array, delta: float = 1.0) -> jax.Array:
    """Elementwise Huber: quadratic within |x|<=delta, linear outside."""
    abs_x = jnp.abs(x)
    quad = 0.5 * jnp.square(x)
    lin = delta * (abs_x - 0.5 * delta)
    return jnp.where(abs_x <= delta, quad, lin)


def prediction_incorrect(
    logits: jax.Array, labels: jax.Array, topk: int = 1
) -> jax.Array:
    """1.0 where the label is NOT in the top-k predictions (error vector)."""
    _, pred = jax.lax.top_k(logits, topk)
    hit = jnp.any(pred == labels[:, None], axis=-1)
    return (~hit).astype(jnp.float32)


def accuracy(logits: jax.Array, labels: jax.Array, topk: int = 1) -> jax.Array:
    return 1.0 - jnp.mean(prediction_incorrect(logits, labels, topk))
