"""Symbolic helper functions.

Reference equivalent: ``tensorpack/tfutils/symbolic_functions.py`` (SURVEY.md
§2.6 #18). Only the helper the RL pipeline actually consumes is kept:
``huber_loss`` backs the optional robust value regression in
:func:`distributed_ba3c_tpu.ops.loss.a3c_loss` (``huber_delta``). The
reference file's supervised-learning metrics (accuracy / top-k error) have
no call sites in an RL framework and were dropped rather than carried as
dead parity filler.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def huber_loss(x: jax.Array, delta: float = 1.0) -> jax.Array:
    """Elementwise Huber: quadratic within |x|<=delta, linear outside."""
    abs_x = jnp.abs(x)
    quad = 0.5 * jnp.square(x)
    lin = delta * (abs_x - 0.5 * delta)
    return jnp.where(abs_x <= delta, quad, lin)
