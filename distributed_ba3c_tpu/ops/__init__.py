"""Pure-function numeric ops: returns, losses, off-policy corrections, grad processing.

Everything here is side-effect free and jit/vmap/scan friendly — the TPU-native
replacement for the symbolic-graph snippets the reference scatters through
``src/train.py`` (loss construction in ``Model._build_graph``) and
``tensorpack/tfutils/{gradproc,symbolic_functions}.py`` (SURVEY.md §2.1 #2, §2.5 #16).
"""

from distributed_ba3c_tpu.ops.returns import (
    discounted_returns,
    discounted_returns_np,
    n_step_returns,
)
from distributed_ba3c_tpu.ops.loss import a3c_loss, A3CLossOut
from distributed_ba3c_tpu.ops.vtrace import vtrace_returns, VTraceOut
from distributed_ba3c_tpu.ops.gradproc import (
    global_norm_clip,
    grad_summaries,
    make_optimizer,
    map_gradient,
)

__all__ = [
    "global_norm_clip",
    "grad_summaries",
    "make_optimizer",
    "map_gradient",
    "discounted_returns",
    "discounted_returns_np",
    "n_step_returns",
    "a3c_loss",
    "A3CLossOut",
    "vtrace_returns",
    "VTraceOut",
]
