"""Pallas TPU kernels for the BA3C conv stack: fused conv+bias+relu+maxpool.

STATUS — measured SLOWER than XLA on the v5e; default OFF; kept as working,
tested, honestly-documented kernel infrastructure (the same policy as
models/packed_conv.py). The round-2 A/B on the real chip (chained in-jit
loops, B=4096 — full story in PERF.md):

    XLA conv1 block (conv+bias+relu+pool)      2.52 us/sample
    this kernel, VPU-assembled patches          4.17-4.75
    this kernel, DMA-engine-assembled patches   7.34

The hypothesis was sound — XLA's conv emitter fills only 32 of the MXU's
128 output lanes on this net (a ~11 us/sample fwd+bwd floor), while the
packed GEMM here fills all 128 (a ~1.1 us/sample conv1 floor) and fuses
bias/relu/pool so the pre-pool activation never touches HBM. What kills it
is im2col patch ASSEMBLY: reorganizing [W*Ci] lanes into overlapping
[G, P*Ci] patch rows is a lane<->sublane relayout that costs more on the
VPU (or the DMA engines) than the MXU occupancy saves at these small
shapes. Mosaic constraints hit along the way, for the record: lane-split
reshapes require 128-multiples (conv0's P*Ci=16 is unreachable), sublane
DMA slices require 8-aligned offsets, and sub-tile flattens relayout unless
the collapsed dim is 16-aligned (hence G=16 here).

Do NOT re-try without new evidence; the remaining ideas (input-channel
padding to 32, space-to-depth, Toeplitz row-GEMMs, stride-2 shifted convs)
are analyzed and rejected in PERF.md.

Reference equivalent: the conv layers of ``Model._build_graph`` in
``src/train.py`` (SURVEY.md §2.1 #2) — re-designed as TPU kernels, not
translated.

The GEMM formulation (lane packing, same algebra as models/packed_conv.py
but fused): a stride-1 SAME conv computing P adjacent output columns per
GEMM row fills P*Co of the MXU's 128 output lanes (P=4, Co=32 -> exactly
128 for the 32-channel layers). For output row y and column group j
(covering columns j*P .. j*P+P-1):

    patch[y, j]  = xpad[y:y+kh, j*P : j*P+2P, :]          (K = kh*2P*Ci)
    out[y, j, (p, co)] = patch[y, j] . Wp[:, (p, co)]

with Wp[ky, q, ci, (p, co)] = W[ky, q-p, ci, co] (zero outside 0<=q-p<kw),
which is exact for kw <= P+1 (all BA3C kernels: 5,5,4,3 with P=4).

Layout notes (Mosaic):
- All HBM-visible tensors are [B, H, W*C] with the (W, C) pair flattened
  into the lane dimension — W*C is 336..1344 lanes, well-tiled, and the
  flattened layout makes every im2col/pool step a *lane slice* instead of
  a gather.
- The 2x2 maxpool runs in the packed layout: with P even, column pairs
  (2t, 2t+1) live in adjacent Co-lane chunks of the same group, so x-pooling
  is a lane-chunk max and y-pooling a sublane-pair max; the pooled packed
  layout [Ho, G, (P/2)*Co] flattens back to [Ho, (Wc/2)*Co] with no
  permutation.
- Numerics match the flax path op-for-op: bf16 GEMM with f32 accumulation,
  round to bf16, add bf16 bias, relu, pool — the same order nn.Conv +
  nn.relu + nn.max_pool produce under XLA.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


@dataclasses.dataclass(frozen=True)
class ConvSpec:
    """Static geometry of one fused conv block."""

    H: int            # input height
    W: int            # input width
    Ci: int           # input channels
    Co: int           # output channels
    kh: int
    kw: int
    pool: bool        # 2x2/2 maxpool after relu
    scale_uint8: bool  # input is uint8; cast and multiply by 1/255
    P: int = 4        # lane-packing factor (even, >= kw-1)
    bt: int = 4       # batch tile per grid step

    # geometry validity is a query, not an invariant: callers gate on
    # supported(); the kernel entry point re-asserts it

    # ---- derived geometry ----
    @property
    def ph(self) -> int:  # top row pad (XLA SAME convention)
        return (self.kh - 1) // 2

    @property
    def pw(self) -> int:  # left col pad
        return (self.kw - 1) // 2

    @property
    def Wc(self) -> int:  # logical width padded up to a multiple of P
        return -(-self.W // self.P) * self.P

    @property
    def G(self) -> int:  # column groups
        return self.Wc // self.P

    @property
    def Hp(self) -> int:  # padded rows held in VMEM
        return self.H + self.kh - 1

    @property
    def Wp(self) -> int:  # padded cols held in VMEM (patch j spans [jP, jP+2P))
        return self.Wc + self.P

    @property
    def K(self) -> int:  # GEMM contraction size
        return self.kh * 2 * self.P * self.Ci

    @property
    def N(self) -> int:  # GEMM output lanes
        return self.P * self.Co

    @property
    def Ho(self) -> int:
        return self.H // 2 if self.pool else self.H

    @property
    def Wo(self) -> int:
        return self.W // 2 if self.pool else self.W

    @property
    def in_dtype(self):
        return jnp.uint8 if self.scale_uint8 else jnp.bfloat16


def ba3c_specs(
    frame_history: int = 4,
    conv_features: Tuple[int, ...] = (32, 32, 64, 64),
    conv_kernels: Tuple[int, ...] = (5, 5, 4, 3),
    batch_tiles: Tuple[int, ...] = (4, 4, 8, 16),
) -> Tuple[ConvSpec, ...]:
    """The four BA3C conv blocks (84x84xhist uint8 in, 10x10x64 out)."""
    specs = []
    h = w = 84
    ci = frame_history
    pooled = (True, True, True, False)
    for i, (co, k, pool, bt) in enumerate(
        zip(conv_features, conv_kernels, pooled, batch_tiles, strict=True)
    ):
        s = ConvSpec(
            H=h, W=w, Ci=ci, Co=co, kh=k, kw=k,
            pool=pool, scale_uint8=(i == 0), bt=bt,
        )
        specs.append(s)
        h, w, ci = s.Ho, s.Wo, co
    return tuple(specs)


# --------------------------------------------------------------------------
# weight packing (host-side jnp; cached by jit as a constant-folded prologue)
# --------------------------------------------------------------------------

def pack_weights(w: jax.Array, s: ConvSpec) -> jax.Array:
    """[kh, kw, Ci, Co] -> [K, P*Co] bf16 shifted-stack (see module doc)."""
    wp = jnp.zeros((s.kh, 2 * s.P, s.Ci, s.P, s.Co), w.dtype)
    for p in range(s.P):
        wp = wp.at[:, p : p + s.kw, :, p, :].set(w)
    return wp.reshape(s.K, s.N).astype(jnp.bfloat16)


def pack_bias(b: jax.Array, s: ConvSpec) -> jax.Array:
    """[Co] -> [1, P*Co] bf16, tiled per packed column."""
    return jnp.tile(b, (s.P,)).reshape(1, s.N).astype(jnp.bfloat16)


# --------------------------------------------------------------------------
# in-kernel building blocks (shared with the VJP kernels)
# --------------------------------------------------------------------------

def _load_padded(x, s: ConvSpec):
    """[bt, H, W*Ci] raw input -> [bt, Hp, Wp*Ci] bf16 zero-padded."""
    if s.scale_uint8:
        # Mosaic has no uint8->bf16 cast; hop through int32/f32 (VPU-cheap)
        x = x.astype(jnp.int32).astype(jnp.float32) * (1.0 / 255.0)
        x = x.astype(jnp.bfloat16)
    else:
        x = x.astype(jnp.bfloat16)
    lpad = s.pw * s.Ci
    rpad = s.Wp * s.Ci - s.W * s.Ci - lpad
    return jnp.pad(
        x, ((0, 0), (s.ph, s.kh - 1 - s.ph), (lpad, rpad))
    )


def _im2col_segs(xp, s: ConvSpec):
    """[bt, Hp, Wp*Ci] -> 2*kh segments [bt*H*G, PCi], K-ordered (ky, h).

    Never materializes the concatenated patch matrix: a 10-way lane concat
    is pure VPU relayout cost (measured 3x slower than XLA). Instead each
    (ky, h) segment feeds its own K=PCi matmul and the products accumulate
    in f32 — identical MXU slot count, zero shuffling. Requires PCi to be a
    multiple of 128 for the lane-split reshape (all 32/64-channel blocks).
    """
    bt = xp.shape[0]
    PCi = s.P * s.Ci
    segs = []
    for ky in range(s.kh):
        row = xp[:, ky : ky + s.H, :]                       # [bt, H, Wp*Ci]
        for h in (0, 1):
            seg = row[:, :, h * PCi : (s.G + h) * PCi]
            segs.append(seg.reshape(bt * s.H * s.G, PCi))
    return segs


def _matmul_segs(segs, w_ref, s: ConvSpec):
    """sum_t segs[t] @ w[t*PCi:(t+1)*PCi, :] with f32 accumulation."""
    PCi = s.P * s.Ci
    acc = None
    for t, seg in enumerate(segs):
        part = jnp.dot(
            seg,
            w_ref[t * PCi : (t + 1) * PCi, :],
            preferred_element_type=jnp.float32,
        )
        acc = part if acc is None else acc + part
    return acc                                              # [M, N] f32


def _pool_packed(acts, s: ConvSpec):
    """[bt, H, G, P*Co] relu'd acts -> pooled [bt, Ho, G, (P/2)*Co].

    x-pooling: adjacent column pairs live in adjacent Co-lane chunks of the
    same group (P even), so it's a lane-chunk max. y-pooling: split the row
    dim (a non-minor dim — Mosaic-legal reshape) and max the pair.
    """
    bt = acts.shape[0]
    cols = [
        jnp.maximum(
            acts[..., (2 * t) * s.Co : (2 * t + 1) * s.Co],
            acts[..., (2 * t + 1) * s.Co : (2 * t + 2) * s.Co],
        )
        for t in range(s.P // 2)
    ]
    ap = jnp.concatenate(cols, axis=-1)                     # [bt,H,G,(P/2)Co]
    ap = ap[:, : 2 * s.Ho].reshape(bt, s.Ho, 2, s.G, (s.P // 2) * s.Co)
    return jnp.maximum(ap[:, :, 0], ap[:, :, 1])            # [bt,Ho,G,(P/2)Co]


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, *, s: ConvSpec):
    bt = s.bt
    xp = _load_padded(x_ref[:], s)
    segs = _im2col_segs(xp, s)
    acts = _matmul_segs(segs, w_ref, s).astype(jnp.bfloat16)
    acts = jnp.maximum(acts + b_ref[:], jnp.bfloat16(0.0))
    acts = acts.reshape(bt, s.H, s.G, s.N)
    # output stays in the 4D packed layout [bt, Ho, G, lanes]; the wrapper
    # flattens/trims it with a free XLA reshape outside the kernel (lane
    # merges of sub-128 chunks are not Mosaic-legal in-kernel)
    if s.pool:
        y_ref[:] = _pool_packed(acts, s)
    else:
        y_ref[:] = acts


def _pad_batch(x: jax.Array, bt: int):
    B = x.shape[0]
    Bp = -(-B // bt) * bt
    if Bp != B:
        x = jnp.pad(x, ((0, Bp - B),) + ((0, 0),) * (x.ndim - 1))
    return x, B, Bp


def conv_block_fwd(
    x: jax.Array,
    w_packed: jax.Array,
    b_packed: jax.Array,
    s: ConvSpec,
    *,
    interpret: bool = False,
) -> jax.Array:
    """Fused conv+bias+relu(+pool). x: [B, H, W*Ci] (uint8 for block 0)."""
    assert supported(s), s
    x, B, Bp = _pad_batch(x, s.bt)
    # packed 4D output: pooled [Bp, Ho, G, (P/2)Co] or plain [Bp, H, G, P*Co]
    out_lanes = (s.P // 2 if s.pool else s.P) * s.Co
    y = pl.pallas_call(
        partial(_fwd_kernel, s=s),
        grid=(Bp // s.bt,),
        in_specs=[
            pl.BlockSpec(
                (s.bt, s.H, s.W * s.Ci), lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((s.K, s.N), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s.N), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (s.bt, s.Ho, s.G, out_lanes), lambda i: (i, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (Bp, s.Ho, s.G, out_lanes), jnp.bfloat16
        ),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * Bp * s.H * s.G * s.K * s.N,
            bytes_accessed=x.size * x.dtype.itemsize
            + Bp * s.Ho * s.G * out_lanes * 2,
            transcendentals=0,
        ),
    )(x, w_packed, b_packed)
    # flatten the packed (G, lanes) pair and trim width padding — free in XLA
    y = y.reshape(Bp, s.Ho, s.G * out_lanes)[:B, :, : s.Wo * s.Co]
    return y


# --------------------------------------------------------------------------
# XLA reference path (tests + CPU fallback); identical op order
# --------------------------------------------------------------------------

def supported(s: ConvSpec) -> bool:
    """Mosaic-compilable geometry: lane-split reshapes need 128-multiples."""
    return (s.P * s.Ci) % 128 == 0 and s.kw <= s.P + 1 and s.P % 2 == 0


def _primal(x, w, b, s: ConvSpec, interpret: bool):
    return conv_block_fwd(
        x, pack_weights(w, s), pack_bias(b, s), s, interpret=interpret
    )


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def conv_block(x, w, b, s: ConvSpec, interpret: bool = False):
    """Trainable fused block: Pallas forward, XLA-vjp backward.

    The backward recomputes the reference forward for its VJP — fine for
    the default-off status of this backend; a Pallas backward was designed
    (unpool-scatter + packed dW/dx GEMMs) but not built once the forward
    A/B came back negative (PERF.md).
    """
    return _primal(x, w, b, s, interpret)


def _cb_fwd(x, w, b, s, interpret):
    return _primal(x, w, b, s, interpret), (x, w, b)


def _cb_bwd(s, interpret, res, g):
    x, w, b = res
    _, vjp = jax.vjp(
        lambda xx, ww, bb: reference_block(xx, ww, bb, s), x, w, b
    )
    return vjp(g.astype(jnp.bfloat16))


conv_block.defvjp(_cb_fwd, _cb_bwd)


def reference_block(
    x: jax.Array, w: jax.Array, b: jax.Array, s: ConvSpec
) -> jax.Array:
    """x: [B, H, W*Ci] -> [B, Ho, Wo*Co], plain XLA ops, same op order."""
    B = x.shape[0]
    x = x.reshape(B, s.H, s.W, s.Ci)
    if s.scale_uint8:
        x = x.astype(jnp.bfloat16) * jnp.bfloat16(1.0 / 255.0)
    else:
        x = x.astype(jnp.bfloat16)
    y = jax.lax.conv_general_dilated(
        x,
        w.astype(jnp.bfloat16),
        window_strides=(1, 1),
        padding=[(s.ph, s.kh - 1 - s.ph), (s.pw, s.kw - 1 - s.pw)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    y = jnp.maximum(y + b.astype(jnp.bfloat16), jnp.bfloat16(0.0))
    if s.pool:
        # reshape-max instead of reduce_window: identical values, and it
        # reverse-differentiates cleanly inside the custom-vjp backward
        # (reduce_window's linearization fails there on the TPU backend)
        y = y[:, : 2 * s.Ho, : 2 * s.Wo, :].reshape(
            B, s.Ho, 2, s.Wo, 2, s.Co
        )
        y = jnp.max(jnp.max(y, axis=4), axis=2)
    return y.reshape(B, s.Ho, s.Wo * s.Co)
