"""Gradient processors: the optax-native equivalent of tensorpack's gradproc.

Reference equivalent: ``tensorpack/tfutils/gradproc.py`` — ``GlobalNormClip``,
``MapGradient``, ``SummaryGradient`` (SURVEY.md §2.5 #16). In the rebuild these
are optax ``GradientTransformation``s chained into the optimizer, plus a pure
function computing gradient statistics for the summary plane.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import optax


def global_norm_clip(max_norm: float) -> optax.GradientTransformation:
    """tensorpack ``GlobalNormClip`` equivalent (tf.clip_by_global_norm)."""
    return optax.clip_by_global_norm(max_norm)


def map_gradient(fn: Callable[[jax.Array], jax.Array]) -> optax.GradientTransformation:
    """tensorpack ``MapGradient`` equivalent: apply fn to every gradient leaf."""

    def init(_params):
        return optax.EmptyState()

    def update(grads, state, params=None):
        del params
        return jax.tree_util.tree_map(fn, grads), state

    return optax.GradientTransformation(init, update)


def grad_summaries(grads) -> Dict[str, jax.Array]:
    """tensorpack ``SummaryGradient`` equivalent: global/max statistics.

    Returned inside the jitted step so it fuses with the backward pass instead
    of being a separate host round-trip.
    """
    leaves = jax.tree_util.tree_leaves(grads)
    gnorm = optax.global_norm(grads)
    gmax = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in leaves]))
    return {"grad_norm": gnorm, "grad_max_abs": gmax}


def inject_learning_rate(opt_state, learning_rate):
    """Functionally set the LR of an opt-state built by :func:`make_optimizer`.

    The runtime-mutable-hyperparam mechanism behind ``ScheduledHyperParamSetter``
    (reference: ``callbacks/param.py``, SURVEY.md §2.7 #21): the trainer passes
    the scheduled LR into the jitted step each call; inside, the
    ``InjectHyperparamsState`` leaf is replaced before ``optimizer.update``.
    No-op (statically) if the optimizer was not built with inject_hyperparams.
    """
    if learning_rate is None:
        return opt_state

    changed = False

    def maybe(s):
        # Duck-typed: installed optax returns InjectStatefulHyperparamsState,
        # which is NOT a subclass of InjectHyperparamsState — match any state
        # carrying a hyperparams dict instead of an exact class.
        nonlocal changed
        hp = getattr(s, "hyperparams", None)
        if hp is not None and hasattr(s, "_replace") and "learning_rate" in hp:
            hp = dict(hp)
            hp["learning_rate"] = jnp.asarray(learning_rate, jnp.float32)
            changed = True
            return s._replace(hyperparams=hp)
        return s

    if isinstance(opt_state, tuple) and not hasattr(opt_state, "_fields"):
        new = tuple(maybe(s) for s in opt_state)
    else:
        new = maybe(opt_state)
    # return the ORIGINAL object when nothing matched so callers can detect
    # (and warn about) an optimizer without an injectable LR leaf
    return new if changed else opt_state


def make_optimizer(
    learning_rate,
    adam_epsilon: float = 1e-3,
    grad_clip_norm: float = 0.5,
) -> optax.GradientTransformation:
    """Adam + global-norm clip, LR injectable at runtime.

    Reference: ``Model._get_optimizer`` (AdamOptimizer with tweaked epsilon,
    SURVEY.md §2.9) wrapped by ``GlobalNormClip``. ``learning_rate`` may be a
    float, an optax schedule, or supplied per-step via ``optax.inject_hyperparams``
    by the caller (the ScheduledHyperParamSetter callback mutates it live).
    """
    return optax.chain(
        global_norm_clip(grad_clip_norm),
        optax.inject_hyperparams(optax.adam)(
            learning_rate=learning_rate, eps=adam_epsilon
        ),
    )
