"""Gradient processors: the optax-native equivalent of tensorpack's gradproc.

Reference equivalent: ``tensorpack/tfutils/gradproc.py`` — ``GlobalNormClip``,
``MapGradient``, ``SummaryGradient`` (SURVEY.md §2.5 #16). In the rebuild these
are optax ``GradientTransformation``s chained into the optimizer, plus a pure
function computing gradient statistics for the summary plane.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import optax


def global_norm_clip(max_norm: float) -> optax.GradientTransformation:
    """tensorpack ``GlobalNormClip`` equivalent (tf.clip_by_global_norm)."""
    return optax.clip_by_global_norm(max_norm)


def map_gradient(fn: Callable[[jax.Array], jax.Array]) -> optax.GradientTransformation:
    """tensorpack ``MapGradient`` equivalent: apply fn to every gradient leaf."""

    def init(_params):
        return optax.EmptyState()

    def update(grads, state, params=None):
        del params
        return jax.tree_util.tree_map(fn, grads), state

    return optax.GradientTransformation(init, update)


def grad_summaries(grads) -> Dict[str, jax.Array]:
    """tensorpack ``SummaryGradient`` equivalent: global/max statistics.

    Returned inside the jitted step so it fuses with the backward pass instead
    of being a separate host round-trip.
    """
    leaves = jax.tree_util.tree_leaves(grads)
    gnorm = optax.global_norm(grads)
    gmax = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in leaves]))
    return {"grad_norm": gnorm, "grad_max_abs": gmax}


def make_optimizer(
    learning_rate,
    adam_epsilon: float = 1e-3,
    grad_clip_norm: float = 0.5,
) -> optax.GradientTransformation:
    """Adam + global-norm clip, LR injectable at runtime.

    Reference: ``Model._get_optimizer`` (AdamOptimizer with tweaked epsilon,
    SURVEY.md §2.9) wrapped by ``GlobalNormClip``. ``learning_rate`` may be a
    float, an optax schedule, or supplied per-step via ``optax.inject_hyperparams``
    by the caller (the ScheduledHyperParamSetter callback mutates it live).
    """
    return optax.chain(
        global_norm_clip(grad_clip_norm),
        optax.inject_hyperparams(optax.adam)(
            learning_rate=learning_rate, eps=adam_epsilon
        ),
    )
