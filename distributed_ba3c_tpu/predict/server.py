"""BatchedPredictor: micro-batching action server on one jitted device call.

Reference equivalent (SURVEY.md §3.3): ``MultiThreadAsyncPredictor`` /
``PredictorWorkerThread`` — N threads each draining a shared queue into a
``sess.run`` on a predict tower. TPU-native redesign per BASELINE.json:

- ONE compiled function: forward + categorical sample, executed on device;
  action sampling never returns logits to the host (A ints instead of A
  floats per sim cross the device boundary).
- Batch shapes are bucketed to powers of two and padded, so XLA compiles a
  handful of programs once instead of one per queue length.
- Weights live in device HBM; the learner publishes fresh params with
  ``update_params`` (an atomic Python ref swap — the reference's predict
  towers read shared TF variables the same way).

The worker thread dispatches callbacks; with the GIL this matches the
reference's callback-from-worker-thread semantics.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.audit import tripwire_jit
from distributed_ba3c_tpu.utils.concurrency import (
    StoppableThread,
    queue_put_stoppable,
)


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


class _BlockTask:
    """One whole [B, ...] state block awaiting ONE batched callback.

    The block wire's unit of work: B states that arrived as one message and
    leave as one ``int32[B]`` action reply — no per-row splitting, no
    per-row Python bookkeeping anywhere between the socket and the device.
    """

    __slots__ = ("states", "callback", "k")

    def __init__(self, states, callback):
        self.states = states
        self.callback = callback
        self.k = states.shape[0]


def make_fwd_sample(model, greedy: bool = False) -> Callable:
    """The action server's compiled program: forward + on-device sampling.

    Module-level (not a closure in ``__init__``) so the audit registry
    (distributed_ba3c_tpu/audit.py, entry ``predict.server``) traces the
    same function the live predictor jits.
    """

    def fwd_sample(params, states, key):
        out = model.apply({"params": params}, states)
        if greedy:
            actions = jnp.argmax(out.logits, axis=-1)
        else:
            actions = jax.random.categorical(key, out.logits, axis=-1)
        actions = actions.astype(jnp.int32)
        # log mu(a|s): the behavior policy record V-trace needs
        log_probs = jax.nn.log_softmax(out.logits, axis=-1)
        logp = jnp.take_along_axis(log_probs, actions[:, None], axis=-1)[:, 0]
        # PACK everything into ONE array: the host fetches a single
        # buffer per serve. Measured on the tunneled-TPU dev setup:
        # device readback costs ~135 ms PER ARRAY regardless of size
        # (latency, not bandwidth), so four separate fetches were 540 ms
        # per serving call — 400x the 1.3 ms compute (see PERF.md).
        greedy_actions = jnp.argmax(out.logits, axis=-1)
        packed = jnp.stack(
            [
                actions.astype(jnp.float32),
                out.value,
                logp,
                greedy_actions.astype(jnp.float32),
            ]
        )
        return packed  # [4, B] float32

    return fwd_sample


class BatchedPredictor:
    """Asynchronous batched (action, value) server.

    Parameters
    ----------
    model: a flax module with ``apply({'params': p}, states) -> PolicyValue``.
    params: initial parameter pytree (host or device).
    batch_size: max micro-batch (reference PREDICT_BATCH_SIZE).
    num_threads: worker threads draining the task queue (device calls
        serialize on the device anyway; >1 only helps overlap host work).
    """

    def __init__(
        self,
        model,
        params,
        batch_size: int = 16,
        num_threads: int = 1,
        seed: int = 0,
        greedy: bool = False,
        coalesce_ms: float = 2.0,
    ):
        self._model = model
        self._params = jax.device_put(params)
        self._batch_size = batch_size
        self._coalesce_s = coalesce_ms / 1000.0
        self._queue: "queue.Queue[Tuple[np.ndarray, Callable]]" = queue.Queue(
            maxsize=4096
        )
        self._key = jax.random.PRNGKey(seed)
        self._key_lock = threading.Lock()
        self._greedy = greedy
        self._stop_evt = threading.Event()

        # telemetry (docs/observability.md): serving-side counters live in
        # the predictor role registry; the bucket-occupancy histogram is
        # what separates "tiny fragmented batches" from "full buckets"
        # when the plane slows down. Unit=1: occupancies are row counts.
        tele = telemetry.registry("predictor")
        self._c_batches = tele.counter("batches_total")
        self._c_rows = tele.counter("rows_total")
        self._c_oversize = tele.counter("blocks_oversize_total")
        self._c_publishes = tele.counter("param_publishes_total")
        self._c_chunked = tele.counter("chunked_calls_total")
        self._c_chunks = tele.counter("chunks_total")
        self._h_occupancy = tele.histogram("batch_rows", unit=1)
        import weakref

        ref = weakref.ref(self)
        tele.gauge(
            "task_queue_depth",
            fn=lambda: p._queue.qsize() if (p := ref()) else 0,
        )

        # registered audit entry point (distributed_ba3c_tpu/audit.py).
        # auto_arm=False: the pow-2 bucket warmup is a LEGITIMATE multi-shape
        # compile sequence; warmup() arms the tripwire when it completes, so
        # only a new bucket size appearing mid-serving raises.
        self._fwd = tripwire_jit(
            "predict.server", make_fwd_sample(model, greedy), auto_arm=False
        )
        self.threads: List[StoppableThread] = [
            StoppableThread(
                target=self._worker, daemon=True, name=f"predictor-{i}"
            )
            for i in range(num_threads)
        ]

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        for t in self.threads:
            t.start()

    def warmup(self, state_shape, dtype=np.uint8) -> None:
        """Precompile every pow-2 bucket up to batch_size.

        Each new bucket size triggers a fresh XLA compile (tens of seconds
        on TPU) the first time it is served; hitting that mid-training
        stalls the whole actor plane. Call once before actors start."""
        b = 1
        while b <= _next_pow2(self._batch_size):
            self._run_device(np.zeros((b, *state_shape), dtype))
            b *= 2
        # BA3C_AUDIT=1: buckets compiled — any retrace from here on is a
        # mid-serving stall and raises AuditError
        getattr(self._fwd, "arm", lambda: None)()

    def stop(self) -> None:
        self._stop_evt.set()
        for t in self.threads:
            t.stop()

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for worker threads to exit (they poll with 0.5s timeout)."""
        for t in self.threads:
            if t.is_alive():
                t.join(timeout)

    # -- API ---------------------------------------------------------------
    def update_params(self, params) -> None:
        """Publish fresh weights (atomic ref swap; next batch uses them)."""
        self._params = params
        self._c_publishes.inc()

    def put_task(
        self, state: np.ndarray, callback: Callable[[int, float, float], None]
    ) -> None:
        """Queue one state; ``callback(action, value, logp)`` fires when
        served — logp is log mu(action|state) under the sampling policy.
        Tasks arriving after ``stop()`` (or while stopping with a full
        queue) are dropped — their simulators are being torn down too."""
        queue_put_stoppable(self._queue, (state, callback), self._stop_evt)

    def put_block_task(
        self,
        states: np.ndarray,
        callback: Callable[[np.ndarray, np.ndarray, np.ndarray], None],
    ) -> None:
        """Queue one [B, ...] state block (the block wire's whole batch);
        ``callback(actions[B], values[B], logps[B])`` fires ONCE when the
        block is served. The block lands in a warmed pow-2 bucket as a
        unit — no per-row splitting; when ``coalesce_ms`` allows, several
        queued blocks share one device call (weighted coalescing in
        :meth:`_fetch_batch`). Same drop-on-stop semantics as
        :meth:`put_task`."""
        cap = _next_pow2(max(self._batch_size, 1))
        if states.shape[0] > cap:
            self._c_oversize.inc()
            raise ValueError(
                f"block of {states.shape[0]} states exceeds the serving "
                f"bucket ({cap}) — raise predict_batch_size to at least "
                "the env-server block size"
            )
        queue_put_stoppable(
            self._queue, _BlockTask(states, callback), self._stop_evt
        )

    def predict_batch(
        self, states: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Synchronous batched predict: (actions, values, greedy_actions).

        ``actions`` follow the serving policy (sampled, or argmax when
        ``greedy=True``); ``greedy_actions`` are always the argmax — the
        Evaluator consumes those without a second device call."""
        actions, values, _, greedy_actions = self._run_rows(
            np.asarray(states)
        )
        return actions, values, greedy_actions

    # -- internals ---------------------------------------------------------
    def _next_key(self):
        with self._key_lock:
            self._key, sub = jax.random.split(self._key)
        return sub

    def _dispatch(self, params, batch: np.ndarray):
        """Pad to the pow-2 bucket and dispatch (async); no host fetch.

        ``params`` is passed explicitly so a multi-chunk caller serves ONE
        parameter version even if the learner publishes mid-batch."""
        # device ingest is where a lazy block-states view (block-shm wire)
        # pays its one materialization — jit can't take a BlockStatesView
        batch = np.asarray(batch)
        k = batch.shape[0]
        padded = _next_pow2(max(k, 1))
        if padded != k:
            pad = np.zeros((padded - k, *batch.shape[1:]), batch.dtype)
            batch = np.concatenate([batch, pad], axis=0)
        return k, self._fwd(params, batch, self._next_key())

    @staticmethod
    def _unpack(packed: np.ndarray, k: int):
        return (
            packed[0, :k].astype(np.int32),
            packed[1, :k],
            packed[2, :k],
            packed[3, :k].astype(np.int32),
        )

    def _run_device(self, batch: np.ndarray):
        k, packed = self._dispatch(self._params, batch)
        # ONE device->host fetch (see fwd_sample)
        return self._unpack(np.asarray(packed), k)

    def _run_rows(self, states: np.ndarray):
        """Serve N rows: (actions, values, logps, greedy_actions).

        Inputs larger than the serving bucket (an Evaluator with more envs
        than ``batch_size``, or a coalesced run of block tasks) are chunked
        to it, so no bucket beyond warmup's is ever compiled — bounded
        device memory, and no post-warmup retrace for the BA3C_AUDIT=1
        tripwire to refuse. The chunked path dispatches EVERY chunk before
        fetching any: jax dispatch is async, so the chunks' compute
        overlaps while fetches (the ~135 ms/array latency documented above)
        drain in order — fetching inside the dispatch loop would serialize
        compute behind readback. Params are snapshotted once per call: a
        learner publish mid-call must not split one logical batch across
        two policies."""
        cap = _next_pow2(max(self._batch_size, 1))
        if states.shape[0] <= cap:
            return self._run_device(states)
        params = self._params
        pending = [
            self._dispatch(params, states[i:i + cap])
            for i in range(0, states.shape[0], cap)
        ]
        # chunking is worth SEEING on the scrape endpoint: a persistently
        # chunked caller (Evaluator sized past the bucket) serializes
        # fetches and should resize instead (docs/observability.md)
        self._c_chunked.inc()
        self._c_chunks.inc(len(pending))
        parts = [self._unpack(np.asarray(packed), k) for k, packed in pending]
        return tuple(np.concatenate(p) for p in zip(*parts))

    def _fetch_batch(self, t: StoppableThread):
        """Block for one task, then coalesce toward a full batch.

        The reference's ``fetch_batch`` drained greedily — right when a
        ``sess.run`` cost microseconds on local CPU. Here one device call
        costs ~1-10 ms of (possibly tunneled) dispatch latency, so waiting
        up to ``coalesce_ms`` to multiply the batch is a large win for the
        actor plane (measured: greedy draining served tiny batches and
        collapsed ZMQ-plane throughput). ``coalesce_ms=0`` restores the
        reference behavior. Tasks are WEIGHTED: a block task counts its B
        rows, so one ``batch_size``-sized block fills the batch alone and
        several small blocks coalesce into one device call."""
        import time as _time

        first = t.queue_get_stoppable(self._queue)
        if first is None:
            return None
        tasks = [first]
        weight = first.k if isinstance(first, _BlockTask) else 1
        deadline = _time.perf_counter() + self._coalesce_s
        while weight < self._batch_size:
            remaining = deadline - _time.perf_counter()
            try:
                if remaining > 0:
                    tk = self._queue.get(timeout=remaining)
                else:
                    tk = self._queue.get_nowait()
            except queue.Empty:
                break
            tasks.append(tk)
            weight += tk.k if isinstance(tk, _BlockTask) else 1
        return tasks

    def _serve_group(self, tasks) -> None:
        """One device call for a ≤-bucket group of tasks."""
        # counted HERE (not _run_device) so the null-device bench predictor,
        # which overrides _run_device, keeps the same series
        n_rows = sum(tk.k if isinstance(tk, _BlockTask) else 1 for tk in tasks)
        self._c_batches.inc()
        self._c_rows.inc(n_rows)
        self._h_occupancy.observe(n_rows)
        singles = [tk for tk in tasks if not isinstance(tk, _BlockTask)]
        blocks = [tk for tk in tasks if isinstance(tk, _BlockTask)]
        rows = []
        if singles:
            rows.append(np.stack([s for s, _ in singles]))
        rows.extend(b.states for b in blocks)
        # a lone block is served AS-IS (its states stay a zero-copy view
        # straight off the wire); mixing tasks pays one concat
        batch = rows[0] if len(rows) == 1 else np.concatenate(
            [np.asarray(r) for r in rows]
        )
        actions, values, logps, _ = self._run_device(batch)
        off = 0
        if singles:
            n = len(singles)
            for (_, cb), a, v, lp in zip(
                singles, actions[:n], values[:n], logps[:n]
            ):
                cb(int(a), float(v), float(lp))
            off = n
        for b in blocks:
            b.callback(
                actions[off:off + b.k],
                values[off:off + b.k],
                logps[off:off + b.k],
            )
            off += b.k

    def _worker(self) -> None:
        t = threading.current_thread()
        assert isinstance(t, StoppableThread)
        cap = _next_pow2(max(self._batch_size, 1))
        while not t.stopped():
            tasks = self._fetch_batch(t)
            if tasks is None:
                return
            # pack into groups that fit the warmed bucket: coalescing can
            # overshoot by up to one block, and a batch beyond the bucket
            # would compile a NEW program mid-serving (the BA3C_AUDIT
            # tripwire refuses exactly that)
            group: list = []
            weight = 0
            for tk in tasks:
                k = tk.k if isinstance(tk, _BlockTask) else 1
                if group and weight + k > cap:
                    self._serve_group(group)
                    group, weight = [], 0
                group.append(tk)
                weight += k
            if group:
                self._serve_group(group)
