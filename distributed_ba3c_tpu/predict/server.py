"""SLO-aware serving plane: continuous batching, deadline admission, N policies.

Reference equivalent (SURVEY.md §3.3): ``MultiThreadAsyncPredictor`` /
``PredictorWorkerThread`` — N threads each draining a shared queue into a
``sess.run`` on a predict tower, best-effort, no latency contract. The
TPU-native redesign (BASELINE.json + ROADMAP item 2, docs/serving.md):

- ONE compiled function per policy: forward + categorical sample on device;
  action sampling never returns logits to the host. Batch shapes are padded
  to warmed pow-2 buckets so XLA compiles a handful of programs once.
- **Continuous batching**: a single scheduler thread keeps up to
  ``dispatch_depth`` device calls in flight and admits freshly queued tasks
  into the NEXT bucket the moment the current one is dispatched — the fetch
  of call k happens only after call k+1 is enqueued (the overlap lesson,
  docs/overlap.md: the host must never sync between dispatches), so the
  device never idles between micro-batches. The in-flight call IS the
  coalesce window; the ``coalesce_ms`` timer only applies when the device
  is idle.
- **Deadline admission + load shedding**: every task can carry a deadline
  (defaulted from ``slo_ms``); the scheduler sheds tasks that cannot make
  their deadline BEFORE spending device time on them, and a bounded
  admission queue turns overload into fast typed rejection
  (:class:`ShedReject`) instead of unbounded latency. Tasks without a
  deadline keep the training plane's backpressure contract (blocking put).
- **Multi-policy serving**: N checkpoints hot simultaneously behind the one
  scheduler (``add_policy``); each task carries a policy id, a canary
  fraction routes live traffic deterministically (``set_canary``), and a
  shadow policy (``set_shadow``) sees every served batch with its results
  dropped before any caller — per-policy row counters keep the evaluation
  observable (docs/observability.md).

Weights live in device HBM; the learner publishes fresh params with
``update_params`` (an atomic Python ref swap — canary/shadow policies stay
pinned at their own checkpoints unless explicitly republished).
"""

from __future__ import annotations

import collections
import queue
import re
import threading
import weakref
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.telemetry import tracing as _tracing
from distributed_ba3c_tpu.audit import tripwire_jit
from distributed_ba3c_tpu.utils import logger
from distributed_ba3c_tpu.utils.concurrency import (
    FastQueue,
    StoppableThread,
    queue_put_stoppable,
)

#: metric-name grammar for policy ids: they are embedded in Prometheus
#: series names (``policy_<id>_rows_total``), so one junk id would poison
#: every scrape (telemetry/exporters.py enforces the same grammar)
_POLICY_ID_RE = re.compile(r"^[a-z0-9_]{1,32}$")


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


#: serving/actor forward precisions (the learner always keeps f32 — this
#: only selects the PARAMS precision of the serving program, the overlap
#: prep-cast extended to the ZMQ serving plane; models/a3c.py keeps the
#: policy/value heads f32 either way). ``int8`` additionally needs a
#: calibration source: a frozen QuantSpec, or N live-traffic batches
#: through the CalibrationTap (distributed_ba3c_tpu/quantize/).
ROLLOUT_DTYPES = ("float32", "bfloat16", "int8")


class _StagePool:
    """Reused per-shape serving staging buffers with the H2D ready fence.

    ``_launch`` materializes each group ONCE into a pooled buffer (lazy
    block-states views interleave straight in, padding included) instead
    of paying a fresh ``np.asarray`` + pad ``np.concatenate`` per
    dispatch. A buffer goes back to its free list when the dispatches
    that read it are fetched; an UNFETCHED release (the no-tap shadow
    mirror, which must never add a host sync) parks on the pending list
    until its output handle reports ready — reusing the host bytes while
    a transfer may still be reading them is the read-after-donate hazard
    (data/staging.py's fence, serving edition)."""

    __slots__ = ("_free", "_pending", "_c_alloc", "_c_copies")

    def __init__(self, tele):
        self._free: dict = {}     # (shape, dtype str) -> [ndarray, ...]
        self._pending: list = []  # (handle, key, ndarray) awaiting ready
        self._c_alloc = tele.counter("stage_alloc_total")
        self._c_copies = tele.counter("stage_copies_total")

    def _drain(self) -> None:
        still = []
        for handle, key, arr in self._pending:
            if getattr(handle, "is_ready", lambda: True)():
                self._free.setdefault(key, []).append(arr)
            else:
                still.append((handle, key, arr))
        self._pending = still

    def acquire(self, shape: tuple, dtype) -> np.ndarray:
        self._drain()
        key = (tuple(shape), np.dtype(dtype).str)
        free = self._free.get(key)
        if free:
            return free.pop()
        self._c_alloc.inc()
        return np.zeros(shape, dtype)

    def release(self, arr: np.ndarray, handle=None) -> None:
        key = (tuple(arr.shape), arr.dtype.str)
        if handle is None or getattr(handle, "is_ready", lambda: True)():
            self._free.setdefault(key, []).append(arr)
        else:
            self._pending.append((handle, key, arr))

    def count_copy(self) -> None:
        self._c_copies.inc()


class _StageLease:
    """One staged group buffer shared by its primary + shadow dispatches."""

    __slots__ = ("arr", "refs")

    def __init__(self, arr: np.ndarray, refs: int = 1):
        self.arr = arr
        self.refs = refs


class ShedReject:
    """Typed reject delivered to a task's ``shed_callback``.

    ``reason`` is one of:

    - ``"deadline"``: the scheduler proved the task could not be served
      before its deadline (queue wait + estimated device time) and shed it
      WITHOUT spending device time on it;
    - ``"queue_full"``: the bounded admission queue was full — the fast
      overload signal; retry after backing off, or fall back;
    - ``"shutdown"``: the predictor stopped while the task waited.

    The serving router (predict/router.py) adds two fleet-level reasons:

    - ``"replica_lost"``: the replica this task was dispatched to died
      before serving it (the router re-sheds a dead replica's
      outstanding tasks so no caller hangs on a corpse);
    - ``"no_replica"``: no live replica to dispatch to (every one is
      draining/dead, or the router is empty).

    Callers decide the fallback: the actor-plane masters reply with a
    uniform-random action (the behavior log-prob stays correct for
    V-trace); a serving frontend would surface a 429/503 equivalent.
    """

    __slots__ = ("reason", "deadline", "now")

    def __init__(self, reason: str, deadline: Optional[float] = None,
                 now: Optional[float] = None):
        self.reason = reason
        self.deadline = deadline
        self.now = now

    def __repr__(self) -> str:
        return f"ShedReject(reason={self.reason!r}, deadline={self.deadline})"


class _BlockTask:
    """One whole [B, ...] state block awaiting ONE batched callback.

    The block wire's unit of work: B states that arrived as one message and
    leave as one ``int32[B]`` action reply — no per-row splitting, no
    per-row Python bookkeeping anywhere between the socket and the device.
    """

    __slots__ = ("states", "callback", "k", "deadline", "policy", "shed_cb",
                 "t_admit", "trace")

    def __init__(self, states, callback, deadline=None, policy=None,
                 shed_cb=None, trace=None):
        self.states = states
        self.callback = callback
        self.k = states.shape[0]
        self.deadline = deadline
        self.policy = policy
        self.shed_cb = shed_cb
        self.t_admit = 0.0
        self.trace = trace  # tracing.TraceRef for a sampled block step


class _RowTask:
    """One single state row (per-env wire); ``k`` is always 1."""

    __slots__ = ("states", "callback", "k", "deadline", "policy", "shed_cb",
                 "t_admit", "trace")

    def __init__(self, state, callback, deadline=None, policy=None,
                 shed_cb=None, trace=None):
        self.states = state
        self.callback = callback
        self.k = 1
        self.deadline = deadline
        self.policy = policy
        self.shed_cb = shed_cb
        self.t_admit = 0.0
        self.trace = trace  # tracing.TraceRef for a sampled row


class _Inflight:
    """One dispatched-not-yet-fetched device call the scheduler tracks."""

    __slots__ = ("tasks", "n", "policy", "handle", "t_dispatch", "t_oldest",
                 "shadow", "states", "t_dispatch_us", "lease")

    def __init__(self, tasks, n, policy, handle, t_dispatch, t_oldest=0.0,
                 shadow=False, states=None, t_dispatch_us=0, lease=None):
        self.tasks = tasks        # ordered singles-then-blocks; None = shadow
        self.n = n
        self.policy = policy
        self.handle = handle      # (k, dispatched device array)
        self.t_dispatch = t_dispatch
        # admit stamp of the group's FIFO-oldest task — tasks is REORDERED
        # (singles first, matching the batch layout), so latency accounting
        # must not read tasks[0]
        self.t_oldest = t_oldest
        self.shadow = shadow
        self.states = states      # batch kept only for a shadow tap
        # µs dispatch stamp for trace spans (0 when no task is traced —
        # the untraced path never reads the clock for it)
        self.t_dispatch_us = t_dispatch_us
        # _StageLease of the pooled staging buffer this call reads (None
        # for pass-through / sync-path batches); released at _complete
        self.lease = lease


def make_fwd_sample(model, greedy: bool = False) -> Callable:
    """The action server's compiled program: forward + on-device sampling.

    Module-level (not a closure in ``__init__``) so the audit registry
    (distributed_ba3c_tpu/audit.py, entries ``predict.server`` and
    ``predict.server_greedy``) traces the same function the live predictor
    jits — BOTH packed shapes are registered so T5 pins them.
    """

    def fwd_sample(params, states, key):
        out = model.apply({"params": params}, states)
        if greedy:
            actions = jnp.argmax(out.logits, axis=-1)
        else:
            actions = jax.random.categorical(key, out.logits, axis=-1)
        actions = actions.astype(jnp.int32)
        # log mu(a|s): the behavior policy record V-trace needs
        log_probs = jax.nn.log_softmax(out.logits, axis=-1)
        logp = jnp.take_along_axis(log_probs, actions[:, None], axis=-1)[:, 0]
        # PACK everything into ONE array: the host fetches a single
        # buffer per serve. Measured on the tunneled-TPU dev setup:
        # device readback costs ~135 ms PER ARRAY regardless of size
        # (latency, not bandwidth), so four separate fetches were 540 ms
        # per serving call — 400x the 1.3 ms compute (see PERF.md).
        rows = [actions.astype(jnp.float32), out.value, logp]
        if not greedy:
            # the sampling server also publishes the argmax channel (the
            # Evaluator consumes it without a second device call); under
            # greedy=True row 0 IS the argmax, so the duplicate row is
            # dropped and the packed fetch shrinks to [3, B]
            rows.append(jnp.argmax(out.logits, axis=-1).astype(jnp.float32))
        return jnp.stack(rows)  # [3, B] greedy / [4, B] sampling, float32

    return fwd_sample


class BatchedPredictor:
    """Asynchronous batched (action, value) server with an SLO contract.

    Parameters
    ----------
    model: a flax module with ``apply({'params': p}, states) -> PolicyValue``.
    params: initial parameter pytree for the ``default`` policy.
    batch_size: micro-batch coalesce target (reference PREDICT_BATCH_SIZE);
        the hard bucket cap is the next power of two.
    num_threads: kept for call-site compatibility; the continuous-batching
        scheduler is ONE thread (dispatch order must be owned by one place
        for the depth pipeline), and pipelined dispatch replaces the old
        multi-worker host overlap.
    slo_ms: default deadline budget applied to every queued task (0 = no
        deadlines — the training plane's backpressure semantics).
    queue_depth: admission-queue bound. With deadlines, a full queue is an
        immediate typed reject (fast overload signal); without, a blocking
        backpressure put as before.
    dispatch_depth: device calls kept in flight by the scheduler (2 = the
        continuous-batching default: fetch k only after dispatching k+1).
    clock: monotonic-clock callable (tests inject a fake clock to make
        shed decisions deterministic).
    tele_role: telemetry registry role — ``predictor`` single-fleet,
        ``telemetry.fleet_role("predictor", k)`` when a learner hosts one
        predictor per fleet (docs/observability.md).
    """

    def __init__(
        self,
        model,
        params,
        batch_size: int = 16,
        num_threads: int = 1,
        seed: int = 0,
        greedy: bool = False,
        coalesce_ms: float = 2.0,
        slo_ms: float = 0.0,
        queue_depth: int = 4096,
        dispatch_depth: int = 2,
        clock: Optional[Callable[[], float]] = None,
        tele_role: str = "predictor",
        rollout_dtype: str = "float32",
        quant_spec=None,
        quant_calibrate: int = 0,
        quant_method: str = "absmax",
        quant_percentile: float = 99.9,
    ):
        import time as _time

        self._model = model
        self.num_actions = int(getattr(model, "num_actions", 0) or 0)
        if rollout_dtype not in ROLLOUT_DTYPES:
            raise ValueError(
                f"rollout_dtype must be one of {ROLLOUT_DTYPES}, got "
                f"{rollout_dtype!r}"
            )
        if rollout_dtype == "int8":
            if (quant_spec is None) == (not quant_calibrate):
                raise ValueError(
                    "rollout_dtype='int8' needs exactly ONE calibration "
                    "source: a frozen quant_spec, or quant_calibrate=N "
                    "live batches through the CalibrationTap"
                )
        elif quant_spec is not None or quant_calibrate:
            raise ValueError(
                "quant_spec/quant_calibrate configure the int8 rung — "
                f"they do not apply to rollout_dtype={rollout_dtype!r}"
            )
        self.rollout_dtype = rollout_dtype
        #: the ACTIVE QuantSpec (int8 serving) — None while f32/bf16, and
        #: None during the live-calibration window (f32 serving until the
        #: tap freezes and the table switches)
        self.quant_spec = None
        # sync-path consistency guard: _switch_to_int8 swaps the compiled
        # program and the policy table together under this lock; the
        # scheduler thread never needs it (the switch runs ON it)
        self._swap_lock = threading.Lock()
        if rollout_dtype == "bfloat16":
            # the overlap split's prep-cast, serving edition: every policy
            # publish casts f32 params to bf16 ON DEVICE (one small pass,
            # amortized over a whole publish interval), halving the
            # forward's param-read bandwidth; the heads stay f32 compute
            # (models/a3c.py) so log mu(a|s) keeps its precision and
            # V-trace clips whatever noise the storage cast adds
            self._cast_params = jax.jit(
                lambda p: jax.tree_util.tree_map(
                    lambda x: x.astype(jnp.bfloat16)  # ba3clint: disable=A16 — THE audited publish cast (entry predict.server_bf16)
                    if x.dtype == jnp.float32 else x,
                    p,
                )
            )
        elif rollout_dtype == "int8" and quant_spec is not None:
            # quantize-on-publish (the bf16 cast's int8 edition): every
            # policy publish runs the f32 -> int8 table build in
            # quantize/qforward.py — per-channel weight scales + the
            # spec's frozen activation scales; the compiled forward
            # depends only on avals, so ONE program serves every publish
            from distributed_ba3c_tpu.quantize import quantize_params

            self.quant_spec = quant_spec
            self._cast_params = jax.jit(
                lambda p: quantize_params(p, quant_spec)
            )
        else:
            self._cast_params = None
        self._policies = {"default": self._put_policy(params)}
        self._batch_size = batch_size
        self._coalesce_s = coalesce_ms / 1000.0
        self._slo_s = slo_ms / 1000.0
        self._depth = max(1, int(dispatch_depth))
        self._clock = clock or _time.monotonic
        # bounded admission queue, deque-based (utils/concurrency.py): at
        # serving rates a mutex+condvar queue.Queue costs a futex per op on
        # sandboxed kernels — the same ceiling the train queue hit in PR 4
        self._queue: FastQueue = FastQueue(maxsize=queue_depth)
        self._key = jax.random.PRNGKey(seed)
        self._key_lock = threading.Lock()
        self._greedy = greedy
        self._stop_evt = threading.Event()
        # serve-time estimate feeding the deadline gate: a DECAYING MAX of
        # dispatch->fetch wall time (includes pipeline wait). Deliberately
        # conservative: the estimator's error mode must be shedding a task
        # that would have made it, never serving one late (docs/serving.md)
        self._est_serve_s = 0.0
        self._inflight_n = 0
        # multi-policy routing state: canary is an atomic (policy, fraction)
        # tuple swap. Routing happens at GROUP granularity in the scheduler
        # (a deficit accumulator — exactly `fraction` of routed rows over
        # time, no RNG): per-task routing would break every group at the
        # policy boundary and collapse batch occupancy whenever canary
        # traffic interleaves.
        self._canary: Optional[Tuple[str, float]] = None
        self._shadow: Optional[str] = None
        self._canary_debt = 0.0  # scheduler-thread only
        self._held = None  # scheduler-local FIFO carry between groups
        #: test/eval tap for shadow results: ``tap(states, actions, policy)``
        #: — when None (production) shadow results are dropped WITHOUT a
        #: host sync
        self.shadow_tap: Optional[Callable] = None

        # telemetry (docs/observability.md): serving-side counters live in
        # the predictor role registry; the bucket-occupancy histogram is
        # what separates "tiny fragmented batches" from "full buckets"
        # when the plane slows down. Unit=1: occupancies are row counts.
        # per-fleet serving identity (telemetry.fleet_role): a learner
        # hosting K fleets runs K predictors, and their occupancy/SLO
        # series must not collapse into one registry (the fn-backed gauges
        # would be silently rebound to whichever predictor came last)
        tele = telemetry.registry(tele_role)
        self.tele_role = tele_role
        self._tele = tele
        self._c_batches = tele.counter("batches_total")
        self._c_rows = tele.counter("rows_total")
        self._c_oversize = tele.counter("blocks_oversize_total")
        self._c_publishes = tele.counter("param_publishes_total")
        self._c_chunked = tele.counter("chunked_calls_total")
        self._c_chunks = tele.counter("chunks_total")
        self._h_occupancy = tele.histogram("batch_rows", unit=1)
        # SLO plane series: sheds are counted in ROWS (a shed block is k
        # lost requests, not one), misses are rows served past their
        # deadline (should stay ~0 — they measure the estimator's error,
        # not the shed policy)
        self._c_sheds = tele.counter("sheds_total")
        self._c_shed_deadline = tele.counter("sheds_deadline_total")
        self._c_shed_full = tele.counter("sheds_queue_full_total")
        self._c_deadline_miss = tele.counter("deadline_misses_total")
        self._h_queue_wait = tele.histogram("queue_wait_s", unit=1e-6)
        self._h_serve = tele.histogram("serve_latency_s", unit=1e-6)
        self._c_shadow_batches = tele.counter("shadow_batches_total")
        self._c_shadow_rows = tele.counter("shadow_rows_total")
        self._c_cb_errors = tele.counter("callback_errors_total")
        self._c_policy_rows = {
            "default": tele.counter("policy_default_rows_total")
        }

        ref = weakref.ref(self)
        tele.gauge(
            "task_queue_depth",
            fn=lambda: p._queue.qsize() if (p := ref()) else 0,
        )
        tele.gauge(
            "slo_ms", fn=lambda: p._slo_s * 1000.0 if (p := ref()) else 0
        )
        tele.gauge(
            "inflight_dispatches",
            fn=lambda: p._inflight_n if (p := ref()) else 0,
        )

        # the serving staging pool (docs/ingest.md): one materialization
        # per dispatched group into a reused buffer, ready-fenced
        self._pool = _StagePool(tele)

        # registered audit entry point (distributed_ba3c_tpu/audit.py).
        # auto_arm=False: the pow-2 bucket warmup is a LEGITIMATE multi-shape
        # compile sequence; warmup() arms the tripwire when it completes, so
        # only a new bucket size appearing mid-serving raises. Continuous
        # batching keeps this contract: every group is padded to a warmed
        # bucket before dispatch. The bf16/int8 variants are their own entry
        # points (predict.server_bf16 / predict.server_int8): different
        # programs, their own T1/T2/T5 pins.
        entry = "predict.server_greedy" if greedy else "predict.server"
        if rollout_dtype == "bfloat16":
            entry += "_bf16"
        if self.quant_spec is not None:
            from distributed_ba3c_tpu.quantize import make_quant_fwd_sample

            self._fwd = tripwire_jit(
                entry + "_int8",
                make_quant_fwd_sample(model, greedy),
                auto_arm=False,
            )
        else:
            self._fwd = tripwire_jit(
                entry,
                make_fwd_sample(model, greedy),
                auto_arm=False,
            )
        # the live-calibration window (rollout_dtype='int8' without a
        # frozen spec): serve f32 while the PR-9 shadow plane mirrors
        # every batch through the CalibrationTap; after N batches the tap
        # freezes and _switch_to_int8 swaps program + table in place
        self._warm_shape = None
        self._warm_dtype = None
        if rollout_dtype == "int8" and self.quant_spec is None:
            from distributed_ba3c_tpu.quantize import CalibrationTap

            self.shadow_tap = CalibrationTap(
                model, params, quant_calibrate,
                method=quant_method, percentile=quant_percentile,
                on_freeze=self._switch_to_int8, tele_role=tele_role,
            )
            self._shadow = "default"
        self.threads: List[StoppableThread] = [
            StoppableThread(
                target=self._scheduler, daemon=True, name="predictor-sched"
            )
        ]

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        for t in self.threads:
            t.start()

    def warmup(self, state_shape, dtype=np.uint8) -> None:
        """Precompile every pow-2 bucket up to batch_size, for EVERY policy.

        Each new bucket size triggers a fresh XLA compile (tens of seconds
        on TPU) the first time it is served; hitting that mid-training
        stalls the whole actor plane. Call once before actors start (and
        after ``add_policy`` — same program, but the warmup proves the
        shapes through)."""
        # remembered for the int8 calibration switch: the swapped-in
        # quantized program must re-prove the same buckets before it
        # takes traffic (same mid-serving-stall contract)
        self._warm_shape = tuple(state_shape)
        self._warm_dtype = dtype
        b = 1
        while b <= _next_pow2(self._batch_size):
            self._run_device(np.zeros((b, *state_shape), dtype))
            b *= 2
        # BA3C_AUDIT=1: buckets compiled — any retrace from here on is a
        # mid-serving stall and raises AuditError
        getattr(self._fwd, "arm", lambda: None)()

    def stop(self) -> None:
        self._stop_evt.set()
        for t in self.threads:
            t.stop()

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for the scheduler thread to exit (it polls with 0.5s
        timeout)."""
        for t in self.threads:
            if t.is_alive():
                t.join(timeout)

    # -- the int8 calibration switch ---------------------------------------
    @property
    def serving_dtype(self) -> str:
        """The precision the table SERVES right now: ``rollout_dtype``,
        except during the int8 live-calibration window (f32 until the
        tap freezes and the switch lands)."""
        if self.rollout_dtype != "int8":
            return self.rollout_dtype
        return "int8" if self.quant_spec is not None else "float32"

    def _switch_to_int8(self, spec) -> None:
        """The CalibrationTap's freeze hook: swap the serving plane to
        int8 IN PLACE — quantize every hot policy, replace the compiled
        program (audit entry gains its ``_int8`` suffix), re-prove the
        warmed buckets, retire the shadow mirror.

        Runs on the scheduler thread (the tap fires from the shadow
        fetch path), so no async dispatch is concurrent with the swap;
        ``_swap_lock`` covers the sync ``predict_batch`` path."""
        from distributed_ba3c_tpu.quantize import (
            make_quant_fwd_sample,
            quantize_params,
        )

        quantize = jax.jit(lambda p: quantize_params(p, spec))
        entry = "predict.server_greedy" if self._greedy else "predict.server"
        fwd = tripwire_jit(
            entry + "_int8",
            make_quant_fwd_sample(self._model, self._greedy),
            auto_arm=False,
        )
        while True:
            # quantize OUTSIDE the lock (device work), commit only if no
            # publish replaced an entry meanwhile — else a fresh f32
            # table would be silently dropped by the rebind
            snapshot = dict(self._policies)
            table = {pid: quantize(p) for pid, p in snapshot.items()}
            with self._swap_lock:
                current = self._policies
                if len(current) == len(snapshot) and all(
                    current.get(pid) is p for pid, p in snapshot.items()
                ):
                    self._cast_params = quantize
                    self._policies = table
                    self._fwd = fwd
                    self.quant_spec = spec
                    break
        # shadow plane retired: the tap saw its N batches; from here the
        # mirror would only double device work
        self._shadow = None
        self.shadow_tap = None
        if self._warm_shape is not None:
            # re-prove the warmed buckets through the NEW program (its
            # own compile set), then re-arm the retrace tripwire
            b = 1
            while b <= _next_pow2(self._batch_size):
                self._run_device(
                    np.zeros((b, *self._warm_shape), self._warm_dtype)
                )
                b *= 2
            getattr(self._fwd, "arm", lambda: None)()
        self._c_publishes.inc()

    # -- policy table ------------------------------------------------------
    def _publish_policy(self, policy_id: str, params) -> None:
        """Commit a publish to the table — cast/quantize OUTSIDE the swap
        lock (device work), then store only if the serving program didn't
        change underneath: a publish racing ``_switch_to_int8`` must land
        through the NEW cast, never as an f32 table behind the int8
        program."""
        while True:
            cast = self._cast_params
            p = jax.device_put(params)
            if cast is not None:
                p = cast(p)
            with self._swap_lock:
                if self._cast_params is cast:
                    self._policies[policy_id] = p
                    return

    def _put_policy(self, params):
        """Params → the serving table's storage: device-resident, cast to
        the rollout dtype (bf16 mode) — ONE place, so every publish path
        (ctor, add_policy, update_params) serves the same precision."""
        p = jax.device_put(params)
        if self._cast_params is not None:
            p = self._cast_params(p)
        return p

    def add_policy(self, policy_id: str, params) -> None:
        """Make a second checkpoint hot behind the same scheduler.

        ``policy_id`` must match ``[a-z0-9_]{1,32}`` — it is embedded in
        the per-policy telemetry series names."""
        if not _POLICY_ID_RE.match(policy_id):
            raise ValueError(
                f"policy id {policy_id!r} must match {_POLICY_ID_RE.pattern} "
                "(it names Prometheus series)"
            )
        self._publish_policy(policy_id, params)
        self._c_policy_rows.setdefault(
            policy_id, self._tele.counter(f"policy_{policy_id}_rows_total")
        )

    def set_canary(self, policy_id: str, fraction: float) -> None:
        """Route ``fraction`` of un-pinned traffic to ``policy_id``.

        Deterministic deficit-accumulator split at GROUP granularity (no
        RNG, full batch occupancy preserved): over time exactly
        ``fraction`` of routed rows serve the canary. 0 clears the
        canary. Callers that pin ``policy=`` on their tasks bypass
        routing."""
        if fraction <= 0:
            self._canary = None
            return
        if not 0 < fraction <= 1:
            raise ValueError(f"canary fraction {fraction} not in (0, 1]")
        if policy_id not in self._policies:
            raise KeyError(f"unknown policy {policy_id!r} — add_policy first")
        self._canary = (policy_id, float(fraction))

    def set_shadow(self, policy_id: Optional[str]) -> None:
        """Mirror EVERY served batch through ``policy_id``.

        The shadow call is dispatched right after the primary with the
        identical padded batch; its results never reach any caller — they
        are dropped undetched (no host sync) unless a ``shadow_tap`` is
        installed. ``None`` clears."""
        if policy_id is not None and policy_id not in self._policies:
            raise KeyError(f"unknown policy {policy_id!r} — add_policy first")
        self._shadow = policy_id

    def update_params(self, params, policy: str = "default") -> None:
        """Publish fresh weights (atomic ref swap; next batch uses them).

        Only EXISTING policies can be republished — a typo'd id must fail
        loudly, not create a dead entry while the real policy silently
        keeps serving its stale weights."""
        if policy not in self._policies:
            raise KeyError(f"unknown policy {policy!r} — add_policy first")
        self._publish_policy(policy, params)
        self._c_publishes.inc()

    # -- API ---------------------------------------------------------------
    def put_task(
        self,
        state: np.ndarray,
        callback: Callable[[int, float, float], None],
        *,
        deadline: Optional[float] = None,
        policy: Optional[str] = None,
        shed_callback: Optional[Callable[[ShedReject], None]] = None,
        trace=None,
    ) -> bool:
        """Queue one state; ``callback(action, value, logp)`` fires when
        served — logp is log mu(action|state) under the sampling policy.

        ``deadline`` is an absolute clock() time (defaulted from ``slo_ms``
        when set); a task that cannot make it is shed with a typed
        :class:`ShedReject` to ``shed_callback`` instead of served late.
        Tasks arriving after ``stop()`` are rejected the same way (their
        simulators are being torn down too). ``trace`` is a sampled
        tracing.TraceRef — the scheduler attributes its dispatch-wait and
        device-fetch spans under this predictor's role (tracing.py).
        Returns True if admitted."""
        return self._admit(
            _RowTask(state, callback, deadline, policy, shed_callback, trace)
        )

    def put_block_task(
        self,
        states: np.ndarray,
        callback: Callable[[np.ndarray, np.ndarray, np.ndarray], None],
        *,
        deadline: Optional[float] = None,
        policy: Optional[str] = None,
        shed_callback: Optional[Callable[[ShedReject], None]] = None,
        trace=None,
    ) -> bool:
        """Queue one [B, ...] state block (the block wire's whole batch);
        ``callback(actions[B], values[B], logps[B])`` fires ONCE when the
        block is served. The block lands in a warmed pow-2 bucket as a
        unit — no per-row splitting; queued neighbors coalesce into one
        device call up to the bucket cap (continuous batching: the
        in-flight dispatch is the coalesce window). Same deadline/shed
        semantics as :meth:`put_task`; ``trace`` as there."""
        cap = _next_pow2(max(self._batch_size, 1))
        if states.shape[0] > cap:
            self._c_oversize.inc()
            raise ValueError(
                f"block of {states.shape[0]} states exceeds the serving "
                f"bucket ({cap}) — raise predict_batch_size to at least "
                "the env-server block size"
            )
        return self._admit(
            _BlockTask(states, callback, deadline, policy, shed_callback,
                       trace)
        )

    def predict_batch(
        self, states: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Synchronous batched predict: (actions, values, greedy_actions).

        ``actions`` follow the serving policy (sampled, or argmax when
        ``greedy=True``); ``greedy_actions`` are always the argmax — the
        Evaluator consumes those without a second device call. Always the
        ``default`` policy; never queued, never shed."""
        actions, values, _, greedy_actions = self._run_rows(
            np.asarray(states)
        )
        return actions, values, greedy_actions

    # -- admission ---------------------------------------------------------
    def _admit(self, task) -> bool:
        now = self._clock()
        task.t_admit = now
        if task.deadline is None and self._slo_s > 0:
            task.deadline = now + self._slo_s
        # task.policy stays None for routed traffic — the SCHEDULER routes
        # whole groups (see _route_group), so un-pinned tasks all group
        # together and canary splits never fragment batches
        if task.policy is not None and task.policy not in self._policies:
            # validated HERE, in the caller's thread: an unknown id reaching
            # the scheduler would KeyError in _launch and kill the one
            # thread the whole serving plane runs on (and mint a junk
            # per-policy series on the way)
            raise KeyError(
                f"unknown policy {task.policy!r} — add_policy first"
            )
        if self._stop_evt.is_set():
            self._shed(task, "shutdown")
            return False
        if task.deadline is not None:
            # serving contract: a full bounded queue is an IMMEDIATE typed
            # reject — overload must surface as fast rejection the caller
            # can act on, never as unbounded queue latency
            try:
                self._queue.put_nowait(task)
            except queue.Full:
                self._shed(task, "queue_full")
                return False
        else:
            # training contract (no deadline): backpressure pauses the
            # caller, but stays shutdown-responsive
            if not queue_put_stoppable(self._queue, task, self._stop_evt):
                self._shed(task, "shutdown")
                return False
        if self._stop_evt.is_set():
            # the put may have raced PAST the scheduler's final teardown
            # drain — resolve the queue from this thread so no task is
            # ever stranded with neither callback delivered (deque pops
            # are atomic: concurrent drains resolve each task once)
            self._drain_shutdown()
        return True

    def _route_group(self, weight: int) -> str:
        """Resolve an un-pinned group's policy (scheduler thread only).

        Deficit accumulator: each routed group adds ``fraction * weight``
        of canary debt; a group dispatches to the canary when the debt
        covers it. Over time exactly ``fraction`` of routed ROWS serve
        the canary, with no RNG and no group fragmentation."""
        c = self._canary
        if c is None:
            return "default"
        pid, frac = c
        self._canary_debt += frac * weight
        if self._canary_debt >= weight:
            self._canary_debt -= weight
            return pid
        return "default"

    def _shed(self, task, reason: str) -> None:
        self._c_sheds.inc(task.k)
        if reason == "deadline":
            self._c_shed_deadline.inc(task.k)
            # transient-stall recovery: the estimate normally decays only
            # at COMPLETIONS, so a one-off stall that inflates it past the
            # whole SLO budget would shed everything forever — no
            # completions, no decay, a permanent outage (found live: one
            # 446 ms scheduler stall on a busy 1-core host shed 7588/7592
            # rows of an otherwise healthy run). A FRESH task (>80% of its
            # budget left — the estimator, not queue wait, is what killed
            # it) decays the estimate 10%, so after a stall the scheduler
            # probes its way back to serving; a slow probe re-measures the
            # truth and sheds resume, bounding the probe duty cycle.
            if task.deadline is not None:
                budget = task.deadline - task.t_admit
                if budget > 0 and (
                    task.deadline - self._clock() > 0.8 * budget
                ):
                    self._est_serve_s *= 0.9
        elif reason == "queue_full":
            self._c_shed_full.inc(task.k)
        cb = task.shed_cb
        if cb is not None:
            self._fire(cb, ShedReject(reason, task.deadline, self._clock()))

    def _fire(self, fn, *args) -> None:
        """Run one user callback; an exception must not kill the ONE
        thread the whole serving plane runs on (the old N-worker design
        at least left the other workers alive). Counted + flight-recorded
        + logged, never silent — the missing result is the caller's
        signal, a dead scheduler would be nobody's."""
        try:
            fn(*args)
        except Exception as e:
            self._c_cb_errors.inc()
            try:
                telemetry.record(
                    "predictor_callback_error", error=str(e)[:200]
                )
            except Exception:
                pass
            logger.error("predictor callback raised %r", e)

    # -- internals ---------------------------------------------------------
    def _next_key(self):
        with self._key_lock:
            self._key, sub = jax.random.split(self._key)
        return sub

    def _dispatch(self, params, batch: np.ndarray, fwd=None):
        """Pad to the pow-2 bucket and dispatch (async); NO host fetch —
        the scheduler fetches via :meth:`_collect` only after the next
        group is dispatched.

        ``params`` is passed explicitly so a multi-chunk caller serves ONE
        parameter version even if the learner publishes mid-batch; ``fwd``
        likewise pins the compiled program across a chunked call (the
        int8 calibration switch swaps ``self._fwd`` mid-serving)."""
        # device ingest is where a lazy block-states view (block-shm wire)
        # pays its one materialization — jit can't take a BlockStatesView
        batch = np.asarray(batch)
        k = batch.shape[0]
        padded = _next_pow2(max(k, 1))
        if padded != k:
            pad = np.zeros((padded - k, *batch.shape[1:]), batch.dtype)
            batch = np.concatenate([batch, pad], axis=0)
        return k, (fwd if fwd is not None else self._fwd)(
            params, batch, self._next_key()
        )

    def _collect(self, handle):
        """ONE device->host fetch of a dispatched call (see fwd_sample)."""
        k, packed = handle
        return self._unpack(np.asarray(packed), k)

    def _unpack(self, packed: np.ndarray, k: int):
        actions = packed[0, :k].astype(np.int32)
        if packed.shape[0] == 3:
            # greedy server: row 0 IS the argmax channel (make_fwd_sample)
            return actions, packed[1, :k], packed[2, :k], actions
        return actions, packed[1, :k], packed[2, :k], packed[3, :k].astype(
            np.int32
        )

    def _run_device(self, batch: np.ndarray):
        return self._collect(self._dispatch(self._params, batch))

    @property
    def _params(self):
        return self._policies["default"]

    def _run_rows(self, states: np.ndarray):
        """Serve N rows synchronously: (actions, values, logps, greedy).

        Inputs larger than the serving bucket (an Evaluator with more envs
        than ``batch_size``) are chunked to it, so no bucket beyond
        warmup's is ever compiled — bounded device memory, and no
        post-warmup retrace for the BA3C_AUDIT=1 tripwire to refuse. The
        chunked path dispatches EVERY chunk before fetching any: jax
        dispatch is async, so the chunks' compute overlaps while fetches
        (the ~135 ms/array latency documented above) drain in order.
        Params are snapshotted once per call: a learner publish mid-call
        must not split one logical batch across two policies."""
        cap = _next_pow2(max(self._batch_size, 1))
        # program + table snapshotted TOGETHER: the int8 calibration
        # switch swaps both under _swap_lock, and a sync caller must not
        # pair the old program with the new table (or vice versa)
        with self._swap_lock:
            fwd, params = self._fwd, self._params
        if states.shape[0] <= cap:
            return self._collect(self._dispatch(params, states, fwd))
        pending = [
            self._dispatch(params, states[i:i + cap], fwd)
            for i in range(0, states.shape[0], cap)
        ]
        # chunking is worth SEEING on the scrape endpoint: a persistently
        # chunked caller (Evaluator sized past the bucket) serializes
        # fetches and should resize instead (docs/observability.md)
        self._c_chunked.inc()
        self._c_chunks.inc(len(pending))
        parts = [self._collect(h) for h in pending]
        return tuple(np.concatenate(p) for p in zip(*parts))

    # -- the continuous-batching scheduler ---------------------------------
    def _viable(self, task, now: float) -> bool:
        """Can this task still make its deadline if dispatched NOW?

        The decaying-max serve-time estimate already includes pipeline
        wait; the extra 25% headroom absorbs scheduler jitter (group
        assembly, callback bursts, sleep-granularity overshoot on loaded
        hosts). Both biases point the same way: the error mode is
        shedding a task that would have made it, never serving one
        late."""
        return (
            task.deadline is None
            or now + self._est_serve_s * 1.25 <= task.deadline
        )

    def _next_task(self, t: StoppableThread, wait: bool):
        """Pop the next VIABLE task (shedding hopeless ones on the way).

        ``wait``: block stoppably (device idle) vs return None immediately
        (a dispatch is in flight — whatever is queued right now rides the
        next bucket, nothing more)."""
        while True:
            if self._held is not None:
                task, self._held = self._held, None
            elif wait:
                task = t.queue_get_stoppable(self._queue)
                if task is None:
                    return None  # stopping
            else:
                try:
                    task = self._queue.get_nowait()
                except queue.Empty:
                    return None
            if self._viable(task, self._clock()):
                return task
            self._shed(task, "deadline")

    def _assemble(self, t: StoppableThread, idle: bool):
        """Build one ≤-bucket, single-policy group of tasks.

        When the device is idle, waits for a first task and then up to
        ``coalesce_ms`` to multiply the batch (the reference's fetch_batch
        drained greedily — right when a sess.run cost microseconds; one
        device call here costs ~1-10 ms of dispatch latency). When a
        dispatch is already in flight, takes only what is queued NOW: the
        in-flight call is the coalesce window (continuous batching)."""
        import time as _time

        first = self._next_task(t, wait=idle)
        if first is None:
            return None
        cap = _next_pow2(max(self._batch_size, 1))
        tasks, weight = [first], first.k
        deadline = _time.perf_counter() + (self._coalesce_s if idle else 0.0)
        while weight < self._batch_size:
            if self._held is not None:
                tk, self._held = self._held, None
            else:
                remaining = deadline - _time.perf_counter()
                try:
                    if remaining > 0:
                        tk = self._queue.get(timeout=remaining)
                    else:
                        tk = self._queue.get_nowait()
                except queue.Empty:
                    break
            if not self._viable(tk, self._clock()):
                self._shed(tk, "deadline")
                continue
            if tk.policy != first.policy or weight + tk.k > cap:
                # one device call serves ONE policy and ONE bucket; the
                # misfit leads the next group (never reordered past FIFO)
                self._held = tk
                break
            tasks.append(tk)
            weight += tk.k
        return tasks, weight, first.policy

    def _stage_group(self, singles, blocks, weight):
        """The group's ONE materialization: rows interleave straight into
        a pooled, bucket-padded staging buffer (lazy block-states views
        included — data/staging.py's write-into discipline replaces the
        old np.asarray-then-concatenate chain at this site). Returns
        ``(batch, lease)``; lease None = zero-copy pass-through (a lone
        already-bucket-shaped ndarray block, served AS-IS like before).
        Pad rows keep stale bytes: only rows :weight reach any callback,
        so zeroing them every reuse would be a copy with no reader."""
        padded = _next_pow2(max(weight, 1))
        if not singles and len(blocks) == 1:
            b0 = blocks[0].states
            if isinstance(b0, np.ndarray) and b0.shape[0] == padded:
                return b0, None
        if singles:
            first = singles[0].states
            tail = tuple(np.shape(first))  # one row's shape
        else:
            first = blocks[0].states
            tail = tuple(np.shape(first))[1:]  # strip the block axis
        dtype = getattr(first, "dtype", np.uint8)
        buf = self._pool.acquire((padded, *tail), dtype)
        off = 0
        for tk in singles:
            buf[off] = tk.states
            off += 1
        for tk in blocks:
            dest = buf[off : off + tk.k]
            mi = getattr(tk.states, "materialize_into", None)
            if mi is not None:
                mi(dest)
            else:
                dest[...] = tk.states
            off += tk.k
        self._pool.count_copy()
        return buf, _StageLease(buf)

    def _release_lease(self, inf: _Inflight, synced: bool) -> None:
        """One dispatch done with its staging buffer; the buffer frees
        when every sharer (primary + shadow) released. ``synced=False``
        (the unfetched shadow) parks on the ready fence instead — the
        host bytes may still be feeding the transfer."""
        lease = inf.lease
        if lease is None:
            return
        lease.refs -= 1
        if lease.refs == 0:
            self._pool.release(
                lease.arr, None if synced else inf.handle[1]
            )

    def _launch(self, group) -> List[_Inflight]:
        """Dispatch one group (plus its shadow mirror) — no host fetch."""
        tasks, weight, policy = group
        if policy is None:
            policy = self._route_group(weight)  # un-pinned: routed here
        singles = [tk for tk in tasks if isinstance(tk, _RowTask)]
        blocks = [tk for tk in tasks if isinstance(tk, _BlockTask)]
        batch, lease = self._stage_group(singles, blocks, weight)
        now = self._clock()
        # counted at LAUNCH (not fetch) so the series lead the latency
        # histograms by exactly the in-flight window
        self._c_batches.inc()
        self._c_rows.inc(weight)
        self._h_occupancy.observe(weight)
        self._policy_counter(policy).inc(weight)
        # tasks[0] is the group's oldest admit (FIFO pop order) — captured
        # BEFORE the singles-first reorder below
        t_oldest = tasks[0].t_admit
        self._h_queue_wait.observe(max(0.0, now - t_oldest))
        ordered = singles + blocks  # callback offsets follow batch layout
        handle = self._dispatch(self._policies[policy], batch)
        # µs stamp only when a sampled trace rides this group — the
        # untraced path pays one attribute scan, never a clock read
        t_us = (
            _tracing.now_us()
            if any(tk.trace is not None for tk in ordered) else 0
        )
        out = [_Inflight(
            ordered, weight, policy, handle, now,
            t_oldest=t_oldest, t_dispatch_us=t_us, lease=lease,
        )]
        shadow = self._shadow
        if shadow is not None:
            self._c_shadow_batches.inc()
            self._c_shadow_rows.inc(weight)
            if lease is not None:
                lease.refs += 1  # the mirror reads the same staged bytes
            out.append(_Inflight(
                None, weight, shadow,
                self._dispatch(self._policies[shadow], batch), now,
                shadow=True,
                states=batch if self.shadow_tap is not None else None,
                lease=lease,
            ))
        return out

    def _policy_counter(self, policy: str):
        c = self._c_policy_rows.get(policy)
        if c is None:
            self._c_policy_rows[policy] = c = self._tele.counter(
                f"policy_{policy}_rows_total"
            )
        return c

    def _complete(self, inf: _Inflight) -> None:
        """Fetch one in-flight call and fire its callbacks."""
        if inf.shadow:
            tap = self.shadow_tap
            # inf.states is captured at LAUNCH only when a tap was already
            # installed — a tap that appears mid-flight skips this call
            if tap is not None and inf.states is not None:
                actions, _, _, _ = self._collect(inf.handle)
                states = np.asarray(inf.states)[: inf.n]
                if inf.lease is not None:
                    # the tap's states must outlive the staging buffer's
                    # reuse (pad rows are sliced off above for the same
                    # reason: the tap sees exactly the SERVED rows)
                    states = states.copy()
                self._fire(tap, states, actions[: inf.n], inf.policy)
                self._release_lease(inf, synced=True)
            else:
                # no tap: DROP without a host sync — shadow evaluation
                # must never add fetch latency to the serving path; the
                # staging buffer parks on the ready fence instead
                self._release_lease(inf, synced=False)
            return
        actions, values, logps, _ = self._collect(inf.handle)
        self._release_lease(inf, synced=True)
        now = self._clock()
        if inf.t_dispatch_us:
            # sampled spans: dispatch wait (admit -> device dispatch) and
            # device fetch (dispatch -> results on host) attributed under
            # THIS predictor's role — the decomposition of the master-side
            # predict RTT span (tracing.py; docs/observability.md)
            for tk in inf.tasks:
                if tk.trace is not None:
                    tk.trace.hop(
                        "predict_dispatch", self.tele_role,
                        t_end_us=inf.t_dispatch_us,
                    ).hop("predict_fetch", self.tele_role)
                    tk.trace = None  # one attribution per task
        # decaying-max serve-time estimate for the deadline gate: tracks
        # the worst recent dispatch->fetch (incl. pipeline wait) and decays
        # 10% per call so a one-off stall doesn't shed forever
        self._est_serve_s = max(
            self._est_serve_s * 0.9, now - inf.t_dispatch
        )
        self._h_serve.observe(max(0.0, now - inf.t_oldest))
        late = sum(
            tk.k for tk in inf.tasks
            if tk.deadline is not None and now > tk.deadline
        )
        if late:
            # served PAST deadline: the estimator was wrong (it never
            # shed them) — the series that must stay ~0 for the SLO claim
            self._c_deadline_miss.inc(late)
        off = 0
        for tk in inf.tasks:
            if isinstance(tk, _RowTask):
                self._fire(
                    tk.callback,
                    int(actions[off]), float(values[off]), float(logps[off]),
                )
                off += 1
            else:
                self._fire(
                    tk.callback,
                    actions[off:off + tk.k],
                    values[off:off + tk.k],
                    logps[off:off + tk.k],
                )
                off += tk.k

    def _scheduler(self) -> None:
        """The serving loop: dispatch-depth-pipelined continuous batching.

        Invariant (the overlap lesson, docs/overlap.md): the fetch of call
        k happens AFTER the dispatch of call k+1 whenever there is queued
        work — the host never syncs between dispatches, so the device
        never idles between micro-batches."""
        t = threading.current_thread()
        assert isinstance(t, StoppableThread)
        inflight: collections.deque = collections.deque()
        while not t.stopped():
            group = self._assemble(t, idle=not inflight)
            if group is not None:
                inflight.extend(self._launch(group))
            self._inflight_n = len(inflight)
            # fetch the oldest call(s) once the pipeline is full — or when
            # there is nothing new to dispatch (drain toward idle). The
            # loop (not a single pop) keeps the depth bound even when a
            # shadow mirror doubles the handles per group; but a drain
            # completion re-checks the queue before fetching the next
            # handle — work that arrived DURING the blocking fetch must be
            # dispatched before the host blocks again (the no-sync-between-
            # dispatches invariant, applied to the drain path too)
            while inflight and (len(inflight) >= self._depth
                                or group is None):
                self._complete(inflight.popleft())
                self._inflight_n = len(inflight)
                if group is None:
                    break
        # teardown: complete what was dispatched (callers may be waiting),
        # then deliver the promised "shutdown" reject to everything still
        # queued — a caller waiting on either callback to resolve must not
        # hang just because stop() won the race
        while inflight:
            self._complete(inflight.popleft())
        self._inflight_n = 0
        if self._held is not None:
            held, self._held = self._held, None
            self._shed(held, "shutdown")
        self._drain_shutdown()

    def _drain_shutdown(self) -> None:
        """Shed everything still queued with the promised "shutdown"
        reject. Called by the scheduler at teardown AND by ``_admit`` when
        its put raced past that final drain — deque pops are atomic, so
        concurrent drains resolve each task exactly once."""
        while True:
            try:
                task = self._queue.get_nowait()
            except queue.Empty:
                return
            self._shed(task, "shutdown")
