"""Inference serving: the SLO-aware batched on-device action server.

Reference equivalent: ``tensorpack/predict/{concurrency,common,base}.py`` —
``MultiThreadAsyncPredictor`` et al. (SURVEY.md §2.3 #10, call stack §3.3).
The N-thread, N-``Session.run`` design collapses into one continuous-
batching scheduler over a jitted forward + on-device categorical sampling:
dispatch-depth-pipelined device calls, deadline admission with typed load
shedding, and multi-policy (canary/shadow) serving — docs/serving.md.
"""

from distributed_ba3c_tpu.predict.server import BatchedPredictor, ShedReject

__all__ = ["BatchedPredictor", "ShedReject"]
