"""Inference serving: the batched on-device action server.

Reference equivalent: ``tensorpack/predict/{concurrency,common,base}.py`` —
``MultiThreadAsyncPredictor`` et al. (SURVEY.md §2.3 #10, call stack §3.3).
The N-thread, N-``Session.run`` design collapses into one jitted forward +
on-device categorical sampling; host threads only batch and dispatch.
"""

from distributed_ba3c_tpu.predict.server import BatchedPredictor

__all__ = ["BatchedPredictor"]
