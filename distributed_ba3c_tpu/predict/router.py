"""The serving router: R predictor replicas behind one SLO-driven front.

PR 9 gave ONE ``BatchedPredictor`` a latency contract (docs/serving.md);
this module is the horizontal axis of that contract (ROADMAP item 3): a
:class:`ServingRouter` spreads request blocks — the block wire's natural
unit, exactly what the masters already hand their predictor — over R
replicas, each a complete serving plane with its own scheduler thread,
admission queue and telemetry role. The IMPALA-shaped decoupling the repo
already has is what makes this safe: actors tolerate stale policies and
V-trace corrects at the MEASURED lag, so a routed request may land on any
replica at any published params version and the math stays exact.

Design:

- **Least-loaded dispatch**: the router tracks its own outstanding rows
  per replica (incremented at admit, decremented at resolve) — fresher
  than any scrape — and routes each task to the live replica with the
  least; ties go to the least-recently-dispatched so an idle plane
  round-robins instead of hammering replica 0.
- **Deadline-aware overflow**: a replica that fast-rejects (bounded
  admission queue full — the typed overload signal) does not decide the
  request's fate; the router retries the remaining live replicas in load
  order and only sheds to the caller when EVERY one refused. Deadline
  sheds are different: the replica's scheduler proved the task can't be
  served in budget anywhere (the estimate includes queue wait the task
  already paid), so they propagate without retry.
- **Health from the telemetry plane, not a new one**: per-replica
  health/latency/shed signals come from the replicas' OWN telemetry
  registries — in-process via :func:`replica_signals`, cross-process via
  :func:`http_replica_signals` over the ``--telemetry_port`` ``/json``
  scrape (the PR-7 ``http_signals`` pattern). A replica whose scrape goes
  stale is DRAINED (no new traffic; in-flight tasks keep their deadline
  semantics — drained, not blackholed) and resumes when the scrape does.
  A replica observed dead (scheduler thread gone, or scrape dead long
  enough) has its outstanding tasks re-shed with a typed
  ``replica_lost`` reject so no caller ever hangs on a corpse — the
  lockstep actor plane's masters answer those with the uniform fallback
  exactly like any other shed.
- **Router-owned canary split**: the router routes the canary fraction
  (the predictor's deficit-accumulator split, lifted one level) by
  PINNING ``policy=`` on the tasks it dispatches, so per-policy latency
  and shed series are router-attributed — the observable feed the
  :class:`~distributed_ba3c_tpu.orchestrate.serving.PromotionController`
  decides from. Replicas just serve pinned policies; their group-granular
  batching is untouched.
- **Non-blocking param fan-out**: ``update_params`` publishes through one
  :class:`~distributed_ba3c_tpu.utils.concurrency.LatestWinsPump` per
  replica — latest wins per policy, a wedged replica stalls only itself,
  and the learner's publish path never blocks (the same pump the
  multi-fleet ``FanoutPredictors`` uses).

The router duck-types ``BatchedPredictor``'s caller surface
(``put_task``/``put_block_task``/``predict_batch``/``update_params``/
``num_actions``/``start``/``stop``/``join``), so masters and the Trainer
hold "a predictor" either way. Replica LIFECYCLE (spawn/retire/autoscale)
deliberately lives one layer up, in orchestrate/serving.py's
:class:`ReplicaSet` — the router only routes.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.predict.server import ShedReject
from distributed_ba3c_tpu.utils import logger, sanitizer
from distributed_ba3c_tpu.utils.concurrency import (
    LatestWinsPump,
    StoppableThread,
)

#: replica ids are embedded in Prometheus series names
#: (``routed_<id>_rows_total``) — same grammar as policy ids
_REPLICA_ID_RE = re.compile(r"^[a-z0-9_]{1,32}$")

#: replica states. UP takes traffic; DRAINING takes none but its
#: in-flight tasks keep their deadline semantics (stale scrape — the
#: replica may well be healthy and unobservable); DEAD is terminal (its
#: outstanding tasks were re-shed; a respawn is a NEW replica id).
UP, DRAINING, DEAD = "up", "draining", "dead"


def replica_role(base: str, idx: int) -> str:
    """The canonical telemetry role for replica ``idx`` of a serving
    plane: ``predictor.r<idx>`` (dotted sub-role, so ``export_scalars``
    and the ``/json`` scrape grow per-replica series with no caller
    enumeration — the same scheme as ``fleet_role``'s ``master.f<k>``).
    Composes with fleets: fleet k's replica j serves as
    ``predictor.f<k>.r<j>``."""
    return f"{base}.r{int(idx)}"


def _histogram_quantile_s(m: dict, q: float) -> Optional[float]:
    """Upper-bound quantile from a log2-bucket histogram snapshot
    (telemetry/metrics.py collect() format). Returns seconds, or None
    when the histogram is empty. The log2 buckets make this a <=2x
    overestimate — conservative in exactly the direction an SLO health
    verdict wants."""
    count = m.get("count", 0)
    if not count:
        return None
    need = q * count
    cum = 0
    unit = m.get("unit", 1e-6)
    for i, c in enumerate(m.get("buckets", ())):
        cum += c
        if cum >= need:
            return unit * (1 << i)
    return unit * (1 << max(0, len(m.get("buckets", ())) - 1))


def signals_from_snapshot(series: Dict[str, dict]) -> Dict[str, float]:
    """One replica's health dict from its registry snapshot (the ``/json``
    document's per-role entry, or ``Registry.collect()`` in-process).
    THE single formula — the in-process and http sources must never
    disagree about what "healthy" reads like."""

    def val(name: str) -> float:
        return float(series.get(name, {}).get("value", 0.0))

    hist = series.get("serve_latency_s", {})
    p99 = _histogram_quantile_s(hist, 0.99)
    out = {
        "rows_total": val("rows_total"),
        "sheds_total": val("sheds_total"),
        "queue_depth": val("task_queue_depth"),
        "inflight": val("inflight_dispatches"),
        "serve_p99_ms": p99 * 1000.0 if p99 is not None else None,
    }
    if hist.get("buckets"):
        # the raw cumulative buckets ride along so the router can compute
        # a WINDOWED p99 (delta between health ticks) — a breach an hour
        # ago must not read as a breach now (autoscaler/rollback inputs)
        out["serve_hist"] = {
            "buckets": list(hist["buckets"]),
            "count": hist.get("count", 0),
            "unit": hist.get("unit", 1e-6),
        }
    return out


def replica_signals(predictor) -> Callable[[], Dict[str, float]]:
    """In-process signal source over a replica's own telemetry registry
    (+ the scheduler thread's liveness, which only an in-process observer
    can read directly)."""

    def scrape() -> Dict[str, float]:
        s = signals_from_snapshot(
            telemetry.registry(predictor.tele_role).collect()
        )
        threads = getattr(predictor, "threads", None)
        if threads:
            s["alive"] = float(all(t.is_alive() for t in threads))
        return s

    return scrape


def http_replica_signals(
    url: str, role: str = "predictor", timeout_s: float = 2.0
) -> Callable[[], Dict[str, float]]:
    """Signal source over a replica's ``--telemetry_port`` ``/json``
    endpoint (the PR-7 ``http_signals`` pattern, serving edition): the
    router and the replica need not share a process. A missing role fails
    LOUDLY — silence would read as a healthy idle replica and blackhole
    routed traffic onto a typo."""
    if not url.endswith("/json"):
        url = url.rstrip("/") + "/json"

    def scrape() -> Dict[str, float]:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            doc = json.loads(r.read().decode())
        series = doc.get(role)
        if series is None:
            raise KeyError(
                f"scrape target {url} exports no {role!r} registry "
                f"(roles: {sorted(doc)}) — wrong replica role, or the "
                "replica's telemetry endpoint is not up"
            )
        return signals_from_snapshot(series)

    return scrape


class _RoutedTask:
    """One request the router owns end-to-end: wraps the caller's
    callbacks so the router can account latency per policy, decrement the
    replica's outstanding load, fail queue-full rejects over to the next
    replica, and re-shed typed if the serving replica dies. ``_lock``
    arbitrates the one real race: a replica's scheduler resolving the
    task concurrently with the health loop declaring that replica dead —
    whoever flips ``_resolved`` first delivers the one outcome."""

    __slots__ = (
        "states", "k", "block", "cb", "shed_cb", "deadline", "policy",
        "trace", "t_admit", "replica_id", "_lock", "_resolved",
        "_admitting", "_sync_rej",
    )

    def __init__(self, states, k, block, cb, shed_cb, deadline, policy,
                 trace, t_admit):
        self.states = states
        self.k = k
        self.block = block
        self.cb = cb
        self.shed_cb = shed_cb
        self.deadline = deadline
        self.policy = policy
        self.trace = trace
        self.t_admit = t_admit
        self.replica_id = None
        self._lock = threading.Lock()
        self._resolved = False
        self._admitting = False
        self._sync_rej: Optional[ShedReject] = None


class _Replica:
    """One replica behind the router: dispatch target + health record."""

    __slots__ = (
        "replica_id", "predictor", "signals", "state", "outstanding",
        "outstanding_rows", "fails", "last_seen", "last_health",
        "last_dispatch_seq", "pump", "c_rows",
    )

    def __init__(self, replica_id, predictor, signals, pump, c_rows, now):
        self.replica_id = replica_id
        self.predictor = predictor
        self.signals = signals
        self.state = UP
        self.outstanding: Dict[int, _RoutedTask] = {}
        self.outstanding_rows = 0
        self.fails = 0
        self.last_seen = now
        self.last_health: Dict[str, float] = {}
        self.last_dispatch_seq = 0
        self.pump = pump
        self.c_rows = c_rows


class ServingRouter:
    """Spread request blocks over R serving replicas under one SLO.

    Parameters
    ----------
    clock: monotonic-clock callable (tests inject a fake one).
    health_interval_s: seconds between health ticks (scrape + verdicts).
    drain_after: consecutive failed scrapes before a replica drains.
    dead_after: consecutive failed scrapes before a drained replica is
        declared dead (its outstanding tasks re-shed ``replica_lost``).
        An in-process replica whose scheduler thread died is declared
        dead on the FIRST tick that sees it — the thread table does not
        flake the way a scrape can.
    tele_role: the router's own telemetry registry role.
    """

    def __init__(
        self,
        clock: Optional[Callable[[], float]] = None,
        health_interval_s: float = 0.5,
        drain_after: int = 3,
        dead_after: int = 12,
        tele_role: str = "router",
    ):
        import time as _time

        self._clock = clock or _time.monotonic
        self.health_interval_s = health_interval_s
        self.drain_after = max(1, int(drain_after))
        self.dead_after = max(self.drain_after, int(dead_after))
        self.tele_role = tele_role
        self._lock = threading.RLock()
        # replica table: read lock-free on the dispatch fast path, shape
        # changed only under _lock — BA3C_SANITIZE=1 enforces the latter
        self._replicas: Dict[str, _Replica] = sanitizer.wrap_guarded_dict(
            self._lock, "router.replicas"
        )
        self._dispatch_seq = 0
        self._canary: Optional[Tuple[str, float]] = None
        self._canary_debt = 0.0
        self._shadow: Optional[str] = None
        #: latest params per policy — what promote() republishes as
        #: default and what a grown replica is seeded from
        self._policy_params: Dict[str, object] = {}
        self._flight = telemetry.flight_recorder()
        #: optional per-request tap: ``tap(policy, latency_s, shed_reason)``
        #: with latency None on sheds — the PromotionController's exact
        #: windowed per-policy sample feed (no histogram approximation)
        self.latency_tap: Optional[Callable] = None

        tele = telemetry.registry(tele_role)
        self._tele = tele
        self._c_tasks = tele.counter("routed_tasks_total")
        self._c_rows = tele.counter("routed_rows_total")
        self._c_overflow = tele.counter("overflow_retries_total")
        self._c_exhausted = tele.counter("overflow_exhausted_total")
        self._c_no_replica = tele.counter("no_replica_sheds_total")
        self._c_lost = tele.counter("replica_lost_sheds_total")
        self._c_drains = tele.counter("replica_drains_total")
        self._c_resumes = tele.counter("replica_resumes_total")
        self._c_deaths = tele.counter("replica_deaths_total")
        self._c_publishes = tele.counter("param_publishes_total")
        self._c_pub_coalesced = tele.counter("param_publish_coalesced_total")
        self._c_pub_errors = tele.counter("param_publish_errors_total")
        self._h_policy_serve: Dict[str, object] = {}
        self._c_policy_rows: Dict[str, object] = {}
        self._c_policy_sheds: Dict[str, object] = {}
        import weakref

        ref = weakref.ref(self)
        # "registered", not "*_total": the value is a level (it falls on
        # retire), and gauges must not wear the monotonic-counter suffix
        tele.gauge(
            "replicas_registered",
            fn=lambda: len(r._replicas) if (r := ref()) else 0,
        )
        tele.gauge(
            "replicas_live", fn=lambda: r.live_count() if (r := ref()) else 0
        )
        # aggregate deltas the autoscaler watermarks on, recomputed by the
        # health loop from per-replica scrapes (docs/observability.md)
        self._agg: Dict[str, float] = {}
        # per-replica histogram state for the windowed-p99 deltas; the
        # fleet (rows, sheds) totals live in their own slot — a replica
        # legally named "all" must not clobber them
        self._agg_last: Dict[str, Tuple[list, int]] = {}
        self._agg_totals: Optional[Tuple[float, float]] = None
        self._health_thread = StoppableThread(
            target=self._health_loop, daemon=True, name="router-health"
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._health_thread.start()

    def stop(self) -> None:
        self._health_thread.stop()
        with self._lock:
            reps = list(self._replicas.values())
        for r in reps:
            r.pump.stop()
        # the router started these threads, so it joins them: a bounded
        # shared deadline, not per-pump, so a fleet of wedged applies
        # cannot stretch shutdown to R * timeout (ba3cflow F5)
        deadline = time.monotonic() + 5.0
        for r in reps:
            r.pump.join(timeout=max(0.0, deadline - time.monotonic()))
        # a router wired by cli.py owns its ReplicaSet's teardown (the
        # startables list holds ONE handle for the whole routed plane)
        rs = getattr(self, "replica_set", None)
        if rs is not None:
            rs.close()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._health_thread.is_alive():
            self._health_thread.join(timeout)

    # -- replica table -----------------------------------------------------
    def add_replica(
        self, replica_id: str, predictor, signals=None
    ) -> None:
        """Register one replica (already started by its owner —
        orchestrate/serving.py's ReplicaSet). ``signals`` defaults to the
        in-process source over the replica's own registry. The replica is
        seeded with every policy the router knows, so a grown replica
        serves the same table as its peers before the first task lands."""
        if not _REPLICA_ID_RE.match(replica_id):
            raise ValueError(
                f"replica id {replica_id!r} must match "
                f"{_REPLICA_ID_RE.pattern} (it names Prometheus series)"
            )
        if signals is None:
            signals = replica_signals(predictor)
        pump = LatestWinsPump(
            apply=lambda policy, params, _p=predictor: _p.update_params(
                params, policy=policy
            ),
            name=f"router-pub-{replica_id}",
            on_coalesce=self._c_pub_coalesced.inc,
            on_error=lambda e, _r=replica_id: self._publish_error(_r, e),
        )
        # snapshot the policy table under the lock but seed OUTSIDE it:
        # add_policy reaches jax.device_put (seconds under first-touch
        # compile), and self._lock gates every dispatch and the health
        # loop — a slow device must not wedge the whole routing plane
        with self._lock:
            if replica_id in self._replicas:
                raise ValueError(f"replica {replica_id!r} already registered")
            seeded = dict(self._policy_params)
            shadow = self._shadow
        for pid, params in seeded.items():
            # synchronous seed: traffic may pin this policy the moment
            # the replica is routable
            predictor.add_policy(pid, params)
        if shadow is not None:
            predictor.set_shadow(shadow)
        c_rows = self._tele.counter(f"routed_{replica_id}_rows_total")
        with self._lock:
            if replica_id in self._replicas:
                raise ValueError(f"replica {replica_id!r} already registered")
            self._replicas[replica_id] = _Replica(
                replica_id, predictor, signals, pump, c_rows, self._clock()
            )
            latest = dict(self._policy_params)
        pump.start()
        # catch-up: a policy added or promoted between the seed snapshot
        # and the table insert missed both the synchronous seed and the
        # table-wide fan-out — publish it through the pump (latest wins)
        for pid, params in latest.items():
            if pid not in seeded or seeded[pid] is not params:
                pump.publish(pid, params)
        self._flight.record("replica_added", replica=replica_id)

    def _publish_error(self, replica_id: str, e: Exception) -> None:
        # a replica whose publishes fail serves a FROZEN policy table —
        # counted, flight-recorded AND logged (the async pump must not
        # turn a loud failure into a silent counter tick)
        self._c_pub_errors.inc()
        self._flight.record(
            "router_publish_error", replica=replica_id, error=repr(e)
        )
        logger.error(
            "param publish to replica %s FAILED (it serves a stale "
            "policy until a publish succeeds): %r", replica_id, e,
        )

    def remove_replica(self, replica_id: str):
        """Retire a replica from routing (scale-down / replacement): no
        new traffic; its in-flight tasks keep their deadline semantics.
        Returns the predictor so the OWNER can drain-then-stop it
        (ReplicaSet._retire) — the router never stops what it never
        started."""
        with self._lock:
            rep = self._replicas.pop(replica_id, None)
            self._agg_last.pop(replica_id, None)
        if rep is None:
            raise KeyError(f"unknown replica {replica_id!r}")
        rep.pump.stop()
        # bounded join: the pump thread must be dead before the caller
        # drains/stops the predictor, or a late publish races teardown
        rep.pump.join(timeout=2.0)
        self._flight.record(
            "replica_retired", replica=replica_id,
            outstanding_rows=rep.outstanding_rows,
        )
        return rep.predictor

    def replica_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    def replica_states(self) -> Dict[str, str]:
        with self._lock:
            return {rid: r.state for rid, r in self._replicas.items()}

    def live_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values() if r.state == UP)

    def outstanding_rows(self, replica_id: Optional[str] = None) -> int:
        with self._lock:
            if replica_id is not None:
                rep = self._replicas.get(replica_id)
                return rep.outstanding_rows if rep is not None else 0
            return sum(r.outstanding_rows for r in self._replicas.values())

    # -- predictor facade (policy table + sync path) -----------------------
    @property
    def num_actions(self) -> int:
        with self._lock:
            for rep in self._replicas.values():
                return rep.predictor.num_actions
        return 0

    def add_policy(self, policy_id: str, params) -> None:
        """Make a checkpoint hot on EVERY replica (synchronous: traffic
        may pin the policy the moment this returns)."""
        with self._lock:
            reps = list(self._replicas.values())
            self._policy_params[policy_id] = params
        for rep in reps:
            rep.predictor.add_policy(policy_id, params)

    def set_canary(self, policy_id: Optional[str], fraction: float = 0.0) -> None:
        """Route ``fraction`` of un-pinned traffic to ``policy_id`` —
        the deficit split at ROUTER granularity, so per-policy latency
        and shed series are router-attributed (the promotion
        controller's evidence). ``None``/0 clears."""
        with self._lock:
            if policy_id is None or fraction <= 0:
                self._canary = None
                return
            if not 0 < fraction <= 1:
                raise ValueError(
                    f"canary fraction {fraction} not in (0, 1]"
                )
            if policy_id not in self._policy_params:
                raise KeyError(
                    f"unknown policy {policy_id!r} — add_policy first"
                )
            self._canary = (policy_id, float(fraction))

    def canary(self) -> Optional[Tuple[str, float]]:
        with self._lock:
            return self._canary

    def set_shadow(self, policy_id: Optional[str]) -> None:
        """Mirror served batches through ``policy_id`` on EVERY replica
        (each replica shadows its own traffic locally — the mirror never
        crosses the router). Replicas added later inherit it."""
        with self._lock:
            if policy_id is not None and policy_id not in self._policy_params:
                raise KeyError(
                    f"unknown policy {policy_id!r} — add_policy first"
                )
            self._shadow = policy_id
            reps = list(self._replicas.values())
        for rep in reps:
            rep.predictor.set_shadow(policy_id)

    def warmup(self, state_shape, dtype=None) -> None:
        """Precompile every replica's serving buckets (fans out the
        predictor's warmup contract; ReplicaSet-grown replicas warm at
        spawn via its ``warm`` hook instead)."""
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if dtype is None:
                rep.predictor.warmup(state_shape)
            else:
                rep.predictor.warmup(state_shape, dtype)

    def promote(self, policy_id: str) -> None:
        """The canary wins: its params BECOME the default on every
        replica (published through the pumps — a wedged replica converges
        when it unwedges, latest wins) and the canary split clears."""
        with self._lock:
            params = self._policy_params.get(policy_id)
            if params is None:
                raise KeyError(
                    f"unknown policy {policy_id!r} — nothing to promote"
                )
            self._canary = None
            self._policy_params["default"] = params
            reps = list(self._replicas.values())
        for rep in reps:
            rep.pump.publish("default", params)
        self._c_publishes.inc()

    def update_params(self, params, policy: str = "default") -> None:
        """Publish fresh weights to every replica, WITHOUT blocking the
        caller: one latest-wins pump per replica, so a wedged replica
        stalls only itself and the learner's publish cadence never
        couples to the slowest serving plane."""
        with self._lock:
            self._policy_params[policy] = params
            reps = list(self._replicas.values())
        for rep in reps:
            rep.pump.publish(policy, params)
        self._c_publishes.inc()

    def flush_params(self, timeout: float = 5.0) -> bool:
        """Barrier: every publish so far applied on every live replica
        (tests/teardown; returns False if some replica stayed wedged)."""
        with self._lock:
            pumps = [r.pump for r in self._replicas.values()]
        ok = True
        for p in pumps:
            ok = p.flush(timeout) and ok
        return ok

    def predict_batch(self, states):
        """Synchronous batched predict (the Evaluator path): served by
        the least-loaded live replica — every replica serves the same
        default policy after any publish."""
        rep = self._pick(None)
        if rep is None:
            raise RuntimeError("no live serving replica for predict_batch")
        return rep.predictor.predict_batch(states)

    # -- the routed dispatch path ------------------------------------------
    def put_task(self, state, callback, *, deadline=None, policy=None,
                 shed_callback=None, trace=None) -> bool:
        return self._route(
            _RoutedTask(state, 1, False, callback, shed_callback, deadline,
                        policy, trace, self._clock())
        )

    def put_block_task(self, states, callback, *, deadline=None, policy=None,
                       shed_callback=None, trace=None) -> bool:
        return self._route(
            _RoutedTask(states, int(states.shape[0]), True, callback,
                        shed_callback, deadline, policy, trace, self._clock())
        )

    def _route_policy(self, weight: int) -> Optional[str]:
        """The router-level deficit split (callers' thread, under lock)."""
        c = self._canary
        if c is None:
            return None
        pid, frac = c
        self._canary_debt += frac * weight
        if self._canary_debt >= weight:
            self._canary_debt -= weight
            return pid
        return None

    def _pick(self, exclude: Optional[set]) -> Optional[_Replica]:
        with self._lock:
            cands = [
                r for r in self._replicas.values()
                if r.state == UP
                and (exclude is None or r.replica_id not in exclude)
            ]
            if not cands:
                return None
            rep = min(
                cands,
                key=lambda r: (r.outstanding_rows, r.last_dispatch_seq),
            )
            self._dispatch_seq += 1
            rep.last_dispatch_seq = self._dispatch_seq
            return rep

    def _route(self, task: _RoutedTask) -> bool:
        if task.policy is None:
            with self._lock:
                task.policy = self._route_policy(task.k)
        tried: set = set()
        last_rej: Optional[ShedReject] = None
        while True:
            rep = self._pick(tried)
            if rep is None:
                break
            tried.add(rep.replica_id)
            if self._try_admit(rep, task):
                return True
            with task._lock:
                if task._resolved:
                    # a death sweep raced the failed admit and already
                    # delivered the typed shed — re-admitting a resolved
                    # task would register rows no resolution ever releases
                    return False
            # the replica fast-rejected (bounded queue full / shutting
            # down): the OVERFLOW path — the next-least-loaded replica
            # gets the task before the caller hears anything
            self._c_overflow.inc()
            last_rej = task._sync_rej
            task._sync_rej = None  # ba3cflow: disable=F1 — single-threaded window: a sync fast-reject means _admitting already dropped, so no shed callback can race this clear
        # nobody could take it: deliver ONE typed reject
        if last_rej is not None:
            self._c_exhausted.inc()
            rej = last_rej
        else:
            self._c_no_replica.inc()
            rej = ShedReject("no_replica", task.deadline, self._clock())
        self._resolve_shed(task, rej, None)
        return False

    def _try_admit(self, rep: _Replica, task: _RoutedTask) -> bool:
        """One admission attempt against one replica. The replica's
        synchronous fast-reject (put returns False, shed fired inline) is
        captured — NOT forwarded — so the router can overflow; any
        asynchronous shed after a successful put resolves normally."""
        token = id(task)
        with self._lock:
            rep.outstanding_rows += task.k
            rep.outstanding[token] = task
            task.replica_id = rep.replica_id
        with task._lock:
            task._admitting = True

        def done_cb(*args):
            self._resolve_done(rep, task, args)

        def shed_cb(rej):
            self._on_replica_shed(rep, task, rej)

        put = (
            rep.predictor.put_block_task if task.block
            else rep.predictor.put_task
        )
        try:
            ok = put(
                task.states, done_cb,
                deadline=task.deadline, policy=task.policy,
                shed_callback=shed_cb, trace=task.trace,
            )
        except BaseException:
            # a RAISING put (unknown policy, oversize block) propagates
            # to the caller — roll the registration back first, or the
            # leaked outstanding rows repel least-loaded dispatch forever
            # and a later _mark_dead sweep would double-deliver a shed to
            # a caller who already saw the exception
            with task._lock:
                task._admitting = False
                task._resolved = True
            with self._lock:
                if rep.outstanding.pop(token, None) is not None:
                    rep.outstanding_rows -= task.k
            raise
        with task._lock:
            task._admitting = False
            sync_rej = task._sync_rej
        if ok:
            self._c_tasks.inc()
            self._c_rows.inc(task.k)
            rep.c_rows.inc(task.k)
            self._policy_rows_counter(task.policy).inc(task.k)
            if rep.state == DEAD:
                # the health loop declared this replica dead BETWEEN pick
                # and put: its orphan sweep may have run before our
                # registration, so deliver the typed loss ourselves —
                # _resolved makes the delivery exactly-once either way
                if self._resolve_shed(
                    task,
                    ShedReject("replica_lost", task.deadline, self._clock()),
                    rep,
                ):
                    self._c_lost.inc(task.k)
                return True
            if sync_rej is not None:
                # an ASYNC shed raced the admit return (scheduler popped
                # and shed before we flipped _admitting) — deliver it now,
                # exactly once
                self._resolve_shed(task, sync_rej, rep)
            return True
        with self._lock:
            # guarded like _deregister: a concurrent _mark_dead may have
            # already swept this registration (and zeroed the counter) —
            # an unconditional decrement would drive it negative forever
            if rep.outstanding.pop(token, None) is not None:
                rep.outstanding_rows -= task.k
        return False

    def _on_replica_shed(self, rep: _Replica, task: _RoutedTask, rej) -> None:
        with task._lock:
            if task._admitting:
                # synchronous fast-reject: stash for the overflow loop
                task._sync_rej = rej
                return
        self._resolve_shed(task, rej, rep)

    def _resolve_done(self, rep: _Replica, task: _RoutedTask, args) -> None:
        with task._lock:
            already = task._resolved
            task._resolved = True
        if already:
            # the health loop already re-shed it (lost race) — the one
            # outcome was delivered, but OUR registration (an overflow
            # re-admit on a second replica) must still be released or its
            # outstanding rows repel least-loaded dispatch forever
            self._deregister(rep, task)
            return
        self._deregister(rep, task)
        lat = self._clock() - task.t_admit
        self._policy_serve_hist(task.policy).observe(lat)
        tap = self.latency_tap
        if tap is not None:
            try:
                tap(task.policy or "default", lat, None)
            except Exception:
                pass
        if task.cb is not None:
            task.cb(*args)

    def _resolve_shed(
        self, task: _RoutedTask, rej, rep: Optional[_Replica]
    ) -> bool:
        with task._lock:
            already = task._resolved
            task._resolved = True
        self._deregister(rep, task)  # idempotent — see _resolve_done
        if already:
            return False
        self._finish_shed(task, rej)
        return True

    def _deregister(self, rep: Optional[_Replica], task: _RoutedTask) -> None:
        if rep is None:
            return
        with self._lock:
            if rep.outstanding.pop(id(task), None) is not None:
                rep.outstanding_rows -= task.k

    def _health_loop(self) -> None:
        t = threading.current_thread()
        while not t.stopped():
            try:
                self.health_tick()
            except Exception as e:
                logger.warn("router health tick failed: %s", e)
            t._stop_evt.wait(self.health_interval_s)

    def health_tick(self) -> None:
        """One health pass (public so tests and the bench drive it
        deterministically): scrape every replica, flip states, re-shed
        the dead, recompute the autoscaler's aggregate."""
        with self._lock:
            reps = list(self._replicas.values())
        now = self._clock()
        for rep in reps:
            if rep.state == DEAD:
                continue
            health = None
            try:
                health = rep.signals()
            except Exception:
                rep.fails += 1
            if health is not None:
                rep.last_health = health
                rep.last_seen = now
                rep.fails = 0
                if health.get("alive", 1.0) < 1.0:
                    self._mark_dead(rep, "scheduler thread died")
                    continue
                if rep.state == DRAINING:
                    rep.state = UP
                    self._c_resumes.inc()
                    self._flight.record(
                        "replica_resume", replica=rep.replica_id
                    )
                    logger.info(
                        "serving replica %s scrape recovered — resumed",
                        rep.replica_id,
                    )
            else:
                if rep.fails >= self.dead_after:
                    self._mark_dead(
                        rep, f"scrape dead x{rep.fails}"
                    )
                elif rep.fails >= self.drain_after and rep.state == UP:
                    rep.state = DRAINING
                    self._c_drains.inc()
                    self._flight.record(
                        "replica_drain", replica=rep.replica_id,
                        fails=rep.fails,
                    )
                    logger.warn(
                        "serving replica %s scrape stale x%d — draining "
                        "(in-flight deadlines still honored)",
                        rep.replica_id, rep.fails,
                    )
        self._recompute_aggregate(reps)

    def _mark_dead(self, rep: _Replica, why: str) -> None:
        with self._lock:
            if rep.state == DEAD:
                return
            rep.state = DEAD
            orphans = list(rep.outstanding.values())
            rep.outstanding.clear()
            rep.outstanding_rows = 0
        self._c_deaths.inc()
        self._flight.record(
            "replica_dead", replica=rep.replica_id, why=why,
            orphaned_tasks=len(orphans),
        )
        logger.error(
            "serving replica %s DEAD (%s) — re-shedding %d outstanding "
            "tasks typed", rep.replica_id, why, len(orphans),
        )
        now = self._clock()
        for task in orphans:
            with task._lock:
                if task._resolved:
                    # the replica's scheduler resolved it in the same
                    # instant we declared the replica dead — its outcome
                    # was already delivered, exactly once
                    continue
                task._resolved = True
            self._c_lost.inc(task.k)
            self._finish_shed(
                task, ShedReject("replica_lost", task.deadline, now)
            )

    def _finish_shed(self, task: _RoutedTask, rej) -> None:
        self._policy_sheds_counter(task.policy).inc(task.k)
        tap = self.latency_tap
        if tap is not None:
            try:
                tap(task.policy or "default", None, rej.reason)
            except Exception:
                pass
        if task.shed_cb is not None:
            task.shed_cb(rej)

    # -- per-policy series -------------------------------------------------
    def _policy_serve_hist(self, policy: Optional[str]):
        pid = policy or "default"
        h = self._h_policy_serve.get(pid)
        if h is None:
            self._h_policy_serve[pid] = h = self._tele.histogram(
                f"policy_{pid}_serve_latency_s", unit=1e-6
            )
        return h

    def _policy_rows_counter(self, policy: Optional[str]):
        pid = policy or "default"
        c = self._c_policy_rows.get(pid)
        if c is None:
            self._c_policy_rows[pid] = c = self._tele.counter(
                f"policy_{pid}_rows_total"
            )
        return c

    def _policy_sheds_counter(self, policy: Optional[str]):
        pid = policy or "default"
        c = self._c_policy_sheds.get(pid)
        if c is None:
            self._c_policy_sheds[pid] = c = self._tele.counter(
                f"policy_{pid}_sheds_total"
            )
        return c

    def policy_health(self, policy: str) -> Dict[str, float]:
        """Router-attributed per-policy evidence (the promotion
        controller's scrape): routed rows, sheds, and the p99 of the
        router-side serve latency."""
        snap = self._tele.collect()
        p99 = _histogram_quantile_s(
            snap.get(f"policy_{policy}_serve_latency_s", {}), 0.99
        )
        return {
            "rows": float(
                snap.get(f"policy_{policy}_rows_total", {}).get("value", 0.0)
            ),
            "sheds": float(
                snap.get(f"policy_{policy}_sheds_total", {}).get("value", 0.0)
            ),
            "p99_ms": p99 * 1000.0 if p99 is not None else None,
        }

    # -- the autoscaler's aggregate ----------------------------------------
    def _recompute_aggregate(self, reps: List[_Replica]) -> None:
        live = [r for r in reps if r.state == UP]
        # windowed fleet p99: per-replica histogram DELTAS since the last
        # tick, summed across live replicas — "what latency did the plane
        # serve THIS window", not "has it ever been slow"
        win_buckets: List[int] = []
        win_count = 0
        unit = 1e-6
        with self._lock:
            prev_last = dict(self._agg_last)
        new_last: Dict[str, Tuple[list, int]] = {}
        for r in live:
            hist = r.last_health.get("serve_hist")
            if not hist:
                continue
            prev = prev_last.get(r.replica_id, ([], 0))[0]
            cur = hist["buckets"]
            delta = [
                max(0, c - (prev[i] if i < len(prev) else 0))
                for i, c in enumerate(cur)
            ]
            new_last[r.replica_id] = (list(cur), hist["count"])
            unit = hist.get("unit", unit)
            if len(delta) > len(win_buckets):
                win_buckets.extend([0] * (len(delta) - len(win_buckets)))
            for i, c in enumerate(delta):
                win_buckets[i] += c
                win_count += c
        p99 = _histogram_quantile_s(
            {"buckets": win_buckets, "count": win_count, "unit": unit}, 0.99
        )
        rows = sum(r.last_health.get("rows_total", 0.0) for r in live)
        sheds = sum(r.last_health.get("sheds_total", 0.0) for r in live)
        last_rows, last_sheds = self._agg_totals or (rows, sheds)
        d_rows = max(0.0, rows - last_rows)
        d_sheds = max(0.0, sheds - last_sheds)
        self._agg_totals = (rows, sheds)
        total = d_rows + d_sheds
        with self._lock:
            # _agg_last writes happen under the lock remove_replica pops
            # it under, and only for replicas still in the table — a
            # concurrent removal mid-tick must not resurrect its entry
            for rid, entry in new_last.items():
                if rid in self._replicas:
                    self._agg_last[rid] = entry
            self._agg = {
                "replicas_live": float(len(live)),
                "replicas_registered": float(len(reps)),
                "served_p99_ms": p99 * 1000.0 if p99 is not None else None,
                "shed_rate": (d_sheds / total) if total > 0 else 0.0,
                "outstanding_rows": float(
                    sum(r.outstanding_rows for r in reps)
                ),
            }

    def aggregate_signals(self) -> Dict[str, float]:
        """The serving autoscaler's watermark inputs, recomputed each
        health tick: worst live-replica p99, fleet-wide shed-rate delta,
        live/total replica counts, router-known outstanding rows."""
        with self._lock:
            return dict(self._agg)
