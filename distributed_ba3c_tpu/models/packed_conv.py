"""Lane-packed convolution: fold spatial positions into MXU output lanes.

Status: built to test the hypothesis that the BA3C net's 32-output-channel
convs underfill the MXU's 128 output lanes. The A/B on v5e came back
NEUTRAL (fwd 3.74 vs 3.66 us/sample — XLA's conv emitter already packs
lanes, and the net is HBM-roofline-bound; PERF.md "tested and disproved").
Kept as exact, gradient-tested infrastructure for backends where the GEMM
shape does bind; default OFF (``BA3CNet.conv_pack``). The reformulation:
a stride-1 SAME conv becomes an equivalent strided conv computing P
adjacent output columns per window:

    out[y, P*j+dx, c] = sum_{ky,kx,ci} xpad[y+ky, P*j+dx+kx, ci] * W[ky,kx,ci,c]

Build W'[ky, kx', ci, dx*C+c] = W[ky, kx'-dx, ci, c] (zero outside), then

    out' = conv(xpad, W', window (kh, kw+P-1), strides (1, P), VALID)

has P*C output channels; reshaping [B, H, W/P, P, C] -> [B, H, W, C]
recovers the exact stride-1 result. Cost: (kw+P-1)/kw more MACs for P-fold
higher nominal lane occupancy — which the v5e A/B showed does NOT
translate into time saved there (see Status above). Everything is
differentiable jnp/lax, so the backward pass inherits the packing through
XLA's conv transposes.

Parameter names/shapes match ``flax.linen.Conv`` ('kernel' [kh,kw,cin,cout],
'bias' [cout]) — checkpoints are interchangeable with the plain layer.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


def _pack_kernel(w: jax.Array, pack: int) -> jax.Array:
    """[kh, kw, ci, co] -> [kh, kw+pack-1, ci, pack*co] shifted-stack."""
    parts = [
        jnp.pad(w, ((0, 0), (dx, pack - 1 - dx), (0, 0), (0, 0)))
        for dx in range(pack)
    ]
    return jnp.concatenate(parts, axis=-1)


def packed_conv_same(
    x: jax.Array, w: jax.Array, pack: int
) -> jax.Array:
    """Stride-1 SAME conv [B,H,W,Ci] x [kh,kw,Ci,Co] via lane packing.

    Requires W % pack == 0 and odd kernel sizes (SAME centering).
    """
    kh, kw, _, co = w.shape
    B, H, W, _ = x.shape
    assert W % pack == 0, (W, pack)
    ph, pw = kh // 2, kw // 2
    xpad = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    wp = _pack_kernel(w, pack)
    out = lax.conv_general_dilated(
        xpad,
        wp,
        window_strides=(1, pack),
        padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=x.dtype,
    )
    # [B, H, W/pack, pack*co] -> [B, H, W, co]
    return out.reshape(B, H, W // pack, pack, co).reshape(B, H, W, co)


class PackedConv(nn.Module):
    """Drop-in for ``nn.Conv(features, (k,k), SAME)`` with lane packing.

    Falls back to the plain conv when the input width is not divisible by
    ``pack`` (or pack==1), so the module is always correct.
    """

    features: int
    kernel_size: int
    pack: int = 4
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        k = self.kernel_size
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (k, k, x.shape[-1], self.features),
            self.param_dtype,
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.features,), self.param_dtype
        )
        x = x.astype(self.dtype)
        w = kernel.astype(self.dtype)
        if self.pack > 1 and x.shape[2] % self.pack == 0:
            y = packed_conv_same(x, w, self.pack)
        else:
            y = lax.conv_general_dilated(
                x,
                w,
                window_strides=(1, 1),
                padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        return y + bias.astype(self.dtype)
