"""Reusable layers mirroring the reference's model zoo where flax lacks them.

Reference equivalent: ``tensorpack/models/nonlin.py`` (PReLU) and friends
(SURVEY.md §2.6 #17). Conv/Dense/Pooling come from flax.linen directly — we do
not re-wrap what the library already expresses idiomatically.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class PReLU(nn.Module):
    """Parametric ReLU with a single learnable slope (tensorpack default).

    tensorpack's ``PReLU`` initialises alpha to 0.001 and shares it across the
    whole activation map; we keep that so the flagship model matches the
    reference architecture knob-for-knob.
    """

    init_alpha: float = 0.001

    @nn.compact
    def __call__(self, x):
        alpha = self.param(
            "alpha", lambda _key, shape: jnp.full(shape, self.init_alpha, jnp.float32), ()
        )
        alpha = alpha.astype(x.dtype)
        return jnp.where(x >= 0, x, alpha * x)
