"""Model library: the BA3C policy/value convnet and reusable layers.

Reference equivalent: ``tensorpack/models/*.py`` layer registry + the concrete
``Model(ModelDesc)`` in ``src/train.py`` (SURVEY.md §2.1 #2, §2.6 #17).
"""

from distributed_ba3c_tpu.models.a3c import BA3CNet, PolicyValue
from distributed_ba3c_tpu.models.layers import PReLU

__all__ = ["BA3CNet", "PolicyValue", "PReLU"]
