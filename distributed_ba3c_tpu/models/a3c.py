"""The BA3C policy/value convnet.

Reference equivalent: ``Model._build_graph`` in ``src/train.py`` (SURVEY.md
§2.1 #2) — the Tensorpack train-atari architecture:

    input uint8 [B, 84, 84, FRAME_HISTORY] / 255
    Conv 32@5x5 -> MaxPool 2 -> Conv 32@5x5 -> MaxPool 2
    Conv 64@4x4 -> MaxPool 2 -> Conv 64@3x3
    FC 512 + PReLU
    -> policy logits [B, A]    (FC A)
    -> value [B]               (FC 1)

TPU-native design decisions:
- NHWC layout, bfloat16 compute / float32 params (MXU-friendly; convs at these
  sizes map onto the MXU as implicit GEMMs).
- uint8 states cross the host->device boundary; the /255 cast happens on
  device, so PCIe/ICI traffic is 1 byte per pixel (the reference ships uint8
  over ZMQ for the same reason).
- One module serves both the learner (value+logits) and the actor serving path
  (vmapped under jit in predict/server.py).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_ba3c_tpu.models.layers import PReLU


class PolicyValue(NamedTuple):
    logits: jax.Array  # [B, A] float32
    value: jax.Array   # [B] float32


def conv_layout(model: "BA3CNet") -> Tuple[Tuple[int, int, bool], ...]:
    """The conv stack's (features, kernel, pooled) triples — the ONE
    layout description shared by :meth:`BA3CNet.__call__` and the
    quantized mirror forward (distributed_ba3c_tpu/quantize/), so the
    int8 program can never drift from the f32 architecture it
    quantizes."""
    return tuple(
        zip(
            model.conv_features,
            model.conv_kernels,
            model.pooled_layers,
            strict=True,
        )
    )


def _conv_spec(x: jax.Array, features: int, k: int, pooled: bool):
    """The ONE ConvSpec construction shared by the gate and the executed
    block, so they can never diverge (ops/pallas_conv.py)."""
    from distributed_ba3c_tpu.ops.pallas_conv import ConvSpec

    return ConvSpec(
        H=x.shape[1], W=x.shape[2], Ci=x.shape[3], Co=features,
        kh=k, kw=k, pool=pooled, scale_uint8=False,
    )


class _PallasConvBlock(nn.Module):
    """conv+bias+relu(+2x2 maxpool) as one fused Pallas kernel.

    Param names/shapes match ``nn.Conv`` ('kernel' [k,k,ci,co], 'bias'
    [co]); interpret mode is selected automatically off-TPU so tests run
    on the CPU backend.
    """

    spec: object  # ConvSpec (static)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from distributed_ba3c_tpu.ops.pallas_conv import conv_block

        s = self.spec
        B = x.shape[0]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (s.kh, s.kw, s.Ci, s.Co), jnp.float32,
        )
        bias = self.param("bias", nn.initializers.zeros, (s.Co,), jnp.float32)
        y = conv_block(
            x.astype(jnp.bfloat16).reshape(B, s.H, s.W * s.Ci),
            kernel, bias, s,
            jax.default_backend() != "tpu",
        )
        return y.reshape(B, s.Ho, s.Wo, s.Co)


class BA3CNet(nn.Module):
    """Policy/value network with the reference's conv stack."""

    num_actions: int
    fc_units: int = 512
    conv_features: Sequence[int] = (32, 32, 64, 64)
    conv_kernels: Sequence[int] = (5, 5, 4, 3)
    # maxpool after first 3 conv layers, as in the reference stack
    pooled_layers: Tuple[bool, ...] = (True, True, True, False)
    compute_dtype: jnp.dtype = jnp.bfloat16
    # lane-packing factor per conv layer (models/packed_conv.py). MEASURED
    # NEUTRAL on v5e (PERF.md: the net is HBM-roofline-bound, and XLA's conv
    # emitter already packs output lanes) — kept as tested infrastructure
    # for backends where the GEMM shape does bind. 0/1 = plain nn.Conv.
    # Numerically EXACT either way (value- and gradient-tested).
    conv_pack: Tuple[int, ...] = (0, 0, 0, 0)
    # "xla" (default) or "pallas": fused Pallas conv+relu+pool blocks where
    # the geometry allows (ops/pallas_conv.py — blocks whose P*Ci is a
    # 128-multiple, i.e. the 32/64-channel layers; conv0's Ci=4 cannot).
    # MEASURED SLOWER on the v5e (patch-assembly relayout outweighs the 4x
    # MXU lane-occupancy win — PERF.md), so the default stays XLA; kept as
    # value- and gradient-tested kernel infrastructure. Checkpoints are
    # interchangeable (same param names/shapes).
    conv_backend: str = "xla"

    @nn.compact
    def __call__(self, state: jax.Array) -> PolicyValue:
        """state: [B, H, W, C] uint8 (or float already scaled)."""
        if state.dtype == jnp.uint8:
            x = state.astype(self.compute_dtype) / 255.0
        else:
            x = state.astype(self.compute_dtype)

        for i, ((feats, k, pooled), pack) in enumerate(
            zip(conv_layout(self), self.conv_pack, strict=True)
        ):
            # explicit name "Conv_i" for ALL branches: PackedConv and
            # _PallasConvBlock own nn.Conv-shaped params, so checkpoints
            # stay interchangeable between configurations
            # the Pallas block is bf16-only; any other compute dtype must
            # use the XLA path to honor the requested precision
            if self.conv_backend == "pallas" and self.compute_dtype == jnp.bfloat16:
                from distributed_ba3c_tpu.ops.pallas_conv import supported

                spec = _conv_spec(x, feats, k, pooled)
                if supported(spec):
                    x = _PallasConvBlock(spec=spec, name=f"Conv_{i}")(x)
                    continue  # relu+pool fused inside the block
            if pack and pack > 1:
                from distributed_ba3c_tpu.models.packed_conv import PackedConv

                x = PackedConv(
                    features=feats,
                    kernel_size=k,
                    pack=pack,
                    dtype=self.compute_dtype,
                    param_dtype=jnp.float32,
                    name=f"Conv_{i}",
                )(x)
            else:
                x = nn.Conv(
                    features=feats,
                    kernel_size=(k, k),
                    padding="SAME",
                    dtype=self.compute_dtype,
                    param_dtype=jnp.float32,
                    name=f"Conv_{i}",
                )(x)
            x = nn.relu(x)
            if pooled:
                x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))

        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.fc_units, dtype=self.compute_dtype, param_dtype=jnp.float32)(x)
        x = PReLU()(x)

        logits = nn.Dense(
            self.num_actions, dtype=jnp.float32, param_dtype=jnp.float32
        )(x.astype(jnp.float32))
        value = nn.Dense(1, dtype=jnp.float32, param_dtype=jnp.float32)(
            x.astype(jnp.float32)
        )[:, 0]
        return PolicyValue(logits=logits, value=value)
