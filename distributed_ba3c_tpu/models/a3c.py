"""The BA3C policy/value convnet.

Reference equivalent: ``Model._build_graph`` in ``src/train.py`` (SURVEY.md
§2.1 #2) — the Tensorpack train-atari architecture:

    input uint8 [B, 84, 84, FRAME_HISTORY] / 255
    Conv 32@5x5 -> MaxPool 2 -> Conv 32@5x5 -> MaxPool 2
    Conv 64@4x4 -> MaxPool 2 -> Conv 64@3x3
    FC 512 + PReLU
    -> policy logits [B, A]    (FC A)
    -> value [B]               (FC 1)

TPU-native design decisions:
- NHWC layout, bfloat16 compute / float32 params (MXU-friendly; convs at these
  sizes map onto the MXU as implicit GEMMs).
- uint8 states cross the host->device boundary; the /255 cast happens on
  device, so PCIe/ICI traffic is 1 byte per pixel (the reference ships uint8
  over ZMQ for the same reason).
- One module serves both the learner (value+logits) and the actor serving path
  (vmapped under jit in predict/server.py).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_ba3c_tpu.models.layers import PReLU


class PolicyValue(NamedTuple):
    logits: jax.Array  # [B, A] float32
    value: jax.Array   # [B] float32


class BA3CNet(nn.Module):
    """Policy/value network with the reference's conv stack."""

    num_actions: int
    fc_units: int = 512
    conv_features: Sequence[int] = (32, 32, 64, 64)
    conv_kernels: Sequence[int] = (5, 5, 4, 3)
    # maxpool after first 3 conv layers, as in the reference stack
    pooled_layers: Tuple[bool, ...] = (True, True, True, False)
    compute_dtype: jnp.dtype = jnp.bfloat16
    # lane-packing factor per conv layer (models/packed_conv.py). MEASURED
    # NEUTRAL on v5e (PERF.md: the net is HBM-roofline-bound, and XLA's conv
    # emitter already packs output lanes) — kept as tested infrastructure
    # for backends where the GEMM shape does bind. 0/1 = plain nn.Conv.
    # Numerically EXACT either way (value- and gradient-tested).
    conv_pack: Tuple[int, ...] = (0, 0, 0, 0)

    @nn.compact
    def __call__(self, state: jax.Array) -> PolicyValue:
        """state: [B, H, W, C] uint8 (or float already scaled)."""
        if state.dtype == jnp.uint8:
            x = state.astype(self.compute_dtype) / 255.0
        else:
            x = state.astype(self.compute_dtype)

        for i, (feats, k, pooled, pack) in enumerate(
            zip(
                self.conv_features,
                self.conv_kernels,
                self.pooled_layers,
                self.conv_pack,
                strict=True,
            )
        ):
            # explicit name "Conv_i" for BOTH branches: PackedConv owns
            # nn.Conv-shaped params, so checkpoints stay interchangeable
            # between packed and plain configurations
            if pack and pack > 1:
                from distributed_ba3c_tpu.models.packed_conv import PackedConv

                x = PackedConv(
                    features=feats,
                    kernel_size=k,
                    pack=pack,
                    dtype=self.compute_dtype,
                    param_dtype=jnp.float32,
                    name=f"Conv_{i}",
                )(x)
            else:
                x = nn.Conv(
                    features=feats,
                    kernel_size=(k, k),
                    padding="SAME",
                    dtype=self.compute_dtype,
                    param_dtype=jnp.float32,
                    name=f"Conv_{i}",
                )(x)
            x = nn.relu(x)
            if pooled:
                x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))

        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.fc_units, dtype=self.compute_dtype, param_dtype=jnp.float32)(x)
        x = PReLU()(x)

        logits = nn.Dense(
            self.num_actions, dtype=jnp.float32, param_dtype=jnp.float32
        )(x.astype(jnp.float32))
        value = nn.Dense(1, dtype=jnp.float32, param_dtype=jnp.float32)(
            x.astype(jnp.float32)
        )[:, 0]
        return PolicyValue(logits=logits, value=value)
