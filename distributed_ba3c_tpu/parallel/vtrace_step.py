"""V-trace (IMPALA-style) train step over rollout batches.

BASELINE.json config #4: "BA3C + V-trace off-policy correction under
actor/learner lag". The reference tolerated actor/learner staleness silently
(async PS updates, SURVEY.md §3.4); the synchronous TPU learner corrects it
explicitly with clipped importance weights (ops/vtrace.py).

Batch layout (time-major, matching the reverse scan):
    state:              [T, B, H, W, C] uint8
    action:             [T, B] int32
    reward:             [T, B] float32
    done:               [T, B] float32/bool
    behavior_log_probs: [T, B] float32  (log mu(a|s) recorded by the actor)
    bootstrap_state:    [B, H, W, C] uint8 (s_T for the value bootstrap)

Sharding: batch axis B over the mesh's data axis; the model forward runs on
[T*B] flattened states so the convs see one large MXU-friendly batch.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ba3c_tpu.audit import tripwire_jit
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import grad_summaries
from distributed_ba3c_tpu.ops.vtrace import vtrace_returns
from distributed_ba3c_tpu.parallel.mesh import (
    DATA_AXIS,
    axis_size,
    grad_allreduce,
    shard_map,
)
from distributed_ba3c_tpu.parallel.train_step import (
    TrainState,
    apply_grads,
    macro_accumulate,
)


def _make_vtrace_loss_fn(
    model: BA3CNet,
    cfg: BA3CConfig,
    batch: Dict[str, jax.Array],
    entropy_beta: jax.Array,
):
    """The per-(sub-)batch V-trace loss closure — ONE definition shared by
    the single step and the multi-fleet macro step (the macro step must
    optimize exactly the single step's objective, sub-batch by sub-batch;
    V-trace couples TIME within an env column but never envs, so equal-size
    sub-batch gradient means equal the full-batch gradient)."""
    T, B = batch["action"].shape

    def loss_fn(params):
        # one big forward over T*B + B states (conv batch stays MXU-sized)
        flat = batch["state"].reshape((T * B, *batch["state"].shape[2:]))
        all_states = jnp.concatenate([flat, batch["bootstrap_state"]], axis=0)
        out = model.apply({"params": params}, all_states)
        logits = out.logits[: T * B].reshape((T, B, -1))
        values = out.value[: T * B].reshape((T, B))
        bootstrap_value = out.value[T * B :]

        log_probs = jax.nn.log_softmax(logits, axis=-1)
        probs = jax.nn.softmax(logits, axis=-1)
        target_lp = jnp.take_along_axis(
            log_probs, batch["action"][..., None].astype(jnp.int32), axis=-1
        )[..., 0]

        vt = vtrace_returns(
            behaviour_log_probs=batch["behavior_log_probs"],
            target_log_probs=jax.lax.stop_gradient(target_lp),
            rewards=batch["reward"],
            dones=batch["done"],
            values=jax.lax.stop_gradient(values),
            bootstrap_value=jax.lax.stop_gradient(bootstrap_value),
            gamma=cfg.gamma,
        )

        policy_loss = -jnp.mean(target_lp * vt.pg_advantages)
        value_loss = 0.5 * jnp.mean(jnp.square(values - vt.vs))
        entropy = -jnp.mean(jnp.sum(probs * log_probs, axis=-1))
        total = (
            policy_loss
            + cfg.value_loss_coef * value_loss
            - entropy_beta * entropy
        )
        aux = {
            "loss": total,
            "policy_loss": policy_loss,
            "value_loss": value_loss,
            "entropy": entropy,
            "mean_rho": jnp.mean(vt.clipped_rhos),
            "pred_value": jnp.mean(values),
        }
        return total, aux

    return loss_fn


def _local_step(
    model: BA3CNet,
    optimizer: optax.GradientTransformation,
    cfg: BA3CConfig,
    state: TrainState,
    batch: Dict[str, jax.Array],
    entropy_beta: jax.Array,
    learning_rate: jax.Array,
) -> Tuple[TrainState, Dict[str, jax.Array]]:
    loss_fn = _make_vtrace_loss_fn(model, cfg, batch, entropy_beta)
    (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
    grads = grad_allreduce(grads, DATA_AXIS)
    n_data = axis_size(DATA_AXIS)
    grads = jax.tree_util.tree_map(lambda g: g / n_data, grads)

    new_state = apply_grads(optimizer, state, grads, learning_rate)
    metrics = {**aux, **grad_summaries(grads)}
    metrics = {k: jax.lax.pmean(v, DATA_AXIS) for k, v in metrics.items()}
    return new_state, metrics


def make_vtrace_train_step(
    model: BA3CNet,
    optimizer: optax.GradientTransformation,
    cfg: BA3CConfig,
    mesh: Mesh,
) -> Callable:
    """Jitted mesh-sharded V-trace step: fn(state, batch, beta, lr)."""
    replicated = P()
    specs = {
        "state": P(None, DATA_AXIS),
        "action": P(None, DATA_AXIS),
        "reward": P(None, DATA_AXIS),
        "done": P(None, DATA_AXIS),
        "behavior_log_probs": P(None, DATA_AXIS),
        "bootstrap_state": P(DATA_AXIS),
    }
    body = functools.partial(_local_step, model, optimizer, cfg)
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(replicated, specs, replicated, replicated),
        out_specs=(replicated, replicated),
    )
    # registered audit entry point (distributed_ba3c_tpu/audit.py)
    jitted = tripwire_jit("parallel.vtrace_step", sharded, donate_argnums=(0,))

    def step(state, batch, entropy_beta, learning_rate=None):
        if learning_rate is None:
            learning_rate = cfg.learning_rate
        return jitted(
            state,
            batch,
            jnp.asarray(entropy_beta, jnp.float32),
            jnp.asarray(learning_rate, jnp.float32),
        )

    step.batch_sharding = {
        k: NamedSharding(mesh, s) for k, s in specs.items()
    }
    step.state_sharding = NamedSharding(mesh, replicated)
    step.mesh = mesh
    step.audit_jit = jitted  # tools/ba3caudit traces THIS program
    return step


def make_vtrace_macro_step(
    model: BA3CNet,
    optimizer: optax.GradientTransformation,
    cfg: BA3CConfig,
    mesh: Mesh,
    n_fleets: int,
) -> Callable:
    """The multi-fleet V-trace macro step: N fleet sub-batches, ONE update.

    Batch layout: every make_vtrace_train_step leaf gains a leading FLEET
    axis (``state [K, T, B, ...]``, ``bootstrap_state [K, B, ...]``, ...)
    and the FLEET axis shards over the mesh's data axis — whole fleets to
    chips, never ``B/D`` slivers, so each chip's fwd+bwd runs the full
    per-fleet unroll batch (docs/actor_plane.md). Chips hosting several
    fleets accumulate sequentially (parallel/train_step.py
    macro_accumulate); ONE gradient psum means over every fleet. V-trace
    couples time within an env column but never envs, so the accumulated
    mean equals the ``[T, K*B]`` full-batch gradient to fp tolerance
    (tests/test_fleet.py pins it).

    Registered audit entry: ``parallel.vtrace_macro_step``.
    """
    if n_fleets < 1:
        raise ValueError(f"n_fleets must be >= 1, got {n_fleets}")
    n_data = mesh.shape[DATA_AXIS]
    if n_fleets % n_data:
        raise ValueError(
            f"n_fleets {n_fleets} must be divisible by the mesh data axis "
            f"{n_data}: fleets shard fleet-major over chips (whole "
            "sub-batches, never slivers)"
        )
    n_local = n_fleets // n_data

    def local_macro_step(state, batch, entropy_beta, learning_rate):
        def loss_grad_one(params, sub):
            loss_fn = _make_vtrace_loss_fn(model, cfg, sub, entropy_beta)
            return jax.value_and_grad(loss_fn, has_aux=True)(params)

        grads, aux = macro_accumulate(
            loss_grad_one, state.params, batch, n_local
        )
        # ONE collective for the whole macro batch (T3 census unchanged)
        grads = grad_allreduce(grads, DATA_AXIS)
        grads = jax.tree_util.tree_map(lambda g: g / n_data, grads)
        new_state = apply_grads(optimizer, state, grads, learning_rate)
        metrics = {**aux, **grad_summaries(grads)}
        metrics = {k: jax.lax.pmean(v, DATA_AXIS) for k, v in metrics.items()}
        return new_state, metrics

    replicated = P()
    fleet_spec = P(DATA_AXIS)  # leading = FLEET axis on every leaf
    specs = {
        "state": fleet_spec,
        "action": fleet_spec,
        "reward": fleet_spec,
        "done": fleet_spec,
        "behavior_log_probs": fleet_spec,
        "bootstrap_state": fleet_spec,
    }
    sharded = shard_map(
        local_macro_step,
        mesh=mesh,
        in_specs=(replicated, specs, replicated, replicated),
        out_specs=(replicated, replicated),
    )
    # registered audit entry point (distributed_ba3c_tpu/audit.py)
    jitted = tripwire_jit(
        "parallel.vtrace_macro_step", sharded, donate_argnums=(0,)
    )

    def step(state, batch, entropy_beta, learning_rate=None):
        if learning_rate is None:
            learning_rate = cfg.learning_rate
        return jitted(
            state,
            batch,
            jnp.asarray(entropy_beta, jnp.float32),
            jnp.asarray(learning_rate, jnp.float32),
        )

    step.batch_sharding = {
        k: NamedSharding(mesh, s) for k, s in specs.items()
    }
    step.state_sharding = NamedSharding(mesh, replicated)
    step.mesh = mesh
    step.n_fleets = n_fleets
    step.audit_jit = jitted  # tools/ba3caudit traces THIS program
    return step
