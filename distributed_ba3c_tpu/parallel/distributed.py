"""Multi-host bootstrap: DCN-coordinated mesh over pod slices.

Reference equivalent (SURVEY.md §2.5 #15, §2.12): ``tf.train.ClusterSpec`` +
``tf.train.Server`` + ``replica_device_setter`` — host:port lists wiring an
async parameter-server gradient plane over gRPC. TPU-native replacement:
``jax.distributed.initialize`` bootstraps all hosts over DCN, every host sees
the global device set, and the SAME mesh/shard_map code compiles into
programs whose collectives ride ICI within a slice and DCN across slices —
no separate code path, no parameter servers.

The reference's CLI surface maps directly:
    --worker_hosts h1:p,h2:p --task_index k
        -> initialize(coordinator=h1:p, num_processes=len(hosts), process_id=k)
    --ps_hosts  -> obsolete (accepted, ignored; cli.py prints why)
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from distributed_ba3c_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from distributed_ba3c_tpu.utils import logger


def initialize_from_flags(
    worker_hosts: str, task_index: int, coordinator_port: Optional[int] = None
) -> bool:
    """Bootstrap jax.distributed from reference-style flags.

    ``worker_hosts`` is the comma-separated host:port list every worker gets
    (identically ordered); ``task_index`` is this worker's rank. Returns True
    if distributed mode was initialized, False for single-host (empty list or
    a single entry).
    """
    hosts = [h for h in worker_hosts.split(",") if h]
    if len(hosts) <= 1:
        return False
    coordinator = hosts[0]
    if coordinator_port is not None:
        coordinator = f"{coordinator.split(':')[0]}:{coordinator_port}"
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=len(hosts),
        process_id=task_index,
    )
    logger.info(
        "jax.distributed up: process %d/%d, %d global devices (%d local)",
        task_index,
        len(hosts),
        len(jax.devices()),
        len(jax.local_devices()),
    )
    return True


def make_global_mesh(
    num_model: int = 1, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Data-parallel mesh over ALL hosts' devices.

    Device order groups each host's local devices contiguously, so the data
    axis's psum segments ride ICI within a host/slice and only the cross-host
    hop uses DCN (the axis is laid out host-major).
    """
    devices = list(devices if devices is not None else jax.devices())
    devices.sort(key=lambda d: (d.process_index, d.id))
    num_data = len(devices) // num_model
    arr = np.asarray(devices).reshape(num_data, num_model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def is_chief() -> bool:
    """Chief == process 0 (the reference's chief-worker saver/summary role)."""
    return jax.process_index() == 0


def local_batch_slice(global_batch: int) -> slice:
    """The rows of a host-major global batch this process should feed.

    Multi-host data loading contract: every host feeds its own actors and
    device_puts only its slice of the global batch; jax assembles the global
    sharded array from per-host shards.
    """
    n = jax.process_count()
    assert global_batch % n == 0, (global_batch, n)
    per = global_batch // n
    k = jax.process_index()
    return slice(k * per, (k + 1) * per)
