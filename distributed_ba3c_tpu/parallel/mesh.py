"""Device-mesh construction.

The reference's notion of topology is a ``tf.train.ClusterSpec`` of ps/worker
host:port strings (SURVEY.md §2.5 #15). The TPU-native equivalent is a
``jax.sharding.Mesh`` over the slice's devices; collectives ride ICI inside a
slice and DCN across hosts, chosen by XLA from the sharding — no address lists.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Axis names used throughout the framework.
DATA_AXIS = "data"    # batch / gradient data-parallel axis (the only one BA3C needs)
MODEL_AXIS = "model"  # reserved for tensor-parallel shardings of larger models

# shard_map moved from jax.experimental to the jax namespace (jax >= 0.6);
# every step builder imports THIS symbol so the repo runs on both. The call
# sites only use the (f, mesh=, in_specs=, out_specs=) surface, which is
# identical across the move.
try:
    shard_map = jax.shard_map
except AttributeError:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map_experimental

    def shard_map(f, **kwargs):
        # The old check_rep machinery cannot infer the transpose-inserted
        # psum for replicated params that the new check_vma semantics
        # handle, and rejects the step's P() out_specs. check_rep=False
        # ALSO disables that automatic psum, leaving grads shard-local —
        # grad_allreduce (below) compensates with an explicit psum on this
        # path, and test_sharded_step_matches_single_device pins the
        # combined numerics.
        kwargs.setdefault("check_rep", False)
        return _shard_map_experimental(f, **kwargs)


def axis_size(name: str):
    """``jax.lax.axis_size`` with a fallback for jax <= 0.4.x, where the
    mesh-axis size inside shard_map is obtained by summing 1 over the axis
    (constant-folded by XLA — no runtime collective)."""
    try:
        return jax.lax.axis_size(name)
    except AttributeError:
        return jax.lax.psum(1, name)


#: On jax >= 0.6 the check_vma transpose auto-inserts the psum for grads of
#: replicated params, so the step bodies receive grads already SUMMED over
#: the data axis. On jax <= 0.4.x we run shard_map with check_rep=False
#: (see above), which disables that insertion — the sum must be explicit.
_NEEDS_EXPLICIT_GRAD_PSUM = not hasattr(jax, "shard_map")


def to_varying(x, axis: str = DATA_AXIS):
    """Mark ``x`` device-varying over ``axis`` under the check_vma machinery
    (jax >= 0.6: ``jax.typeof(...).vma`` + ``jax.lax.pcast``). Identity on
    jax <= 0.4.x, where check_rep=False tracks no rep types — constants in
    scan carries need no marking there."""
    try:
        typeof = jax.typeof
        pcast = jax.lax.pcast
    except AttributeError:
        return x
    if axis in getattr(typeof(x), "vma", frozenset()):
        return x  # already varying (e.g. key-derived fields)
    return pcast(x, (axis,), to="varying")


def grad_allreduce(grads, axis: str = DATA_AXIS):
    """Make ``grads`` the axis-SUMMED gradients on every jax version.

    Identity where the shard_map transpose already summed them (new jax);
    an explicit ``psum`` where check_rep=False left them shard-local (old
    jax). Callers divide by :func:`axis_size` afterwards for the mean —
    numerical parity is pinned by test_sharded_step_matches_single_device.
    """
    if _NEEDS_EXPLICIT_GRAD_PSUM:
        return jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, axis), grads
        )
    return grads


def make_mesh(
    num_data: Optional[int] = None,
    num_model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data[, model]) mesh over the available devices.

    Defaults to a 1-D data-parallel mesh over every addressable device — the
    BA3C workload is pure DP (SURVEY.md §2.11: TP/PP/SP/EP are absent in the
    reference by construction; the model is a tiny convnet).
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_data is None:
        num_data = len(devices) // num_model
    if num_data * num_model != len(devices):
        raise ValueError(
            f"mesh {num_data}x{num_model} does not cover {len(devices)} devices"
        )
    dev_array = np.asarray(devices).reshape(num_data, num_model)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))
