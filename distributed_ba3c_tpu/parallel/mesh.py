"""Device-mesh construction.

The reference's notion of topology is a ``tf.train.ClusterSpec`` of ps/worker
host:port strings (SURVEY.md §2.5 #15). The TPU-native equivalent is a
``jax.sharding.Mesh`` over the slice's devices; collectives ride ICI inside a
slice and DCN across hosts, chosen by XLA from the sharding — no address lists.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Axis names used throughout the framework.
DATA_AXIS = "data"    # batch / gradient data-parallel axis (the only one BA3C needs)
MODEL_AXIS = "model"  # reserved for tensor-parallel shardings of larger models


def make_mesh(
    num_data: Optional[int] = None,
    num_model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data[, model]) mesh over the available devices.

    Defaults to a 1-D data-parallel mesh over every addressable device — the
    BA3C workload is pure DP (SURVEY.md §2.11: TP/PP/SP/EP are absent in the
    reference by construction; the model is a tiny convnet).
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_data is None:
        num_data = len(devices) // num_model
    if num_data * num_model != len(devices):
        raise ValueError(
            f"mesh {num_data}x{num_model} does not cover {len(devices)} devices"
        )
    dev_array = np.asarray(devices).reshape(num_data, num_model)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))
