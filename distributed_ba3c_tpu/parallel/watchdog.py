"""Lockstep liveness watchdog for the multi-host gradient plane.

Failure model (SURVEY.md §5 failure detection — the reference had NONE on
its gRPC parameter-server plane; this plane defines the semantics instead of
inheriting an undefined hang): every rank of a multi-host fused run executes
the same jitted program in lockstep, synchronized by the psum inside the
update and by collective orbax saves. When ONE rank dies (OOM-kill, host
loss, SIGKILL), every survivor blocks forever inside the next collective —
the Python loop cannot observe the stall from inside, because dispatches are
async and the block happens in the runtime.

So detection is out-of-band: a daemon thread armed with a deadline. The
training loop calls ``beat()`` at every point of PROVEN global progress —
the epoch's metrics fetch (which forces the dispatch window's collectives
to completion), the end of the greedy eval, and the collective save — so
the timeout bounds one *window*, not a whole epoch: a long 128-episode
eval no longer counts against the compute window's budget (VERDICT r4
weak #4). If no beat lands within the limit, the
watchdog logs the diagnosis and hard-exits the process with code 75
(EX_TEMPFAIL: transient, retry-able). ``os._exit`` is deliberate — the main
thread is wedged in a collective and cannot unwind; a clean shutdown is
impossible by construction.

Recovery contract: every rank exits nonzero within ``timeout_s`` of the
failure; the launcher relaunches the job with ``--load <shared ckpt dir>``
and the run CONTINUES its schedule (fused resume derives the epoch from the
restored step). Proven end-to-end by ``tests/test_rank_failure.py``, which
SIGKILLs one of two ranks mid-soak and then completes the run by resuming.
"""

from __future__ import annotations

import os
import threading
import time

from distributed_ba3c_tpu.utils import logger

EXIT_CODE = 75  # EX_TEMPFAIL: lockstep lost, relaunch with --load to resume
DEFAULT_TIMEOUT_S = 600.0


def resolve_timeout(configured: float) -> float:
    """The one place the arming policy lives: multi-host runs get
    ``configured`` seconds (or the 600s default when 0/unset); a NEGATIVE
    value (``--rank_stall_timeout -1``) disables the watchdog even
    multi-host — for runs whose steady-state windows legitimately exceed
    any sane bound. Single-host runs get 0 (disabled — the external stall
    launcher owns that case)."""
    import jax

    if jax.process_count() <= 1:
        return 0.0
    configured = float(configured)
    if configured < 0:
        return 0.0
    return configured if configured > 0 else DEFAULT_TIMEOUT_S


class LockstepWatchdog:
    """Hard-exit the process if ``beat()`` stalls for ``timeout_s``.

    Use as a context manager around the epoch loop; ``beat()`` at every
    proven-progress point (metrics fetch, eval end, save end).
    ``timeout_s`` must exceed the slowest single WINDOW between beats (the
    3x first-beat grace covers the first compile; the observed-interval
    margin raises the limit for runs whose healthy windows creep past it)
    — it bounds failure DETECTION latency, not epoch time.
    """

    #: effective limit grows to MARGIN x the slowest healthy beat interval
    #: ever observed — a run whose windows legitimately creep past the
    #: configured bound raises its own limit instead of suiciding. The
    #: ratchet is capped at ``first_timeout_s`` (3x the configured bound by
    #: default): without a cap each healthy window may be up to the current
    #: limit, compounding it geometrically, and a gradually degrading run
    #: would never be detected. Detection latency is therefore bounded by
    #: max(timeout_s, first_timeout_s) at all times.
    MARGIN = 2.0

    def __init__(
        self,
        timeout_s: float,
        what: str = "multi-host lockstep",
        first_timeout_s: float | None = None,
    ):
        self.timeout_s = float(timeout_s)
        # the FIRST epoch includes the XLA compile (tens of seconds); before
        # the first beat the deadline is therefore more generous, or a
        # healthy rank would suicide mid-compile
        self.first_timeout_s = (
            float(first_timeout_s)
            if first_timeout_s is not None
            else 3.0 * self.timeout_s
        )
        self.what = what
        self._last = time.monotonic()
        self._beaten = False
        self._graced = False
        self._derived_limit = self.timeout_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def grace(self) -> None:
        """Arm the generous pre-first-beat deadline for the NEXT window.

        Call before a known compile-heavy section that lands mid-run — the
        first greedy eval's jit, which the 3x first-beat grace does not
        cover (a step-function window there would otherwise 75-loop every
        relaunch straight back into the same compile). The graced window is
        excluded from the derived-limit ratchet: 2x a compile would weaken
        all later detection."""
        self._last = time.monotonic()
        self._graced = True

    def beat(self) -> None:
        now = time.monotonic()
        # timeout_s == 0 means disarmed (no watcher thread): skip the
        # derived-limit bookkeeping and its log lines entirely
        if self._beaten and not self._graced and self.timeout_s > 0:
            # cap the ratchet at the first-beat grace: each healthy window
            # can otherwise be up to the CURRENT limit, compounding the
            # limit geometrically — a gradually degrading run would never
            # be detected, and detection latency must stay bounded
            derived = min(self.MARGIN * (now - self._last), self.first_timeout_s)
            if derived > self._derived_limit:
                self._derived_limit = derived
                if derived > self.timeout_s:
                    logger.info(
                        "%s: slowest healthy window %.0fs — stall limit "
                        "raised to %.0fs (%.1fx margin; configured %.0fs, "
                        "cap %.0fs)",
                        self.what, now - self._last, derived,
                        self.MARGIN, self.timeout_s, self.first_timeout_s,
                    )
        self._last = now
        self._beaten = True
        self._graced = False

    def _watch(self) -> None:
        while not self._stop.wait(min(self.timeout_s / 4, 5.0)):
            limit = (
                max(self.timeout_s, self._derived_limit)
                if self._beaten and not self._graced
                # graced/pre-first-beat windows must never be TIGHTER than
                # what a normal window has already earned via the ratchet
                else max(self.first_timeout_s, self._derived_limit)
            )
            stalled = time.monotonic() - self._last
            if stalled > limit:
                logger.error(
                    "%s stalled %.0fs (> %.0fs limit): a peer rank likely "
                    "died — this rank is blocked in a collective and cannot "
                    "recover in-place. Exiting %d; relaunch all ranks with "
                    "--load on the shared checkpoint dir to resume.",
                    self.what, stalled, limit, EXIT_CODE,
                )
                try:
                    # postmortem before the hard exit: os._exit skips every
                    # atexit/finally, so this is the run's LAST chance to
                    # leave evidence (telemetry/recorder.py)
                    from distributed_ba3c_tpu import telemetry

                    telemetry.record(
                        "watchdog", what=self.what,
                        stalled_s=round(stalled, 1), limit_s=round(limit, 1),
                    )
                    telemetry.dump("watchdog kill")
                except Exception:
                    pass  # the exit must happen regardless
                # flush logs before the hard exit
                for h in getattr(logger._LOGGER, "handlers", []):
                    try:
                        h.flush()
                    except Exception:
                        pass
                os._exit(EXIT_CODE)

    def __enter__(self) -> "LockstepWatchdog":
        if self.timeout_s > 0:
            # deliberately a bare thread: its loop IS an Event.wait on
            # self._stop (set in __exit__, joined below) — a StoppableThread
            # would just duplicate that event
            self._thread = threading.Thread(  # ba3clint: disable=A1
                target=self._watch, name="lockstep-watchdog", daemon=True
            )
            self._last = time.monotonic()
            self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
