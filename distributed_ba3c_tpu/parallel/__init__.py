"""Parallel execution: device meshes, the sync data-parallel train step, multi-host.

This package replaces the reference's entire distributed-execution layer
(SURVEY.md §2.5): ``AsyncMultiGPUTrainer``'s lock-free threads and the TF
parameter-server/gRPC gradient plane both collapse into one jitted synchronous
update whose per-device gradients meet in a single ``lax.psum`` over the ICI
mesh (BASELINE.json north_star). There is no parameter server: params live
replicated in HBM.
"""

from distributed_ba3c_tpu.parallel.mesh import make_mesh, DATA_AXIS, MODEL_AXIS
from distributed_ba3c_tpu.parallel.train_step import (
    TrainState,
    create_train_state,
    make_train_step,
)

__all__ = [
    "make_mesh",
    "DATA_AXIS",
    "MODEL_AXIS",
    "TrainState",
    "create_train_state",
    "make_train_step",
]
