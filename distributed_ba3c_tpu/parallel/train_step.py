"""The synchronous data-parallel BA3C train step.

Replaces, wholesale (SURVEY.md §3.4): the reference's
``sess.run(train_op)`` → per-variable async gradient push to parameter servers
over gRPC. Here: each device computes gradients on its batch shard, a single
``lax.psum`` averages them over the ICI ``data`` axis, and every device applies
the identical Adam update to its replicated params. One jitted computation, no
staleness, no PS.

Sharding layout:
  params/opt_state: replicated (PartitionSpec())
  batch:            sharded on the leading axis (PartitionSpec('data'))
The step is expressed with ``jax.shard_map`` so the collective is explicit and
the compiled module is identical regardless of host count (multi-host just
widens the mesh; see parallel/distributed.py).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ba3c_tpu.audit import tripwire_jit
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import grad_summaries, inject_learning_rate
from distributed_ba3c_tpu.ops.loss import a3c_loss
from distributed_ba3c_tpu.parallel.mesh import (
    DATA_AXIS,
    axis_size,
    grad_allreduce,
    shard_map,
)


class TrainState(struct.PyTreeNode):
    """Learner state: params + optimizer state + step counter.

    Reference equivalent: the TF variables living on parameter servers plus the
    global_step (SURVEY.md §2.5). Replicated across the mesh.
    """

    step: jax.Array
    params: Any
    opt_state: Any


def create_train_state(
    rng: jax.Array,
    model: BA3CNet,
    cfg: BA3CConfig,
    optimizer: optax.GradientTransformation,
) -> TrainState:
    dummy = jnp.zeros((1, *cfg.state_shape), jnp.uint8)
    params = model.init(rng, dummy)["params"]
    opt_state = optimizer.init(params)
    if inject_learning_rate(opt_state, 0.0) is opt_state:
        from distributed_ba3c_tpu.utils import logger

        logger.warn(
            "optimizer has no injectable learning_rate leaf — runtime LR "
            "schedules (ScheduledHyperParamSetter etc.) will be SILENT no-ops;"
            " build it with ops.gradproc.make_optimizer"
        )
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)


def _make_loss_fn(
    model: BA3CNet,
    cfg: BA3CConfig,
    batch: Dict[str, jax.Array],
    entropy_beta: jax.Array,
):
    """The per-(sub-)batch A3C loss closure — ONE definition shared by the
    single step and the multi-fleet macro step (parity between the two is a
    contract, not luck: the macro step must optimize exactly the objective
    the single step does, sub-batch by sub-batch)."""

    def loss_fn(params):
        out = model.apply({"params": params}, batch["state"])
        loss = a3c_loss(
            out.logits,
            out.value,
            batch["action"],
            batch["return"],
            entropy_beta=entropy_beta,
            value_loss_coef=cfg.value_loss_coef,
            huber_delta=cfg.value_huber_delta,
        )
        return loss.total, loss

    return loss_fn


def apply_grads(
    optimizer: optax.GradientTransformation,
    state: TrainState,
    grads,
    learning_rate: jax.Array,
) -> TrainState:
    """Shared tail of every learner step: LR injection + Adam + step bump."""
    opt_state = inject_learning_rate(state.opt_state, learning_rate)
    updates, new_opt_state = optimizer.update(grads, opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    return TrainState(
        step=state.step + 1, params=new_params, opt_state=new_opt_state
    )


def _local_step(
    model: BA3CNet,
    optimizer: optax.GradientTransformation,
    cfg: BA3CConfig,
    state: TrainState,
    batch: Dict[str, jax.Array],
    entropy_beta: jax.Array,
    learning_rate: jax.Array,
) -> Tuple[TrainState, Dict[str, jax.Array]]:
    """Per-device shard-local step body; runs inside shard_map."""

    loss_fn = _make_loss_fn(model, cfg, batch, entropy_beta)
    (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)

    # The one collective that replaces the reference's whole PS gradient plane.
    # Under shard_map's check_vma=True semantics the transpose auto-inserts the
    # psum for the replicated params (grads arrive device-invariant, SUMMED over
    # the data axis); dividing by the axis size yields the global batch mean.
    # (An explicit lax.pmean here would double-count by the axis size;
    # grad_allreduce is identity there and psums only on old-jax check_rep=False.)
    grads = grad_allreduce(grads, DATA_AXIS)
    n_data = axis_size(DATA_AXIS)
    grads = jax.tree_util.tree_map(lambda g: g / n_data, grads)

    new_state = apply_grads(optimizer, state, grads, learning_rate)

    metrics = {
        "loss": aux.total,
        "policy_loss": aux.policy_loss,
        "value_loss": aux.value_loss,
        "entropy": aux.entropy,
        "advantage": aux.advantage,
        "pred_value": aux.pred_value,
        **grad_summaries(grads),
    }
    metrics = {k: jax.lax.pmean(v, DATA_AXIS) for k, v in metrics.items()}
    return new_state, metrics


def make_train_step(
    model: BA3CNet,
    optimizer: optax.GradientTransformation,
    cfg: BA3CConfig,
    mesh: Mesh,
) -> Callable[[TrainState, Dict[str, jax.Array], jax.Array], Tuple[TrainState, Dict[str, jax.Array]]]:
    """Build the jitted, mesh-sharded train step.

    Returns fn(state, batch, entropy_beta) -> (state, metrics) with donated
    state buffers. ``batch`` leading dim must be divisible by the mesh's data
    axis size.
    """
    replicated = P()
    batch_spec = P(DATA_AXIS)

    body = functools.partial(_local_step, model, optimizer, cfg)
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(replicated, batch_spec, replicated, replicated),
        out_specs=(replicated, replicated),
    )

    # registered audit entry point (distributed_ba3c_tpu/audit.py): under
    # BA3C_AUDIT=1 a post-warmup retrace raises instead of silently stalling
    jitted = tripwire_jit("parallel.train_step", sharded, donate_argnums=(0,))

    def step(state, batch, entropy_beta, learning_rate=None):
        if learning_rate is None:
            learning_rate = cfg.learning_rate
        return jitted(
            state,
            batch,
            jnp.asarray(entropy_beta, jnp.float32),
            jnp.asarray(learning_rate, jnp.float32),
        )

    # expose shardings so callers can device_put batches asynchronously
    step.batch_sharding = NamedSharding(mesh, batch_spec)
    step.state_sharding = NamedSharding(mesh, replicated)
    step.mesh = mesh
    step.audit_jit = jitted  # tools/ba3caudit traces THIS program
    return step


def macro_accumulate(loss_grad_one, params, batch, n_local: int):
    """Mean of per-sub-batch (grads, aux) over the local fleet axis.

    ``batch`` leaves are ``[n_local, ...]`` (this shard's fleets);
    ``loss_grad_one(params, sub)`` returns ``((loss, aux), grads)``. The
    accumulation is a ``lax.scan`` over fleets — ONE fwd+bwd program
    reused per sub-batch, activations bounded to a single sub-batch (the
    whole point: every sub-batch runs at its full per-chip occupancy
    instead of a 1/K sliver). Mean-of-equal-size-sub-batch grads equals
    the full-macro-batch gradient; tests/test_fleet.py pins it to fp
    tolerance against the single step on the concatenated batch.

    Shared by the BA3C and V-trace macro steps — the accumulation
    schedule (first sub-batch unrolled, rest scanned, symmetric mean) is
    one definition, same idiom as the fused learner's chunk accumulation
    (fused/loop.py).
    """
    first = jax.tree_util.tree_map(lambda x: x[0], batch)
    (_, aux0), g0 = loss_grad_one(params, first)
    if n_local == 1:
        return g0, aux0

    def acc_body(carry, sub):
        g_acc, aux_acc = carry
        (_, aux), g = loss_grad_one(params, sub)
        g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
        aux_acc = jax.tree_util.tree_map(jnp.add, aux_acc, aux)
        return (g_acc, aux_acc), None

    rest = jax.tree_util.tree_map(lambda x: x[1:], batch)
    (grads, aux_sum), _ = jax.lax.scan(acc_body, (g0, aux0), rest)
    grads = jax.tree_util.tree_map(lambda g: g / n_local, grads)
    aux = jax.tree_util.tree_map(lambda a: a / n_local, aux_sum)
    return grads, aux


def make_macro_train_step(
    model: BA3CNet,
    optimizer: optax.GradientTransformation,
    cfg: BA3CConfig,
    mesh: Mesh,
    n_fleets: int,
) -> Callable:
    """The multi-fleet macro step: N fleet sub-batches, ONE update.

    Batch layout (vs make_train_step's flat ``[B]`` leaves): every leaf
    gains a leading FLEET axis — ``state [K, B, ...]``, ``action [K, B]``,
    ``return [K, B]`` — and it is the FLEET axis that shards over the
    mesh's data axis. That inversion is the macro-batching contract
    (docs/actor_plane.md): a data-parallel deployment assigns whole fleets
    to chips, so each chip's fwd+bwd runs at the full per-fleet batch ``B``
    (the recipe batch) instead of the ``B/D`` sliver that wastes the MXU
    (PERF.md's 65.6k -> ~38k shard ladder). Chips hosting several fleets
    (K > D) accumulate their sub-batch gradients sequentially; the one
    gradient psum then means over every fleet — mathematically the
    ``[K*B]`` full-batch update, structurally K full-occupancy programs.

    Registered audit entry: ``parallel.train_macro_step``.
    """
    if n_fleets < 1:
        raise ValueError(f"n_fleets must be >= 1, got {n_fleets}")
    n_data = mesh.shape[DATA_AXIS]
    if n_fleets % n_data:
        raise ValueError(
            f"n_fleets {n_fleets} must be divisible by the mesh data axis "
            f"{n_data}: fleets shard fleet-major over chips (whole "
            "sub-batches, never slivers)"
        )
    n_local = n_fleets // n_data

    def local_macro_step(state, batch, entropy_beta, learning_rate):
        def loss_grad_one(params, sub):
            loss_fn = _make_loss_fn(model, cfg, sub, entropy_beta)
            return jax.value_and_grad(loss_fn, has_aux=True)(params)

        grads, aux = macro_accumulate(
            loss_grad_one, state.params, batch, n_local
        )
        # ONE collective for the whole macro batch (T3 census unchanged):
        # the psum sums over the data axis, the divide completes the mean
        # over all K fleets
        grads = grad_allreduce(grads, DATA_AXIS)
        grads = jax.tree_util.tree_map(lambda g: g / n_data, grads)
        new_state = apply_grads(optimizer, state, grads, learning_rate)
        metrics = {
            "loss": aux.total,
            "policy_loss": aux.policy_loss,
            "value_loss": aux.value_loss,
            "entropy": aux.entropy,
            "advantage": aux.advantage,
            "pred_value": aux.pred_value,
            **grad_summaries(grads),
        }
        metrics = {k: jax.lax.pmean(v, DATA_AXIS) for k, v in metrics.items()}
        return new_state, metrics

    replicated = P()
    batch_spec = P(DATA_AXIS)  # leading = FLEET axis
    sharded = shard_map(
        local_macro_step,
        mesh=mesh,
        in_specs=(replicated, batch_spec, replicated, replicated),
        out_specs=(replicated, replicated),
    )
    # registered audit entry point (distributed_ba3c_tpu/audit.py)
    jitted = tripwire_jit(
        "parallel.train_macro_step", sharded, donate_argnums=(0,)
    )

    def step(state, batch, entropy_beta, learning_rate=None):
        if learning_rate is None:
            learning_rate = cfg.learning_rate
        return jitted(
            state,
            batch,
            jnp.asarray(entropy_beta, jnp.float32),
            jnp.asarray(learning_rate, jnp.float32),
        )

    step.batch_sharding = NamedSharding(mesh, batch_spec)
    step.state_sharding = NamedSharding(mesh, replicated)
    step.mesh = mesh
    step.n_fleets = n_fleets
    step.audit_jit = jitted  # tools/ba3caudit traces THIS program
    return step
