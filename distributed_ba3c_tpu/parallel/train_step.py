"""The synchronous data-parallel BA3C train step.

Replaces, wholesale (SURVEY.md §3.4): the reference's
``sess.run(train_op)`` → per-variable async gradient push to parameter servers
over gRPC. Here: each device computes gradients on its batch shard, a single
``lax.psum`` averages them over the ICI ``data`` axis, and every device applies
the identical Adam update to its replicated params. One jitted computation, no
staleness, no PS.

Sharding layout:
  params/opt_state: replicated (PartitionSpec())
  batch:            sharded on the leading axis (PartitionSpec('data'))
The step is expressed with ``jax.shard_map`` so the collective is explicit and
the compiled module is identical regardless of host count (multi-host just
widens the mesh; see parallel/distributed.py).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ba3c_tpu.audit import tripwire_jit
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import grad_summaries, inject_learning_rate
from distributed_ba3c_tpu.ops.loss import a3c_loss
from distributed_ba3c_tpu.parallel.mesh import (
    DATA_AXIS,
    axis_size,
    grad_allreduce,
    shard_map,
)


class TrainState(struct.PyTreeNode):
    """Learner state: params + optimizer state + step counter.

    Reference equivalent: the TF variables living on parameter servers plus the
    global_step (SURVEY.md §2.5). Replicated across the mesh.
    """

    step: jax.Array
    params: Any
    opt_state: Any


def create_train_state(
    rng: jax.Array,
    model: BA3CNet,
    cfg: BA3CConfig,
    optimizer: optax.GradientTransformation,
) -> TrainState:
    dummy = jnp.zeros((1, *cfg.state_shape), jnp.uint8)
    params = model.init(rng, dummy)["params"]
    opt_state = optimizer.init(params)
    if inject_learning_rate(opt_state, 0.0) is opt_state:
        from distributed_ba3c_tpu.utils import logger

        logger.warn(
            "optimizer has no injectable learning_rate leaf — runtime LR "
            "schedules (ScheduledHyperParamSetter etc.) will be SILENT no-ops;"
            " build it with ops.gradproc.make_optimizer"
        )
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)


def _local_step(
    model: BA3CNet,
    optimizer: optax.GradientTransformation,
    cfg: BA3CConfig,
    state: TrainState,
    batch: Dict[str, jax.Array],
    entropy_beta: jax.Array,
    learning_rate: jax.Array,
) -> Tuple[TrainState, Dict[str, jax.Array]]:
    """Per-device shard-local step body; runs inside shard_map."""

    def loss_fn(params):
        out = model.apply({"params": params}, batch["state"])
        loss = a3c_loss(
            out.logits,
            out.value,
            batch["action"],
            batch["return"],
            entropy_beta=entropy_beta,
            value_loss_coef=cfg.value_loss_coef,
            huber_delta=cfg.value_huber_delta,
        )
        return loss.total, loss

    (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)

    # The one collective that replaces the reference's whole PS gradient plane.
    # Under shard_map's check_vma=True semantics the transpose auto-inserts the
    # psum for the replicated params (grads arrive device-invariant, SUMMED over
    # the data axis); dividing by the axis size yields the global batch mean.
    # (An explicit lax.pmean here would double-count by the axis size;
    # grad_allreduce is identity there and psums only on old-jax check_rep=False.)
    grads = grad_allreduce(grads, DATA_AXIS)
    n_data = axis_size(DATA_AXIS)
    grads = jax.tree_util.tree_map(lambda g: g / n_data, grads)

    opt_state = inject_learning_rate(state.opt_state, learning_rate)
    updates, new_opt_state = optimizer.update(grads, opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    new_state = TrainState(
        step=state.step + 1, params=new_params, opt_state=new_opt_state
    )

    metrics = {
        "loss": aux.total,
        "policy_loss": aux.policy_loss,
        "value_loss": aux.value_loss,
        "entropy": aux.entropy,
        "advantage": aux.advantage,
        "pred_value": aux.pred_value,
        **grad_summaries(grads),
    }
    metrics = {k: jax.lax.pmean(v, DATA_AXIS) for k, v in metrics.items()}
    return new_state, metrics


def make_train_step(
    model: BA3CNet,
    optimizer: optax.GradientTransformation,
    cfg: BA3CConfig,
    mesh: Mesh,
) -> Callable[[TrainState, Dict[str, jax.Array], jax.Array], Tuple[TrainState, Dict[str, jax.Array]]]:
    """Build the jitted, mesh-sharded train step.

    Returns fn(state, batch, entropy_beta) -> (state, metrics) with donated
    state buffers. ``batch`` leading dim must be divisible by the mesh's data
    axis size.
    """
    replicated = P()
    batch_spec = P(DATA_AXIS)

    body = functools.partial(_local_step, model, optimizer, cfg)
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(replicated, batch_spec, replicated, replicated),
        out_specs=(replicated, replicated),
    )

    # registered audit entry point (distributed_ba3c_tpu/audit.py): under
    # BA3C_AUDIT=1 a post-warmup retrace raises instead of silently stalling
    jitted = tripwire_jit("parallel.train_step", sharded, donate_argnums=(0,))

    def step(state, batch, entropy_beta, learning_rate=None):
        if learning_rate is None:
            learning_rate = cfg.learning_rate
        return jitted(
            state,
            batch,
            jnp.asarray(entropy_beta, jnp.float32),
            jnp.asarray(learning_rate, jnp.float32),
        )

    # expose shardings so callers can device_put batches asynchronously
    step.batch_sharding = NamedSharding(mesh, batch_spec)
    step.state_sharding = NamedSharding(mesh, replicated)
    step.mesh = mesh
    step.audit_jit = jitted  # tools/ba3caudit traces THIS program
    return step
