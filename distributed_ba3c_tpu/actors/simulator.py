"""Simulator processes and the master's receive loop (ZMQ experience plane).

Reference equivalent: ``tensorpack/RL/simulator.py`` — ``SimulatorProcess``,
``SimulatorMaster``, ``ClientState``, ``TransitionExperience`` (SURVEY.md §2.3
#8-9, call stack §3.2). Wire protocol, kept byte-compatible in spirit:

    sim -> master (PUSH -> PULL):  msgpack [ident, state u8-array, reward, isOver]
    master -> sim (ROUTER -> DEALER ident-routed): msgpack action

Both pipes default to ipc:// within a host; tcp:// works unchanged for
remote actor hosts (the multi-host layout keeps actors host-side and only
gradients on ICI — SURVEY.md §2.12).

The child-process side imports no jax: children must stay lightweight (the
reference ran ~50 per worker; we target hundreds per TPU host).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue
import threading
import time
from abc import abstractmethod
from typing import Callable, Dict, List, Optional

import zmq

from distributed_ba3c_tpu.envs.base import RLEnvironment
from distributed_ba3c_tpu.utils import logger, sanitizer
from distributed_ba3c_tpu.utils.concurrency import (
    StoppableThread,
    queue_put_stoppable,
)
from distributed_ba3c_tpu.utils.serialize import dumps, loads


class TransitionExperience:
    """One (state, action, value) awaiting its reward attachment."""

    __slots__ = ("state", "action", "reward", "value")

    def __init__(self, state, action, value, reward=None):
        self.state = state
        self.action = action
        self.value = value
        self.reward = reward


class ClientState:
    """Per-simulator state held by the master, keyed by ZMQ ident."""

    __slots__ = ("memory", "ident", "score", "last_seen")

    def __init__(self, ident: bytes):
        self.ident = ident
        self.memory: List[TransitionExperience] = []
        self.score = 0.0
        # initialized to creation time so a client that NEVER sends again
        # (e.g. resurrected by a late predictor callback after pruning) still
        # ages out instead of being exempt forever. MONOTONIC, not wall
        # clock: an NTP step/suspend would otherwise mass-expire (or
        # immortalize) every actor at once (ba3clint A4 caught this).
        self.last_seen = time.monotonic()


def default_pipes(name: str = "ba3c") -> tuple[str, str]:
    """ipc:// pipe pair for one host (unique per pid so tests can nest)."""
    base = f"ipc:///tmp/{name}-{os.getpid()}"
    return f"{base}-c2s", f"{base}-s2c"


_spawn_ctx = mp.get_context("spawn")


class SimulatorProcess(_spawn_ctx.Process):  # type: ignore[name-defined]
    """One OS process owning one player; loop: send state, await action, step.

    Reference: ``SimulatorProcess._run`` (SURVEY.md §3.2). ``build_player``
    must be picklable (a top-level function or functools.partial).

    Spawned (not forked): the trainer process is multithreaded (JAX runtime,
    predictor, master) and ``fork()`` from a threaded parent can deadlock the
    child. Child processes import only numpy/zmq modules, never jax.
    """

    def __init__(
        self,
        idx: int,
        pipe_c2s: str,
        pipe_s2c: str,
        build_player: Callable[[int], RLEnvironment],
    ):
        super().__init__(daemon=True, name=f"simulator-{idx}")
        self.idx = idx
        self.c2s = pipe_c2s
        self.s2c = pipe_s2c
        self._build_player = build_player

    def run(self) -> None:
        player = self._build_player(self.idx)
        ident = f"simulator-{self.idx}".encode()
        context = zmq.Context()
        c2s = context.socket(zmq.PUSH)
        c2s.setsockopt(zmq.IDENTITY, ident)
        c2s.set_hwm(4)
        c2s.connect(self.c2s)
        s2c = context.socket(zmq.DEALER)
        s2c.setsockopt(zmq.IDENTITY, ident)
        s2c.connect(self.s2c)

        state = player.current_state()
        reward, is_over = 0.0, False
        try:
            while True:
                c2s.send(dumps([ident, state, reward, is_over]))
                action = loads(s2c.recv())
                reward, is_over = player.action(action)
                state = player.current_state()
        except (KeyboardInterrupt, zmq.ContextTerminated):
            pass
        finally:
            c2s.close(0)
            s2c.close(0)
            context.term()


class SimulatorMaster(threading.Thread):
    """Master thread: multiplexes all simulators, dispatches subclass hooks.

    Reference: ``SimulatorMaster.run`` (SURVEY.md §3.2) — attach the incoming
    reward to the previous transition, fire ``_on_episode_over`` /
    ``_on_datapoint``, then ``_on_state`` for the fresh state. A dedicated
    send thread drains ``send_queue`` so predictor callbacks never block on
    the socket.
    """

    def __init__(
        self,
        pipe_c2s: str,
        pipe_s2c: str,
        actor_timeout: Optional[float] = None,
        reward_clip: float = 0.0,
    ):
        """``actor_timeout``: seconds of silence after which a client's state
        is dropped (failure detection the reference lacked, SURVEY.md §5 —
        a dead simulator would otherwise pin its half-built rollout forever).
        None disables pruning. ``reward_clip``: clip the LEARNING reward to
        [-c, c] (0 = off); episode scores always accumulate raw rewards."""
        super().__init__(daemon=True, name="SimulatorMaster")
        self.actor_timeout = actor_timeout
        assert reward_clip >= 0, (
            f"reward_clip must be >= 0, got {reward_clip} (a negative bound "
            "would silently map every learning reward to a constant)"
        )
        self.reward_clip = reward_clip
        self._last_prune = 0.0
        self.context = zmq.Context()
        self.c2s_socket = self.context.socket(zmq.PULL)
        self.c2s_socket.bind(pipe_c2s)
        self.c2s_socket.set_hwm(32)
        self.s2c_socket = self.context.socket(zmq.ROUTER)
        self.s2c_socket.bind(pipe_s2c)
        self.s2c_socket.set_hwm(32)

        # sanitizer wrapping (BA3C_SANITIZE=1 in tests): the client table's
        # structure is owned by the receive loop, the send queue has exactly
        # one drain thread — plain defaultdict/Queue when disabled
        self.clients: Dict[bytes, ClientState] = sanitizer.wrap_client_table(
            lambda: ClientState(b""), name="SimulatorMaster.clients"
        )
        self.send_queue: "queue.Queue[list]" = sanitizer.wrap_queue(
            queue.Queue(maxsize=1024), name="SimulatorMaster.send_queue"
        )
        self._stop_evt = threading.Event()

        def send_loop():
            t = threading.current_thread()
            assert isinstance(t, StoppableThread)
            while not t.stopped():
                msg = t.queue_get_stoppable(self.send_queue, timeout=0.2)
                if msg is None:
                    return
                try:
                    self.s2c_socket.send_multipart(msg)
                except zmq.ZMQError:
                    if t.stopped() or self._stop_evt.is_set():
                        return  # socket closed during teardown
                    raise

        self.send_thread = StoppableThread(
            target=send_loop, daemon=True, name="SimulatorMaster-send"
        )
        self.send_thread.start()

    def run(self) -> None:
        poller = zmq.Poller()
        poller.register(self.c2s_socket, zmq.POLLIN)
        # this receive loop is the structural owner of the client table;
        # the sanitizer (when enabled) flags any other thread that
        # creates/deletes entries
        sanitizer.claim_owner(self.clients)

        try:
            while not self._stop_evt.is_set():
                # prune on EVERY iteration (it self-rate-limits): gating it
                # on poll timeouts would starve pruning exactly when the
                # surviving actors keep the socket busy
                self._prune_dead_actors()
                if not poller.poll(timeout=200):
                    continue
                ident, state, reward, is_over = loads(self.c2s_socket.recv())
                client = self.clients[ident]
                client.ident = ident
                client.last_seen = time.monotonic()
                self._on_message(ident, state, reward, is_over)
        except zmq.ContextTerminated:
            logger.info("SimulatorMaster context terminated")
        except zmq.ZMQError:
            # teardown race: close() destroyed the sockets while we polled.
            # Only swallow when shutting down — a live-loop ZMQError is a bug.
            if not self._stop_evt.is_set():
                raise
            logger.info("SimulatorMaster socket closed during shutdown")

    def _prune_dead_actors(self) -> None:
        """Drop state of clients silent for > actor_timeout (actor loss is
        tolerated: its partial rollout is discarded, training continues)."""
        if self.actor_timeout is None:
            return
        now = time.monotonic()
        if now - self._last_prune < self.actor_timeout / 4:
            return
        self._last_prune = now
        dead = [
            ident
            for ident, c in self.clients.items()
            if now - c.last_seen > self.actor_timeout
        ]
        for ident in dead:
            del self.clients[ident]
            logger.warn(
                "actor %s silent for >%.0fs — dropped its client state",
                ident,
                self.actor_timeout,
            )

    def _on_message(self, ident: bytes, state, reward: float, is_over: bool) -> None:
        """Handle one simulator message (overridable; runs in master thread).

        Default semantics: attach the reward to the previous transition, fire
        the episode/datapoint hooks, then request an action for the new state.
        Per-client ordering is serialized by the protocol — the simulator
        blocks on its action, so no second message from ``ident`` can arrive
        before ``_on_state``'s callback has run.
        """
        client = self.clients[ident]
        if len(client.memory) > 0:
            client.memory[-1].reward = self._learn_reward(reward)
            client.score += reward  # scores stay RAW
            if is_over:
                self._on_episode_over(ident)
            else:
                self._on_datapoint(ident)
        self._on_state(state, ident)

    def _learn_reward(self, reward: float) -> float:
        """The LEARNING reward: clipped to [-c, c] when reward_clip is set
        (single definition shared by every master subclass)."""
        c = self.reward_clip
        return max(-c, min(c, reward)) if c else reward

    def send_action(self, ident: bytes, action: int) -> None:
        self._put_stoppable(self.send_queue, [ident, dumps(int(action))])

    def _put_stoppable(self, q: queue.Queue, item, timeout: float = 0.5) -> bool:
        """Backpressure that stays shutdown-responsive: bounded-timeout puts
        re-checking the stop flag (the plane's only sanctioned blocking put —
        ba3clint A2). Returns False if the master stopped while waiting."""
        return queue_put_stoppable(q, item, self._stop_evt, timeout)

    def stop(self) -> None:
        self._stop_evt.set()
        self.send_thread.stop()

    def close(self) -> None:
        """Stop threads and tear down ZMQ without lingering sends.

        Idempotent; joins the receive loop BEFORE destroying the context so
        no ZMQ background thread outlives the master (a leaked io-thread can
        wedge later in-process jit dispatch — the round-1 pytest deadlock).
        """
        self._stop_evt.set()
        self.send_thread.stop()
        self.send_thread.join(timeout=2)
        if self.is_alive():
            self.join(timeout=2)
        try:
            self.context.destroy(linger=0)
        except zmq.ZMQError:
            pass  # already destroyed

    @abstractmethod
    def _on_state(self, state, ident: bytes) -> None:
        """A fresh state arrived: request an action and record the transition."""

    @abstractmethod
    def _on_episode_over(self, ident: bytes) -> None:
        """The client's episode ended (reward already attached)."""

    @abstractmethod
    def _on_datapoint(self, ident: bytes) -> None:
        """A mid-episode transition completed (reward already attached)."""

    def __del__(self):
        try:
            self._stop_evt.set()
            self.send_thread.stop()
            self.context.destroy(0)
        except Exception:
            pass
